"""Optimizable least-squares meta-solver.

TPU-native re-design of reference:
nodes/learning/LeastSquaresEstimator.scala:26-87 — a cost-model-driven
choice among the concrete least-squares solvers:

- dense L-BFGS          (few features, dense data)
- Sparsify ∘ sparse L-BFGS  (sparse data)
- Densify ∘ block solve (many features, dense)
- Densify ∘ exact normal equations (few features)

Statistics (n, d, k, sparsity) come from the node-level optimizer's sample
pass; machine count from the mesh. Cost formulas mirror the reference's
(flops / bytes-scanned / network per solver), with the caveat the
reference itself documents: the weights were fit on its 16-node cluster
and should be re-fit per deployment.

HBM discipline: the exact normal-equation rung (``LinearMapEstimator``)
and the block rung both donate their private row-sharded data copies
into the solve (``donate_xy`` in parallel/linalg.py), so the update's
Gram/residual workspace reuses the data buffers instead of doubling
residency — same pattern as conv_block.py's donated prediction carry.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...data.dataset import ArrayDataset, Dataset
from ...parallel.mesh import num_devices
from ...workflow.optimize import DataStats, Optimizable
from ...workflow.pipeline import LabelEstimator, Transformer
from .block import BlockLeastSquaresEstimator
from .cost import (
    DEFAULT_COST_WEIGHTS,
    CostModel,
    CostWeights,
    default_cost_weights,
)
from .lbfgs import DenseLBFGSEstimator, SparseLBFGSEstimator
from .linear import LinearMapEstimator


class _DenseLBFGSCost(CostModel):
    def cost(self, n, d, k, sparsity, num_machines, w=DEFAULT_COST_WEIGHTS):
        iters = 20
        flops = iters * n * d * k * max(sparsity, 1e-12) / num_machines
        bytes_scanned = iters * n * d * max(sparsity, 1e-12) / num_machines
        network = iters * d * k * np.log2(max(num_machines, 2))
        return max(w.cpu * flops, w.mem * bytes_scanned) + w.network * network


class _SparseLBFGSCost(_DenseLBFGSCost):
    pass


class _BlockSolveCost(CostModel):
    def __init__(self, block_size=1000, num_iter=3):
        self.block_size = block_size
        self.num_iter = num_iter

    def cost(self, n, d, k, sparsity, num_machines, w=DEFAULT_COST_WEIGHTS):
        b = self.block_size
        iters = self.num_iter * max(d // b, 1)
        flops = iters * (n * b * (b + k)) / num_machines
        bytes_scanned = iters * n * b / num_machines
        network = iters * (b * b + b * k) * np.log2(max(num_machines, 2))
        return max(w.cpu * flops, w.mem * bytes_scanned) + w.network * network


class _ExactCost(CostModel):
    def cost(self, n, d, k, sparsity, num_machines, w=DEFAULT_COST_WEIGHTS):
        flops = n * d * (d + k) / num_machines + d * d * d
        bytes_scanned = n * d / num_machines + d * d
        network = d * (d + k)
        return max(w.cpu * flops, w.mem * bytes_scanned) + w.network * network


class _SketchCost(CostModel):
    """The sketched rung (keystone_tpu/sketch): one data pass into an
    O(s·d) carry plus an s×s finish solve. Priced at infinity below
    ``sketch_min_width()`` — at moderate widths the raw flop count would
    win while accuracy argues for the exact/Gram rungs, so the width
    floor IS the eligibility gate (docs/SOLVERS.md)."""

    def __init__(self, sketch_size: int):
        self.sketch_size = sketch_size

    def cost(self, n, d, k, sparsity, num_machines, w=DEFAULT_COST_WEIGHTS):
        from ...sketch.solvers import sketch_min_width

        if d < sketch_min_width():
            return np.inf
        s = self.sketch_size
        flops = n * (d + k) / num_machines + s * s * (d + k) + s * s * s
        bytes_scanned = n * d / num_machines + s * (d + k)
        network = s * (d + k)
        return max(w.cpu * flops, w.mem * bytes_scanned) + w.network * network


class LeastSquaresEstimator(LabelEstimator, Optimizable):
    """Meta-solver choosing the concrete least-squares implementation."""

    #: Chunked-fit protocol (workflow/streaming.py). The streaming path
    #: always has the full Gram in hand after accumulation, so the
    #: meta-choice collapses: exact solve for narrow problems, Gram-BCD
    #: for wide ones (L-BFGS needs materialized data passes and is never
    #: the streaming pick).
    supports_fit_stream = True

    #: Refit state contract (docs/REFIT.md): the meta-solver's state is
    #: whatever its delegated concrete rung accumulates — Gram for the
    #: exact/block rungs, "sketch" past ``sketch_min_width()``. The
    #: class attr is the narrow default; per-stream resolution goes
    #: through ``stream_state_kind_for`` (reliability/durable.py).
    stream_state_kind = "gram"

    #: 2-D partitioner protocol (docs/PARTITIONING.md "2-D layouts"):
    #: every rung this meta-solver delegates to folds a blocked-carry
    #: step (gram_stream_step / sketch_stream_step), so its streamed
    #: state can shard the feature axis.
    supports_model_axis = True

    def fit_stream(self, stream, state=None):
        inner = self._stream_solver(
            _stream_width(stream, self.block_size),
            model_shards=_stream_model_shards(stream),
        )
        fitted = inner.fit_stream(stream, state=state)
        # Surface the delegate's captured statistics as OUR export, so
        # the refit loop can hold the meta-solver and never care which
        # concrete rung the width picked.
        self._stream_state = inner.export_stream_state()
        return fitted

    def _stream_solver(self, width: int, model_shards: int = 1):
        """The concrete streaming rung for a featurized ``width``:
        exact (narrow) → Gram-BCD (wide) → sketched (very wide, where
        the O(d²) Gram itself is the memory problem — KV303's regime).
        The rung is priced on PER-DEVICE state: a 2-D plan splits the
        Gram's feature rows ``model_shards`` ways, so the sketch floor
        scales with it — a mesh that feature-shards keeps the exact Gram
        rung ``model_shards``× wider before sketching truncates."""
        from ...sketch.solvers import (
            SketchedLeastSquaresEstimator,
            sketch_min_width,
        )

        if width >= sketch_min_width() * max(1, model_shards):
            inner = SketchedLeastSquaresEstimator(reg=self.reg)
            tuned = getattr(self, "_tuned_sketch_size", None)
            if tuned:
                # Measured-knob override (workflow/knobs.py) rides the
                # meta-solver down to whichever rung the width picks.
                inner._tuned_sketch_size = int(tuned)
            return inner
        return self._gram_stream_solver(width)

    def _gram_stream_solver(self, width: int):
        """The Gram-family rung for ``width`` (also the finish path for
        persisted Gram carries of ANY width — a pre-sketch-tier state
        must never be finished by the sketched rung)."""
        if width > self.block_size:
            return BlockLeastSquaresEstimator(
                self.block_size, num_iter=self.block_iters, reg=self.reg
            )
        from .linear import LinearMapEstimator

        # Same contract as the exact rung: reg>0 is ridge, reg=0 is
        # plain least squares that fails LOUDLY on a singular Gram
        # (check_finite) rather than degrading to NaN predictions.
        return LinearMapEstimator(reg=self.reg or None)

    def stream_state_kind_for(self, stream) -> str:
        """Durable-fold protocol: the committed StreamState's kind must
        be the CHOSEN rung's, resolved after the stream geometry is
        final (a sketched fold commits kind="sketch" carries)."""
        return self._stream_solver(
            _stream_width(stream, self.block_size),
            model_shards=_stream_model_shards(stream),
        ).stream_state_kind

    def stream_state_meta_for(self, stream):
        """Durable-fold protocol: the chosen rung's envelope meta (the
        sketch rung's (variant, seed); empty for the Gram family)."""
        inner = self._stream_solver(
            _stream_width(stream, self.block_size),
            model_shards=_stream_model_shards(stream),
        )
        return dict(getattr(inner, "stream_state_meta", {}) or {})

    # ------------------------------------------------ refit state contract
    def export_stream_state(self):
        return getattr(self, "_stream_state", None)

    def merge_stream_state(self, a, b):
        from ...refit.state import merge_stream_states

        return merge_stream_states(a, b)

    def finish_from_state(self, state):
        """Finish from statistics alone. The state's ``kind`` names the
        rung family that accumulated it: sketch carries finish on the
        sketched rung regardless of width, Gram carries re-run the
        width dispatch (the carry's Gram is (d, d), so the width is in
        the state itself — capped below the sketch floor, which never
        produces Gram carries)."""
        if state.kind == "sketch":
            from ...sketch.solvers import SketchedLeastSquaresEstimator

            inner = SketchedLeastSquaresEstimator(reg=self.reg)
            if state.meta.get("sketch_variant"):
                inner.variant = state.meta["sketch_variant"]
                inner.seed = int(state.meta.get("sketch_seed", inner.seed))
            return inner.finish_from_state(state)
        return self._gram_stream_solver(
            int(state.carry[0].shape[0])
        ).finish_from_state(state)

    def __init__(
        self,
        reg: float = 0.0,
        num_machines: Optional[int] = None,
        weights: Optional[CostWeights] = None,
        sparse_threshold: float = 0.2,
        block_size: int = 1000,
        block_iters: int = 3,
    ):
        self.reg = reg
        self.num_machines = num_machines
        # None → resolved per-backend at optimize() time (measured-TPU
        # constants on accelerators, the reference's on CPU).
        self.weights = weights
        self.sparse_threshold = sparse_threshold
        self.block_size = block_size
        self.block_iters = block_iters

    def out_spec(self, in_specs):
        """Plan-time spec protocol (workflow/verify.py): whichever
        concrete solver the cost model picks, the fitted map is
        (m, d) -> (m, k)."""
        from ...workflow.verify import dense_fit_spec

        return dense_fit_spec(in_specs, self.label)

    # default implementation when node-level optimization never ran
    def fit(self, data: Dataset, labels: Dataset) -> Transformer:
        from ...obs import solver as solver_obs
        from ...reliability import DegradationLadder, probe

        # Solver-grade degradation (the Panther mindset, PAPERS.md): when
        # the preferred solver OOMs, fall through to the block solver —
        # whose own internal ladder then shrinks its block size — rather
        # than aborting the run. Non-OOM failures propagate from rung 1.
        ladder = DegradationLadder(
            [
                ("dense_lbfgs", self._default),
                (
                    "block",
                    lambda: BlockLeastSquaresEstimator(
                        self.block_size, num_iter=self.block_iters, reg=self.reg
                    ),
                ),
            ],
            label="LeastSquaresEstimator.fit",
        )

        attempts = iter(range(len(ladder.rungs)))

        def attempt(rung):
            name, factory = rung
            probe("LeastSquaresEstimator.solve")
            with solver_obs.rung_span("least_squares", name, next(attempts)):
                return factory().fit(data, labels)

        import time as _time

        t_fit = _time.perf_counter()
        with solver_obs.fit_span(
            "least_squares", **solver_obs.predicted_attrs(self)
        ):
            model = ladder.run(attempt)
        # Meta-solver observation: the rung that finally held and what it
        # cost, keyed per shape class — the profile store's record of
        # which concrete solver this problem size actually wants.
        try:
            from ...obs import store as obs_store

            store = obs_store.get_store()
            if store is not None:
                n_rows = len(data)
                d_cols = 0
                if isinstance(data, ArrayDataset):
                    arr = data.data
                    d_cols = int(arr.shape[1]) if getattr(arr, "ndim", 1) > 1 else 1
                rung = "dense_lbfgs" if not ladder.reduced else (
                    ladder.record["rung"][0]
                )
                store.record(
                    f"solver:least_squares:rung_{rung}",
                    obs_store.shape_class(n_rows, (d_cols,), "float32"),
                    wall_s=round(_time.perf_counter() - t_fit, 6),
                    solver_rung=rung,
                )
        except Exception:
            pass
        if ladder.reduced:
            record = dict(
                ladder.record, rung=ladder.record["rung"][0],
                first_rung=ladder.record["first_rung"][0],
            )
            # The fallback solver may have degraded internally too (block
            # halving in block.py) — nest its record, don't clobber it.
            inner = getattr(model, "degradation", None)
            if inner is not None:
                record["inner"] = inner
            model.degradation = record
        return model

    def _default(self) -> LabelEstimator:
        return DenseLBFGSEstimator(reg=self.reg)

    def optimize(self, samples: List[Dataset], stats: DataStats):
        sample_x = samples[0]
        n = stats.n_total
        d, k, sparsity = _sample_shape_stats(sample_x, samples[1] if len(samples) > 1 else None)
        machines = self.num_machines or num_devices()
        # Resolve per call, not in __init__: the right weights depend on
        # the backend active when planning runs.
        weights = self.weights if self.weights is not None else default_cost_weights()

        from ...sketch.solvers import (
            SketchedLeastSquaresEstimator,
            sketch_min_width,
        )

        sparse_ok = sparsity < self.sparse_threshold
        sketch_ok = d >= sketch_min_width()
        # Price the sketch size that will actually run (env knob >
        # constructor > measured-knob winner > width default) — pricing
        # the width default when KEYSTONE_SKETCH_SIZE or a tuned winner
        # pins a smaller s would mischarge the rung ~s² and hand the
        # argmin to a Gram rung the user explicitly sized the sketch for.
        sketch_probe = SketchedLeastSquaresEstimator(reg=self.reg)
        tuned_s = getattr(self, "_tuned_sketch_size", None)
        if tuned_s:
            sketch_probe._tuned_sketch_size = int(tuned_s)
        sketch_s = sketch_probe._resolve_sketch_size(d)
        # (name, cost, estimator, ineligible-reason). Ineligible rungs
        # price at inf but STAY in the list: `keystone-tpu explain`
        # surfaces every rung the argmin saw, with why it lost.
        candidates = [
            (
                "sparse_lbfgs",
                _SparseLBFGSCost().cost(n, d, k, sparsity, machines, weights)
                if sparse_ok
                else np.inf,
                SparseLBFGSEstimator(reg=self.reg),
                ""
                if sparse_ok
                else f"density {sparsity:.3f} ≥ sparse_threshold "
                f"{self.sparse_threshold}",
            ),
            (
                "dense_lbfgs",
                _DenseLBFGSCost().cost(n, d, k, 1.0, machines, weights),
                DenseLBFGSEstimator(reg=self.reg),
                "",
            ),
            (
                "block",
                _BlockSolveCost(self.block_size, self.block_iters).cost(
                    n, d, k, 1.0, machines, weights
                ),
                BlockLeastSquaresEstimator(
                    self.block_size, num_iter=self.block_iters, reg=self.reg
                ),
                "",
            ),
            (
                "exact",
                _ExactCost().cost(n, d, k, 1.0, machines, weights),
                LinearMapEstimator(reg=self.reg),
                "",
            ),
            (
                "sketched",
                _SketchCost(sketch_s).cost(
                    n, d, k, 1.0, machines, weights
                ),
                sketch_probe,
                ""
                if sketch_ok
                else f"width {d} < KEYSTONE_SKETCH_MIN_WIDTH "
                f"{sketch_min_width()}",
            ),
        ]
        cost_ms, chosen = min(
            ((c, est) for _, c, est, _ in candidates), key=lambda c: c[0]
        )
        # Cost-observatory provenance (obs/cost.py): the rung's predicted
        # cost rides the chosen estimator into the perf ledger and the
        # solver:fit span — with EVERY candidate's cost and the rejected
        # rungs' reasons, so the three-rung ladder's decisions are
        # auditable in `keystone-tpu explain`. The ladder's constants are
        # RELATIVE (only the argmin matters; the reference fit them on
        # its own cluster), so the prediction is displayed but never
        # drift-scored (calibrated=False).
        from ...obs.cost import Prediction

        provenance = []
        for name, c, est, why in candidates:
            if est is chosen:
                reason = "chosen"
            elif why:
                reason = why
            elif np.isfinite(c):
                reason = f"cost above chosen rung ({c / 1e3:.3g}s)"
            else:
                reason = "ineligible"
            provenance.append(
                (name, None if not np.isfinite(c) else float(c) / 1e3, reason)
            )
        chosen.predicted_cost = Prediction(
            model="solver_ladder",
            key=f"solver:ladder:{type(chosen).__name__}",
            shape=f"n{n}|{d}|k{k}",
            seconds=float(cost_ms) / 1e3,
            calibrated=False,
            candidates=tuple(provenance),
        )
        return chosen


def _stream_width(stream, default: int) -> int:
    """Featurized width of a ChunkStream (shape-only, no data touched);
    ``default`` when the chain output is not a plain matrix — the
    downstream fold will fall back to the materialized path anyway."""
    import jax

    try:
        leaves = jax.tree_util.tree_leaves(stream.feature_aval())
    except Exception:
        return default
    if len(leaves) == 1 and len(leaves[0].shape) == 2:
        return int(leaves[0].shape[1])
    return default


def _stream_model_shards(stream) -> int:
    """Feature-axis shards of the stream's pinned partition decision —
    what makes the rung dispatch price PER-DEVICE state bytes instead of
    the global carry (a (d, d) Gram on p model shards costs each device
    d²/p). 1 for unpartitioned or row-only streams."""
    part = getattr(stream, "partition", None)
    return max(1, int(getattr(part, "model_shards", 1) or 1))


def _sample_shape_stats(sample_x: Dataset, sample_y: Optional[Dataset]):
    import jax

    if isinstance(sample_x, ArrayDataset):
        x = np.asarray(jax.device_get(sample_x.data))[: sample_x.num_examples]
        d = x.shape[1] if x.ndim > 1 else 1
        sparsity = float((x != 0).mean())
    else:
        items = sample_x.take(32)
        first = items[0]
        if hasattr(first, "nnz"):  # scipy sparse rows
            d = first.shape[1]
            nnz = sum(i.nnz for i in items)
            sparsity = nnz / (len(items) * d)
        else:
            arr = np.stack([np.asarray(i) for i in items])
            d = arr.shape[1]
            sparsity = float((arr != 0).mean())
    if sample_y is not None and isinstance(sample_y, ArrayDataset):
        ydata = np.asarray(jax.device_get(sample_y.data))
        k = ydata.shape[1] if ydata.ndim > 1 else 1
    elif sample_y is not None:
        items = sample_y.take(1)
        k = np.asarray(items[0]).size if items else 1
    else:
        k = 1
    return d, k, sparsity
