"""ZCA whitening.

TPU-native re-design of reference: nodes/learning/ZCAWhitener.scala:12-77.
Fit: SVD of the centered patch matrix; whitener = V·diag((s²/(n−1)+ε)^-½)·Vᵀ.
Apply: (M − μ) · W for per-item patch matrices — one batched matmul when
items are uniformly shaped.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...data.dataset import ArrayDataset, Dataset
from ...parallel import linalg
from ...workflow.pipeline import Estimator, Transformer


class ZCAWhitener(Transformer):
    def __init__(self, whitener: jnp.ndarray, means: jnp.ndarray):
        self.whitener = jnp.asarray(whitener)  # (d, d)
        self.means = jnp.asarray(means)  # (d,)

    def apply(self, mat):
        return np.asarray((jnp.asarray(mat) - self.means) @ self.whitener)

    def apply_batch(self, dataset: Dataset) -> Dataset:
        if isinstance(dataset, ArrayDataset):
            x = jnp.asarray(dataset.data)
            out = linalg.mm(x - self.means, self.whitener)
            return ArrayDataset(out, dataset.num_examples)
        return dataset.map(self.apply)


class ZCAWhitenerEstimator(Estimator):
    """Fit on the (first / full) patch matrix
    (reference: ZCAWhitener.scala fitSingle)."""

    def __init__(self, eps: float = 0.1):
        self.eps = eps

    def out_spec(self, in_specs):
        """Plan-time spec protocol (workflow/verify.py): whitening
        preserves shape and dtype."""
        from ...workflow.verify import elementwise_fit_spec

        return elementwise_fit_spec(in_specs, self.label)

    def fit(self, data: Dataset) -> ZCAWhitener:
        if isinstance(data, ArrayDataset):
            mat = jnp.asarray(data.data, dtype=jnp.float32)[: data.num_examples]
            if mat.ndim == 3:  # dataset of matrices: use the first, like the reference
                mat = mat[0]
        else:
            mat = jnp.asarray(np.asarray(data.take(1)[0]), dtype=jnp.float32)
        return self.fit_single(mat)

    def fit_single(self, mat: jnp.ndarray) -> ZCAWhitener:
        whitener, means = _zca_fit(mat, jnp.float32(self.eps))
        return ZCAWhitener(whitener, means)


@linalg.mode_jit
def _zca_fit(mat, eps):
    means = jnp.mean(mat, axis=0)
    centered = mat - means
    n = mat.shape[0]
    _, s, vt = jnp.linalg.svd(centered, full_matrices=False)
    scale = (s**2 / (n - 1.0) + eps) ** -0.5
    whitener = linalg.mm(vt.T * scale, vt)
    return whitener, means
