"""Fused featurize-and-solve: BCD whose feature blocks are rematerialized
on device instead of stored.

The reference's CIFAR RandomPatch caches the 80,000-wide featurized RDD
and streams feature blocks out of the cache into BCD (reference:
RandomPatchCifar.scala:59-77, nodes/util/VectorSplitter.scala:10-37,
BlockLinearMapper.scala:234-240). On TPU the roles invert: HBM is the
scarce resource and the MXU makes convolution nearly free, so instead of
storing the (n, 80000) feature matrix anywhere (16 GB fp32 — beyond one
chip's HBM, and host streaming is PCIe/DCN-bound), each solver block's
features are *recomputed* from the raw images at the moment the block
update needs them. A solver block is chosen to coincide with a filter
block of the fused conv featurizer, so across one epoch every filter is
convolved exactly once — the same total conv work as featurizing once,
with device residency = raw images + one block panel + the (n, k)
predictions.

One jitted step serves every block: the kernel slice, filter sums and
whitener offsets are traced arguments of fixed shape. Mean/std
normalization (the pipeline's StandardScaler) happens inside the step
from masked psums, and the returned model folds 1/σ into the weights so
it applies to ordinary featurizer output.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ...data.dataset import ArrayDataset, Dataset
from ...parallel import linalg
from ...parallel.collectives import shard_map
from ...parallel.mesh import get_mesh, row_axes, row_shard_count
from ...parallel.partitioner import fit_mesh
from ...workflow.pipeline import BatchTransformer, LabelEstimator
from ..images.core import FusedConvFeaturizer
from ..stats.core import _as_array_dataset
from .block import BlockLinearMapper


class ConvBlockModel(BatchTransformer):
    """Featurize (fused conv) then apply the solved linear model — the
    fitted form of :class:`ConvBlockLeastSquaresEstimator`.

    Application chunks the image batch so the full (n, 8·numFilters)
    feature matrix is never materialized at predict time either — only
    one chunk's features and the (n, k) scores are live."""

    def __init__(
        self,
        featurizer: FusedConvFeaturizer,
        linear: BlockLinearMapper,
        image_chunk: int = 2048,
    ):
        self.featurizer = featurizer
        self.linear = linear
        self.image_chunk = image_chunk

    @property
    def weights(self):
        return self.linear.weights

    def apply_arrays(self, images):
        n = images.shape[0]
        chunk = min(self.image_chunk, n)
        n_pad = _round_up(n, chunk)
        images = _pad_rows(images, n_pad)
        xr = images.reshape((n_pad // chunk, chunk) + images.shape[1:])

        def per_chunk(xc):
            return self.linear.apply_arrays(self.featurizer.apply_arrays(xc))

        out = lax.map(per_chunk, xr)
        return out.reshape(n_pad, -1)[:n]


class ConvBlockLeastSquaresEstimator(LabelEstimator):
    """Least squares over fused-conv features with on-device block
    rematerialization (featurize → standardize → BCD as one machine).

    Equivalent to the pipeline ``FusedConvFeaturizer → StandardScaler →
    BlockLeastSquaresEstimator(block_size, num_iter, reg)`` (both apply
    a scale-aware λ floor when reg=0 to keep the per-block solves PD;
    the block update order here is filter-major rather than
    column-contiguous, same fixed point) but the full feature matrix
    never exists; each epoch
    refeaturizes every filter block once. ``block_size`` must correspond
    to a whole number of filters (block_size divisible by the per-filter
    feature count — pool_x·pool_y·2 for the symmetric rectifier).
    """

    def __init__(
        self,
        featurizer: FusedConvFeaturizer,
        block_size: Optional[int] = 4096,
        num_iter: int = 1,
        reg: float = 0.0,
        standardize: bool = True,
        image_chunk: int = 2048,
    ):
        self.featurizer = featurizer
        # None = auto: the largest whole-filter block ≤ 4096 features.
        self.block_size = block_size
        self.num_iter = num_iter
        self.reg = reg
        self.standardize = standardize
        self.image_chunk = image_chunk

    @property
    def weight(self) -> int:
        return 3 * self.num_iter + 1

    # ------------------------------------------------------------ geometry

    def _geometry(self, image_shape):
        """(features_per_filter, filters_per_block, num_blocks, px, py)."""
        conv = self.featurizer.conv
        rx = image_shape[0] - conv.conv_size + 1
        ry = image_shape[1] - conv.conv_size + 1
        pooled = jax.eval_shape(
            self.featurizer.pool.apply_arrays,
            jax.ShapeDtypeStruct((1, rx, ry, 1), jnp.float32),
        )
        px, py = int(pooled.shape[1]), int(pooled.shape[2])
        fpf = px * py * 2  # pos+neg channels per filter, per pool cell
        bs = self.block_size
        if bs is None:  # auto: largest whole-filter block ≤ 4096 features
            bs = max(fpf, (4096 // fpf) * fpf)
        if bs % fpf != 0:
            raise ValueError(
                f"block_size={bs} not divisible by the "
                f"per-filter feature count {fpf}"
            )
        fb = bs // fpf
        f = conv.num_filters
        nb = -(-f // fb)
        return fpf, fb, nb, px, py

    def _standard_permutation(self, px: int, py: int, fb: int, nb: int) -> np.ndarray:
        """Map block-major solved rows to the standard featurizer layout.

        Block-major: for block b, ``ImageVectorizer`` over the pooled
        (N, px, py, 2·fb) panel → index (y, x, c_local) with channels
        [pos_b | neg_b]. Standard: (y, x, c_global) over 2F channels
        [pos all | neg all]. Returns ``perm`` with
        ``standard_index = perm[block_major_index]``.
        """
        f_pad = nb * fb
        f = self.featurizer.conv.num_filters
        perm = np.empty(nb * px * py * 2 * fb, dtype=np.int64)
        i = 0
        for b in range(nb):
            for y in range(py):
                for x in range(px):
                    for c in range(2 * fb):
                        half, fi = divmod(c, fb)
                        g = half * f_pad + b * fb + fi  # padded-global channel
                        perm[i] = y * (px * 2 * f_pad) + x * (2 * f_pad) + g
                        i += 1
        return perm

    # ---------------------------------------------------------------- fit

    def fit(self, data: Dataset, labels: Dataset) -> ConvBlockModel:
        features = _as_array_dataset(data)
        targets = _as_array_dataset(labels)
        mesh = fit_mesh(self)
        fz = self.featurizer
        conv = fz.conv

        images = jnp.asarray(features.data, jnp.float32)
        y = jnp.asarray(targets.data, jnp.float32)
        n = features.num_examples
        k = y.shape[1]
        fpf, fb, nb, px, py = self._geometry(images.shape[1:3])
        f_pad = nb * fb

        # Shared packing with the featurizer, at the solver's block width.
        kblocks, fsum_blocks, offset_blocks = fz.packed_filter_blocks(fb)

        # Row-shard images/labels; chunk size must divide the per-shard rows.
        ndev = row_shard_count(mesh)
        chunk = min(self.image_chunk, max(1, images.shape[0] // ndev))
        n_pad = _round_up(images.shape[0], chunk * ndev)
        images = _pad_rows(images, n_pad)
        y = _pad_rows(y, n_pad)
        x_dev = linalg.prepare_row_sharded(images, mesh)

        mu_b = jnp.sum(y[:n], axis=0) / n
        yc = y.at[:n].add(-mu_b).at[n:].set(0.0)
        y_dev = linalg.prepare_row_sharded(yc, mesh)
        mask = np.zeros((n_pad, 1), np.float32)
        mask[:n] = 1.0
        mask_dev = linalg.prepare_row_sharded(jnp.asarray(mask), mesh)
        p_dev = linalg.prepare_row_sharded(jnp.zeros((n_pad, k), jnp.float32), mesh)

        step = _conv_bcd_step_fn(
            mesh, fz, chunk, self.standardize, fpf, fb, px, py
        )
        if self.reg > 0:
            reg = jnp.float32(self.reg)
        elif self.standardize:
            # Standardized blocks have Gram diagonal ≈ n (unit variance):
            # floor λ relative to that scale so a rank-deficient block
            # stays fp32-Cholesky-finite (an absolute 1e-6 floor leaves
            # condition ~n/1e-6 and silent NaNs — see block.py's
            # _scale_aware_reg_floor for the full story).
            reg = jnp.float32(max(1e-6 * n, 1e-6))
        else:
            probe = self.featurizer.apply_arrays(images[: min(n, 256)])
            probe = probe - jnp.mean(probe, axis=0, keepdims=True)
            reg = jnp.float32(
                max(1e-6 * n * float(jnp.mean(jnp.square(probe))), 1e-6)
            )
        n_f = jnp.float32(n)
        bs = fpf * fb
        w_blocks = [jnp.zeros((bs, k), jnp.float32) for _ in range(nb)]
        mus = [None] * nb
        inv_sds = [None] * nb
        for _ in range(self.num_iter):
            for b in range(nb):
                w_blocks[b], p_dev, mus[b], inv_sds[b] = step(
                    x_dev, mask_dev, y_dev, p_dev, w_blocks[b],
                    kblocks[b], fsum_blocks[b], offset_blocks[b], reg, n_f,
                )

        # Assemble the standard-layout model: fold 1/σ into the weights so
        # the model applies directly to raw featurizer output.
        w_bm = jnp.concatenate(
            [w * isd[:, None] for w, isd in zip(w_blocks, inv_sds)], axis=0
        )
        mu_bm = jnp.concatenate(mus, axis=0)
        perm = self._standard_permutation(px, py, fb, nb)
        d_std = px * py * 2 * f_pad
        w_std = jnp.zeros((d_std, k), jnp.float32).at[perm].set(w_bm)
        mu_std = jnp.zeros((d_std,), jnp.float32).at[perm].set(mu_bm)
        # Drop padded-filter channels back to the true featurizer width
        # (standard layout interleaves (y, x) cells of 2·f_pad channels).
        f = conv.num_filters
        fi = np.arange(d_std) % (2 * f_pad) % f_pad
        keep_mask = fi < f
        w_std = w_std[keep_mask]
        mu_std = mu_std[keep_mask]

        linear = BlockLinearMapper(
            w_std, block_size=bs, intercept=mu_b,
            feature_mean=mu_std,
        )
        return ConvBlockModel(fz, linear, image_chunk=self.image_chunk)


# Bounded: each entry pins a featurizer's device arrays + a compiled
# executable, and the key includes a featurizer *instance* — unbounded
# growth would leak repeatedly-built pipelines.
@linalg.mode_cached(maxsize=8)
def _conv_bcd_step_fn(
    mesh: Mesh,
    featurizer: FusedConvFeaturizer,
    chunk: int,
    standardize: bool,
    fpf: int,
    fb: int,
    px: int,
    py: int,
):
    """One BCD update with on-device block featurization. Cached on
    (mesh, featurizer, static config); the kernel slice/filter sums/
    offsets are traced, so one executable serves every block."""
    axes = row_axes(mesh)
    bs = fpf * fb

    def featurize_block(x_local, kb, fs_b, off_b):
        nloc = x_local.shape[0]
        xr = x_local.reshape((nloc // chunk, chunk) + x_local.shape[1:])

        def per_chunk(xc):
            # Shared featurizer math (FusedConvFeaturizer.block_pooled) —
            # the solver computes exactly what the featurizer computes.
            m, sd = featurizer.norm_stats(xc)
            pooled = featurizer.block_pooled(xc, kb, fs_b, off_b, m, sd)
            return jnp.transpose(pooled, (0, 2, 1, 3)).reshape(chunk, bs)

        return lax.map(per_chunk, xr).reshape(nloc, bs)

    def per_device(x_local, mask_local, y_local, p_local, w_b,
                   kb, fs_b, off_b, reg, n):
        a_raw = featurize_block(x_local, kb, fs_b, off_b)
        # Masked mean/std over the real rows (StandardScaler semantics,
        # reference: nodes/stats/StandardScaler.scala:16-77).
        s1 = lax.psum(jnp.sum(a_raw * mask_local, axis=0), axes)
        mu = s1 / n
        if standardize:
            s2 = lax.psum(jnp.sum((a_raw * mask_local) ** 2, axis=0), axes)
            var = (s2 - n * mu**2) / jnp.maximum(n - 1.0, 1.0)
            sd = jnp.sqrt(jnp.maximum(var, 0.0))
            inv_sd = jnp.where((sd < 1e-8) | ~jnp.isfinite(sd), 1.0, 1.0 / sd)
        else:
            inv_sd = jnp.ones_like(mu)
        a_b = (a_raw - mu) * inv_sd * mask_local
        eye = jnp.eye(bs, dtype=a_b.dtype)
        r_local = y_local - p_local + linalg.mm(a_b, w_b)
        g = lax.psum(linalg.mm(a_b.T, a_b), axes)
        cvec = lax.psum(linalg.mm(a_b.T, r_local), axes)
        factor = jax.scipy.linalg.cho_factor(g + reg * eye, lower=True)
        w_b_new = jax.scipy.linalg.cho_solve(factor, cvec)
        p_local = p_local + linalg.mm(a_b, w_b_new - w_b)
        return w_b_new, p_local, mu, inv_sd

    fn = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(
            P(axes, None, None, None), P(axes, None), P(axes, None),
            P(axes, None), P(), P(), P(), P(), P(), P(),
        ),
        out_specs=(P(), P(axes, None), P(), P()),
    )
    # arg 3 is the loop-owned residual carry, rebuilt every call from
    # this jit's own output. Suppressed where the persistent cache makes
    # donation unsound (linalg.donation_safe).  # keystone: owns-donated
    return jax.jit(fn, donate_argnums=(3,) if linalg.donation_safe() else ())


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _pad_rows(a: jnp.ndarray, target: int) -> jnp.ndarray:
    if a.shape[0] == target:
        return a
    return jnp.pad(a, [(0, target - a.shape[0])] + [(0, 0)] * (a.ndim - 1))
