"""Kernel methods: blockwise Gaussian kernel, Gauss-Seidel kernel ridge
regression, and streaming kernel-block application.

TPU-native re-design of the reference's kernel suite
(reference: nodes/learning/KernelGenerator.scala:36-206,
nodes/learning/KernelMatrix.scala:17-90,
nodes/learning/KernelRidgeRegression.scala:37-275,
nodes/learning/KernelBlockLinearMapper.scala:28-90).

This is the framework's long-context machinery: the n×n kernel matrix is
the quadratic-in-samples object (the attention-matrix analog) and is never
materialized. The re-design maps the reference's Spark dataflow onto the
mesh:

- **Training (Gauss-Seidel BCD on the dual, arXiv:1602.05310).** Train
  rows (and the dual model) are sharded over the ``data`` axis. Per column
  block: the block's rows are assembled by a psum-scatter (the broadcast
  analog), each shard computes its K(x_local, X_b) panel on the MXU,
  K_bᵀW partial products psum over ICI, and the b×b regularized solve runs
  replicated. The whole epochs×blocks loop is ONE compiled XLA program —
  the reference needed a Spark job per block plus RDD lineage checkpoints
  every 25 blocks (truncateLineage); with no lineage, that subsystem
  disappears by construction.
- **Application** (``KernelBlockLinearMapper``): ring rotation. Test rows
  stay put; (train shard, dual-weight shard) pairs rotate around the ICI
  ring via ppermute, each step contributing K(test_local, x_shard)·W_shard
  — structurally ring attention.

Behavioral parity: λ is applied as K_bb + λI (not λnI); per-epoch block
permutation via ``block_permuter`` seed; the last short block is handled
by zero-padding (padded rows solve to exactly zero duals).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ...data.dataset import ArrayDataset, Dataset
from ...parallel import linalg
from ...parallel.collectives import shard_map
from ...parallel.mesh import DATA_AXIS, REPLICA_AXIS, get_mesh, row_axes, row_shard_count
from ...parallel.partitioner import fit_mesh
from ...workflow.pipeline import BatchTransformer, Estimator, LabelEstimator, Transformer
from ..stats.core import _as_array_dataset


# ------------------------------------------------------------------- kernels


def gaussian_kernel_block(xa, xb, gamma):
    """exp(−γ‖a−b‖²) panel via one MXU matmul + fused exp epilogue.

    Pure XLA by measurement: a hand-tiled Pallas version ran 1.6× slower
    on v5e (see ops/pallas/__init__.py for the numbers) — the emitter
    already keeps the squared-distance intermediate out of HBM."""
    an = jnp.sum(xa * xa, axis=1, keepdims=True)
    bn = jnp.sum(xb * xb, axis=1)
    sq = an - 2.0 * linalg.mm(xa, xb.T) + bn
    return jnp.exp(-gamma * jnp.maximum(sq, 0.0))


class KernelTransformer:
    """Materializes kernel blocks against fixed training data
    (reference: KernelGenerator.scala KernelTransformer + KernelMatrix)."""

    def __init__(self, train: jnp.ndarray, gamma: float, num_train: int):
        self.train = train  # (n_pad, d) row-sharded
        self.gamma = gamma
        self.num_train = num_train

    def column_block(self, start: int, size: int) -> jnp.ndarray:
        """K(X, X[start:start+size]) — (n_pad, size)."""
        xb = lax.dynamic_slice(
            self.train, (start, 0), (size, self.train.shape[1])
        )
        return gaussian_kernel_block(self.train, xb, self.gamma)

    def diag_block(self, start: int, size: int) -> jnp.ndarray:
        xb = lax.dynamic_slice(
            self.train, (start, 0), (size, self.train.shape[1])
        )
        return gaussian_kernel_block(xb, xb, self.gamma)


class BlockKernelMatrix:
    """Cache-managing view over kernel column blocks
    (reference: KernelMatrix.scala:50-90 BlockKernelMatrix). On TPU the
    cache is HBM residency of computed panels."""

    def __init__(self, transformer: KernelTransformer, cache_blocks: bool = True):
        self.transformer = transformer
        self.cache_blocks = cache_blocks
        self._cache = {}

    def __call__(self, start: int, size: int) -> jnp.ndarray:
        key = (start, size)
        if self.cache_blocks and key in self._cache:
            return self._cache[key]
        block = self.transformer.column_block(start, size)
        if self.cache_blocks:
            self._cache[key] = block
        return block

    def diag_block(self, start: int, size: int) -> jnp.ndarray:
        return self.transformer.diag_block(start, size)

    def unpersist(self) -> None:
        self._cache.clear()


class GaussianKernelGenerator(Estimator):
    """reference: KernelGenerator.scala GaussianKernelGenerator."""

    def __init__(self, gamma: float):
        self.gamma = gamma

    def fit(self, data: Dataset) -> KernelTransformer:
        ds = _as_array_dataset(data)
        mesh = fit_mesh(self)
        x = linalg.prepare_row_sharded(jnp.asarray(ds.data, jnp.float32), mesh)
        return KernelTransformer(x, self.gamma, ds.num_examples)


# ---------------------------------------------------------------------- KRR


class KernelRidgeRegression(LabelEstimator):
    """Gauss-Seidel block coordinate descent on the kernel dual."""

    def __init__(
        self,
        kernel_generator: GaussianKernelGenerator,
        reg: float,
        block_size: int,
        num_epochs: int,
        block_permuter: Optional[int] = None,
    ):
        self.kernel_generator = kernel_generator
        self.reg = reg
        self.block_size = block_size
        self.num_epochs = num_epochs
        self.block_permuter = block_permuter

    def out_spec(self, in_specs):
        """Plan-time spec protocol (workflow/verify.py): the dual
        model scores through the kernel against the training set,
        (m, d) -> (m, k) with d pinned to the training width."""
        from ...workflow.verify import dense_fit_spec

        return dense_fit_spec(in_specs, self.label)

    def fit(self, data: Dataset, labels: Dataset) -> "KernelBlockLinearMapper":
        from ...reliability import DegradationLadder, halving_rungs

        features = _as_array_dataset(data)
        targets = _as_array_dataset(labels)
        n = features.num_examples

        from ...envknobs import env_int

        landmarks = env_int("KEYSTONE_KERNEL_NYSTROM", 0)
        if 0 < landmarks < n:
            return self._fit_nystrom(features, targets, landmarks)

        # OOM degradation: the live kernel panel is (n_pad, bs) — halving
        # the block halves it (and the replicated bs×bs solve) while the
        # Gauss-Seidel sweep still visits every training row.
        bs0 = min(self.block_size, n)
        ladder = DegradationLadder(
            halving_rungs(bs0, max(bs0 // 4, 1)),
            label="KernelRidgeRegression.fit",
        )
        from ...obs import solver as solver_obs

        attempts = iter(range(len(ladder.rungs)))

        def attempt(bs):
            with solver_obs.rung_span("kernel_ridge", bs, next(attempts)):
                return self._fit_with_block(features, targets, bs)

        with solver_obs.fit_span(
            "kernel_ridge", n=n, epochs=self.num_epochs
        ):
            model = ladder.run(attempt)
        if ladder.reduced:
            model.degradation = dict(ladder.record)
        return model

    def _fit_nystrom(self, features, targets, landmarks) -> "KernelBlockLinearMapper":
        """Randomized Nyström rung (``KEYSTONE_KERNEL_NYSTROM=m``, 0=off):
        m uniform landmark rows stand in for the full training set, the
        duals solve against the m×m landmark kernel, and scoring reuses
        the ring mapper with the landmarks AS the training set — exactly
        K(x, landmarks)·α. Trades the n-dual Gauss-Seidel sweep for an
        O(n·m + m³) solve; docs/SOLVERS.md has the bound."""
        from ...envknobs import env_int
        from ...obs import names as _names
        from ...obs import solver as solver_obs
        from ...sketch.solvers import nystrom_krr

        n = features.num_examples
        gamma = self.kernel_generator.gamma
        x = np.asarray(features.data, np.float32)
        y = np.asarray(targets.data, np.float32)
        with solver_obs.fit_span("kernel_nystrom", n=n, landmarks=landmarks):
            idx, duals = nystrom_krr(
                x, y, gamma, self.reg, landmarks,
                seed=env_int("KEYSTONE_SKETCH_SEED", 0),
            )
        try:
            _names.metric(_names.SKETCH_FITS).inc(variant="nystrom")
        except Exception:
            pass
        return KernelBlockLinearMapper(
            jnp.asarray(x[np.asarray(idx)]), jnp.asarray(duals), gamma,
            num_train=landmarks,
            block_size=min(self.block_size, landmarks),
        )

    def _fit_with_block(self, features, targets, bs) -> "KernelBlockLinearMapper":
        from ...reliability import probe

        probe("KernelRidgeRegression.solve")
        mesh = fit_mesh(self)
        n = features.num_examples
        gamma = self.kernel_generator.gamma

        ndev = row_shard_count(mesh)
        # pad rows to lcm-ish: multiple of both block size and shard count
        n_pad = _round_up_multiple(n, bs, ndev)

        x = jnp.asarray(features.data, jnp.float32)
        y = jnp.asarray(targets.data, jnp.float32)
        x = _pad_rows_to(x, n_pad)
        y = _pad_rows_to(y, n_pad)
        x = linalg.prepare_row_sharded(x, mesh)
        y = linalg.prepare_row_sharded(y, mesh)

        num_blocks = n_pad // bs
        rng = np.random.default_rng(self.block_permuter)
        starts = []
        for _ in range(self.num_epochs):
            order = np.arange(num_blocks)
            if self.block_permuter is not None:
                rng.shuffle(order)
            starts.extend((order * bs).tolist())
        starts = jnp.asarray(np.asarray(starts, np.int32))

        w = _krr_fit(mesh, bs)(
            x, y, starts, jnp.float32(gamma), jnp.float32(self.reg), jnp.int32(n)
        )
        return KernelBlockLinearMapper(x, w, gamma, num_train=n, block_size=bs)


@linalg.mode_cached()
def _krr_fit(mesh: Mesh, bs: int):
    axes = row_axes(mesh)
    ndev = row_shard_count(mesh)

    def per_device(x_local, y_local, starts, gamma, lam, n):
        n_local, d = x_local.shape
        k = y_local.shape[1]
        n_pad = n_local * ndev
        dev = _linear_shard_index(mesh, axes)
        global_rows = dev * n_local + jnp.arange(n_local)
        row_valid = (global_rows < n).astype(x_local.dtype)
        eye = jnp.eye(bs, dtype=x_local.dtype)

        def gather_block(mat, s):
            """Assemble rows [s, s+bs) of the global matrix via psum-scatter."""
            pos = global_rows - s
            inside = (pos >= 0) & (pos < bs)
            idx = jnp.where(inside, pos, bs)  # bs row = dropped
            out = jnp.zeros((bs + 1, mat.shape[1]), mat.dtype)
            out = out.at[idx].add(mat * inside[:, None].astype(mat.dtype))
            return lax.psum(out[:bs], axes)

        def step(w, s):
            xb = gather_block(x_local, s)                     # (bs, d) replicated
            col_valid = ((s + jnp.arange(bs)) < n).astype(x_local.dtype)
            k_panel = gaussian_kernel_block(x_local, xb, gamma)
            k_panel = k_panel * row_valid[:, None] * col_valid[None, :]
            w_rows = lax.dynamic_slice(w, (dev * n_local, 0), (n_local, k))
            resid = lax.psum(linalg.mm(k_panel.T, w_rows), axes)  # (bs, k)
            kbb = gaussian_kernel_block(xb, xb, gamma)
            kbb = kbb * col_valid[:, None] * col_valid[None, :]
            w_b_old = lax.dynamic_slice(w, (s, 0), (bs, k))
            y_b = gather_block(y_local, s)
            rhs = y_b - (resid - linalg.mm(kbb.T, w_b_old))
            factor = jax.scipy.linalg.cho_factor(kbb + lam * eye, lower=True)
            w_b_new = jax.scipy.linalg.cho_solve(factor, rhs)
            w = lax.dynamic_update_slice(w, w_b_new, (s, 0))
            return w, None

        w0 = jnp.zeros((n_pad, y_local.shape[1]), x_local.dtype)
        w, _ = lax.scan(step, w0, starts)
        return w

    fn = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(axes, None), P(axes, None), P(), P(), P(), P()),
        out_specs=P(),
    )
    return jax.jit(fn)


# ------------------------------------------------------------------- apply


class KernelBlockLinearMapper(BatchTransformer):
    """Apply the kernel model to test data via ring rotation
    (reference: KernelBlockLinearMapper.scala:28-90, re-designed as ring
    dataflow: the train/dual shards travel the ICI ring while test rows
    stay put — the same schedule as ring attention)."""

    # Manages its own sharded placement + ring dispatch: composing this
    # apply_arrays inside another operator's jit would re-trace the
    # device_put/shard_map choreography — keep it a standalone dispatch.
    fusable = False

    def __init__(self, train: jnp.ndarray, duals: jnp.ndarray, gamma: float,
                 num_train: int, block_size: int):
        self.train = train      # (n_pad, d) row-sharded
        self.duals = jnp.asarray(duals)  # (n_pad, k); zero rows at padding
        self.gamma = gamma
        self.num_train = num_train
        self.block_size = block_size

    def apply_arrays(self, x):
        mesh = get_mesh()
        ndev = row_shard_count(mesh)
        m = x.shape[0]
        m_pad = _round_up_multiple(m, ndev)
        xt = linalg.prepare_row_sharded(_pad_rows_to(jnp.asarray(x, jnp.float32), m_pad), mesh)
        train_sharded = linalg.prepare_row_sharded(self.train, mesh)
        duals_sharded = linalg.prepare_row_sharded(self.duals, mesh)
        # gamma is traced, so one compiled executable serves every gamma.
        out = _ring_kernel_apply(mesh)(
            xt, train_sharded, duals_sharded, jnp.float32(self.gamma)
        )
        return out[:m]


@linalg.mode_cached()
def _ring_kernel_apply(mesh: Mesh):
    axes = row_axes(mesh)
    nd = mesh.shape[DATA_AXIS]
    nr = mesh.shape.get(REPLICA_AXIS, 1)
    nshards = nd * nr

    def per_device(xt_local, xs, ws, gamma):
        data_perm = [(j, (j + 1) % nd) for j in range(nd)]
        replica_perm = [(j, (j + 1) % nr) for j in range(nr)]

        def hop_replica(val):
            return lax.ppermute(val, REPLICA_AXIS, replica_perm)

        def ring_step(i, carry):
            acc, xs, ws = carry
            panel = gaussian_kernel_block(xt_local, xs, gamma)
            acc = acc + linalg.mm(panel, ws)
            # inner ICI ring every step; after each full data cycle the
            # shards hop once across the DCN replica ring, so nd*nr steps
            # visit every (replica, data) shard exactly once.
            xs = lax.ppermute(xs, DATA_AXIS, data_perm)
            ws = lax.ppermute(ws, DATA_AXIS, data_perm)
            if nr > 1:
                do_hop = (i + 1) % nd == 0
                xs = lax.cond(do_hop, hop_replica, lambda v: v, xs)
                ws = lax.cond(do_hop, hop_replica, lambda v: v, ws)
            return acc, xs, ws

        acc0 = jnp.zeros((xt_local.shape[0], ws.shape[1]), xt_local.dtype)
        acc, _, _ = lax.fori_loop(0, nshards, ring_step, (acc0, xs, ws))
        return acc

    fn = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(axes, None), P(axes, None), P(axes, None), P()),
        out_specs=P(axes, None),
    )
    return jax.jit(fn)  # gamma (4th arg) is replicated + traced


def _linear_shard_index(mesh: Mesh, axes):
    """Row-major linear index of this device's shard over ``axes``."""
    idx = jnp.int32(0)
    for axis in axes:
        idx = idx * mesh.shape[axis] + lax.axis_index(axis)
    return idx


# -------------------------------------------------------------------- utils


def _round_up_multiple(n: int, *multiples: int) -> int:
    out = n
    for m in multiples:
        out = ((out + m - 1) // m) * m
    # ensure divisibility by all (multiples are not necessarily coprime-safe
    # after sequential rounding; iterate to fixpoint)
    changed = True
    while changed:
        changed = False
        for m in multiples:
            if out % m != 0:
                out = ((out + m - 1) // m) * m
                changed = True
    return out


def _pad_rows_to(a: jnp.ndarray, target: int) -> jnp.ndarray:
    if a.shape[0] == target:
        return a
    return jnp.pad(a, [(0, target - a.shape[0])] + [(0, 0)] * (a.ndim - 1))
