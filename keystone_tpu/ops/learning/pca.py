"""PCA family: local SVD, distributed TSQR, randomized, optimizable wrapper.

TPU-native re-design of the reference's PCA suite
(reference: nodes/learning/PCA.scala:51-247,
nodes/learning/DistributedPCA.scala:20-74,
nodes/learning/ApproximatePCA.scala:22-85).

Behavioral parity:
- Columns are mean-centered before decomposition.
- The MATLAB sign convention is enforced: each component's largest-magnitude
  coefficient is positive (PCA.scala enforceMatlabPCASignConvention).
- ``PCATransformer`` projects vectors x ↦ xᵀ·P; ``BatchPCATransformer``
  projects per-item (d, nᵢ) descriptor matrices Pᵀ·M.

TPU re-design notes: the "distributed" variant runs TSQR over the row
shards and eigendecomposes the centered d×d Gram (algebraic centering,
RᵀR − n·μμᵀ) — no centered copy, one all_gather of tiny R factors over
ICI. The randomized variant is Halko et al. alg. 4.4/5.1 with the power
iterations expressed as a lax.fori_loop of device matmuls + QRs.
"""

from __future__ import annotations

import functools
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ...data.dataset import ArrayDataset, Dataset, ObjectDataset
from ...parallel import linalg
from ...parallel.mesh import get_mesh, num_devices
from ...parallel.partitioner import fit_mesh
from ...workflow.optimize import DataStats, Optimizable
from ...workflow.pipeline import BatchTransformer, Estimator, Transformer
from .cost import DEFAULT_COST_WEIGHTS, CostModel
from ..stats.core import _as_array_dataset


def enforce_sign_convention(components: jnp.ndarray) -> jnp.ndarray:
    """Largest-|coefficient| entry of each column made positive
    (reference: PCA.scala enforceMatlabPCASignConvention)."""
    col_max = jnp.max(components, axis=0)
    col_absmax = jnp.max(jnp.abs(components), axis=0)
    signs = jnp.where(col_max == col_absmax, 1.0, -1.0)
    return components * signs


class PCATransformer(BatchTransformer):
    """Project feature vectors onto the top components: (n,d) @ (d,k)."""

    def __init__(self, components: jnp.ndarray):  # (d, k)
        self.components = jnp.asarray(components)

    def apply_arrays(self, x):
        return linalg.mm(x, self.components)


class BatchPCATransformer(Transformer):
    """Project per-item (nᵢ, d) descriptor matrices: M · P → (nᵢ, k)
    (reference: PCA.scala BatchPCATransformer — the reference holds
    descriptors as columns of (d, nᵢ) matrices; this framework's extractors
    emit descriptor-rows with the feature dim last, the TPU-friendly
    layout, so the projection is a plain right-multiply)."""

    def __init__(self, components: jnp.ndarray):
        self.components = jnp.asarray(components)

    def apply(self, mat):
        return np.asarray(mat) @ np.asarray(self.components)

    def apply_batch(self, dataset: Dataset) -> Dataset:
        from ...data.dataset import BucketedDataset

        if isinstance(dataset, BucketedDataset):
            return dataset.map_datasets(self.apply_batch)
        if isinstance(dataset, ArrayDataset):
            if isinstance(dataset.data, dict) and "valid" in dataset.data:
                # Masked descriptors: project, validity flows through
                # (zero rows stay zero under a right-multiply).
                out = jnp.einsum(
                    "ncd,dk->nck", jnp.asarray(dataset.data["desc"]),
                    self.components, precision=linalg.precision(),
                )
                return ArrayDataset(
                    {"desc": out, "valid": dataset.data["valid"]},
                    dataset.num_examples,
                )
            x = jnp.asarray(dataset.data)
            if x.ndim == 2:  # flat (n, d) descriptor rows
                out = linalg.mm(x, self.components)
            else:  # uniform (n, cols, d) stack: one batched einsum on the MXU
                out = jnp.einsum(
                    "ncd,dk->nck", x, self.components, precision=linalg.precision()
                )
            return ArrayDataset(out, dataset.num_examples)
        return dataset.map(self.apply)


class PCAEstimator(Estimator, CostModel):
    """Local (single-computation) SVD PCA (reference: PCA.scala:163-247)."""

    def __init__(self, dims: int):
        self.dims = dims

    def out_spec(self, in_specs):
        """Plan-time spec protocol (workflow/verify.py): the fitted
        projection replaces the descriptor axis with ``dims``."""
        from ...workflow.verify import projection_fit_spec

        return projection_fit_spec(in_specs, self.label, dims=self.dims)

    def fit(self, data: Dataset) -> PCATransformer:
        x = jnp.asarray(_as_array_dataset(data).data, dtype=jnp.float32)
        n = _as_array_dataset(data).num_examples
        x = x[:n]
        return PCATransformer(compute_pca(x, self.dims))

    def cost(self, n, d, k, sparsity, num_machines, w=DEFAULT_COST_WEIGHTS):
        flops = float(n) * d * d
        bytes_scanned = float(n) * d
        network = float(n) * d  # collect to one device
        return max(w.cpu * flops, w.mem * bytes_scanned) + w.network * network


@linalg.mode_jit
def _pca_svd(x):
    mu = jnp.mean(x, axis=0)
    _, _, vt = jnp.linalg.svd(x - mu, full_matrices=False)
    return enforce_sign_convention(vt.T)


def compute_pca(x: jnp.ndarray, dims: int) -> jnp.ndarray:
    return _pca_svd(x)[:, :dims]


class DistributedPCAEstimator(Estimator, CostModel):
    """TSQR-based PCA over the row-sharded sample
    (reference: DistributedPCA.scala:20-74, mlmatrix TSQR).

    Centering is algebraic: eigh(RᵀR − n·μμᵀ) gives the centered
    covariance eigenvectors without materializing A − μ.
    """

    def __init__(self, dims: int):
        self.dims = dims

    def out_spec(self, in_specs):
        from ...workflow.verify import projection_fit_spec

        return projection_fit_spec(in_specs, self.label, dims=self.dims)

    def fit(self, data: Dataset) -> PCATransformer:
        ds = _as_array_dataset(data)
        mesh = fit_mesh(self)
        x = linalg.prepare_row_sharded(jnp.asarray(ds.data, dtype=jnp.float32), mesh)
        n = ds.num_examples
        r = linalg.tsqr_r(x, mesh=mesh)
        sa = jnp.sum(x, axis=0)  # zero-padded rows are inert
        components = _centered_eig_components(r, sa, jnp.float32(n))
        return PCATransformer(components[:, : self.dims])

    def cost(self, n, d, k, sparsity, num_machines, w=DEFAULT_COST_WEIGHTS):
        flops = float(n) * d * d / num_machines + d * d * d
        bytes_scanned = float(n) * d / num_machines
        network = float(d) * d * np.log2(max(num_machines, 2))
        return max(w.cpu * flops, w.mem * bytes_scanned) + w.network * network


@linalg.mode_jit
def _centered_eig_components(r, sa, n):
    mu = sa / n
    cov = linalg.mm(r.T, r) - n * jnp.outer(mu, mu)
    # eigh returns ascending eigenvalues; PCA wants descending.
    _, vecs = jnp.linalg.eigh(cov)
    return enforce_sign_convention(vecs[:, ::-1])


class ApproximatePCAEstimator(Estimator, CostModel):
    """Randomized range-finder PCA (Halko/Martinsson/Tropp 2011, alg 4.4+5.1;
    reference: ApproximatePCA.scala:22-85)."""

    def __init__(self, dims: int, q: int = 10, p: int = 5, seed: int = 0):
        self.dims = dims
        self.q = q
        self.p = p
        self.seed = seed

    def out_spec(self, in_specs):
        from ...workflow.verify import projection_fit_spec

        return projection_fit_spec(in_specs, self.label, dims=self.dims)

    def fit(self, data: Dataset) -> PCATransformer:
        ds = _as_array_dataset(data)
        x = jnp.asarray(ds.data, dtype=jnp.float32)[: ds.num_examples]
        comps = _approximate_pca(x, self.dims + self.p, self.q, self.seed)
        return PCATransformer(comps[:, : self.dims])

    def cost(self, n, d, k, sparsity, num_machines, w=DEFAULT_COST_WEIGHTS):
        l = k + 5
        flops = float(n) * d * l * (1 + 10)
        bytes_scanned = float(n) * l
        network = float(n) * d
        return max(w.cpu * flops, w.mem * bytes_scanned) + w.network * network


def _approximate_pca(x, l, q, seed):
    return _approx_pca_jit(x, jax.random.PRNGKey(seed), l, q)


@functools.partial(linalg.mode_jit, static_argnums=(2, 3))
def _approx_pca_jit(x, key, l, q):
    mu = jnp.mean(x, axis=0)
    a = x - mu
    d = a.shape[1]
    omega = jax.random.normal(key, (d, l), dtype=a.dtype)
    y0 = linalg.mm(a, omega)
    qmat, _ = jnp.linalg.qr(y0)

    def power_iter(_, qm):
        yh = linalg.mm(qm.T, a)          # (l, d)
        qh, _ = jnp.linalg.qr(yh.T)      # (d, l)
        yj = linalg.mm(a, qh)            # (n, l)
        qn, _ = jnp.linalg.qr(yj)
        return qn

    qmat = jax.lax.fori_loop(0, q, power_iter, qmat)
    b = linalg.mm(qmat.T, a)             # (l, d)
    _, _, vt = jnp.linalg.svd(b, full_matrices=False)
    return enforce_sign_convention(vt.T)


# ------------------------------------------------- optimizable column wrapper


class LocalColumnPCAEstimator(Estimator, CostModel):
    """PCA over the descriptors of per-item (nᵢ, d) matrices, local SVD
    (reference: PCA.scala:51-73 — the reference's matrices are (d, nᵢ)
    column-major; this framework holds descriptor rows)."""

    def __init__(self, dims: int):
        self.dims = dims
        self._inner = PCAEstimator(dims)

    def out_spec(self, in_specs):
        from ...workflow.verify import projection_fit_spec

        return projection_fit_spec(in_specs, self.label, dims=self.dims)

    def fit(self, data: Dataset) -> BatchPCATransformer:
        flat = _columns_to_vectors(data)
        t = self._inner.fit(flat)
        return BatchPCATransformer(t.components)

    def cost(self, *args, **kw):
        return self._inner.cost(*args, **kw)


class DistributedColumnPCAEstimator(Estimator, CostModel):
    """Descriptor PCA over per-item (nᵢ, d) matrices via distributed TSQR
    (reference: PCA.scala:75-103)."""

    def __init__(self, dims: int):
        self.dims = dims
        self._inner = DistributedPCAEstimator(dims)

    def out_spec(self, in_specs):
        from ...workflow.verify import projection_fit_spec

        return projection_fit_spec(in_specs, self.label, dims=self.dims)

    def fit(self, data: Dataset) -> BatchPCATransformer:
        flat = _columns_to_vectors(data)
        t = self._inner.fit(flat)
        return BatchPCATransformer(t.components)

    def cost(self, *args, **kw):
        return self._inner.cost(*args, **kw)


class ColumnPCAEstimator(Estimator, Optimizable, CostModel):
    """Cost-model-driven choice between local and distributed column PCA
    (reference: PCA.scala:105-161 ColumnPCAEstimator). Default weights were
    fit on the reference's 16-node cluster; TPU re-fit pending."""

    def __init__(self, dims: int, num_machines: Optional[int] = None,
                 weights=DEFAULT_COST_WEIGHTS):
        self.dims = dims
        self.num_machines = num_machines
        self.weights = weights
        self.local = LocalColumnPCAEstimator(dims)
        self.distributed = DistributedColumnPCAEstimator(dims)

    def out_spec(self, in_specs):
        from ...workflow.verify import projection_fit_spec

        return projection_fit_spec(in_specs, self.label, dims=self.dims)

    def fit(self, data: Dataset):
        return self.distributed.fit(data)  # the reference's default

    def optimize(self, samples: List[Dataset], stats: DataStats):
        sample = samples[0]
        items = sample.take(8)
        if not items:
            return self.distributed
        if isinstance(items[0], dict) and "valid" in items[0]:
            # Masked-descriptor items ({"desc": (n_pad, d), "valid": ...}):
            # the true per-item descriptor count is the valid total.
            cols = float(np.mean([np.asarray(m["valid"]).sum() for m in items]))
            d = int(np.asarray(items[0]["desc"]).shape[-1])
        elif np.asarray(items[0]).ndim == 1:
            # Plain feature vectors: one row per item.
            cols = 1.0
            d = int(np.asarray(items[0]).shape[0])
        else:
            cols = float(np.mean([np.asarray(m).shape[0] for m in items]))
            d = int(np.asarray(items[0]).shape[1])
        n = int(cols * stats.n_total)
        machines = self.num_machines or num_devices()
        lc = self.local.cost(n, d, self.dims, 1.0, machines, self.weights)
        dc = self.distributed.cost(n, d, self.dims, 1.0, machines, self.weights)
        return self.local if lc < dc else self.distributed


def _columns_to_vectors(data: Dataset) -> ArrayDataset:
    """Flatten per-item (nᵢ, d) descriptor matrices into one (Σnᵢ, d)
    vector dataset."""
    if isinstance(data, ArrayDataset):
        x = jnp.asarray(data.data)
        if x.ndim == 2:
            return ArrayDataset(x, data.num_examples)
        # (n, c, d) → (n·c, d)
        n, c, d = x.shape
        return ArrayDataset(x.reshape(n * c, d))
    mats = [np.asarray(m) for m in data.collect()]
    return ArrayDataset(np.concatenate(mats, axis=0))
