"""Distributed L-BFGS least-squares solvers (dense + sparse data).

TPU-native re-design of reference: nodes/learning/LBFGS.scala:14-281 and
nodes/learning/Gradient.scala:10-119. The reference drives Breeze's L-BFGS
on the master with per-iteration gradients treeReduce'd from the cluster;
here the entire optimization — two-loop recursion, zoom line search
(optax.lbfgs), and the data-parallel gradient — is one compiled XLA loop.
With the feature matrix row-sharded over the mesh, XLA partitions the
gradient matmuls and inserts the ICI all-reduce automatically.

Loss (matching LeastSquaresDenseGradient): ½‖XW − Y‖²/n + ½λ‖W‖².

The sparse variant keeps the reference's capability (Amazon-style
n=65M, d=16k, 0.5% dense) but solves ON THE HOST: scipy L-BFGS-B over
CSR matvecs, chosen by measurement (56× faster than BCOO sparse-dense
matmuls on the TPU at the measured shape, n=1M × d=1024 —
docs/PERFORMANCE.md). Host RAM is the binding resource: the FULL
Amazon shape is ~5.2e9 nonzeros ≈ 42 GB as float32 CSR, and
``_sparse_lbfgs_host`` also builds a transposed copy (another ~42 GB)
plus a float64 dense label matrix (~1 GB at k=2) — so that extreme
needs a ~100 GB-RAM host or an out-of-core/sharded extension; text
workloads at the tested scales (≤ tens of GB nnz) fit as-is.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
import optax

from ...data.dataset import ArrayDataset, Dataset, ObjectDataset
from ...parallel import linalg
from ...parallel.mesh import get_mesh
from ...parallel.partitioner import fit_mesh
from ...workflow.pipeline import LabelEstimator
from ..stats.core import _as_array_dataset
from .linear import LinearMapper, SparseLinearMapper


class DenseLBFGSEstimator(LabelEstimator):
    """reference: LBFGS.scala DenseLBFGSwithL2 (weight = 2·numIterations)."""

    def __init__(
        self,
        reg: float = 0.0,
        num_iterations: int = 100,
        memory_size: int = 10,
        tol: float = 1e-6,
        fit_intercept: bool = True,
    ):
        self.reg = reg
        self.num_iterations = num_iterations
        self.memory_size = memory_size
        self.tol = tol
        self.fit_intercept = fit_intercept

    @property
    def weight(self) -> int:
        return 2 * self.num_iterations

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        features = _as_array_dataset(data)
        targets = _as_array_dataset(labels)
        mesh = fit_mesh(self)
        x = linalg.prepare_row_sharded(jnp.asarray(features.data, jnp.float32), mesh)
        y = linalg.prepare_row_sharded(jnp.asarray(targets.data, jnp.float32), mesh)
        n = features.num_examples

        mu_a = jnp.sum(x, axis=0) / n
        mu_b = jnp.sum(y, axis=0) / n
        if not self.fit_intercept:
            mu_a = jnp.zeros_like(mu_a)
            mu_b = jnp.zeros_like(mu_b)
        mask = (jnp.arange(x.shape[0]) < n).astype(x.dtype)[:, None]

        w = _lbfgs_least_squares(
            x, y, mu_a, mu_b, mask,
            jnp.float32(n), jnp.float32(self.reg),
            self.num_iterations, self.memory_size, self.tol,
        )
        return LinearMapper(w, intercept=mu_b if self.fit_intercept else None,
                            feature_mean=mu_a if self.fit_intercept else None)


@functools.partial(linalg.mode_jit, static_argnums=(7, 8, 9))
def _lbfgs_least_squares(x, y, mu_a, mu_b, mask, n, reg,
                         num_iterations, memory_size, tol):
    d, k = x.shape[1], y.shape[1]

    def loss(w):
        # centered residuals; padded rows masked out of the objective
        pred = linalg.mm(x - mu_a, w)
        r = (pred - (y - mu_b)) * mask
        return 0.5 * jnp.sum(r * r) / n + 0.5 * reg * jnp.sum(w * w)

    solver = optax.lbfgs(memory_size=memory_size)
    value_and_grad = optax.value_and_grad_from_state(loss)

    w0 = jnp.zeros((d, k), dtype=x.dtype)
    state0 = solver.init(w0)

    def cond(carry):
        _, state, i, gnorm = carry
        return (i < num_iterations) & (gnorm > tol)

    def body(carry):
        w, state, i, _ = carry
        value, grad = value_and_grad(w, state=state)
        updates, state = solver.update(
            grad, state, w, value=value, grad=grad, value_fn=loss
        )
        w = optax.apply_updates(w, updates)
        return w, state, i + 1, jnp.linalg.norm(grad)

    w, *_ = jax.lax.while_loop(cond, body, (w0, state0, jnp.int32(0), jnp.float32(jnp.inf)))
    return w


class SparseLBFGSEstimator(LabelEstimator):
    """reference: LBFGS.scala SparseLBFGSwithL2.

    Accepts an ObjectDataset of scipy CSR rows (the Sparsify output) or a
    dense ArrayDataset. The solve is HOST-side scipy L-BFGS over the CSR
    matrix: at text-feature densities (~0.5%) a TPU adds nothing — sparse
    gathers are pathological on the MXU, and every line-search probe
    would pay a host→device round trip. The reference likewise ran this
    solver on host (Breeze) workers rather than BLAS. A BCOO-on-device
    variant measured 91.5 s at (n=1M, d=1024, nnz=5M) where this path
    takes ~2 s (scripts/solver-comparisons-tpu.csv).
    """

    def __init__(self, reg: float = 0.0, num_iterations: int = 100,
                 memory_size: int = 10, tol: float = 1e-6):
        self.reg = reg
        self.num_iterations = num_iterations
        self.memory_size = memory_size
        self.tol = tol

    @property
    def weight(self) -> int:
        return 2 * self.num_iterations

    def fit(self, data: Dataset, labels: Dataset) -> SparseLinearMapper:
        import scipy.sparse as sp

        targets = _as_array_dataset(labels)
        y = np.asarray(jax.device_get(targets.data), dtype=np.float64)[
            : targets.num_examples
        ]

        if isinstance(data, ArrayDataset):
            mat = sp.csr_matrix(np.asarray(jax.device_get(data.data))[: data.num_examples])
        else:
            rows = data.collect()
            mat = sp.vstack([r if sp.issparse(r) else sp.csr_matrix(np.asarray(r).reshape(1, -1)) for r in rows])
        w = _sparse_lbfgs_host(
            mat.tocsr(), y, float(self.reg),
            self.num_iterations, self.memory_size, self.tol,
        )
        return SparseLinearMapper(jnp.asarray(w, dtype=jnp.float32))


def _sparse_lbfgs_host(mat, y, reg, num_iterations, memory_size, tol):
    """scipy L-BFGS-B on 0.5·‖Xw − y‖²/n + 0.5·reg·‖w‖² with CSR matvecs.

    One Xw + one Xᵀr per objective evaluation (~2·nnz·k flops); scipy's
    Wolfe line search typically needs 1-2 evaluations per iteration.

    Stop rule: the estimator's documented ‖g‖₂ ≤ tol, enforced directly
    by a callback over the most recently evaluated gradient (scipy's own
    gtol tests the inf-norm; bounding ‖g‖₂ through √(d·k)·max|gᵢ| made
    early stopping unreachable at realistic d·k). The callback raises
    StopIteration: scipy >= 1.11 treats that as clean termination
    (status 99, current iterate returned); on older scipy the exception
    propagates out of ``minimize``, so it is caught here and the last
    accepted iterate (recorded by the callback before raising) is
    returned — identical result either way.
    """
    from scipy.optimize import minimize

    n, d = mat.shape
    k = y.shape[1]
    mat_t = mat.T.tocsr()  # one-time CSC→CSR so Xᵀr is also a fast product
    last_grad_norm = [np.inf]  # written by value_and_grad, read by callback
    last_xk = [None]  # pre-raise snapshot for the scipy<1.11 escape path

    def value_and_grad(w_flat):
        w = w_flat.reshape(d, k)
        r = mat @ w - y
        value = 0.5 * float(np.sum(r * r)) / n + 0.5 * reg * float(np.sum(w * w))
        grad = (mat_t @ r) / n + reg * w
        last_grad_norm[0] = float(np.linalg.norm(grad))
        return value, grad.ravel()

    def stop_on_grad_norm(xk):
        from ...obs import solver as solver_obs

        solver_obs.count_iteration(
            "sparse_lbfgs", grad_norm=round(last_grad_norm[0], 8)
        )
        # The last gradient the line search evaluated is at (or adjacent
        # to) the accepted iterate xk — close enough for a stop test.
        if last_grad_norm[0] <= tol:
            last_xk[0] = np.array(xk, copy=True)
            raise StopIteration

    try:
        res = minimize(
            value_and_grad,
            np.zeros(d * k),
            jac=True,
            method="L-BFGS-B",
            callback=stop_on_grad_norm,
            options={
                "maxiter": num_iterations,
                "maxcor": memory_size,
                # The callback owns the gradient stop; disable scipy's
                # inf-norm gtol and the ftol flat-step stop (the previous
                # device solver had neither).
                "gtol": 0.0,
                "ftol": 0.0,
                # keep line-search probes bounded at huge nnz
                "maxls": 20,
            },
        )
        w_flat = res.x
    except StopIteration:  # scipy < 1.11: the callback's stop propagates
        w_flat = last_xk[0]
    return w_flat.reshape(d, k)
