"""Cost-model framework for optimizable operators.

TPU-native re-design of the reference's solver cost models
(reference: nodes/learning/CostModel.scala:6-17,
nodes/learning/LeastSquaresEstimator.scala:17-31). Costs combine cpu
(flops), memory-bandwidth (bytes scanned) and network (bytes moved across
the mesh) terms:  max(cpu·flops, mem·bytes) + network·moved.

The default weights are the reference's — "determined empirically via
results run on a 16 r3.4xlarge node cluster" — kept as the starting point;
``tpu_weights()`` rescales them with first-principles v5e numbers
(MXU ~200 TFLOP/s bf16, HBM ~819 GB/s, ICI ~400 GB/s per link) so the
meta-solvers make sane choices on-chip until measured constants land.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class CostWeights:
    cpu: float
    mem: float
    network: float


# reference: LeastSquaresEstimator.scala:29-31 (16×r3.4xlarge cluster)
DEFAULT_COST_WEIGHTS = CostWeights(cpu=3.8e-4, mem=2.9e-1, network=1.32)


def tpu_weights() -> CostWeights:
    """First-principles per-unit costs (ms per Mflop / MB) for one v5e."""
    cpu = 1.0 / 2.0e8   # ~200 TFLOP/s → 2e8 flops per ms
    mem = 1.0 / 8.2e5   # ~819 GB/s → 8.2e5 bytes per ms... scaled to MB
    network = 1.0 / 4.0e5
    return CostWeights(cpu=cpu, mem=mem, network=network)


class CostModel:
    """Mixin: operators expose cost(n, d, k, sparsity, num_machines)."""

    def cost(self, n, d, k, sparsity, num_machines, w=DEFAULT_COST_WEIGHTS) -> float:
        raise NotImplementedError
