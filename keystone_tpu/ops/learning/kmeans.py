"""k-means++ with Lloyd refinement.

TPU-native re-design of reference: nodes/learning/KMeansPlusPlus.scala:16-181.
Behavioral parity: k-means++ seeding by D² sampling, Lloyd iterations with
relative-cost stopping (tolerance on mean min-distance), model emits the
one-hot nearest-center assignment matrix.

The Lloyd loop is a single compiled ``lax.while_loop``; the distance
matrix X·Mᵀ rides the MXU. Seeding runs on host numpy (k sequential
categorical draws over a driver-sized sample, as in the reference).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ...data.dataset import ArrayDataset, Dataset
from ...parallel import linalg
from ...workflow.pipeline import BatchTransformer, Estimator
from ..stats.core import _as_array_dataset


class KMeansModel(BatchTransformer):
    """x ↦ one-hot(nearest center): (n, d) → (n, k)."""

    def __init__(self, means: jnp.ndarray):  # (k, d)
        self.means = jnp.asarray(means)

    def apply_arrays(self, x):
        dists = _half_sq_dists(x, self.means)
        nearest = jnp.argmin(dists, axis=1)
        return jax.nn.one_hot(nearest, self.means.shape[0], dtype=x.dtype)


def _half_sq_dists(x, means):
    """½‖x−m‖² up to a per-row constant — enough for argmin."""
    xn = 0.5 * jnp.sum(x * x, axis=1, keepdims=True)
    mn = 0.5 * jnp.sum(means * means, axis=1)
    return xn - linalg.mm(x, means.T) + mn


class KMeansPlusPlusEstimator(Estimator):
    def __init__(self, num_means: int, max_iterations: int,
                 stop_tolerance: float = 1e-3, seed: int = 0):
        self.num_means = num_means
        self.max_iterations = max_iterations
        self.stop_tolerance = stop_tolerance
        self.seed = seed

    def out_spec(self, in_specs):
        """Plan-time spec protocol (workflow/verify.py): one-hot
        nearest-center assignments, (m, d) -> (m, num_means)."""
        from ...workflow.verify import dense_fit_spec

        return dense_fit_spec(in_specs, self.label, out_width=self.num_means)

    def fit(self, data: Dataset) -> KMeansModel:
        ds = _as_array_dataset(data)
        x = np.asarray(jax.device_get(ds.data), dtype=np.float32)[: ds.num_examples]
        init = _kmeanspp_init(x, self.num_means, self.seed)
        means = _lloyd(
            jnp.asarray(x), jnp.asarray(init),
            self.max_iterations, jnp.float32(self.stop_tolerance),
        )
        return KMeansModel(means)


def _kmeanspp_init(x: np.ndarray, k: int, seed: int) -> np.ndarray:
    """D²-weighted sequential seeding (reference: KMeansPlusPlus.scala:96-125)."""
    rng = np.random.default_rng(seed)
    n = x.shape[0]
    x_norm_half = 0.5 * np.einsum("ij,ij->i", x, x)
    centers = np.zeros(k, dtype=np.int64)
    centers[0] = rng.integers(n)
    cur_sq = None
    for j in range(k - 1):
        c = x[centers[j]]
        sq = x_norm_half - x @ c + 0.5 * float(c @ c)
        cur_sq = sq if cur_sq is None else np.minimum(cur_sq, sq)
        probs = np.maximum(cur_sq, 0.0)
        total = probs.sum()
        if total <= 0:
            centers[j + 1] = rng.integers(n)
        else:
            centers[j + 1] = rng.choice(n, p=probs / total)
    return x[centers]


@functools.partial(linalg.mode_jit, static_argnums=(2,))
def _lloyd(x, means0, max_iterations, tol):
    n = x.shape[0]

    def cond(state):
        _, i, improving, _ = state
        return (i < max_iterations) & improving

    def body(state):
        means, i, _, prev_cost = state
        dists = _half_sq_dists(x, means)
        cost = jnp.mean(jnp.min(dists, axis=1))
        nearest = jnp.argmin(dists, axis=1)
        assign = jax.nn.one_hot(nearest, means.shape[0], dtype=x.dtype)
        mass = jnp.sum(assign, axis=0)
        new_means = linalg.mm(assign.T, x) / jnp.maximum(mass, 1.0)[:, None]
        # keep old center when a cluster empties (mass 0)
        new_means = jnp.where(mass[:, None] > 0, new_means, means)
        improving = jnp.where(
            i > 0, (prev_cost - cost) >= tol * jnp.abs(prev_cost), True
        )
        return new_means, i + 1, improving, cost

    means, *_ = jax.lax.while_loop(
        cond, body, (means0, jnp.int32(0), jnp.bool_(True), jnp.float32(jnp.inf))
    )
    return means
