"""Diagonal-covariance Gaussian mixture model fit by EM.

TPU-native re-design of
reference: nodes/learning/GaussianMixtureModelEstimator.scala:25-203 and
nodes/learning/GaussianMixtureModel.scala:19-106.

Behavioral parity with the reference's (Xerox/enceval-style) EM:
- init from one round of k-means++ (or uniform-random within column range);
- global variance lower bound max(smallVarianceThreshold·var_global,
  absoluteVarianceThreshold), re-applied each M-step;
- aggressive posterior thresholding (weights < weightThreshold → 0,
  renormalized) in both training E-steps and model application;
- stop when mean log-likelihood stops improving by tolerance, or when any
  cluster would fall under min_cluster_size (fit keeps the last good
  parameters, like the reference's largeEnoughClusters guard).

The whole EM loop is one compiled ``lax.while_loop``; E-step distances are
two MXU matmuls (X·(μ/σ²)ᵀ and X²·(1/2σ²)ᵀ) and the posterior uses a
standard logsumexp instead of the reference's incremental host loop.

The model stores means/variances as (d, k) — column per cluster — matching
the reference's layout (GaussianMixtureModel.scala:19-24), which the
Fisher-vector encoder relies on.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ...data.dataset import ArrayDataset, Dataset
from ...parallel import linalg
from ...workflow.pipeline import BatchTransformer, Estimator
from ..stats.core import _as_array_dataset
from .kmeans import KMeansPlusPlusEstimator, _half_sq_dists

KMEANS_PLUS_PLUS_INITIALIZATION = "kmeans++"
RANDOM_INITIALIZATION = "random"


class GaussianMixtureModel(BatchTransformer):
    """x ↦ thresholded posterior cluster assignments (n, k)."""

    def __init__(self, means, variances, weights, weight_threshold: float = 1e-4):
        self.means = jnp.asarray(means)          # (d, k)
        self.variances = jnp.asarray(variances)  # (d, k)
        self.weights = jnp.asarray(weights).ravel()  # (k,)
        self.weight_threshold = weight_threshold
        assert self.means.shape == self.variances.shape
        assert self.weights.shape[0] == self.means.shape[1]

    @property
    def k(self) -> int:
        return self.means.shape[1]

    @property
    def dim(self) -> int:
        return self.means.shape[0]

    def apply_arrays(self, x):
        return _gmm_posteriors(
            x, self.means.T, self.variances.T, self.weights,
            jnp.float32(self.weight_threshold),
        )

    @staticmethod
    def load(mean_file: str, vars_file: str, weights_file: str) -> "GaussianMixtureModel":
        """CSV warm-start (reference: GaussianMixtureModel.scala:97-105)."""
        means = np.loadtxt(mean_file, delimiter=",", ndmin=2)
        variances = np.loadtxt(vars_file, delimiter=",", ndmin=2)
        weights = np.loadtxt(weights_file, delimiter=",").ravel()
        return GaussianMixtureModel(means, variances, weights)


@linalg.mode_jit
def _gmm_log_likelihood(x, means, variances, weights):
    """Per-sample per-cluster log-likelihood. means/vars here are (k, d)."""
    d = x.shape[1]
    xsq = x * x
    inv_var = 1.0 / variances
    sq_mahal = (
        linalg.mm(xsq, (0.5 * inv_var).T)
        - linalg.mm(x, (means * inv_var).T)
        + 0.5 * jnp.sum(means * means * inv_var, axis=1)
    )
    log_norm = (
        -0.5 * d * jnp.log(2 * jnp.pi)
        - 0.5 * jnp.sum(jnp.log(variances), axis=1)
        + jnp.log(weights)
    )
    return log_norm - sq_mahal


@linalg.mode_jit
def _gmm_posteriors(x, means, variances, weights, weight_threshold):
    llh = _gmm_log_likelihood(x, means, variances, weights)
    llh = llh - jnp.max(llh, axis=1, keepdims=True)
    q = jnp.exp(llh)
    q = q / jnp.sum(q, axis=1, keepdims=True)
    q = jnp.where(q > weight_threshold, q, 0.0)
    return q / jnp.maximum(jnp.sum(q, axis=1, keepdims=True), 1e-30)


class GaussianMixtureModelEstimator(Estimator):
    def __init__(
        self,
        k: int,
        max_iterations: int = 100,
        min_cluster_size: int = 40,
        stop_tolerance: float = 1e-4,
        weight_threshold: float = 1e-4,
        small_variance_threshold: float = 1e-2,
        absolute_variance_threshold: float = 1e-9,
        initialization_method: str = KMEANS_PLUS_PLUS_INITIALIZATION,
        seed: int = 0,
    ):
        assert min_cluster_size > 0 and max_iterations > 0
        self.k = k
        self.max_iterations = max_iterations
        self.min_cluster_size = min_cluster_size
        self.stop_tolerance = stop_tolerance
        self.weight_threshold = weight_threshold
        self.small_variance_threshold = small_variance_threshold
        self.absolute_variance_threshold = absolute_variance_threshold
        self.initialization_method = initialization_method
        self.seed = seed

    def out_spec(self, in_specs):
        """Plan-time spec protocol (workflow/verify.py): thresholded
        posterior cluster assignments, (m, d) -> (m, k)."""
        from ...workflow.verify import dense_fit_spec

        return dense_fit_spec(in_specs, self.label, out_width=self.k)

    def fit(self, data: Dataset) -> GaussianMixtureModel:
        ds = _as_array_dataset(data)
        x = np.asarray(jax.device_get(ds.data), dtype=np.float32)[: ds.num_examples]
        n, d = x.shape

        if self.initialization_method == KMEANS_PLUS_PLUS_INITIALIZATION:
            km = KMeansPlusPlusEstimator(self.k, 1, seed=self.seed).fit(ArrayDataset(x))
            assign = np.asarray(km.apply_arrays(jnp.asarray(x)))
            mass = assign.sum(axis=0)
            safe = np.maximum(mass, 1.0)
            means0 = (assign.T @ x) / safe[:, None]
            vars0 = (assign.T @ (x * x)) / safe[:, None] - means0**2
            weights0 = mass / n
        else:
            rng = np.random.default_rng(self.seed)
            lo, hi = x.min(axis=0), x.max(axis=0)
            span = hi - lo
            means0 = rng.uniform(size=(self.k, d)).astype(np.float32) * span + lo
            vars0 = np.tile(0.1 * span * span, (self.k, 1)).astype(np.float32)
            weights0 = np.full(self.k, 1.0 / self.k, dtype=np.float32)

        var_global = x.var(axis=0)
        var_lb = np.maximum(
            self.small_variance_threshold * var_global, self.absolute_variance_threshold
        ).astype(np.float32)
        vars0 = np.maximum(vars0, var_lb)

        means, variances, weights = _gmm_em(
            jnp.asarray(x),
            jnp.asarray(means0, dtype=jnp.float32),
            jnp.asarray(vars0, dtype=jnp.float32),
            jnp.asarray(weights0, dtype=jnp.float32),
            jnp.asarray(var_lb),
            self.max_iterations,
            jnp.float32(self.stop_tolerance),
            jnp.float32(self.weight_threshold),
            jnp.float32(self.min_cluster_size),
        )
        return GaussianMixtureModel(
            means.T, variances.T, weights, self.weight_threshold
        )


@functools.partial(linalg.mode_jit, static_argnums=(5,))
def _gmm_em(x, means0, vars0, weights0, var_lb, max_iterations, tol,
            weight_threshold, min_cluster_size):
    n = x.shape[0]
    xsq = x * x

    def cond(state):
        _, _, _, i, prev_cost, keep_going = state
        return (i < max_iterations) & keep_going

    def body(state):
        means, variances, weights, i, prev_cost, _ = state
        llh = _gmm_log_likelihood(x, means, variances, weights)
        cost = jnp.mean(jax.scipy.special.logsumexp(llh, axis=1))
        improving = jnp.where(i > 0, (cost - prev_cost) >= tol * jnp.abs(prev_cost), True)

        q = llh - jnp.max(llh, axis=1, keepdims=True)
        q = jnp.exp(q)
        q = q / jnp.sum(q, axis=1, keepdims=True)
        q = jnp.where(q > weight_threshold, q, 0.0)
        q = q / jnp.maximum(jnp.sum(q, axis=1, keepdims=True), 1e-30)

        q_sum = jnp.sum(q, axis=0)
        large_enough = jnp.all(q_sum >= min_cluster_size)

        do_update = improving & large_enough
        safe = jnp.maximum(q_sum, 1e-12)[:, None]
        new_means = linalg.mm(q.T, x) / safe
        new_vars = jnp.maximum(linalg.mm(q.T, xsq) / safe - new_means**2, var_lb)
        new_weights = q_sum / n

        means = jnp.where(do_update, new_means, means)
        variances = jnp.where(do_update, new_vars, variances)
        weights = jnp.where(do_update, new_weights, weights)
        return means, variances, weights, i + 1, cost, do_update

    means, variances, weights, *_ = jax.lax.while_loop(
        cond, body,
        (means0, vars0, weights0, jnp.int32(0), jnp.float32(-jnp.inf), jnp.bool_(True)),
    )
    return means, variances, weights
