"""Logistic / softmax regression by L-BFGS.

TPU-native re-design of reference:
nodes/learning/LogisticRegressionModel.scala:19-94 (which wrapped Spark
MLlib's LogisticRegressionWithLBFGS). Here the multinomial cross-entropy
objective and its data-parallel gradient compile into the same XLA L-BFGS
loop as the least-squares solvers — no external dependency.

The fitted transformer maps features to per-class scores (logits); argmax
matches the reference's classify-by-max behavior.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
import optax

from ...data.dataset import Dataset
from ...parallel import linalg
from ...parallel.mesh import get_mesh
from ...parallel.partitioner import fit_mesh
from ...workflow.pipeline import LabelEstimator
from ..stats.core import _as_array_dataset
from .linear import LinearMapper


class LogisticRegressionEstimator(LabelEstimator):
    """Multinomial logistic regression; labels are int class ids."""

    def __init__(self, num_classes: int, reg: float = 0.0,
                 num_iterations: int = 100, memory_size: int = 10,
                 tol: float = 1e-6):
        self.num_classes = num_classes
        self.reg = reg
        self.num_iterations = num_iterations
        self.memory_size = memory_size
        self.tol = tol

    def out_spec(self, in_specs):
        """Plan-time spec protocol (workflow/verify.py): int class-id
        labels, scores out at the declared class count."""
        from ...workflow.verify import dense_fit_spec

        return dense_fit_spec(in_specs, self.label, out_width=self.num_classes)

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        features = _as_array_dataset(data)
        targets = _as_array_dataset(labels)
        mesh = fit_mesh(self)
        x = linalg.prepare_row_sharded(jnp.asarray(features.data, jnp.float32), mesh)
        y = jnp.asarray(targets.data).astype(jnp.int32).ravel()
        y = linalg.prepare_row_sharded(y, mesh)
        n = features.num_examples
        mask = (jnp.arange(x.shape[0]) < n).astype(jnp.float32)

        w = _lbfgs_softmax(
            x, y, mask, jnp.float32(n), jnp.float32(self.reg),
            self.num_classes, self.num_iterations, self.memory_size, self.tol,
        )
        return LinearMapper(w)


@functools.partial(linalg.mode_jit, static_argnums=(5, 6, 7, 8))
def _lbfgs_softmax(x, y, mask, n, reg, num_classes,
                   num_iterations, memory_size, tol):
    d = x.shape[1]

    def loss(w):
        logits = linalg.mm(x, w)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
        return jnp.sum(nll * mask) / n + 0.5 * reg * jnp.sum(w * w)

    solver = optax.lbfgs(memory_size=memory_size)
    value_and_grad = optax.value_and_grad_from_state(loss)
    w0 = jnp.zeros((d, num_classes), dtype=x.dtype)
    state0 = solver.init(w0)

    def cond(carry):
        _, _, i, gnorm = carry
        return (i < num_iterations) & (gnorm > tol)

    def body(carry):
        w, state, i, _ = carry
        value, grad = value_and_grad(w, state=state)
        updates, state = solver.update(
            grad, state, w, value=value, grad=grad, value_fn=loss
        )
        w = optax.apply_updates(w, updates)
        return w, state, i + 1, jnp.linalg.norm(grad)

    w, *_ = jax.lax.while_loop(cond, body, (w0, state0, jnp.int32(0), jnp.float32(jnp.inf)))
    return w
