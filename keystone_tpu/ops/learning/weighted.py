"""Per-class mixture-weighted block least squares.

TPU-native re-design of
reference: nodes/learning/BlockWeightedLeastSquares.scala:36-372 and
nodes/learning/internal/ReWeightedLeastSquares.scala:18-142.

The solver fits, per class c, weights against a mixture of population and
class-conditional second-moment statistics controlled by ``mixture_weight``
(the reference's ImageNet configuration uses 0.25):

    jointXTX_c = (1−w)·popCov + w·classCov_c + w(1−w)·δ_c δ_cᵀ
    jointXTR_c = (1−w)·popXTR[:,c] + w·classXTR_c − jointMean_c·meanMix_c
    ΔW_c       = (jointXTX_c + λI)⁻¹ (jointXTR_c − λ·W_old[:,c])

with δ_c = classMean_c − popMean, per-block Gauss-Seidel over feature
blocks, and intercept b_c = jlm_c − Σ_d jointMean[c,d]·W[d,c] where
jlm_c = 2w + 2(1−w)·n_c/n − 1 (BlockWeightedLeastSquares.scala:149,318).

Execution re-design: the reference partitions the RDD so each partition
holds one class and computes class statistics partition-locally. Here
examples are sorted by class once; per-class covariances come from a
``lax.scan`` over classes reading static-size padded row windows of the
sorted batch, and cross-class quantities (classMean, classXTR, popXTR)
are single one-hot matmuls on the MXU.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ...data.dataset import Dataset
from ...parallel import linalg
from ...workflow.pipeline import LabelEstimator
from ..stats.core import _as_array_dataset
from .block import BlockLinearMapper, _round_up


def joint_label_means(counts, n, mixture_weight):
    """jlm_c = 2·mw + 2(1−mw)·n_c/n − 1, with the absent-class fallback:
    an all −1 target column's least-squares-consistent constant is −1
    (2·mw−1 would let a phantom class outrank trained negatives in top-k).
    Shared by both weighted estimators
    (reference: BlockWeightedLeastSquares.scala:149,318,
    PerClassWeightedLeastSquares.scala:190-196 computeJointLabelMean)."""
    counts = jnp.asarray(counts, jnp.float32)
    mw = mixture_weight
    jlm = 2.0 * mw + 2.0 * (1.0 - mw) * counts / jnp.float32(n) - 1.0
    return jnp.where(counts > 0, jlm, -1.0)


def weighted_intercept(jlm, joint_means, w):
    """b_c = jlm_c − Σ_d jointMean[c, d]·W[d, c]
    (reference: BlockWeightedLeastSquares.scala:318,
    PerClassWeightedLeastSquares.scala:122 finalB)."""
    return jnp.asarray(jlm, jnp.float32) - jnp.einsum(
        "cd,dc->c", joint_means, w, precision=linalg.precision()
    )


class BlockWeightedLeastSquaresEstimator(LabelEstimator):
    def __init__(self, block_size: int, num_iter: int, reg: float,
                 mixture_weight: float, solve_path: str = "auto"):
        self.block_size = block_size
        self.num_iter = num_iter
        self.reg = reg
        if not 0.0 <= mixture_weight <= 1.0:
            raise ValueError(f"mixture_weight must be in [0, 1], got {mixture_weight}")
        self.mixture_weight = mixture_weight
        # "auto" (flop-crossover Woodbury/dense choice) | "dense" |
        # "woodbury" — the explicit forms exist for A/B measurement.
        assert solve_path in ("auto", "dense", "woodbury"), solve_path
        # Woodbury's C diagonal divides by mw and mw·(1−mw): at either
        # endpoint the rank-update system is singular (inf/NaN weights)
        # where the dense path just loses its class/population term
        # gracefully — so the endpoints always take the dense path.
        if not 0.0 < mixture_weight < 1.0:
            if solve_path == "woodbury":
                raise ValueError(
                    "solve_path='woodbury' requires 0 < mixture_weight < 1 "
                    f"(got {mixture_weight}); use 'dense' or 'auto'"
                )
            solve_path = "dense"
        self.solve_path = solve_path

    @property
    def weight(self) -> int:
        return 3 * self.num_iter + 1

    def out_spec(self, in_specs):
        from ...workflow.verify import dense_fit_spec

        return dense_fit_spec(in_specs, self.label)

    def fit(self, data: Dataset, labels: Dataset) -> BlockLinearMapper:
        features = _as_array_dataset(data)
        targets = _as_array_dataset(labels)
        x = np.asarray(jax.device_get(features.data), np.float32)[: features.num_examples]
        y = np.asarray(jax.device_get(targets.data), np.float32)[: targets.num_examples]
        n, d = x.shape
        num_classes = y.shape[1]

        class_idx = np.argmax(y, axis=1)
        counts = np.bincount(class_idx, minlength=num_classes).astype(np.int64)
        order = np.argsort(class_idx, kind="stable")
        offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
        m = int(counts.max())

        bs = min(self.block_size, d)
        d_pad = _round_up(d, bs)
        if d_pad != d:
            x = np.pad(x, ((0, 0), (0, d_pad - d)))
        num_blocks = d_pad // bs

        # Sorted copies with m zero rows appended so static windows may overrun.
        xs = np.concatenate([x[order], np.zeros((m, d_pad), np.float32)])
        onehot = np.zeros((n, num_classes), np.float32)
        onehot[np.arange(n), class_idx] = 1.0

        w, joint_means = _weighted_bcd(
            jnp.asarray(x),
            jnp.asarray(xs),
            jnp.asarray(y),
            jnp.asarray(onehot),
            jnp.asarray(offsets),
            jnp.asarray(counts.astype(np.float32)),
            jnp.float32(self.reg),
            jnp.float32(self.mixture_weight),
            num_blocks, bs, m, self.num_iter, self.solve_path,
        )

        jlm = joint_label_means(counts, n, self.mixture_weight)
        b = weighted_intercept(jlm, joint_means, w)
        return BlockLinearMapper(w, block_size=bs, intercept=b)


@functools.partial(linalg.mode_jit, static_argnums=(8, 9, 10, 11, 12))
def _weighted_bcd(x, xs, y, onehot, offsets, counts, reg, mw,
                  num_blocks, bs, m, num_iter, force_path="auto"):
    n, d_pad = x.shape
    num_classes = y.shape[1]
    nf = jnp.float32(n)
    jlm = joint_label_means(counts, n, mw)
    residual0 = y - jlm  # (n, C)
    eye = jnp.eye(bs, dtype=x.dtype)
    row_win = jnp.arange(m)
    # Per-class system structure: jointXTX_c = S + U_c C U_cᵀ with the
    # CLASS-INDEPENDENT part S = (1−mw)·popCov + λI and a rank-(m+2)
    # update (m window rows, −μ_cμ_cᵀ, +δ_cδ_cᵀ). When the update rank is
    # small against the block size, factoring S ONCE per block and
    # solving each class by Woodbury replaces C = num_classes Cholesky
    # factorizations (bs³/3 each — the whole cost of the flagship solve,
    # 1000 at bs=4096) with batched triangular solves of m+3 rhs. Flop
    # crossover: Woodbury ≈ 2(m+3)·bs² per class vs bs³/3 — use it when
    # the update work is under a third of a refactorization. One
    # structured residual-correction step keeps it solver-grade
    # (Woodbury's error grows with update conditioning; the correction
    # reuses the same factored apply).
    use_woodbury = (
        2 * (m + 3) < bs // 3 if force_path == "auto"
        else force_path == "woodbury"  # test seam: path parity checks
    )

    def block_slice(mat, block):
        return jax.lax.dynamic_slice(mat, (0, block * bs), (mat.shape[0], bs))

    def per_class(block_xs, residual, res_mean, pop_mean, pop_cov, pop_xtr,
                  w_old_b, factor_s):
        """scan over classes: returns (C, bs) ΔW and (C, bs) joint means."""

        def class_system(c):
            """Shared per-class quantities for both solve paths."""
            off = offsets[c]
            n_c = counts[c]
            # Classes absent from the data get no weight update (the
            # reference only ever iterates over observed class groups).
            present = (n_c > 0).astype(x.dtype)
            n_c_safe = jnp.maximum(n_c, 1.0)
            win = jax.lax.dynamic_slice(block_xs, (off, 0), (m, bs))
            valid = (row_win < n_c).astype(x.dtype)[:, None]
            win = win * valid
            r_win = jax.lax.dynamic_slice(residual, (off, 0), (m, num_classes))
            r_c = jax.lax.dynamic_index_in_dim(r_win, c, axis=1, keepdims=False)
            r_c = r_c * valid[:, 0]

            class_mean = jnp.sum(win, axis=0) / n_c_safe
            class_xtr = linalg.mm(win.T, r_c[:, None])[:, 0] / n_c_safe

            delta = class_mean - pop_mean
            joint_mean = mw * class_mean + (1 - mw) * pop_mean
            mean_mix = (1 - mw) * res_mean[c] + mw * jnp.sum(r_c) / n_c_safe
            pop_xtr_c = jax.lax.dynamic_index_in_dim(pop_xtr, c, axis=1, keepdims=False)
            joint_xtr = (1 - mw) * pop_xtr_c + mw * class_xtr - joint_mean * mean_mix

            w_old_c = jax.lax.dynamic_index_in_dim(w_old_b, c, axis=1, keepdims=False)
            rhs = joint_xtr - reg * w_old_c
            return present, n_c_safe, win, class_mean, delta, joint_mean, rhs

        def step_dense(carry, c):
            present, n_c_safe, win, class_mean, delta, joint_mean, rhs = (
                class_system(c)
            )
            class_cov = linalg.mm(win.T, win) / n_c_safe - jnp.outer(
                class_mean, class_mean
            )
            joint_xtx = (
                (1 - mw) * pop_cov + mw * class_cov
                + mw * (1 - mw) * jnp.outer(delta, delta)
            )
            factor = jax.scipy.linalg.cho_factor(joint_xtx + reg * eye, lower=True)
            dw = jax.scipy.linalg.cho_solve(factor, rhs)
            return carry, (dw * present, joint_mean)

        def step_woodbury(carry, c):
            present, n_c_safe, win, class_mean, delta, joint_mean, rhs = (
                class_system(c)
            )
            # jointXTX = S + U C Uᵀ, U = [√(mw/n_c)·winᵀ | μ_c | δ'],
            # C = diag(1,…,1, −mw, +mw(1−mw)); signs folded into c_diag.
            u = jnp.concatenate(
                [
                    win.T * jnp.sqrt(mw / n_c_safe),
                    class_mean[:, None],
                    delta[:, None],
                ],
                axis=1,
            )  # (bs, m+2)
            c_diag = jnp.concatenate([
                jnp.ones((m,), x.dtype),
                jnp.array([-mw], x.dtype),
                jnp.array([mw * (1 - mw)], x.dtype),
            ])

            z = jax.scipy.linalg.cho_solve(
                factor_s, jnp.concatenate([u, rhs[:, None]], axis=1)
            )  # S⁻¹[U | rhs], one batched triangular-solve pair
            zu, zr = z[:, :-1], z[:, -1]
            small = jnp.diag(1.0 / c_diag) + linalg.mm(u.T, zu)

            def wood_apply(sr, su_t_r):
                # (S + UCUᵀ)⁻¹ r given sr = S⁻¹r and Uᵀ·S⁻¹r.
                return sr - linalg.mm(zu, jnp.linalg.solve(small, su_t_r[:, None]))[:, 0]

            dw = wood_apply(zr, linalg.mm(u.T, zr[:, None])[:, 0])
            # One residual-correction step against the STRUCTURED
            # operator (never materializes jointXTX): r = rhs − (S·dw +
            # U·C·(Uᵀdw)), correct with the same factored apply.
            s_dw = (1 - mw) * linalg.mm(pop_cov, dw[:, None])[:, 0] + reg * dw
            ut_dw = linalg.mm(u.T, dw[:, None])[:, 0]
            resid = rhs - s_dw - linalg.mm(u, (c_diag * ut_dw)[:, None])[:, 0]
            s_res = jax.scipy.linalg.cho_solve(factor_s, resid[:, None])[:, 0]
            dw = dw + wood_apply(s_res, linalg.mm(u.T, s_res[:, None])[:, 0])
            return carry, (dw * present, joint_mean)

        _, (dws, joint_means) = jax.lax.scan(
            step_woodbury if use_woodbury else step_dense, 0,
            jnp.arange(num_classes),
        )
        return dws, joint_means  # (C, bs) each

    def one_block(state, block):
        w, residual, joint_means_all = state
        block_x = block_slice(x, block)          # original order (n, bs)
        block_xs = block_slice(xs, block)        # sorted + padded (n+m, bs)
        w_b = jax.lax.dynamic_slice(w, (block * bs, 0), (bs, num_classes))

        pop_mean = jnp.mean(block_x, axis=0)
        pop_cov = linalg.mm(block_x.T, block_x) / nf - jnp.outer(pop_mean, pop_mean)
        pop_xtr = linalg.mm(block_x.T, residual) / nf      # (bs, C)
        res_mean = jnp.mean(residual, axis=0)              # (C,)
        factor_s = (
            jax.scipy.linalg.cho_factor((1 - mw) * pop_cov + reg * eye, lower=True)
            if use_woodbury else None
        )

        dws, joint_means = per_class(
            block_xs, _sorted_residual(residual), res_mean,
            pop_mean, pop_cov, pop_xtr, w_b, factor_s,
        )
        w = jax.lax.dynamic_update_slice(w, w_b + dws.T, (block * bs, 0))
        residual = residual - linalg.mm(block_x, dws.T)
        joint_means_all = jax.lax.dynamic_update_slice(
            joint_means_all, joint_means, (0, block * bs)
        )
        return (w, residual, joint_means_all), None

    # residual must be readable in sorted order inside per_class; precompute
    # the sort permutation application as a gather captured in closure.
    sort_gather = None

    def _sorted_residual(residual):
        rs = residual[_order_idx]
        return jnp.concatenate([rs, jnp.zeros((m, num_classes), residual.dtype)])

    # offsets/counts refer to sorted order; reconstruct the permutation from
    # them via argsort of the (stable) class ordering used on host. We pass
    # it in as a constant derived from onehot.
    _order_idx = jnp.argsort(jnp.argmax(onehot, axis=1), stable=True)

    w0 = jnp.zeros((d_pad, num_classes), dtype=x.dtype)
    jm0 = jnp.zeros((num_classes, d_pad), dtype=x.dtype)
    blocks = jnp.tile(jnp.arange(num_blocks), num_iter)
    (w, _, joint_means), _ = jax.lax.scan(one_block, (w0, residual0, jm0), blocks)
    return w, joint_means


# --------------------------------------------- per-class re-weighted variant


class PerClassWeightedLeastSquaresEstimator(LabelEstimator):
    """Per-class example-weighted least squares.

    TPU-native re-design of
    reference: nodes/learning/PerClassWeightedLeastSquares.scala:31-223 +
    internal/ReWeightedLeastSquares.scala:18-142. Where
    :class:`BlockWeightedLeastSquaresEstimator` mixes per-class second
    moments, this variant solves one weighted problem per class c with
    scalar example weights

        b_i(c) = (1−mw)/n + 1[class_i = c]·mw/n_c

    features centered by the class's joint mean jfm_c = mw·classMean_c +
    (1−mw)·popMean, labels centered by jlm_c, via weighted BCD

        W_b = (X̃_bᵀ diag(b) X̃_b + λI) \\ X̃_bᵀ(b ∘ ỹ − r + b ∘ X̃_b W_b)

    The reference runs C sequential Spark solves with treeReduce per
    block; here the class loop, pass loop and block loop are one compiled
    ``lax.scan`` nest with the per-shard products on the MXU.
    """

    def __init__(self, block_size: int, num_iter: int, reg: float,
                 mixture_weight: float):
        self.block_size = block_size
        self.num_iter = num_iter
        self.reg = reg
        if not 0.0 <= mixture_weight <= 1.0:
            raise ValueError(f"mixture_weight must be in [0, 1], got {mixture_weight}")
        self.mixture_weight = mixture_weight

    def out_spec(self, in_specs):
        from ...workflow.verify import dense_fit_spec

        return dense_fit_spec(in_specs, self.label)

    @property
    def weight(self) -> int:
        return 3 * self.num_iter + 1

    def fit(self, data: Dataset, labels: Dataset) -> BlockLinearMapper:
        features = _as_array_dataset(data)
        targets = _as_array_dataset(labels)
        x = np.asarray(jax.device_get(features.data), np.float32)[: features.num_examples]
        y = np.asarray(jax.device_get(targets.data), np.float32)[: targets.num_examples]
        n, d = x.shape
        num_classes = y.shape[1]

        class_idx = np.argmax(y, axis=1)
        counts = np.bincount(class_idx, minlength=num_classes).astype(np.float32)
        onehot = np.zeros((n, num_classes), np.float32)
        onehot[np.arange(n), class_idx] = 1.0

        bs = min(self.block_size, d)
        d_pad = _round_up(d, bs)
        if d_pad != d:
            x = np.pad(x, ((0, 0), (0, d_pad - d)))

        w, jfm, jlm = _pcwls_fit(
            jnp.asarray(x), jnp.asarray(y), jnp.asarray(onehot),
            jnp.asarray(counts), jnp.float32(self.reg),
            jnp.float32(self.mixture_weight),
            d_pad // bs, bs, self.num_iter,
        )
        b = weighted_intercept(jlm, jfm, w)
        return BlockLinearMapper(w, block_size=bs, intercept=b)


@functools.partial(linalg.mode_jit, static_argnums=(6, 7, 8))
def _pcwls_fit(x, y, onehot, counts, reg, mw, num_blocks, bs, num_iter):
    n, d_pad = x.shape
    num_classes = y.shape[1]
    nf = jnp.float32(n)
    counts_safe = jnp.maximum(counts, 1.0)
    present = (counts > 0).astype(x.dtype)

    pop_mean = jnp.mean(x, axis=0)                                   # (d,)
    class_mean = linalg.mm(onehot.T, x) / counts_safe[:, None]       # (C, d)
    jfm = mw * class_mean + (1.0 - mw) * pop_mean[None, :]           # (C, d)
    jlm = joint_label_means(counts, n, mw)                           # (C,)
    eye = jnp.eye(bs, dtype=x.dtype)

    def per_class(carry, c):
        xc = x - jax.lax.dynamic_index_in_dim(jfm, c, keepdims=True)   # (n, d)
        yc = jax.lax.dynamic_index_in_dim(y, c, axis=1, keepdims=False) \
            - jax.lax.dynamic_index_in_dim(jlm, c, keepdims=False)
        oc = jax.lax.dynamic_index_in_dim(onehot, c, axis=1, keepdims=False)
        n_c = jax.lax.dynamic_index_in_dim(counts_safe, c, keepdims=False)
        b_wt = (1.0 - mw) / nf + oc * (mw / n_c)                        # (n,)
        by = b_wt * yc

        def one_block(state, block):
            w_col, resid = state  # resid = b ∘ (X̃·w) accumulated
            start = block * bs
            xb = jax.lax.dynamic_slice(xc, (0, start), (n, bs))
            w_b = jax.lax.dynamic_slice(w_col, (start, 0), (bs, 1))
            g = linalg.mm(xb.T, b_wt[:, None] * xb)
            pred_old = b_wt * linalg.mm(xb, w_b)[:, 0]
            rhs = linalg.mm(xb.T, (by - (resid - pred_old))[:, None])
            factor = jax.scipy.linalg.cho_factor(g + reg * eye, lower=True)
            w_b_new = jax.scipy.linalg.cho_solve(factor, rhs)
            resid = resid + b_wt * linalg.mm(xb, w_b_new - w_b)[:, 0]
            w_col = jax.lax.dynamic_update_slice(w_col, w_b_new, (start, 0))
            return (w_col, resid), None

        blocks = jnp.tile(jnp.arange(num_blocks), num_iter)
        (w_col, _), _ = jax.lax.scan(
            one_block, (jnp.zeros((d_pad, 1), x.dtype), jnp.zeros((n,), x.dtype)),
            blocks,
        )
        w_col = w_col * jax.lax.dynamic_index_in_dim(present, c, keepdims=False)
        return carry, w_col[:, 0]

    _, w_cols = jax.lax.scan(per_class, 0, jnp.arange(num_classes))
    return w_cols.T, jfm, jlm  # (d_pad, C)
