"""Image featurization operators (reference: nodes/images/)."""

from .core import (
    CenterCornerPatcher,
    Convolver,
    Cropper,
    GrayScaler,
    ImageExtractor,
    ImageVectorizer,
    LabelExtractor,
    MultiLabelExtractor,
    MultiLabeledImageExtractor,
    PixelScaler,
    Pooler,
    RandomImageTransformer,
    RandomPatcher,
    SymmetricRectifier,
    Windower,
    pack_filters,
)

__all__ = [
    "CenterCornerPatcher",
    "Convolver",
    "Cropper",
    "GrayScaler",
    "ImageExtractor",
    "ImageVectorizer",
    "LabelExtractor",
    "MultiLabelExtractor",
    "MultiLabeledImageExtractor",
    "PixelScaler",
    "Pooler",
    "RandomImageTransformer",
    "RandomPatcher",
    "SymmetricRectifier",
    "Windower",
    "pack_filters",
]
