"""Statistical / elementwise vector operators.

TPU-native re-designs of the reference's stats nodes — each one is a
whole-batch XLA computation over (n, d) device arrays instead of a
per-vector Breeze loop:

- ``RandomSignNode``       (reference: nodes/stats/RandomSignNode.scala)
- ``PaddedFFT``            (reference: nodes/stats/PaddedFFT.scala:13-21)
- ``LinearRectifier``      (reference: nodes/stats/LinearRectifier.scala)
- ``NormalizeRows``        (reference: nodes/stats/NormalizeRows.scala)
- ``SignedHellingerMapper``(reference: nodes/stats/SignedHellingerMapper.scala)
- ``StandardScaler``       (reference: nodes/stats/StandardScaler.scala:16-77)
- ``Sampler``/``ColumnSampler`` (reference: nodes/stats/Sampler.scala)
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp

from ...data.dataset import ArrayDataset, Dataset
from ...workflow.pipeline import BatchTransformer, Estimator, Transformer


class RandomSignNode(BatchTransformer):
    """Multiply each feature by a fixed random ±1 sign."""

    def __init__(self, signs: np.ndarray):
        self.signs = jnp.asarray(signs, dtype=jnp.float32)

    @staticmethod
    def create(size: int, seed: int = 0) -> "RandomSignNode":
        rng = np.random.default_rng(seed)
        return RandomSignNode(2.0 * rng.integers(0, 2, size=size) - 1.0)

    def apply_arrays(self, x):
        return x * self.signs


def next_power_of_two(n: int) -> int:
    return 1 << (n - 1).bit_length()


class PaddedFFT(BatchTransformer):
    """Zero-pad features to the next power of two; return the real parts of
    the first half of the Fourier transform (size p/2 output)."""

    def apply_arrays(self, x):
        d = x.shape[-1]
        p = next_power_of_two(d)
        padded = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, p - d)])
        # rfft returns p//2+1 coefficients; the reference keeps [0, p/2).
        return jnp.fft.rfft(padded, axis=-1).real[..., : p // 2].astype(x.dtype)


class CosineRandomFeatures(BatchTransformer):
    """Rahimi-Recht random cosine features: cos(x·Wᵀ + b)
    (reference: nodes/stats/CosineRandomFeatures.scala:19-75).

    One whole-batch GEMM on the MXU replaces the reference's
    partition-blocked Breeze GEMM; W rides along as a (d_out, d_in)
    device constant."""

    def __init__(self, w: np.ndarray, b: np.ndarray):
        if b.shape[0] != w.shape[0]:
            raise ValueError("rows of W and size of b must match")
        self.w = jnp.asarray(w, dtype=jnp.float32)
        self.b = jnp.asarray(b, dtype=jnp.float32)

    @staticmethod
    def create(
        num_input_features: int,
        num_output_features: int,
        gamma: float,
        dist: str = "gaussian",
        seed: int = 0,
    ) -> "CosineRandomFeatures":
        """W ~ gamma·dist, b ~ U[0, 2π) (reference: CosineRandomFeatures
        companion object; Cauchy variant for the TIMIT rfType flag)."""
        rng = np.random.default_rng(seed)
        if dist == "gaussian":
            w = rng.normal(size=(num_output_features, num_input_features))
        elif dist == "cauchy":
            w = rng.standard_cauchy(size=(num_output_features, num_input_features))
        else:
            raise ValueError(f"unknown distribution {dist!r}")
        b = rng.uniform(0.0, 2.0 * np.pi, size=num_output_features)
        return CosineRandomFeatures(w * gamma, b)

    def apply_arrays(self, x):
        return jnp.cos(x @ self.w.T + self.b)


class LinearRectifier(BatchTransformer):
    """f(x) = max(max_val, x - alpha)."""

    def __init__(self, max_val: float = 0.0, alpha: float = 0.0):
        self.max_val = max_val
        self.alpha = alpha

    def apply_arrays(self, x):
        return jnp.maximum(self.max_val, x - self.alpha)


class NormalizeRows(BatchTransformer):
    """Scale each row to unit L2 norm (zero rows stay zero)."""

    def apply_arrays(self, x):
        norms = jnp.linalg.norm(x, axis=-1, keepdims=True)
        return x / jnp.where(norms == 0, 1.0, norms)


class SignedHellingerMapper(BatchTransformer):
    """x ↦ sign(x)·sqrt(|x|) (reference applies this before/after FV)."""

    def apply_arrays(self, x):
        return jnp.sign(x) * jnp.sqrt(jnp.abs(x))


class Clipper(BatchTransformer):
    """Elementwise clip to [lo, hi]."""

    def __init__(self, lo: float, hi: float):
        self.lo, self.hi = lo, hi

    def apply_arrays(self, x):
        return jnp.clip(x, self.lo, self.hi)


class StandardScalerModel(BatchTransformer):
    """Subtract column means; optionally divide by column stds."""

    def __init__(self, mean: jnp.ndarray, std: Optional[jnp.ndarray] = None):
        self.mean = jnp.asarray(mean)
        self.std = None if std is None else jnp.asarray(std)

    def apply_arrays(self, x):
        out = x - self.mean
        if self.std is not None:
            out = out / self.std
        return out


class StandardScaler(Estimator):
    """Fit column mean/std in one masked pass over the sharded batch.

    Degenerate stds (0, NaN, inf, <eps) become 1.0, matching the
    reference's guard (StandardScaler.scala:50-56). Uses the unbiased
    (n-1) variance like MLlib's summarizer.
    """

    def __init__(self, normalize_std_dev: bool = True, eps: float = 1e-12):
        self.normalize_std_dev = normalize_std_dev
        self.eps = eps

    def out_spec(self, in_specs):
        from ...workflow.verify import elementwise_fit_spec

        return elementwise_fit_spec(in_specs, self.label)

    def fit(self, data: Dataset) -> StandardScalerModel:
        ds = _as_array_dataset(data)
        x = ds.data
        n = ds.num_examples
        mask = ds.mask().reshape((-1,) + (1,) * (x.ndim - 1))
        s1 = jnp.sum(x * mask, axis=0)
        mean = s1 / n
        if not self.normalize_std_dev:
            return StandardScalerModel(mean, None)
        s2 = jnp.sum((x * mask) ** 2, axis=0)
        var = (s2 - n * mean**2) / max(n - 1, 1)
        std = jnp.sqrt(jnp.maximum(var, 0.0))
        std = jnp.where(
            jnp.isnan(std) | jnp.isinf(std) | (jnp.abs(std) < self.eps), 1.0, std
        )
        return StandardScalerModel(mean, std)


class Sampler(Transformer):
    """Random subsample of n_samples items
    (reference: nodes/stats/Sampler.scala FunctionNode via takeSample)."""

    def __init__(self, num_samples: int, seed: int = 42):
        self.num_samples = num_samples
        self.seed = seed

    def apply(self, datum):
        return datum

    def apply_batch(self, dataset: Dataset) -> Dataset:
        rng = np.random.default_rng(self.seed)
        n = len(dataset)
        take = min(self.num_samples, n)
        idx = np.sort(rng.choice(n, size=take, replace=False))
        if isinstance(dataset, ArrayDataset):
            import jax

            data = jax.tree_util.tree_map(lambda a: jnp.asarray(a)[idx], dataset.data)
            return ArrayDataset(data, num_examples=take)
        items = dataset.collect()
        return type(dataset)([items[i] for i in idx])


class ColumnSampler(Transformer):
    """Sample descriptors from per-item (n_i, d) descriptor matrices and
    emit a flat (num_samples_total, d) dataset
    (reference: nodes/stats/ColumnSampler used by the ImageNet/VOC
    pipelines — the reference's matrices are (d, nᵢ) column-major; this
    framework's extractors emit descriptor rows, so "columns" here are the
    descriptor axis)."""

    def __init__(self, num_samples_per_item: int, seed: int = 42):
        self.num_samples_per_item = num_samples_per_item
        self.seed = seed

    def _sample(self, datum, rng) -> np.ndarray:
        mat = np.asarray(datum)
        n_desc = mat.shape[0]
        take = min(self.num_samples_per_item, n_desc)
        idx = rng.choice(n_desc, size=take, replace=False)
        return mat[idx]  # (take, d)

    def apply(self, datum):
        return self._sample(datum, np.random.default_rng(self.seed))

    def apply_batch(self, dataset: Dataset) -> ArrayDataset:
        from ...data.dataset import BucketedDataset

        if isinstance(dataset, BucketedDataset):
            # Masked/bucketed descriptors: sample on device per bucket
            # (Gumbel top-k over valid slots — no host desc[valid] fancy
            # indexing), concatenate the small sample matrices.
            parts = [
                np.asarray(self._sample_bucket(b, i).data)
                for i, b in enumerate(dataset.buckets)
            ]
            return ArrayDataset(np.concatenate(parts, axis=0))
        if isinstance(dataset, ArrayDataset) and isinstance(dataset.data, dict) \
                and "valid" in dataset.data:
            return self._sample_bucket(dataset, 0)
        if isinstance(dataset, ArrayDataset):
            # (N, c, d) uniform batch: one vectorized gather per batch.
            x = np.asarray(dataset.data)[: dataset.num_examples]
            n, c, _ = x.shape
            take = min(self.num_samples_per_item, c)
            rng = np.random.default_rng(self.seed)
            # per-row sample-without-replacement in one shot: argsort of a
            # random matrix (per-row choice() would be O(n) host calls)
            idx = np.argsort(rng.random((n, c)), axis=1)[:, :take]
            return ArrayDataset(x[np.arange(n)[:, None], idx].reshape(n * take, -1))
        # One rng threaded across items — re-seeding per item would sample
        # identical descriptor positions from every matrix.
        rng = np.random.default_rng(self.seed)
        rows = [self._sample(item, rng) for item in dataset.collect()]
        return ArrayDataset(np.concatenate(rows, axis=0))

    def _sample_bucket(self, bucket: ArrayDataset, bucket_idx: int) -> ArrayDataset:
        """Uniform sample-without-replacement of valid descriptors, on
        device: Gumbel perturbation + top_k over the flattened valid slots
        (invalid slots get −inf, so they are never chosen while the take
        count stays within the valid total)."""
        import jax

        desc = jnp.asarray(bucket.data["desc"])
        valid = jnp.asarray(bucket.data["valid"])
        n = bucket.num_examples
        desc = desc[:n]
        valid = valid[:n]
        flat = desc.reshape(-1, desc.shape[-1])
        v = valid.reshape(-1).astype(bool)
        num_valid = int(jnp.sum(v))  # one scalar fetch per bucket
        take = min(self.num_samples_per_item * n, num_valid)
        if take == 0:
            return ArrayDataset(np.zeros((0, desc.shape[-1]), np.float32))
        key = jax.random.PRNGKey(self.seed + 7919 * bucket_idx)
        g = jax.random.gumbel(key, v.shape) + jnp.where(v, 0.0, -jnp.inf)
        _, idx = jax.lax.top_k(g, take)
        return ArrayDataset(flat[idx])


def _as_array_dataset(data: Dataset) -> ArrayDataset:
    if isinstance(data, ArrayDataset):
        return data
    from ...data.dataset import BucketedDataset

    if isinstance(data, BucketedDataset):
        return data.concat()
    return data.to_arrays()  # type: ignore[attr-defined]
