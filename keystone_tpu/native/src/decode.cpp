// Batch JPEG decode + bilinear resize, host-native ingest kernel.
//
// The TPU-native analog of the reference's executor-side ImageIO decode
// (reference: loaders/ImageLoaderUtils.scala:84-88, utils/images/
// ImageConversions.scala:5-80): the input pipeline is the classic host-side
// bottleneck feeding the chip, so decode fans out over OpenMP threads with
// libjpeg doing the hot loop. Output matches the framework's image
// convention — (X=rows, Y=cols, C) float arrays in BGR channel order
// (keystone_tpu/utils/image.py load_image).

#include <algorithm>
#include <csetjmp>
#include <cstdio>
#include <cstring>
#include <vector>

#include <jpeglib.h>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  std::jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* err = reinterpret_cast<ErrorMgr*>(cinfo->err);
  std::longjmp(err->jump, 1);
}

void silent_output(j_common_ptr) {}

// Decode one JPEG into an RGB byte buffer. Returns false on any error.
// min_x/min_y (>0): the caller's resample target — decode is DCT-domain
// scaled to the smallest 1/2^k size still >= the target in both dims, so
// IDCT + memory traffic scale with output pixels, not source pixels (the
// bilinear resample that follows eats the remaining gap). 0 disables.
bool decode_rgb(const unsigned char* buf, long long len, std::vector<unsigned char>& rgb,
                int& width, int& height, int min_x, int min_y) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  jerr.pub.output_message = silent_output;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(buf), (unsigned long)len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  if (min_x > 0 && min_y > 0) {
    // ceil division: libjpeg's scaled output is ceil(dim/denom)
    // (jdiv_round_up), so floor would reject valid just-under-2^k sizes
    for (int d = 8; d >= 2; d /= 2) {
      if ((int)((cinfo.image_height + d - 1) / d) >= min_x &&
          (int)((cinfo.image_width + d - 1) / d) >= min_y) {
        cinfo.scale_num = 1;
        cinfo.scale_denom = d;
        break;
      }
    }
  }
  jpeg_start_decompress(&cinfo);
  width = cinfo.output_width;
  height = cinfo.output_height;
  if (width <= 0 || height <= 0 || cinfo.output_components != 3) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  rgb.resize((size_t)width * height * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    unsigned char* row = rgb.data() + (size_t)cinfo.output_scanline * width * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

}  // namespace

extern "C" {

// bufs[i]: raw JPEG bytes of length lens[i]. out: (n, out_x, out_y, 3)
// float32 BGR. ok[i] = 1 on success, 0 on decode failure (row left zero).
// out_x and out_y must be positive — every image is resampled to that
// fixed shape (ragged native sizes cannot share one output buffer).
void ks_decode_jpeg_batch(const unsigned char* const* bufs,
                          const long long* lens, int n, int out_x, int out_y,
                          float* out, unsigned char* ok) {
#ifdef _OPENMP
#pragma omp parallel for schedule(dynamic)
#endif
  for (int i = 0; i < n; ++i) {
    std::vector<unsigned char> rgb;
    int w = 0, h = 0;
    ok[i] = 0;
    float* dst = out + (size_t)i * out_x * out_y * 3;
    std::memset(dst, 0, sizeof(float) * (size_t)out_x * out_y * 3);
    if (!decode_rgb(bufs[i], lens[i], rgb, w, h, out_x, out_y)) continue;
    // scale factors map output pixel centers into source coordinates
    const float sx = out_x > 1 ? (float)(h - 1) / (float)(out_x - 1) : 0.0f;
    const float sy = out_y > 1 ? (float)(w - 1) / (float)(out_y - 1) : 0.0f;
    // Bilinear resample with column neighbors/weights precomputed once
    // (identical for every row and channel) and row neighbors hoisted
    // per row; neighbor indices clamped independently so 1-pixel
    // wide/tall sources stay in bounds.
    std::vector<int> y0s(out_y), y1s(out_y);
    std::vector<float> ays(out_y);
    for (int y = 0; y < out_y; ++y) {
      float fy = y * sy;
      int y0 = (int)fy;
      if (y0 > w - 1) y0 = w - 1;
      if (y0 < 0) y0 = 0;
      y0s[y] = y0;
      y1s[y] = std::min(y0 + 1, w - 1);
      ays[y] = fy - y0;
    }
    for (int x = 0; x < out_x; ++x) {
      float fx = x * sx;
      int x0 = (int)fx;
      if (x0 > h - 1) x0 = h - 1;
      if (x0 < 0) x0 = 0;
      const int x1 = std::min(x0 + 1, h - 1);
      const float ax = fx - x0;
      const unsigned char* r0 = rgb.data() + (size_t)x0 * w * 3;
      const unsigned char* r1 = rgb.data() + (size_t)x1 * w * 3;
      float* px = dst + (size_t)x * out_y * 3;
      for (int y = 0; y < out_y; ++y, px += 3) {
        const int o0 = y0s[y] * 3, o1 = y1s[y] * 3;
        const float ay = ays[y];
        // channel c of source RGB -> output BGR (px[2-c])
        for (int c = 0; c < 3; ++c) {
          const float top = r0[o0 + c] * (1 - ay) + r0[o1 + c] * ay;
          const float bot = r1[o0 + c] * (1 - ay) + r1[o1 + c] * ay;
          px[2 - c] = top * (1 - ax) + bot * ax;
        }
      }
    }
    ok[i] = 1;
  }
}

// Cap the decode pool (bench scaling curves; 0 = library default).
void ks_set_threads(int n) {
#ifdef _OPENMP
  if (n > 0) omp_set_num_threads(n);
#else
  (void)n;
#endif
}

// Probe: returns 1 and fills (height=rows, width=cols) without full decode.
int ks_jpeg_dims(const unsigned char* buf, long long len, int* rows, int* cols) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  jerr.pub.output_message = silent_output;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return 0;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(buf), (unsigned long)len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return 0;
  }
  *rows = cinfo.image_height;
  *cols = cinfo.image_width;
  jpeg_destroy_decompress(&cinfo);
  return 1;
}

}  // extern "C"
