"""Untyped operator algebra + lazy expressions.

TPU-native re-design of the reference's execution units
(reference: workflow/Operator.scala:10-177, workflow/Expression.scala:8-44).

Operators are the graph IR's payloads; they dispatch between per-datum and
whole-dataset execution, and their outputs are call-by-name memoized
``Expression``s so building a pipeline never eagerly launches device work —
the analog of the reference's "no Spark job until someone forces .get".
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence

from ..data.dataset import Dataset


_UNSET = object()


class Expression:
    """Call-by-name memoized result.

    ``get`` is thread-safe: the memo is guarded by a per-expression lock,
    so two threads forcing the same expression run the thunk exactly once
    and both observe the one memoized value. This matters to the
    reliability layer — a deadline-abandoned watchdog thread can still be
    inside ``get`` when a retry (or a concurrent serving reader) arrives;
    without the lock the racers could both run the thunk or read a
    half-written memo. (Retries still execute the op FRESH rather than
    re-entering an abandoned expression — see executor._wrap_reliability —
    because a watchdog stuck in a hung thunk holds the lock until it
    dies; the lock protects concurrent readers, not hung work.)
    """

    def __init__(self, thunk: Callable[[], Any]):
        self._thunk: Optional[Callable[[], Any]] = thunk
        self._value: Any = _UNSET
        self._lock = threading.Lock()

    def get(self) -> Any:
        # Double-checked: the unlocked fast path is safe because _value
        # is written exactly once, under the lock, after the thunk ran.
        if self._value is _UNSET:
            with self._lock:
                if self._value is _UNSET:
                    assert self._thunk is not None
                    self._value = self._thunk()
                    self._thunk = None
        return self._value

    def __getstate__(self):
        # Locks don't pickle; a forced expression (thunk already dropped)
        # must stay serializable — SavedStateLoadRule splices expressions
        # into graphs that FittedPipeline.save pickles.
        state = self.__dict__.copy()
        state["_lock"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()

    @classmethod
    def of(cls, value: Any) -> "Expression":
        e = cls(lambda: value)
        e.get()
        return e


class DatasetExpression(Expression):
    """Lazily yields a :class:`~keystone_tpu.data.dataset.Dataset`."""


class DatumExpression(Expression):
    """Lazily yields a single item."""


class TransformerExpression(Expression):
    """Lazily yields a fit :class:`TransformerOperator`."""


def wrap_expression(value: Any) -> "Expression":
    """Wrap an already-computed value, preserving dataset-ness so
    :meth:`TransformerOperator.execute` picks the batch path. Used by the
    sample/profiling mini-interpreters in the optimizer layer."""
    if isinstance(value, Dataset):
        return DatasetExpression.of(value)
    return Expression.of(value)


class Operator:
    """Base execution unit stored at graph nodes."""

    @property
    def label(self) -> str:
        return type(self).__name__

    def execute(self, deps: Sequence[Expression]) -> Expression:
        raise NotImplementedError

    def __repr__(self) -> str:
        return self.label


class DatasetOperator(Operator):
    """Zero-dependency constant dataset (a bound pipeline input)."""

    def __init__(self, dataset: Dataset):
        self.dataset = dataset

    @property
    def label(self) -> str:
        return f"Dataset[n={len(self.dataset)}]"

    def execute(self, deps: Sequence[Expression]) -> DatasetExpression:
        assert not deps
        return DatasetExpression.of(self.dataset)

    # Structural equality on the underlying dataset object so that two
    # applications of the same pipeline to the same data produce equal
    # prefixes (the fit-once-across-applications guarantee).
    def __eq__(self, other: object) -> bool:
        return isinstance(other, DatasetOperator) and other.dataset is self.dataset

    def __hash__(self) -> int:
        return hash((DatasetOperator, id(self.dataset)))


class DatumOperator(Operator):
    """Zero-dependency constant datum."""

    def __init__(self, datum: Any):
        self.datum = datum

    @property
    def label(self) -> str:
        return "Datum"

    def execute(self, deps: Sequence[Expression]) -> DatumExpression:
        assert not deps
        return DatumExpression.of(self.datum)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, DatumOperator) and other.datum is self.datum

    def __hash__(self) -> int:
        return hash((DatumOperator, id(self.datum)))


class TransformerOperator(Operator):
    """An operator that maps inputs to outputs datum-by-datum or batchwise.

    Subclasses implement ``single_transform`` (one datum per dependency) and
    ``batch_transform`` (one Dataset per dependency). Dispatch follows the
    reference's rule: if any dependency is a dataset, run batch; datum
    dependencies are broadcast (reference: workflow/Operator.scala:60-108).
    """

    def single_transform(self, datums: List[Any]) -> Any:
        raise NotImplementedError

    def batch_transform(self, datasets: List[Dataset]) -> Dataset:
        raise NotImplementedError

    def execute(self, deps: Sequence[Expression]) -> Expression:
        if any(isinstance(d, DatasetExpression) for d in deps):

            def thunk() -> Dataset:
                materialized: List[Dataset] = []
                for d in deps:
                    value = d.get()
                    if not isinstance(value, Dataset):
                        raise TypeError(
                            f"{self.label}: mixed datum/dataset dependencies are not supported "
                            "in batch execution"
                        )
                    materialized.append(value)
                return self.batch_transform(materialized)

            return DatasetExpression(thunk)

        def datum_thunk() -> Any:
            return self.single_transform([d.get() for d in deps])

        return DatumExpression(datum_thunk)


class EstimatorOperator(Operator):
    """Fits datasets into a TransformerOperator (reference: Operator.scala:112-124).

    Estimators that can consume their training data INCREMENTALLY — via
    sufficient statistics (Gram accumulation) rather than a materialized
    feature matrix — advertise ``supports_fit_stream = True`` and
    implement :meth:`fit_stream`; the streaming planner
    (workflow/streaming.py) then rewrites eligible
    ``ingest → featurize → fit`` graphs into chunked plans where the
    full feature matrix never exists.
    """

    #: True when :meth:`fit_stream` is implemented (streaming planner gate).
    supports_fit_stream: bool = False

    def fit_datasets(self, datasets: List[Dataset]) -> TransformerOperator:
        raise NotImplementedError

    def fit_stream(self, stream) -> TransformerOperator:
        """Fit from a :class:`~keystone_tpu.workflow.streaming.ChunkStream`
        (see its ``fold`` contract). Only called when
        ``supports_fit_stream`` is True."""
        raise NotImplementedError(f"{self.label} does not support fit_stream")

    def execute(self, deps: Sequence[Expression]) -> TransformerExpression:
        def thunk() -> TransformerOperator:
            datasets = []
            for d in deps:
                value = d.get()
                if not isinstance(value, Dataset):
                    raise TypeError(f"{self.label}: estimator dependencies must be datasets")
                datasets.append(value)
            # A measured precision choice (MeasuredKnobRule pins
            # ``solver_precision`` onto the operator) applies only around
            # THIS fit — thread-local and restored on exit, so it can
            # never leak into solves that were not planned under it.
            mode = getattr(self, "solver_precision", None)
            if mode is None:
                return self.fit_datasets(datasets)
            from ..parallel import linalg

            with linalg.solver_mode_scope(mode):
                return self.fit_datasets(datasets)

        return TransformerExpression(thunk)


class DelegatingOperator(Operator):
    """Applies a fit transformer: first dep is the TransformerExpression,
    the rest are its data (reference: Operator.scala:130-160)."""

    def execute(self, deps: Sequence[Expression]) -> Expression:
        transformer_dep, data_deps = deps[0], list(deps[1:])
        if any(isinstance(d, DatasetExpression) for d in data_deps):

            def thunk() -> Dataset:
                transformer: TransformerOperator = transformer_dep.get()
                datasets = [d.get() for d in data_deps]
                return transformer.batch_transform(datasets)

            return DatasetExpression(thunk)

        def datum_thunk() -> Any:
            transformer: TransformerOperator = transformer_dep.get()
            return transformer.single_transform([d.get() for d in data_deps])

        return DatumExpression(datum_thunk)


class ExpressionOperator(Operator):
    """Wraps an already-computed expression — how prefix-state reuse splices
    previous results into a new plan (reference: Operator.scala:166-177)."""

    def __init__(self, expression: Expression):
        self.expression = expression

    @property
    def label(self) -> str:
        return "Expr"

    def execute(self, deps: Sequence[Expression]) -> Expression:
        return self.expression
