"""Profile-driven automatic caching: the HBM-residency planner.

TPU-native re-design of the reference's AutoCacheRule
(reference: workflow/AutoCacheRule.scala:12-664, workflow/WeightedNode.scala,
workflow/WeightedOperator.scala, workflow/DefaultOptimizer.scala:17-26).

The reference profiles candidate nodes by executing scaled samples (2 and 4
items per partition), times them, reads RDD storage sizes, extrapolates both
metrics to full scale with per-metric linear fits, then greedily selects the
cache set that minimizes estimated total runtime under a cluster-memory
budget (default 75% of free executor memory) and splices ``Cacher`` nodes in.

On TPU "caching" is an HBM-residency decision: a cached intermediate stays
materialized on device between uses instead of being recomputed by every
downstream pull. The same profile → linear-extrapolate → greedy-knapsack
pipeline applies, with the budget taken from per-device HBM via
:func:`keystone_tpu.parallel.mesh.device_memory_budget_bytes`, and node
weights (``operator.weight``, e.g. 3·num_iter+1 for the block solver)
multiplying the recomputation count exactly as the reference's
``WeightedNode`` does.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..data.dataset import ArrayDataset, Dataset, ObjectDataset
from ..obs import cost as _cost
from ..obs import names as _names
from ..obs import spans as _spans
from ..obs import store as _store
from .analysis import get_ancestors
from .graph import Graph, NodeId, SinkId, SourceId
from .operators import (
    DatasetOperator,
    DatumOperator,
    EstimatorOperator,
    Expression,
    Operator,
    wrap_expression,
)
from .rules import PrefixMap, Rule


@dataclass
class Profile:
    """Extrapolated full-scale execution profile of one node
    (reference: AutoCacheRule.scala ``Profile``)."""

    run_time_s: float
    size_bytes: int

    def __add__(self, other: "Profile") -> "Profile":
        return Profile(self.run_time_s + other.run_time_s, self.size_bytes + other.size_bytes)


@dataclass
class SampleProfile:
    """One measured (scale, time, bytes) observation
    (reference: AutoCacheRule.scala ``SampleProfile``)."""

    scale: int
    run_time_s: float
    size_bytes: int


def _operator_weight(op: Operator) -> int:
    """Number of passes the operator makes over its inputs
    (reference: WeightedOperator.scala; e.g. BCD weight = 3·numIter+1)."""
    w = getattr(op, "weight", 1)
    try:
        return max(1, int(w))
    except (TypeError, ValueError):
        return 1


def _estimate_bytes(value) -> int:
    """Materialized size of a node output."""
    if isinstance(value, ArrayDataset):
        import jax

        return sum(a.size * a.dtype.itemsize for a in jax.tree_util.tree_leaves(value.data))
    if isinstance(value, ObjectDataset):
        total = 0
        for item in value.collect():
            if isinstance(item, np.ndarray):
                total += item.nbytes
            elif isinstance(item, (bytes, str)):
                total += len(item)
            else:
                total += 64  # flat object estimate, matches SizeEstimator's role
        return total
    return 64


def _fit_linear_coeffs(
    samples: List[SampleProfile],
) -> Tuple[float, float, float, float]:
    """Per-metric linear-fit coefficients ``(t0, t1, b0, b1)`` in scale —
    the REUSABLE form of a profile: plain floats that JSON-round-trip
    exactly, so a profile persisted by one process evaluates to the
    byte-identical :class:`Profile` in the next."""
    if len(samples) == 1:
        s = samples[0]
        scale = max(1, s.scale)
        return (0.0, s.run_time_s / scale, 0.0, s.size_bytes / scale)
    xs = np.array([[1.0, s.scale] for s in samples])
    times = np.array([s.run_time_s for s in samples])
    sizes = np.array([float(s.size_bytes) for s in samples])
    t_coef, *_ = np.linalg.lstsq(xs, times, rcond=None)
    s_coef, *_ = np.linalg.lstsq(xs, sizes, rcond=None)
    return (
        float(t_coef[0]), float(t_coef[1]), float(s_coef[0]), float(s_coef[1])
    )


def _profile_from_coeffs(
    coeffs: Tuple[float, float, float, float], full_n: int
) -> Profile:
    t = coeffs[0] + coeffs[1] * full_n
    b = coeffs[2] + coeffs[3] * full_n
    return Profile(max(t, 0.0), max(int(b), 0))


def _fit_linear(samples: List[SampleProfile], full_n: int) -> Profile:
    """Per-metric linear fit in scale, evaluated at full scale
    (reference: AutoCacheRule.scala:104-135 ``X \\ y``)."""
    return _profile_from_coeffs(_fit_linear_coeffs(samples), full_n)


class _ProfilingInterpreter:
    """Executes the plan with bound datasets truncated to ``scale`` rows,
    timing each node (the analog of the reference's per-node sample
    profiling, AutoCacheRule.scala:153-465)."""

    def __init__(self, graph: Graph, scale: int, clock=time.perf_counter):
        self.graph = graph
        self.scale = scale
        self.clock = clock
        self.times: Dict[NodeId, float] = {}
        self.sizes: Dict[NodeId, int] = {}
        self._memo: Dict = {}

    def execute(self, graph_id):
        if graph_id in self._memo:
            return self._memo[graph_id]
        if isinstance(graph_id, SourceId):
            raise ValueError("unbound source")
        if isinstance(graph_id, SinkId):
            return self.execute(self.graph.get_sink_dependency(graph_id))
        op = self.graph.get_operator(graph_id)
        if isinstance(op, DatasetOperator):
            result = _truncate(op.dataset, self.scale)
        else:
            deps = [self.execute(d) for d in self.graph.get_dependencies(graph_id)]
            expressions = [wrap_expression(d) for d in deps]
            start = self.clock()
            result = op.execute(expressions).get()
            _block(result)
            self.times[graph_id] = self.clock() - start
            if isinstance(result, Dataset):
                self.sizes[graph_id] = _estimate_bytes(result)
        self._memo[graph_id] = result
        return result


def _truncate(dataset: Dataset, n: int) -> Dataset:
    if len(dataset) <= n:
        return dataset
    if isinstance(dataset, ArrayDataset):
        import jax

        return ArrayDataset(jax.tree_util.tree_map(lambda a: a[:n], dataset.data), num_examples=n)
    return ObjectDataset(dataset.take(n))


def _block(value) -> None:
    """Force device work so timings are real."""
    if isinstance(value, ArrayDataset):
        import jax

        jax.block_until_ready(value.data)


class AutoCacheRule(Rule):
    """Insert ``CacherOperator`` nodes minimizing estimated runtime under an
    HBM budget (reference: AutoCacheRule.scala:12-664)."""

    def __init__(
        self,
        budget_bytes: Optional[int] = None,
        strategy: str = "greedy",
        profile_scales: Tuple[int, ...] = (2, 4),
        num_trials: int = 1,
        clock=time.perf_counter,
        profile_store="auto",
    ):
        assert strategy in ("greedy", "aggressive")
        self.budget_bytes = budget_bytes
        self.strategy = strategy
        self.profile_scales = profile_scales
        self.num_trials = num_trials
        # Injectable timer: profile-driven tests replace the wall clock
        # with a deterministic fake so cache choices don't depend on
        # machine load.
        self.clock = clock
        # Persistent profile store (docs/OBSERVABILITY.md): "auto" uses
        # the process store (None when KEYSTONE_PROFILE_STORE=off), None
        # disables warm-starting for this rule, an instance pins one.
        self.profile_store = profile_store

    def _store(self):
        if self.profile_store == "auto":
            return _store.get_store()
        return self.profile_store

    # ------------------------------------------------------------- structure
    def _dependents(self, graph: Graph) -> Dict[NodeId, List]:
        """node → list of (dependent node-or-sink) — the shared
        :meth:`Graph.dependents` view, also used by the fusion pass."""
        return graph.dependents()

    def _candidates(self, graph: Graph, dependents: Dict[NodeId, List]) -> List[NodeId]:
        """Nodes worth caching: dataset-producing, used more than once when
        downstream weights are counted (reference: AutoCacheRule.scala
        ``nodesToCache`` — reused non-cached dataset outputs)."""
        from ..ops.util.misc import CacherOperator

        result = []
        for node in sorted(graph.nodes):
            op = graph.get_operator(node)
            if isinstance(op, (DatasetOperator, DatumOperator, CacherOperator, EstimatorOperator)):
                continue
            deps = dependents[node]
            uses = 0
            for d in deps:
                if isinstance(d, SinkId):
                    uses += 1
                else:
                    child_op = graph.get_operator(d)
                    if isinstance(child_op, CacherOperator):
                        uses = 0  # already cached
                        break
                    uses += _operator_weight(child_op)
            if uses > 1:
                result.append(node)
        return result

    # ------------------------------------------------------------- profiling
    def _profiled_nodes(self, graph: Graph) -> List[NodeId]:
        """The nodes sample-profiling will time: SOURCE-FREE operator
        nodes in the ancestry of any sink. Source-dependent branches (the
        delegating apply path of a ``with_data`` pipeline) are excluded
        rather than aborting the whole profile — the fit-cost subgraph is
        exactly the source-free part."""
        live: set = set()
        for sink in graph.sinks:
            live |= get_ancestors(graph, sink)
            live.add(graph.get_sink_dependency(sink))
        out: List[NodeId] = []
        for node in sorted(n for n in live if isinstance(n, NodeId)):
            if any(
                isinstance(a, SourceId) for a in get_ancestors(graph, node)
            ):
                continue
            if isinstance(graph.get_operator(node), DatasetOperator):
                continue
            out.append(node)
        return out

    def _node_digests(
        self, graph: Graph, nodes: List[NodeId]
    ) -> Optional[Dict[NodeId, str]]:
        """Cross-process stable digest per node (structural prefix +
        content-hashed operator state — the checkpoint layer's key), or
        None when any node can't be digested (store is then skipped)."""
        from ..reliability.checkpoint import prefix_digest, token_memo
        from .prefix import find_prefix

        digests: Dict[NodeId, str] = {}
        try:
            # One memo for the whole pass: every prefix re-tokenizes the
            # same DatasetOperator, and without the memo each node pays a
            # full content hash of the training data.
            with token_memo():
                for node in nodes:
                    prefix = find_prefix(graph, node)
                    if prefix is None:
                        return None
                    digests[node] = prefix_digest(prefix)
        except Exception:
            return None
        return digests

    def _profile(self, graph: Graph) -> Dict[NodeId, Profile]:
        """Profile EVERY executed node, not just cache candidates: caching a
        shared node also saves recomputing its whole (possibly expensive)
        ancestry, and the cost model must see those ancestor times.

        With a persistent profile store attached, a plan whose every node
        has a fresh stored profile (same structural digest, shape class,
        backend, environment fingerprint, and full row count) skips
        sample execution entirely and rebuilds byte-identical profiles
        from the stored linear-fit coefficients; a cold plan records its
        coefficients back so the NEXT process skips."""
        full_n = max(
            (len(graph.get_operator(n).dataset) for n in graph.nodes
             if isinstance(graph.get_operator(n), DatasetOperator)),
            default=0,
        )
        if full_n == 0:
            return {}
        targets = self._profiled_nodes(graph)
        if not targets:
            return {}

        store = self._store()
        digests: Optional[Dict[NodeId, str]] = None
        sc = _store.shape_class(full_n)
        if store is not None:
            digests = self._node_digests(graph, targets)
        if store is not None and digests is not None:
            warm: Optional[Dict[NodeId, Profile]] = {}
            for node in targets:
                m = store.lookup(f"autocache:{digests[node]}", sc)
                # An entry only covers this plan when it was measured
                # under the SAME profiling config: coefficients fit from
                # different sample scales/trial counts are different
                # measurements, and reusing them would make a
                # reconfigured rule silently inert.
                if (
                    m is None
                    or m.get("full_n") != full_n
                    or m.get("scales") != str(self.profile_scales)
                    or m.get("trials") != self.num_trials
                ):
                    warm = None
                    break
                warm[node] = _profile_from_coeffs(
                    (m["t0"], m["t1"], m["b0"], m["b1"]), full_n
                )
            if warm is not None:
                _spans.add_span_event(
                    "autocache_profile_store", nodes=len(warm), full_n=full_n
                )
                self._note_predictions(graph, warm, digests, sc)
                return warm

        samples: Dict[NodeId, List[SampleProfile]] = {}
        t_profile = time.perf_counter()
        with _spans.span(
            "autocache:profile", scales=str(self.profile_scales), full_n=full_n
        ):
            for scale in self.profile_scales:
                for _ in range(self.num_trials):
                    interp = _ProfilingInterpreter(graph, scale, clock=self.clock)
                    try:
                        for node in targets:
                            interp.execute(node)
                    except Exception as e:
                        # unbound sources etc.: no profile, no caching
                        logging.getLogger(__name__).warning(
                            "auto-cache profiling failed (%s): running without "
                            "cache planning", e,
                        )
                        return {}
                    for n, t in interp.times.items():
                        samples.setdefault(n, []).append(
                            SampleProfile(scale, t, interp.sizes.get(n, 0))
                        )
        _names.metric(_names.AUTOCACHE_PROFILE_SECONDS).observe(
            time.perf_counter() - t_profile
        )
        coeffs = {n: _fit_linear_coeffs(obs) for n, obs in samples.items() if obs}
        profiles = {
            n: _profile_from_coeffs(c, full_n) for n, c in coeffs.items()
        }
        if store is not None and digests is not None:
            for n, c in coeffs.items():
                store.record(
                    f"autocache:{digests[n]}",
                    sc,
                    full_n=full_n,
                    scales=str(self.profile_scales),
                    trials=self.num_trials,
                    t0=c[0], t1=c[1], b0=c[2], b1=c[3],
                    run_time_s=profiles[n].run_time_s,
                    size_bytes=profiles[n].size_bytes,
                )
        self._note_predictions(graph, profiles, digests, sc)
        return profiles

    def _note_predictions(
        self,
        graph: Graph,
        profiles: Dict[NodeId, Profile],
        digests: Optional[Dict[NodeId, str]],
        sc: str,
    ) -> None:
        """Publish each profiled node's predicted full-scale runtime into
        the cost observatory's plan book (obs/cost.py) — the ledger
        joins them to the measured walls ``timed_execute`` records, and
        the drift sentinel scores them (a warm-started profile is the
        canonical silent-staleness hazard: it skips re-measurement
        entirely). Label-keyed best-effort attribution; no-op when the
        observatory is off."""
        if digests is None or not _cost.cost_observatory_enabled():
            return
        for node, profile in profiles.items():
            digest = digests.get(node)
            if digest is None:
                continue
            op = graph.get_operator(node)
            _cost.note_plan_prediction(
                str(getattr(op, "label", type(op).__name__)),
                _cost.Prediction(
                    model="autocache",
                    key=f"autocache:{digest}",
                    shape=sc,
                    seconds=profile.run_time_s,
                    calibrated=True,
                ),
            )

    # ------------------------------------------------------------- cost model
    def _estimate_runtime(
        self,
        graph: Graph,
        dependents: Dict[NodeId, List],
        profiles: Dict[NodeId, Profile],
        cached: Set[NodeId],
    ) -> float:
        """Σ runs(n)·time(n) where runs counts weighted recomputations
        (reference: AutoCacheRule.scala ``estimateCachedRunTime``/``getRuns``)."""
        runs: Dict[NodeId, float] = {}

        def get_runs(node: NodeId) -> float:
            if node in runs:
                return runs[node]
            total = 0.0
            for d in dependents.get(node, []):
                if isinstance(d, SinkId):
                    total += 1.0
                else:
                    total += get_runs(d) * _operator_weight(graph.get_operator(d))
            total = max(total, 1.0)
            if node in cached:
                total = 1.0
            runs[node] = total
            return total

        return sum(get_runs(n) * p.run_time_s for n, p in profiles.items())

    def _greedy_select(
        self,
        graph: Graph,
        dependents: Dict[NodeId, List],
        profiles: Dict[NodeId, Profile],
        candidates: List[NodeId],
        budget: int,
    ) -> Set[NodeId]:
        """Greedy knapsack: repeatedly cache the node with the best
        runtime-saving that still fits (reference: AutoCacheRule.scala
        ``greedyCache``)."""
        cached: Set[NodeId] = set()
        used = 0
        remaining = {n for n in candidates if n in profiles}
        current = self._estimate_runtime(graph, dependents, profiles, cached)
        while remaining:
            best, best_time = None, current
            for n in sorted(remaining):
                if used + profiles[n].size_bytes > budget:
                    continue
                t = self._estimate_runtime(graph, dependents, profiles, cached | {n})
                if t < best_time:
                    best, best_time = n, t
            if best is None:
                break
            cached.add(best)
            used += profiles[best].size_bytes
            current = best_time
            remaining.discard(best)
        return cached

    # --------------------------------------------------------------- rewrite
    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        from ..ops.util.misc import CacherOperator
        from ..parallel.mesh import device_memory_budget_bytes
        from .fusion import FusedTransformerOperator

        # Ordering contract (docs/OPTIMIZER.md): cache planning must see
        # REAL node boundaries — the standard stacks run fusion strictly
        # after this rule, keeping cache decisions byte-identical to
        # pre-fusion plans. A custom stack that fused first would have
        # this planner profiling synthetic merged nodes (it still works,
        # but candidate boundaries inside fused chains are gone) — warn
        # so the mis-ordering is visible.
        if any(
            isinstance(op, FusedTransformerOperator)
            for op in graph.operators.values()
        ):
            logging.getLogger(__name__).warning(
                "AutoCacheRule running on an already-fused graph: cache "
                "planning cannot see boundaries inside fused chains; run "
                "fusion after auto-cache (the default optimizer ordering)"
            )

        dependents = self._dependents(graph)
        candidates = self._candidates(graph, dependents)
        if not candidates:
            return graph, prefixes

        if self.strategy == "aggressive":
            selected = set(candidates)
        else:
            profiles = self._profile(graph)
            if not profiles:
                return graph, prefixes
            budget = (
                self.budget_bytes
                if self.budget_bytes is not None
                else device_memory_budget_bytes()
            )
            selected = self._greedy_select(graph, dependents, profiles, candidates, budget)

        if selected:
            _names.metric(_names.AUTOCACHE_CACHED_NODES).inc(len(selected))
            _spans.add_span_event(
                "autocache_selected",
                nodes=len(selected),
                strategy=self.strategy,
            )
        for node in sorted(selected):
            graph = _insert_cacher_after(graph, node, CacherOperator(level="hbm"))
        return graph, prefixes


def _insert_cacher_after(graph: Graph, node: NodeId, cacher) -> Graph:
    """Splice ``node -> cacher`` and repoint every other consumer of ``node``
    at the cacher (reference: AutoCacheRule.scala ``addCachesToPipeline``)."""
    graph, cache_node = graph.add_node(cacher, [node])
    for other in list(graph.nodes):
        if other == cache_node:
            continue
        deps = graph.get_dependencies(other)
        if node in deps:
            graph = graph.set_dependencies(
                other, [cache_node if d == node else d for d in deps]
            )
    for sink in graph.sinks:
        if graph.get_sink_dependency(sink) == node:
            graph = graph.set_sink_dependency(sink, cache_node)
    return graph
