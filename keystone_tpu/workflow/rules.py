"""Rewrite-rule engine + the standard optimizer stacks.

TPU-native re-design of the reference's Catalyst-style planner
(reference: workflow/Rule.scala:11-19, workflow/RuleExecutor.scala:5-88,
workflow/DefaultOptimizer.scala:8-26, workflow/EquivalentNodeMergeRule.scala:13-48,
workflow/UnusedBranchRemovalRule.scala:7-24, workflow/SavedStateLoadRule.scala:7-20,
workflow/ExtractSaveablePrefixes.scala:9-22).

Rules rewrite ``(Graph, prefix-map)`` pairs. The prefix map marks nodes whose
results should be persisted to the process-wide state table after execution,
enabling cross-pipeline reuse of fit estimator work.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..obs import names as _names
from ..obs import spans as _spans
from .analysis import get_ancestors
from .graph import Graph, NodeId, SinkId, SourceId
from .operators import DelegatingOperator, EstimatorOperator, ExpressionOperator
from .prefix import Prefix, find_prefix

logger = logging.getLogger(__name__)

PrefixMap = Dict[NodeId, Prefix]


class Rule:
    """One graph rewrite. Must be pure: returns new (graph, prefixes)."""

    @property
    def name(self) -> str:
        return type(self).__name__

    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        raise NotImplementedError


@dataclass
class Batch:
    """A named group of rules run once or to fixed point."""

    name: str
    rules: Sequence[Rule]
    fixed_point: bool = False
    max_iterations: int = 100


class RuleExecutor:
    """Runs batches in order; fixed-point batches iterate until stable."""

    def __init__(self, batches: Sequence[Batch]):
        self.batches = list(batches)

    def execute(self, graph: Graph, prefixes: Optional[PrefixMap] = None) -> Tuple[Graph, PrefixMap]:
        runs_c = _names.metric(_names.RULE_RUNS)
        rewrites_c = _names.metric(_names.RULE_REWRITES)
        prefixes = dict(prefixes or {})
        t0 = time.perf_counter()
        with _spans.span("optimize:rules", batches=len(self.batches)):
            for batch in self.batches:
                iterations = batch.max_iterations if batch.fixed_point else 1
                with _spans.span(f"optimize:batch:{batch.name}"):
                    for _ in range(iterations):
                        before = graph
                        for rule in batch.rules:
                            new_graph, prefixes = rule.apply(graph, prefixes)
                            runs_c.inc(rule=rule.name)
                            if new_graph != graph:
                                rewrites_c.inc(rule=rule.name)
                                _spans.add_span_event(
                                    "rule_rewrite", rule=rule.name
                                )
                                if logger.isEnabledFor(logging.DEBUG):
                                    logger.debug(
                                        "rule %s rewrote graph:\n%s",
                                        rule.name, new_graph.to_dot(),
                                    )
                            graph = new_graph
                        if graph == before:
                            break
        _names.metric(_names.OPTIMIZE_SECONDS).observe(time.perf_counter() - t0)
        return graph, prefixes


# --------------------------------------------------------------------- rules


class EquivalentNodeMergeRule(Rule):
    """Common-subexpression elimination: merge nodes with equal operators and
    equal dependency lists, repeating until fixed point
    (reference: EquivalentNodeMergeRule.scala:13-48)."""

    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        while True:
            groups: Dict[Tuple, List[NodeId]] = {}
            for node in sorted(graph.nodes):
                op = graph.get_operator(node)
                try:
                    key = (op, graph.get_dependencies(node))
                    groups.setdefault(key, []).append(node)
                except TypeError:  # unhashable operator: never merged
                    continue
            merged_any = False
            for key, nodes in groups.items():
                if len(nodes) < 2:
                    continue
                keep, rest = nodes[0], nodes[1:]
                for node in rest:
                    graph = graph.replace_dependency(node, keep)
                    graph = graph.remove_node(node)
                    prefixes.pop(node, None)
                merged_any = True
            if not merged_any:
                return graph, prefixes


class UnusedBranchRemovalRule(Rule):
    """Prune nodes and sources that no sink transitively depends on
    (reference: UnusedBranchRemovalRule.scala:7-24)."""

    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        live = set()
        for sink in graph.sinks:
            live |= get_ancestors(graph, sink)
            live.add(graph.get_sink_dependency(sink))
        dead_nodes = [n for n in graph.nodes if n not in live]
        dead_sources = [s for s in graph.sources if s not in live]
        # Iteratively remove (a dead node may be referenced by another dead node).
        pending = set(dead_nodes)
        while pending:
            progressed = False
            for node in sorted(pending):
                try:
                    graph = graph.remove_node(node)
                except ValueError:
                    continue
                pending.discard(node)
                prefixes.pop(node, None)
                progressed = True
            if not progressed:  # pragma: no cover - cycle, should not happen
                break
        for source in dead_sources:
            try:
                graph = graph.remove_source(source)
            except ValueError:  # pragma: no cover
                pass
        return graph, prefixes


def _is_saveable(op) -> bool:
    from ..ops.util.misc import CacherOperator  # local import to avoid cycle

    return isinstance(op, (EstimatorOperator, CacherOperator))


class ExtractSaveablePrefixes(Rule):
    """Mark estimator and cacher nodes' prefixes for state-table persistence
    (reference: ExtractSaveablePrefixes.scala:9-22)."""

    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        out = dict(prefixes)
        for node in graph.nodes:
            if _is_saveable(graph.get_operator(node)):
                prefix = find_prefix(graph, node)
                if prefix is not None:
                    out[node] = prefix
        return graph, out


class SavedStateLoadRule(Rule):
    """Replace nodes whose prefix already has a stored result with an
    ExpressionOperator splice (reference: SavedStateLoadRule.scala:7-20)."""

    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        from .executor import PipelineEnv

        state = PipelineEnv.get_or_create().state
        for node, prefix in list(prefixes.items()):
            if prefix in state and node in graph.operators:
                graph = graph.set_operator(node, ExpressionOperator(state[prefix]))
                graph = graph.set_dependencies(node, [])
                del prefixes[node]
        return graph, prefixes


# ----------------------------------------------------------------- optimizers


def default_optimizer() -> RuleExecutor:
    """The standard stack: saved-state reuse → CSE → node-level optimization
    → chain fusion → streaming (reference: DefaultOptimizer.scala:8-26;
    fusion and streaming are TPU-native, docs/OPTIMIZER.md +
    docs/STREAMING.md). Fusion runs late so every structural decision
    upstream sees real node boundaries; streaming runs second-to-last so
    it can absorb already-fused chains into chunked fit plans; the
    measured-knob pass runs next-to-last so the StreamingFitOperator
    nodes it tunes from profile-store history already exist; the
    partition pass runs LAST so the mesh/sharding decision sees the
    final operators and knobs (docs/PARTITIONING.md)."""
    from .fusion import NodeFusionRule
    from .knobs import MeasuredKnobRule
    from .optimize import NodeOptimizationRule, PartitionPlanRule
    from .streaming import StreamingPlanRule

    return RuleExecutor(
        [
            Batch(
                "load-saved-state",
                [ExtractSaveablePrefixes(), SavedStateLoadRule(), UnusedBranchRemovalRule()],
            ),
            Batch("cse", [EquivalentNodeMergeRule()], fixed_point=True),
            Batch("node-level-optimization", [NodeOptimizationRule()]),
            Batch("fusion", [NodeFusionRule()]),
            Batch("streaming", [StreamingPlanRule()]),
            Batch("measured-knobs", [MeasuredKnobRule()]),
            Batch("partition", [PartitionPlanRule()]),
        ]
    )


def auto_caching_optimizer(budget_bytes: Optional[int] = None, strategy: str = "greedy") -> RuleExecutor:
    """Default stack plus profile-driven cache insertion
    (reference: DefaultOptimizer.scala AutoCachingOptimizer). Fusion runs
    AFTER cache insertion: the cache planner profiles and splices against
    real node boundaries, so its decisions are byte-identical to
    pre-fusion plans, and inserted Cacher nodes then act as hard fusion
    boundaries — and as streaming-chain boundaries for the streaming
    batch that follows (a stream starts from a Cacher's materialized
    output, never crosses it)."""
    from .autocache import AutoCacheRule
    from .fusion import NodeFusionRule
    from .knobs import MeasuredKnobRule
    from .optimize import NodeOptimizationRule, PartitionPlanRule
    from .streaming import StreamingPlanRule

    return RuleExecutor(
        [
            Batch(
                "load-saved-state",
                [ExtractSaveablePrefixes(), SavedStateLoadRule(), UnusedBranchRemovalRule()],
            ),
            Batch("cse", [EquivalentNodeMergeRule()], fixed_point=True),
            Batch("node-level-optimization", [NodeOptimizationRule()]),
            Batch("auto-cache", [AutoCacheRule(budget_bytes=budget_bytes, strategy=strategy)]),
            Batch("fusion", [NodeFusionRule()]),
            Batch("streaming", [StreamingPlanRule()]),
            Batch("measured-knobs", [MeasuredKnobRule()]),
            Batch("partition", [PartitionPlanRule()]),
        ]
    )
