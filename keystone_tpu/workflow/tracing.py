"""Per-operator execution tracing, now backed by the unified span layer.

The reference's observability is (1) per-rule DOT logging
(reference: workflow/RuleExecutor.scala:42-49) and (2) the AutoCacheRule
profiler that eagerly executes scaled samples under ``System.nanoTime``
(reference: workflow/AutoCacheRule.scala:153-465). This module adds the
per-op timeline the reference lacked — and since the observability PR it
is a thin compatibility shim over :mod:`keystone_tpu.obs.spans`:
``trace()`` opens a real :class:`~keystone_tpu.obs.spans.TraceSession`
with a ``pipeline`` root span, each forced operator becomes a
``node:<label>`` child span (exportable as a Chrome trace via
``obs.export``), and node wall times land in the
``keystone_executor_node_seconds`` histogram. The legacy
:class:`PipelineTrace` view (``timings`` / ``report()``) is preserved so
existing callers and tests keep working unchanged.

Timing forces each operator's lazy result (and on accelerators blocks on a
scalar fetch) — tracing is a profiling mode, not a zero-cost observer;
laziness across operators is preserved apart from the forcing. The same
forcing applies under an ``obs.spans`` session that declares
``sync_timings=True`` (the default, e.g. the ``keystone-tpu profile``
CLI) even when no ``trace()`` shim is active; a ``sync_timings=False``
session — and a metrics-registry-only run with no session at all —
skips the per-node sync entirely, preserving async dispatch between
nodes (spans then carry ``synced=False``).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, List, Optional

from ..obs import cost as _cost
from ..obs import names as _names
from ..obs import spans as _spans
from ..obs.device import device_annotation


@dataclass
class OpTiming:
    label: str
    seconds: float


@dataclass
class PipelineTrace:
    """Back-compat flat view of one traced run; ``session`` carries the
    underlying span session for callers that want the hierarchy."""

    timings: List[OpTiming] = field(default_factory=list)
    session: Optional[Any] = None  # obs.spans.TraceSession

    def record(self, label: str, seconds: float) -> None:
        self.timings.append(OpTiming(label, seconds))

    @property
    def total_seconds(self) -> float:
        return sum(t.seconds for t in self.timings)

    def report(self) -> str:
        """Pretty table, slowest first."""
        rows = sorted(self.timings, key=lambda t: -t.seconds)
        width = max([len("operator"), len("TOTAL")] + [len(t.label) for t in rows])
        lines = [f"{'operator':<{width}}  seconds"]
        for t in rows:
            lines.append(f"{t.label:<{width}}  {t.seconds:8.4f}")
        lines.append(f"{'TOTAL':<{width}}  {self.total_seconds:8.4f}")
        return "\n".join(lines)


_local = threading.local()


def current_trace() -> Optional[PipelineTrace]:
    return getattr(_local, "trace", None)


@contextmanager
def trace():
    """Context manager: trace all pipeline executions in this thread.

    >>> with trace() as t:
    ...     pipeline(data).get()
    >>> print(t.report())

    Also opens (or joins) an ``obs.spans`` tracing session with a
    ``pipeline`` root span, so ``t.session`` can be exported with
    ``obs.export.write_chrome_trace`` after the block.
    """
    prev = current_trace()
    tr = PipelineTrace()
    _local.trace = tr
    try:
        with _spans.tracing_session("pipeline") as session:
            tr.session = session
            with _spans.span("pipeline"):
                yield tr
    finally:
        _local.trace = prev


def _force(value: Any) -> None:
    """Force lazy/async results so timings measure real work.

    Datasets are unwrapped to their array pytree; device arrays are
    synced with block_until_ready plus a one-element host fetch (some
    accelerator relays only guarantee completion on a host readback)."""
    data = getattr(value, "data", value)  # ArrayDataset → pytree
    try:
        import jax
        import numpy as np

        leaves = [
            l for l in jax.tree_util.tree_leaves(data) if hasattr(l, "dtype")
        ]
        # This IS the sync primitive: every call site gates it behind
        # the session's sync_timings (timed_execute's `if sync:`).
        jax.block_until_ready(leaves)  # keystone: allow-sync
        for leaf in leaves[:1]:
            if leaf.size:
                np.asarray(leaf.ravel()[:1])  # scalar host fetch  # keystone: allow-sync
    except Exception:
        pass


def _node_seconds_hist():
    return _names.metric(_names.NODE_SECONDS)


def timed_execute(op, deps):
    """Execute ``op`` under the active trace/span session (or plainly if
    neither is active).

    The blocking device sync (:func:`_force`) runs only when someone
    actually needs real per-node timings — an active ``trace()`` shim or
    a span session with ``sync_timings`` (the default). A metrics-only
    run (no session) or a ``sync_timings=False`` session keeps async
    dispatch between nodes: spans/histograms then record dispatch time,
    flagged ``synced=False`` so a reader never mistakes it for work time.

    A fused chain (workflow/fusion.py) appears as ONE ``node:Fused[...]``
    span carrying the member labels as an attribute — the per-member
    spans collapse along with the dispatches.

    With the cost observatory enabled (obs/cost.py,
    ``KEYSTONE_COST_OBS``) each forcing additionally runs inside a
    harvest frame: operators note their jitted computations into it and
    the frame finalizes into a perf-ledger entry — predicted cost,
    measured wall, flop/byte facts, roofline placement — AFTER the wall
    measurement, so first-shape harvesting never inflates node timings.
    The entry's lowering digest lands on the span
    (``lowering_digest``), joining spans to ProfileStore keys
    deterministically — the fused-member-names attribute alone never
    identified the executable.
    """
    tr = current_trace()
    session = _spans.active_session()
    expression = op.execute(deps)
    cost_on = _cost.cost_observatory_enabled()
    if tr is None and session is None and not cost_on:
        return expression
    # Ledger-only runs (observatory on, no trace/session) keep async
    # dispatch: seconds then measures dispatch, marked synced=False so a
    # reader never mistakes it for work time.
    sync = tr is not None or (
        session is not None and getattr(session, "sync_timings", True)
    )
    label = str(getattr(op, "label", type(op).__name__))
    members = getattr(op, "member_labels", None)
    partition = getattr(op, "partition", None)
    frame = _cost.push_frame(label) if cost_on else None
    with _spans.span(f"node:{label}", op=type(op).__name__) as sp:
        if members is not None:
            sp.set_attribute("fused_members", ",".join(members))
        if partition is not None and getattr(partition, "eligible", False):
            # The partitioner's pinned decision, on the node's own span:
            # a sharded fit is identifiable in any trace without
            # cross-referencing the plan report (docs/PARTITIONING.md).
            sp.set_attribute(
                "mesh_shape", "x".join(str(s) for s in partition.mesh_shape)
            )
            sp.set_attribute("partition_spec", partition.spec)
            sp.set_attribute(
                "model_shards",
                int(getattr(partition, "model_shards", 1) or 1),
            )
        try:
            if frame is not None:
                # Compile events during the forcing mark the wall as
                # cold: compile-inflated timings never anchor or score
                # the drift sentinel (obs/cost.py).
                from ..utils.compilation_cache import compile_count

                compiles_before = compile_count()
            with device_annotation(f"keystone/node/{label}"):
                start = time.perf_counter()
                value = expression.get()
                if sync:
                    _force(value)
                seconds = time.perf_counter() - start
        finally:
            if frame is not None:
                frame.compiles = compile_count() - compiles_before
                _cost.pop_frame(frame)
        sp.set_attribute("seconds", round(seconds, 6))
        if not sync:
            sp.set_attribute("synced", False)
    if frame is not None:
        # Post-measurement: resolves noted computations to flop/byte
        # facts (jit trace-cache hits — zero backend compiles), joins
        # the plan's prediction, drift-scores, lands the ledger entry,
        # and back-fills the span's cost attributes.
        _cost.finalize_node(label, seconds, sync, op=op, span=sp, frame=frame)
    if tr is not None:
        tr.record(label, seconds)
    if tr is not None or session is not None:
        _node_seconds_hist().observe(seconds, op=label)
    return expression
