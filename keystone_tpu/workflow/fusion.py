"""Whole-pipeline XLA fusion: collapse transformer chains into one dispatch.

The executor launches every transformer node as its own XLA dispatch with
a host round-trip between nodes — and on relay-backed attachments the
round-trip dwarfs the kernel time (BENCH_r05 gram leg: 97.9 ms dispatch
vs 8.3 ms bf16 compute). This module closes that gap at the *plan* level:
:class:`NodeFusionRule` rewrites maximal chains of array-in/array-out
transformers (``BatchTransformer`` subclasses implementing
``apply_arrays``) into a single :class:`FusedTransformerOperator` whose
``apply_arrays`` composes the member functions inside ONE ``jax.jit`` —
so a k-node featurization chain costs one dispatch instead of k
dispatches + k host syncs, and every inter-member buffer lives entirely
inside the compiled computation where XLA frees/reuses it automatically
(the moral equivalent of donating each inter-node buffer; no buffer ever
returns to the host between members).

Fusion boundaries — nodes that always stay unfused:

- ``CacherOperator`` nodes: an auto-cache materialization point must stay
  a real node so its output is memoized/pinned (it is not a
  ``BatchTransformer``, so the type gate excludes it).
- Estimator fits and ``DelegatingOperator`` applications (fit-time
  control flow is host-side by design).
- Saveable-prefix cut points: any node in the optimizer's prefix map is
  about to have its result written to the process state table and must
  keep its own identity.
- Transformers that override ``apply``/``apply_batch`` with bespoke host
  behavior (e.g. ragged masked-descriptor encoders, sparse densifiers),
  or that set ``fusable = False`` (ops that manage their own sharding
  and dispatch, like the ring kernel mapper).

Ordering: fusion is the LAST optimizer batch — after auto-cache — so
cache decisions profile real node boundaries and remain byte-identical
to pre-fusion plans. ``Pipeline.fit`` applies the same rewrite to the
transformer-only fitted graph, so serving (``FittedPipeline.
compiled_apply`` + ``utils/aot.warm_buckets``) warms the *fused*
executable per shape bucket and keeps its zero-recompile-after-warmup
guarantee. See docs/OPTIMIZER.md.
"""

from __future__ import annotations

import logging
import threading
from contextlib import contextmanager
from typing import List, Optional, Sequence, Tuple

from ..envknobs import env_disabled
from ..obs import cost as _cost
from ..obs import names as _names
from .graph import Graph, NodeId, SinkId
from .operators import TransformerOperator
from .pipeline import BatchTransformer
from .rules import PrefixMap, Rule

logger = logging.getLogger(__name__)


# ------------------------------------------------------------------ enablement

# Tri-state: None → env default (on unless KEYSTONE_FUSION=off/0). Tests
# flip it with set_fusion_enabled / fusion_disabled to build unfused
# reference pipelines for parity checks.
_enabled: Optional[bool] = None
_enabled_lock = threading.Lock()


def fusion_enabled() -> bool:
    if _enabled is not None:
        return _enabled
    return not env_disabled("KEYSTONE_FUSION")


def set_fusion_enabled(value: Optional[bool]) -> None:
    """Force fusion on/off process-wide; ``None`` restores the env default."""
    global _enabled
    with _enabled_lock:
        _enabled = value


@contextmanager
def fusion_disabled():
    """Scoped off-switch (parity tests build the unfused reference here)."""
    global _enabled
    with _enabled_lock:
        prev = _enabled
        _enabled = False
    try:
        yield
    finally:
        with _enabled_lock:
            _enabled = prev


# ------------------------------------------------------------------- fusability


def _overrides(op, method: str) -> bool:
    return getattr(type(op), method, None) is not getattr(BatchTransformer, method)


def is_fusable(op) -> bool:
    """True when ``op``'s whole batch semantics are its ``apply_arrays``.

    Requires a ``BatchTransformer`` that (a) actually implements
    ``apply_arrays``, (b) does NOT override the generic ``apply`` /
    ``apply_batch`` wrappers (a bespoke override means the op does
    something the composed-array chain would silently skip — masked
    descriptors, sparse densification), and (c) has not opted out via
    ``fusable = False``.
    """
    if not isinstance(op, BatchTransformer):
        return False
    if not getattr(op, "fusable", True):
        return False
    if not _overrides(op, "apply_arrays"):
        return False
    if _overrides(op, "apply") or _overrides(op, "apply_batch"):
        return False
    return True


# ------------------------------------------------------------------ fused op


class FusedTransformerOperator(BatchTransformer):
    """One operator standing in for a chain of array transformers.

    ``apply_arrays`` composes the members' ``apply_arrays`` inside a
    single ``jax.jit``: one dispatch, one device round-trip, and every
    intermediate buffer stays device-side inside the compiled program
    (XLA aliases/frees them — none is ever materialized to a host-visible
    handle). The inherited :meth:`BatchTransformer.apply_batch` supplies
    the framework conventions exactly once for the whole chain (masked
    descriptors pass through, pad rows re-zeroed at the end — valid
    because ``apply_arrays`` is row-independent by contract, so
    once-at-the-end equals once-per-member).

    The jitted chain is built lazily (pickle-safe: the executable is
    dropped by ``__getstate__``) and increments
    ``keystone_fusion_compiles_total`` at trace time — once per new
    shape/dtype, never on cached executions — so the compilation-cache
    story covers fused executables too. Chains over the same member
    operator instances share one jitted callable through a bounded
    module cache: every optimizer run of an unfitted pipeline builds a
    fresh FusedTransformerOperator, and without sharing each apply would
    retrace + recompile the whole chain. If a member turns out not to be
    traceable after all, the chain falls back to eager composition
    (still one logical node, dispatch-fused no longer, logged once);
    runtime failures of the compiled chain (OOM, device errors)
    propagate — they are the caller's reliability layer's business, not
    a reason to silently unfuse.
    """

    _is_fused = True

    def __init__(self, members: Sequence[TransformerOperator]):
        flat: List[TransformerOperator] = []
        for m in members:
            # Re-fusing a fused node flattens instead of nesting.
            if isinstance(m, FusedTransformerOperator):
                flat.extend(m.members)
            else:
                flat.append(m)
        if len(flat) < 2:
            raise ValueError("FusedTransformerOperator needs >= 2 members")
        self.members = tuple(flat)
        self._jitted = None
        self._eager_fallback = False

    @property
    def label(self) -> str:
        return "Fused[" + "+".join(self.member_labels) + "]"

    @property
    def member_labels(self) -> Tuple[str, ...]:
        return tuple(
            str(getattr(m, "label", type(m).__name__)) for m in self.members
        )

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_jitted"] = None  # jitted callables don't pickle
        return state

    def _chain(self, x):
        for m in self.members:
            x = m.apply_arrays(x)
        return x

    def _compiled(self):
        if self._jitted is None:
            self._jitted = _shared_chain_jit(self.members)
        return self._jitted

    def apply_arrays(self, data):
        if self._eager_fallback:
            return self._chain(data)
        try:
            jitted = self._compiled()
            result = jitted(data)
            # Cost-observatory attribution (obs/cost.py): a single
            # thread-local read when no harvest frame is active (the
            # serving hot path); under an executor frame the fused
            # chain's flop/byte facts are harvested through the jit
            # trace cache at node finalize — zero extra compiles.
            _cost.note_jit_call("fused_chain", jitted, (data,))
            return result
        except _trace_error_types() as e:
            # A member that escaped the fusability gate (host-side value
            # branching, stale cached tracers) — degrade to the exact
            # eager semantics rather than failing the pipeline. ONLY
            # jax trace-construction failures land here: a runtime error
            # from the compiled chain (OOM, device fault, a TypeError
            # from a malformed payload) propagates so the reliability
            # layer sees it and the single-dispatch guarantee is never
            # silently dropped.
            value = self._chain(data)  # raises if the INPUT was the problem
            # The eager retry succeeded → the chain genuinely doesn't
            # trace; only now latch the fallback (a failing retry leaves
            # the operator fused for the next, valid batch). Evict the
            # shared jit too: the next fused operator built over these
            # same members must not fetch the known-broken callable and
            # pay the failing trace again.
            self._eager_fallback = True
            self._jitted = None
            _evict_chain_jit(self.members)
            logger.warning(
                "fused chain %s not jit-traceable (%s: %s); falling back to "
                "eager member-by-member composition",
                self.label, type(e).__name__, str(e)[:200],
            )
            return value


def _trace_error_types():
    import jax

    return (
        jax.errors.JAXTypeError,  # concretization / tracer-conversion
        jax.errors.UnexpectedTracerError,
    )


# One jitted callable per member-instance tuple, shared by every
# FusedTransformerOperator built over those instances: each optimizer run
# of an UNFITTED pipeline constructs a fresh fused operator, and a
# per-operator jit would retrace + recompile the identical chain on every
# apply. Keys are member ids; the cached value keeps strong refs to the
# members so ids can never be recycled while an entry lives. Bounded LRU
# for the same reason as linalg's ``_bcd_remat_fn`` cache: each entry
# pins a compiled executable AND its member operators (fitted weights),
# so retired chains must age out rather than accumulate — 32 entries
# comfortably covers live pipelines while bounding what eviction-lagged
# models can pin. (ModelRegistry itself keeps every published version
# for rollback, so in serving processes the registry, not this cache, is
# what holds retired models.)
_CHAIN_JIT_CACHE: "OrderedDict[Tuple[int, ...], Tuple[tuple, object]]" = None  # type: ignore
_CHAIN_JIT_MAX = 32
_chain_cache_lock = threading.Lock()


def _evict_chain_jit(members: tuple) -> None:
    with _chain_cache_lock:
        if _CHAIN_JIT_CACHE is not None:
            _CHAIN_JIT_CACHE.pop(tuple(id(m) for m in members), None)


def _shared_chain_jit(members: tuple):
    global _CHAIN_JIT_CACHE
    import jax

    key = tuple(id(m) for m in members)
    with _chain_cache_lock:
        if _CHAIN_JIT_CACHE is None:
            from collections import OrderedDict

            _CHAIN_JIT_CACHE = OrderedDict()
        hit = _CHAIN_JIT_CACHE.get(key)
        if hit is not None:
            _CHAIN_JIT_CACHE.move_to_end(key)
            return hit[1]

    compiles_c = _names.metric(_names.FUSION_COMPILES)

    def fused_chain(x):
        # Trace-time side effect: fires once per new shape/dtype, never
        # on cached executions — the fused-compile counter.
        compiles_c.inc()
        for m in members:
            x = m.apply_arrays(x)
        return x

    jitted = jax.jit(fused_chain)
    with _chain_cache_lock:
        _CHAIN_JIT_CACHE[key] = (members, jitted)
        _CHAIN_JIT_CACHE.move_to_end(key)
        while len(_CHAIN_JIT_CACHE) > _CHAIN_JIT_MAX:
            _CHAIN_JIT_CACHE.popitem(last=False)
    return jitted


# --------------------------------------------------------------------- the rule


class NodeFusionRule(Rule):
    """Rewrite maximal fusable chains into single fused nodes.

    A chain ``v1 → v2 → … → vk`` (k ≥ 2) qualifies when every member is
    fusable (:func:`is_fusable`), unary, outside the prefix map, and each
    interior member's ONLY consumer is its successor (a second consumer —
    node or sink — needs the intermediate value on the host side of the
    fused program, so the chain is cut there). The final member may fan
    out freely: its consumers are repointed at the fused node.
    """

    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        if not fusion_enabled():
            return graph, prefixes
        chains = _find_chains(graph, prefixes)
        if not chains:
            return graph, prefixes
        members_total = 0
        for chain in chains:
            graph = _fuse_chain(graph, chain)
            members_total += len(chain)
        _names.metric(_names.FUSION_CHAINS).inc(len(chains))
        _names.metric(_names.FUSION_FUSED_NODES).inc(members_total)
        _names.metric(_names.FUSION_DISPATCHES_SAVED).inc(
            members_total - len(chains)
        )
        return graph, prefixes


def _find_chains(graph: Graph, prefixes: PrefixMap) -> List[List[NodeId]]:
    dependents = graph.dependents()

    def fusable(node: NodeId) -> bool:
        return (
            node not in prefixes  # saveable-prefix cut point
            and len(graph.get_dependencies(node)) == 1
            and is_fusable(graph.get_operator(node))
        )

    def sole_successor(node: NodeId) -> Optional[NodeId]:
        deps = dependents.get(node, [])
        if len(deps) != 1 or isinstance(deps[0], SinkId):
            return None
        (succ,) = deps
        if fusable(succ) and graph.get_dependencies(succ) == (node,):
            return succ
        return None

    chains: List[List[NodeId]] = []
    consumed = set()
    for node in sorted(graph.nodes):
        if node in consumed or not fusable(node):
            continue
        # Only start at a chain head: a fusable predecessor would have
        # already absorbed this node.
        (dep,) = graph.get_dependencies(node)
        if (
            isinstance(dep, NodeId)
            and dep not in consumed
            and fusable(dep)
            and sole_successor(dep) == node
        ):
            continue
        chain = [node]
        nxt = sole_successor(node)
        while nxt is not None:
            chain.append(nxt)
            nxt = sole_successor(chain[-1])
        if len(chain) >= 2:
            chains.append(chain)
            consumed.update(chain)
    return chains


def _fuse_chain(graph: Graph, chain: List[NodeId]) -> Graph:
    ops = [graph.get_operator(n) for n in chain]
    deps0 = graph.get_dependencies(chain[0])
    graph, fused_node = graph.add_node(FusedTransformerOperator(ops), deps0)
    graph = graph.replace_dependency(chain[-1], fused_node)
    for node in reversed(chain):
        graph = graph.remove_node(node)
    return graph


def fuse_graph(graph: Graph, prefixes: Optional[PrefixMap] = None) -> Graph:
    """Apply :class:`NodeFusionRule` directly to a graph (``Pipeline.fit``
    fuses the transformer-only fitted graph this way; the serving
    registry re-fuses artifacts saved unfused)."""
    out, _ = NodeFusionRule().apply(graph, dict(prefixes or {}))
    return out
