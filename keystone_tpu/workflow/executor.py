"""Pull-based memoized graph execution + the process-wide pipeline env.

TPU-native re-design of the reference's interpreter
(reference: workflow/GraphExecutor.scala:14-81, workflow/PipelineEnv.scala:7-37).

``GraphExecutor`` optimizes its graph once (on first pull), then recursively
executes dependencies with memoization. Results are lazy ``Expression``s:
forcing a ``DatasetExpression``'s ``get`` is what actually runs XLA
computations, exactly as forcing an RDD ran Spark jobs in the reference.

``PipelineEnv`` holds the prefix-state table used for cross-pipeline reuse
of fit estimators and cached datasets, plus the active optimizer stack and
the reliability hooks (retry policy, checkpoint store) the executor
consults per node — see keystone_tpu/reliability/ and docs/RELIABILITY.md.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..obs import names as _names
from ..obs import spans as _spans
from ..reliability import faultinject
from ..reliability.recovery import reset_recovery_log
from .graph import Graph, GraphId, NodeId, SinkId, SourceId
from .operators import EstimatorOperator, Expression
from .prefix import Prefix, find_prefix
from .tracing import timed_execute


def _executor_counters():
    """Resolve the executor's always-on counters (schema-driven). Cached
    per GraphExecutor (executors are per-application, so a test-time
    registry reset can't strand handles for long)."""
    return (
        _names.metric(_names.NODES_EXECUTED),
        _names.metric(_names.MEMO_HITS),
        _names.metric(_names.AUTOCACHE_HITS),
        _names.metric(_names.AUTOCACHE_MISSES),
    )


def _is_cacher(op) -> bool:
    from ..ops.util.misc import CacherOperator

    return isinstance(op, CacherOperator)


class PipelineEnv:
    """Process-wide executor state (reference: PipelineEnv.scala:7-37)."""

    _instance: Optional["PipelineEnv"] = None
    _lock = threading.Lock()

    def __init__(self):
        self.state: Dict[Prefix, Expression] = {}
        self._optimizer = None
        # Reliability hooks — both default OFF (zero per-node overhead).
        # retry_policy: a reliability.RetryPolicy applied to every node
        # forcing (transient faults retried, per-node deadline enforced).
        # checkpoint: a reliability.CheckpointStore; estimator fits write
        # through and digest-matching fits restore instead of refitting.
        self.retry_policy = None
        self.checkpoint = None

    @classmethod
    def get_or_create(cls) -> "PipelineEnv":
        with cls._lock:
            if cls._instance is None:
                cls._instance = PipelineEnv()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        """Drop all global state — required between tests
        (reference: test fixture PipelineContext.scala:9-25). Clears the
        recovery ledger too: it is per-run state like the prefix table."""
        with cls._lock:
            cls._instance = None
        reset_recovery_log()

    @property
    def optimizer(self):
        if self._optimizer is None:
            from .rules import default_optimizer

            self._optimizer = default_optimizer()
        return self._optimizer

    @optimizer.setter
    def optimizer(self, value) -> None:
        self._optimizer = value


class GraphExecutor:
    """Memoized recursive interpreter over an (optionally optimized) graph."""

    def __init__(self, graph: Graph, optimize: bool = True):
        self._raw_graph = graph
        self._optimize = optimize
        self._optimized: Optional[Graph] = None
        self._prefixes: Dict[NodeId, Prefix] = {}
        self._memo: Dict[GraphId, Expression] = {}
        self._counters = None  # resolved lazily, once per executor
        #: Partition decisions the planner recorded for THIS plan
        #: (parallel/partitioner.py), captured at optimize time — a
        #: stable per-executor snapshot for programmatic consumers that
        #: outlive later optimizer runs (the global
        #: ``last_partition_report()`` describes only the LAST plan).
        #: Pinned by tests/workflow/test_partition.py.
        self.partition_decisions: list = []

    @property
    def graph(self) -> Graph:
        """The optimized graph (optimizes on first access)."""
        if self._optimized is None:
            if self._optimize:
                from ..parallel.partitioner import (
                    last_partition_report,
                    partition_report_generation,
                )

                env = PipelineEnv.get_or_create()
                generation = partition_report_generation()
                with _spans.span("optimize"):
                    self._optimized, self._prefixes = env.optimizer.execute(
                        self._raw_graph
                    )
                # Only adopt the report if THIS optimize ran a partition
                # batch (the reset bumps the generation) — a custom
                # stack without one must not inherit a previous plan's
                # decisions. (Optimizer runs are process-serial in
                # practice; concurrent optimizes would interleave the
                # global report either way.)
                if partition_report_generation() != generation:
                    self.partition_decisions = last_partition_report()
            else:
                self._optimized = self._raw_graph
        return self._optimized

    @property
    def raw_graph(self) -> Graph:
        return self._raw_graph

    def execute(self, graph_id: GraphId) -> Expression:
        graph = self.graph
        if self._counters is None:
            self._counters = _executor_counters()
        nodes_c, memo_c, cache_hit_c, cache_miss_c = self._counters
        if graph_id in self._memo:
            # Memo hits are the executor-level reuse signal; hits on Cacher
            # nodes specifically are the auto-cache planner's payoff (each
            # one is a recomputation of the cached subtree avoided).
            if isinstance(graph_id, NodeId):
                memo_c.inc()
                if _is_cacher(graph.get_operator(graph_id)):
                    cache_hit_c.inc()
            return self._memo[graph_id]
        if isinstance(graph_id, SourceId):
            raise ValueError(
                f"cannot execute unbound source {graph_id}: bind pipeline inputs first"
            )
        if isinstance(graph_id, SinkId):
            result = self.execute(graph.get_sink_dependency(graph_id))
            self._memo[graph_id] = result
            return result

        deps = [self.execute(d) for d in graph.get_dependencies(graph_id)]
        op = graph.get_operator(graph_id)
        nodes_c.inc()
        if _is_cacher(op):
            cache_miss_c.inc()
        expression = timed_execute(op, deps)

        prefix = self._prefixes.get(graph_id)
        expression = _wrap_reliability(op, deps, expression, prefix)

        # Prefix write-back: make this node's result reusable by later
        # pipelines (reference: GraphExecutor.scala:65-71).
        if prefix is not None:
            PipelineEnv.get_or_create().state[prefix] = expression

        self._memo[graph_id] = expression
        return expression


def _wrap_reliability(
    op, deps, expression: Expression, prefix: Optional[Prefix]
) -> Expression:
    """Layer the reliability hooks around a node's lazy result.

    Expressions are call-by-name memoized and a failing thunk leaves the
    memo unset, so re-forcing after a failure genuinely re-executes — which
    is what makes wrapping the *expression* (not the eager execute call)
    the right retry boundary: the heavy work happens at force time.

    Wrapping order, innermost out:
      1. fault injection — stands in for the op itself failing;
      2. checkpoint — a digest hit skips the op (and any injected faults:
         restored work is not re-executed, same as lineage recovery);
      3. retry + per-node deadline — sees injected and real faults alike.
    All three default off; with none active the original expression is
    returned untouched.

    Each attempt executes the op FRESH (``op.execute`` is cheap — it only
    builds lazy thunks; deps stay memoized) rather than re-entering the
    shared Expression: after a deadline abandonment the watchdog thread
    may still be inside the old expression's ``get`` holding its memo
    lock, and a retry re-entering it would block behind the hung attempt
    (``Expression.get`` is lock-guarded, so the race is gone — but the
    hang would remain). The wrapper expression below memoizes the one
    successful result for all downstream readers.
    """
    env = PipelineEnv.get_or_create()
    injector = faultinject.current()
    policy = env.retry_policy
    store = env.checkpoint
    checkpointable = (
        store is not None and prefix is not None and isinstance(op, EstimatorOperator)
    )
    if injector is None and policy is None and not checkpointable:
        return expression

    label = str(getattr(op, "label", type(op).__name__))
    first = expression

    def thunk(_first=[first]):
        # First attempt consumes the already-built expression; retries get
        # a fresh one (see docstring).
        inner = _first.pop() if _first else timed_execute(op, deps)
        return inner.get()

    if injector is not None:
        thunk = injector.wrap(label, thunk)
    if checkpointable:
        inner_thunk = thunk
        thunk = lambda: store.get_or_compute(prefix, inner_thunk, label=label)  # noqa: E731
    if policy is not None:
        attempt = thunk
        thunk = lambda: policy.call(attempt, label=label)  # noqa: E731
    return type(expression)(thunk)
