"""Plan-time static verification: shapes, dtypes, and feasibility before
any data touches a device.

KeystoneML's signature move is reasoning about the whole pipeline before
executing it — the optimizer inspects the DAG to plan caching and
solvers. This module extends that plan-time reasoning to *correctness
and feasibility*: an abstract interpreter propagates
``jax.ShapeDtypeStruct`` specs through the (optimized) graph via
``jax.eval_shape`` — pure tracing, ZERO device execution and ZERO XLA
compiles — and emits :class:`Diagnostic`s with severities for the
failure classes that today only surface deep inside a jit trace at fit
time, or as a steady-state recompile in serving:

========  ========  ====================================================
code      severity  meaning
========  ========  ====================================================
KV101     error     shape/dtype mismatch at a node boundary
KV102     warning   silent float64 widening introduced by a node
KV201     info      fusion-ineligible node / chain cut, with the reason
KV202     info      streaming-ineligible estimator fit, with the reason
KV301     error     serving batch bucket not in the warmed bucket set
                    (the steady-state-recompile hazard)
KV302     warning   estimated peak bytes exceed the device memory budget
KV303     warning   Gram/sufficient-stat state for a streamed fit does
                    not fit the device memory budget
KV305     error     a refit-published candidate's apply spec or bucket
                    set disagrees with the incumbent's warmed buckets
                    (the steady-state-recompile hazard on the publish
                    path; :func:`verify_refit_publish`)
KV306     error     a persisted mid-stream resume entry's fingerprints
                    (dataset/labels content digest, featurize-chain
                    digest, featurized width/dtype) disagree with the
                    re-planned pipeline — seeding a fold from it would
                    silently corrupt the fit (:func:`verify_stream_resume`)
KV307     error     a serving boot image's environment fingerprints
                    (format version, jax version, backend, device kind,
                    weights digest) disagree with the loading worker's —
                    serving through its executables could return garbage;
                    the image is refused and the worker falls back to the
                    classic warm path (:func:`verify_boot_image`)
KV308     error     a streamed fit routed onto the sketched tier
                    (keystone_tpu/sketch) is infeasible or meaningless:
                    even the O(s·d) sketch state exceeds the device
                    memory budget (no further rung exists below the
                    sketch), or the sketch size fails the conditioning
                    heuristic (s below the label width / dual-solve
                    floor), so the sketched objective's error bound is
                    vacuous
KV401     error     dependency cycle in the graph
KV402     info      node not statically analyzable (no ``out_spec``,
                    not eval_shape-able) — propagation continues unknown
========  ========  ====================================================

(Lint-rule codes KV501-KV505 live in ``keystone_tpu/lint/rules.py``,
concurrency codes KV601-KV605 in ``keystone_tpu/lint/concurrency.py``;
all three tiers emit the shared :class:`keystone_tpu.diagnostics.
Diagnostic`, and docs/VERIFICATION.md documents the whole table.)

The ``out_spec`` protocol
-------------------------

Operators may define ``out_spec(in_specs)`` where ``in_specs`` is one
abstract value per graph dependency. For transformers the abstract
values are pytrees of ``jax.ShapeDtypeStruct``; the return value is the
output spec pytree. For estimators the return value is a
:class:`TransformerSpec` — the abstract value of the *fitted
transformer* edge, which the verifier later applies to the delegating
node's data specs. Raise :class:`SpecMismatch` for inputs the operator
cannot accept; return :data:`UNKNOWN` (or any part of it) where the
answer is data-dependent.

Operators without ``out_spec`` still verify when they are fusable
``BatchTransformer``s (``apply_arrays`` chains): the verifier falls back
to ``jax.eval_shape`` over ``apply_arrays``, so the whole fused serving
path is covered for free. See docs/VERIFICATION.md for the contract.

Entry points: :func:`verify_graph` / :func:`verify_pipeline` (the
``keystone-tpu check --pipeline`` engine), and :func:`verify_and_enforce`
— called from ``Pipeline.fit()`` and ``ModelRegistry.load_fitted``,
warn-by-default, ``KEYSTONE_VERIFY=strict`` to raise
:class:`VerificationError`, ``KEYSTONE_VERIFY=off`` to skip.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..diagnostics import ERROR, INFO, WARNING, Diagnostic
from ..envknobs import env_str
from ..obs import names as _names
from .analysis import GraphCycleError, linearize_whole
from .graph import Graph, GraphId, NodeId, SinkId, SourceId
from .operators import (
    DatasetOperator,
    DatumOperator,
    DelegatingOperator,
    EstimatorOperator,
    ExpressionOperator,
    Operator,
    TransformerOperator,
)

logger = logging.getLogger(__name__)

#: code → (default severity, short title). docs/VERIFICATION.md documents
#: every row; tests/workflow/test_verify.py enforces the sync.
CODES: Dict[str, Tuple[str, str]] = {
    "KV101": (ERROR, "shape/dtype mismatch at node boundary"),
    "KV102": (WARNING, "silent float64 widening"),
    "KV201": (INFO, "fusion-ineligible node"),
    "KV202": (INFO, "streaming-ineligible fit"),
    "KV203": (INFO, "sharding-ineligible fit"),
    "KV301": (ERROR, "serving bucket not warmed"),
    "KV302": (WARNING, "estimated peak memory exceeds budget"),
    "KV303": (WARNING, "streamed-fit Gram state exceeds memory budget"),
    "KV304": (ERROR, "sharded per-device residency exceeds memory budget"),
    "KV305": (ERROR, "refit candidate disagrees with incumbent warm state"),
    "KV306": (ERROR, "stale stream-resume entry refused"),
    "KV307": (ERROR, "stale boot image refused"),
    "KV308": (ERROR, "sketched-fit state infeasible or bound too weak"),
    "KV401": (ERROR, "dependency cycle"),
    "KV402": (INFO, "node not statically analyzable"),
}


class _Unknown:
    """Singleton abstract value: statically unknowable, propagates."""

    def __repr__(self) -> str:  # pragma: no cover - repr only
        return "UNKNOWN"


UNKNOWN = _Unknown()


class SpecMismatch(Exception):
    """Raised by ``out_spec``/``apply_spec`` when an input spec is one
    the operator can never accept (wrong rank, wrong width, row-count
    disagreement). Becomes a KV101 error diagnostic."""


@dataclass
class NodeAnnotation:
    """Per-node result of spec propagation: what the verifier believes
    flows out of this node, and roughly how many bytes it holds."""

    node: str
    label: str
    spec: str
    est_bytes: Optional[int] = None

    def to_json(self) -> Dict[str, Any]:
        return {
            "node": self.node,
            "label": self.label,
            "spec": self.spec,
            "est_bytes": self.est_bytes,
        }


@dataclass
class VerifyReport:
    diagnostics: List[Diagnostic] = field(default_factory=list)
    annotations: List[NodeAnnotation] = field(default_factory=list)
    seconds: float = 0.0
    context: str = ""
    #: Per-fit partition decisions the verifier derived (mesh shape, row
    #: PartitionSpec, eligibility/fallback reason) — the explainable
    #: sharding plan ``keystone-tpu check --pipeline --json`` surfaces.
    partition: List[Dict[str, Any]] = field(default_factory=list)

    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == ERROR]

    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == WARNING]

    @property
    def ok(self) -> bool:
        return not self.errors()

    def by_code(self, code: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.code == code]

    def to_json(self) -> Dict[str, Any]:
        out = {
            "context": self.context,
            "ok": self.ok,
            "seconds": round(self.seconds, 4),
            "diagnostics": [d.to_json() for d in self.diagnostics],
            "nodes": [a.to_json() for a in self.annotations],
        }
        if self.partition:
            out["partition"] = self.partition
        return out

    def render(self) -> str:
        lines = [
            f"verify[{self.context}]: {len(self.annotations)} nodes, "
            f"{len(self.errors())} errors, {len(self.warnings())} warnings, "
            f"{len(self.diagnostics)} diagnostics, {self.seconds * 1e3:.1f} ms"
        ]
        lines += [d.render() for d in self.diagnostics]
        return "\n".join(lines)


class VerificationError(RuntimeError):
    """Strict-mode failure: plan-time verification found errors."""

    def __init__(self, report: VerifyReport):
        self.report = report
        errors = "; ".join(d.render() for d in report.errors())
        super().__init__(
            f"plan-time verification failed ({report.context}): {errors} "
            "— set KEYSTONE_VERIFY=warn to downgrade, see docs/VERIFICATION.md"
        )


# ------------------------------------------------------------ abstract values


class TransformerSpec:
    """Abstract value of a fitted-transformer edge (an estimator node's
    output): maps apply-time input specs to output specs.

    ``fn(data_spec) -> out_spec`` may raise :class:`SpecMismatch`; pass
    ``fn=None`` for a fitted transformer whose apply shape is
    data-dependent (the verifier then propagates :data:`UNKNOWN`).
    """

    def __init__(self, fn: Optional[Callable[[Any], Any]] = None, label: str = ""):
        self._fn = fn
        self.label = label

    def apply_spec(self, data_spec: Any) -> Any:
        if self._fn is None:
            return UNKNOWN
        return self._fn(data_spec)

    def __repr__(self) -> str:  # pragma: no cover - repr only
        return f"TransformerSpec[{self.label or 'unknown'}]"


def _leaves(spec: Any) -> List[Any]:
    """ShapeDtypeStruct-ish leaves of an abstract value (empty for
    UNKNOWN / TransformerSpec)."""
    if spec is UNKNOWN or spec is None or isinstance(spec, TransformerSpec):
        return []
    import jax

    return [
        leaf
        for leaf in jax.tree_util.tree_leaves(spec)
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype")
    ]


def spec_bytes(spec: Any) -> Optional[int]:
    """Estimated bytes of an abstract value (None when unknown)."""
    leaves = _leaves(spec)
    if not leaves:
        return None
    total = 0
    import numpy as np

    for leaf in leaves:
        size = 1
        for dim in leaf.shape:
            size *= int(dim)
        total += size * np.dtype(leaf.dtype).itemsize
    return total


def _render_spec(spec: Any) -> str:
    if spec is UNKNOWN:
        return "unknown"
    if isinstance(spec, TransformerSpec):
        return repr(spec)
    leaves = _leaves(spec)
    if not leaves:
        return "unknown"
    return ", ".join(
        f"{tuple(int(d) for d in leaf.shape)}:{leaf.dtype}" for leaf in leaves
    )


def _single_matrix(spec: Any) -> Optional[Any]:
    """The single rank>=1 array leaf of a spec, or None when the spec is
    unknown / not a single array."""
    leaves = _leaves(spec)
    if len(leaves) != 1:
        return None
    return leaves[0]


def _rows(spec: Any) -> Optional[int]:
    leaf = _single_matrix(spec)
    if leaf is None or not leaf.shape:
        return None
    return int(leaf.shape[0])


def _width(spec: Any) -> Optional[int]:
    leaf = _single_matrix(spec)
    if leaf is None or len(leaf.shape) < 2:
        return None
    return int(leaf.shape[-1])


def _result_dtype(*specs: Any):
    """float64 if any input leaf (or bare dtype argument) is float64,
    else float32 — the dtype discipline of the solver layer (everything
    is cast to f32 unless the caller explicitly trafficks in f64)."""
    import numpy as np

    for spec in specs:
        if isinstance(spec, np.dtype):
            if spec == np.float64:
                return np.dtype(np.float64)
            continue
        for leaf in _leaves(spec):
            if np.dtype(leaf.dtype) == np.float64:
                return np.dtype(np.float64)
    return np.dtype(np.float32)


# ------------------------------------------------- out_spec helpers (for ops)


def dense_fit_spec(
    in_specs: Sequence[Any],
    label: str,
    out_width: Optional[int] = None,
) -> TransformerSpec:
    """Shared ``out_spec`` for estimators that fit a row-matrix into a
    dense map ``(m, d) -> (m, k)``.

    ``in_specs[0]`` is the feature spec (n, d); ``in_specs[1]`` (when
    present) the labels. ``out_width`` fixes k (num_classes, dims);
    ``None`` takes k from the labels' width (1 for rank-1 labels).
    Validates what is statically knowable — feature rank, train-time row
    agreement between features and labels, apply-time width agreement —
    and leaves the rest unknown.
    """
    import jax

    x = _single_matrix(in_specs[0]) if in_specs else None
    y_spec = in_specs[1] if len(in_specs) > 1 else None
    d = None
    dtype = _result_dtype(*in_specs)
    if x is not None:
        if len(x.shape) != 2:
            raise SpecMismatch(
                f"{label}: features must be a rank-2 (rows, features) "
                f"matrix, got shape {tuple(x.shape)}"
            )
        d = int(x.shape[1])
        n = int(x.shape[0])
        y = _single_matrix(y_spec) if y_spec is not None else None
        if y is not None and y.shape and int(y.shape[0]) != n:
            raise SpecMismatch(
                f"{label}: features have {n} rows but labels have "
                f"{int(y.shape[0])} rows"
            )
    k = out_width
    if k is None and y_spec is not None:
        y = _single_matrix(y_spec)
        if y is not None:
            k = int(y.shape[1]) if len(y.shape) >= 2 else 1

    def apply_fn(data_spec: Any) -> Any:
        leaf = _single_matrix(data_spec)
        if leaf is None:
            return UNKNOWN
        if len(leaf.shape) < 2:
            raise SpecMismatch(
                f"{label}: fitted map expects rank-2 input, got shape "
                f"{tuple(leaf.shape)}"
            )
        if d is not None and int(leaf.shape[-1]) != d:
            raise SpecMismatch(
                f"{label}: fitted on {d}-wide features but applied to "
                f"{int(leaf.shape[-1])}-wide input"
            )
        if k is None:
            return UNKNOWN
        out_shape = tuple(leaf.shape[:-1]) + (k,)
        return jax.ShapeDtypeStruct(out_shape, _result_dtype(data_spec, dtype))

    return TransformerSpec(apply_fn, label=f"{label}(d={d},k={k})")


def projection_fit_spec(
    in_specs: Sequence[Any], label: str, dims: int
) -> TransformerSpec:
    """``out_spec`` for projection estimators (PCA families): the fitted
    transformer replaces the LAST axis (descriptor width d) with
    ``dims``, preserving leading axes — covers both flat (m, d) rows and
    (m, cols, d) descriptor stacks."""
    import jax

    x = _single_matrix(in_specs[0]) if in_specs else None
    d = int(x.shape[-1]) if x is not None and len(x.shape) >= 2 else None

    def apply_fn(data_spec: Any) -> Any:
        leaf = _single_matrix(data_spec)
        if leaf is None:
            return UNKNOWN
        if len(leaf.shape) < 2:
            raise SpecMismatch(
                f"{label}: projection expects rank>=2 input, got shape "
                f"{tuple(leaf.shape)}"
            )
        if d is not None and int(leaf.shape[-1]) != d:
            raise SpecMismatch(
                f"{label}: fitted on {d}-wide descriptors but applied to "
                f"{int(leaf.shape[-1])}-wide input"
            )
        out_shape = tuple(leaf.shape[:-1]) + (int(dims),)
        return jax.ShapeDtypeStruct(out_shape, _result_dtype(data_spec))

    return TransformerSpec(apply_fn, label=f"{label}(d={d},dims={dims})")


def elementwise_fit_spec(in_specs: Sequence[Any], label: str) -> TransformerSpec:
    """``out_spec`` for estimators whose fitted transformer preserves the
    input spec exactly (scalers, whiteners): shape and dtype pass
    through, width checked against the training width when both are
    known."""
    x = _single_matrix(in_specs[0]) if in_specs else None
    d = int(x.shape[-1]) if x is not None and len(x.shape) >= 2 else None

    def apply_fn(data_spec: Any) -> Any:
        leaf = _single_matrix(data_spec)
        if leaf is None:
            return UNKNOWN
        if d is not None and len(leaf.shape) >= 2 and int(leaf.shape[-1]) != d:
            raise SpecMismatch(
                f"{label}: fitted on {d}-wide input but applied to "
                f"{int(leaf.shape[-1])}-wide input"
            )
        return data_spec

    return TransformerSpec(apply_fn, label=f"{label}(d={d})")


# ------------------------------------------------------------ the interpreter


def _dataset_spec(dataset: Any, probe_objects: bool) -> Any:
    """Spec of a bound dataset — shapes/dtypes read off host metadata,
    never moving data. ObjectDatasets decode one item to learn the
    per-item shape only when ``probe_objects`` (the CLI path; the
    fit-hook path stays zero-cost)."""
    import jax
    import numpy as np

    from ..data.dataset import ArrayDataset, ObjectDataset

    if isinstance(dataset, ArrayDataset):
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(
                tuple(int(d) for d in np.shape(a)),
                np.dtype(getattr(a, "dtype", np.float32)),
            ),
            dataset.data,
        )
    if isinstance(dataset, ObjectDataset) and probe_objects and len(dataset):
        first = dataset.take(1)[0]
        n = len(dataset)
        return jax.tree_util.tree_map(
            lambda leaf: jax.ShapeDtypeStruct(
                (n,) + tuple(np.asarray(leaf).shape), np.asarray(leaf).dtype
            ),
            first,
        )
    return UNKNOWN


def _datum_spec(datum: Any) -> Any:
    import jax
    import numpy as np

    if hasattr(datum, "shape") and hasattr(datum, "dtype"):
        return jax.ShapeDtypeStruct(
            tuple(int(d) for d in datum.shape), np.dtype(datum.dtype)
        )
    return UNKNOWN


def _eval_shape_apply(op: Any, in_spec: Any) -> Any:
    """eval_shape over ``apply_arrays``, honoring the masked-descriptor
    dict convention ({"desc": ..., "valid": ...}) the batch path uses."""
    import jax

    if (
        isinstance(in_spec, dict)
        and "desc" in in_spec
        and "valid" in in_spec
    ):
        out = jax.eval_shape(op.apply_arrays, in_spec["desc"])
        return {"desc": out, "valid": in_spec["valid"]}
    return jax.eval_shape(op.apply_arrays, in_spec)


class _Interpreter:
    def __init__(
        self,
        graph: Graph,
        diagnostics: List[Diagnostic],
        probe_objects: bool,
    ):
        self.graph = graph
        self.diagnostics = diagnostics
        self.probe_objects = probe_objects
        self.specs: Dict[GraphId, Any] = {}

    def diag(self, code: str, message: str, node=None, **details) -> None:
        severity, _title = CODES[code]
        self.diagnostics.append(
            Diagnostic(
                code=code,
                severity=severity,
                message=message,
                node=None if node is None else repr(node),
                details=details,
            )
        )

    # ---------------------------------------------------------------- nodes
    def node_out_spec(self, node: NodeId, op: Operator, in_specs: List[Any]) -> Any:
        from ..ops.util.misc import CacherOperator
        from .fusion import FusedTransformerOperator, is_fusable
        from .pipeline import Identity
        from .streaming import StreamingFitOperator

        label = str(getattr(op, "label", type(op).__name__))

        # Explicit protocol wins — it can see what tracing can't (what a
        # fit will produce).
        out_spec = getattr(op, "out_spec", None)
        if callable(out_spec):
            try:
                return out_spec(in_specs)
            except SpecMismatch as e:
                self.diag("KV101", str(e), node=node, op=label)
                return UNKNOWN
            except Exception as e:  # a broken out_spec must not kill planning
                self.diag(
                    "KV402",
                    f"{label}: out_spec failed ({type(e).__name__}: {e})",
                    node=node,
                    op=label,
                )
                return UNKNOWN

        if isinstance(op, DatasetOperator):
            return _dataset_spec(op.dataset, self.probe_objects)
        if isinstance(op, DatumOperator):
            return _datum_spec(op.datum)
        if isinstance(op, ExpressionOperator):
            # A spliced already-computed expression: if it has been
            # forced, read the value's metadata; otherwise unknown.
            value = getattr(op.expression, "_value", None)
            if value is not None and hasattr(value, "data"):
                return _dataset_spec(value, self.probe_objects)
            return UNKNOWN
        if isinstance(op, (CacherOperator, Identity)):
            return in_specs[0] if in_specs else UNKNOWN

        if isinstance(op, DelegatingOperator):
            transformer = in_specs[0] if in_specs else UNKNOWN
            data = in_specs[1] if len(in_specs) > 1 else UNKNOWN
            if isinstance(transformer, TransformerSpec):
                try:
                    return transformer.apply_spec(data)
                except SpecMismatch as e:
                    self.diag("KV101", str(e), node=node, op=label)
                    return UNKNOWN
            return UNKNOWN

        if isinstance(op, StreamingFitOperator):
            return self._streaming_fit_spec(node, op, in_specs)

        if isinstance(op, EstimatorOperator):
            self.diag(
                "KV402",
                f"{label}: estimator has no out_spec — fitted-transformer "
                "shape unknown at plan time (docs/VERIFICATION.md "
                "documents the protocol)",
                node=node,
                op=label,
            )
            return TransformerSpec(None, label=label)

        if isinstance(op, FusedTransformerOperator) or (
            isinstance(op, TransformerOperator) and is_fusable(op)
        ):
            in_spec = in_specs[0] if in_specs else UNKNOWN
            if not _leaves(in_spec):
                return UNKNOWN
            try:
                return _eval_shape_apply(op, in_spec)
            except Exception as e:
                self.diag(
                    "KV101",
                    f"{label}: apply_arrays rejects input "
                    f"{_render_spec(in_spec)} ({type(e).__name__}: "
                    f"{str(e)[:300]})",
                    node=node,
                    op=label,
                )
                return UNKNOWN

        self.diag(
            "KV402",
            f"{label}: no out_spec and not an eval_shape-able "
            "apply_arrays transformer",
            node=node,
            op=label,
        )
        return UNKNOWN

    def _streaming_fit_spec(
        self, node: NodeId, op: Any, in_specs: List[Any]
    ) -> Any:
        """A StreamingFitOperator: featurized spec = chain over the raw
        data spec; the wrapped estimator's out_spec (when present) then
        gives the fitted-transformer edge. Also records the featurized
        width for the Gram-feasibility check."""
        label = str(getattr(op, "label", type(op).__name__))
        data_spec = in_specs[0] if in_specs else UNKNOWN
        feat_spec = data_spec
        if _leaves(data_spec) and op.members:
            import jax

            try:
                # Cast-to-float first, like the real chunk step.
                def chain(x):
                    import jax.numpy as jnp

                    def cast(a):
                        if jnp.issubdtype(a.dtype, jnp.floating):
                            return a
                        return a.astype(jnp.float32)

                    x = jax.tree_util.tree_map(cast, x)
                    for m in op.members:
                        x = m.apply_arrays(x)
                    return x

                feat_spec = jax.eval_shape(chain, data_spec)
            except Exception as e:
                self.diag(
                    "KV101",
                    f"{label}: featurize chain rejects input "
                    f"{_render_spec(data_spec)} ({type(e).__name__}: "
                    f"{str(e)[:300]})",
                    node=node,
                    op=label,
                )
                feat_spec = UNKNOWN
        self.specs[("feat", node)] = feat_spec  # side-channel for gram check
        est_out_spec = getattr(op.estimator, "out_spec", None)
        if callable(est_out_spec):
            try:
                return est_out_spec([feat_spec] + list(in_specs[1:]))
            except SpecMismatch as e:
                self.diag("KV101", str(e), node=node, op=label)
                return UNKNOWN
            except Exception as e:
                self.diag(
                    "KV402",
                    f"{label}: estimator out_spec failed "
                    f"({type(e).__name__}: {e})",
                    node=node,
                    op=label,
                )
                return UNKNOWN
        return TransformerSpec(None, label=label)


# ----------------------------------------------------------- eligibility scan


def _fusion_diagnostics(graph: Graph, interp: _Interpreter) -> None:
    """Why is each transformer not (or no longer) fusable? Mirrors the
    NodeFusionRule gates so the reasons are the rule's reasons."""
    from ..ops.util.misc import CacherOperator
    from .fusion import FusedTransformerOperator, _overrides, is_fusable
    from .pipeline import BatchTransformer

    dependents = graph.dependents()
    for node in sorted(graph.nodes):
        op = graph.get_operator(node)
        label = str(getattr(op, "label", type(op).__name__))
        if isinstance(op, FusedTransformerOperator):
            continue
        if isinstance(op, CacherOperator):
            interp.diag(
                "KV201",
                f"{label}: Cacher boundary — chains never fuse across a "
                "cache materialization point",
                node=node,
                reason="cacher-boundary",
            )
            continue
        if not isinstance(op, BatchTransformer):
            continue
        if is_fusable(op):
            consumers = dependents.get(node, [])
            node_consumers = [c for c in consumers if isinstance(c, NodeId)]
            if len(consumers) > 1 and node_consumers:
                interp.diag(
                    "KV201",
                    f"{label}: multi-consumer interior — {len(consumers)} "
                    "consumers need this value host-side, so a fused chain "
                    "is cut here",
                    node=node,
                    reason="multi-consumer",
                )
            continue
        if not getattr(op, "fusable", True):
            reason = "opted out (fusable=False — op manages its own dispatch)"
            key = "opt-out"
        elif not _overrides(op, "apply_arrays"):
            reason = "does not implement apply_arrays"
            key = "no-apply-arrays"
        else:
            reason = (
                "bespoke apply/apply_batch override — whole-batch semantics "
                "are not its apply_arrays"
            )
            key = "bespoke-apply"
        interp.diag(
            "KV201",
            f"{label}: not fusable — {reason}",
            node=node,
            reason=key,
        )


def _streaming_diagnostics(
    graph: Graph, interp: _Interpreter, memory_limit: Optional[int]
) -> None:
    from .streaming import (
        StreamingFitOperator,
        stream_chunk_rows,
        stream_min_rows,
    )

    floor = max(2 * stream_chunk_rows(), stream_min_rows())
    for node in sorted(graph.nodes):
        op = graph.get_operator(node)
        label = str(getattr(op, "label", type(op).__name__))
        if isinstance(op, StreamingFitOperator):
            if _plan_state_kind(interp, node, op) == "sketch":
                _sketch_feasibility(graph, interp, node, op, memory_limit)
            else:
                _gram_feasibility(graph, interp, node, op, memory_limit)
            continue
        if not isinstance(op, EstimatorOperator):
            continue
        if not getattr(op, "supports_fit_stream", False):
            interp.diag(
                "KV202",
                f"{label}: estimator does not implement fit_stream — fit "
                "materializes the full feature matrix",
                node=node,
                reason="no-fit-stream",
            )
            continue
        # Supports streaming but was not rewritten: explain with the
        # planner's own gates.
        deps = graph.get_dependencies(node)
        head = deps[0] if deps else None
        reason, key = "no chunkable bound dataset upstream", "no-bound-data"
        if isinstance(head, NodeId):
            head_op = graph.get_operator(head)
            if isinstance(head_op, DatasetOperator):
                try:
                    n = len(head_op.dataset)
                except Exception:
                    n = -1
                if 0 <= n < floor:
                    reason = (
                        f"dataset holds {n} rows, below the streaming floor "
                        f"{floor} (max(2*chunk_rows, KEYSTONE_STREAM_MIN_ROWS))"
                    )
                    key = "below-row-floor"
        interp.diag(
            "KV202",
            f"{label}: fit_stream-capable but not planned onto the "
            f"streaming engine — {reason}",
            node=node,
            reason=key,
        )


def _partition_diagnostics(
    graph: Graph,
    interp: _Interpreter,
    memory_limit: Optional[int],
    report: VerifyReport,
) -> None:
    """The partitioner's own view of every fit in the plan, re-derived
    (never re-recorded — the last plan's report and metrics stay
    untouched): KV203 explains a single-device fallback with the
    partitioner's reason key; KV304 errors when an ELIGIBLE sharded plan
    still cannot fit its per-device slice next to the replicated O(d²)
    statistics — sharding divides the rows, not the Gram."""
    from ..parallel.partitioner import Partitioner
    from .streaming import StreamingFitOperator, stream_chunk_rows

    part = Partitioner()
    for node in sorted(graph.nodes):
        op = graph.get_operator(node)
        if not isinstance(op, EstimatorOperator):
            continue
        label = str(getattr(op, "label", type(op).__name__))
        deps = graph.get_dependencies(node)
        in_spec = interp.specs.get(deps[0], UNKNOWN) if deps else UNKNOWN
        rows = _rows(in_spec)
        streaming = isinstance(op, StreamingFitOperator)
        pinned = getattr(op, "partition", None)
        if pinned is not None:
            # Post-optimizer graphs carry the plan's own decision both
            # ways (eligible or recorded fallback) — report THAT, never
            # a re-derivation that could disagree with the runtime.
            decision = pinned
        else:
            target = op.estimator if streaming else op
            opt_out = getattr(target, "partitionable", True) is False
            # Same inputs the plan rule feeds the partitioner: the raw
            # upstream width as the featurized-width proxy, and the
            # estimator's 2-D protocol opt-in.
            model_ok = getattr(target, "supports_model_axis", False)
            width = _width(in_spec)
            if streaming:
                decision = part.decide_stream(
                    label, op.chunk_rows or stream_chunk_rows(), rows=rows,
                    record=False, opt_out=opt_out,
                    width=width, model_ok=model_ok,
                )
            else:
                decision = part.decide_fit(
                    label, rows, record=False, opt_out=opt_out,
                    width=width, model_ok=model_ok,
                )
        report.partition.append(decision.to_json())
        if not decision.eligible:
            interp.diag(
                "KV203",
                f"{label}: fit is not partition-managed "
                f"({decision.reason}"
                + (f": {decision.detail}" if decision.detail else "")
                + ") — streamed/serve fallbacks run single-device, "
                "in-core fits keep the legacy ambient-mesh path",
                node=node,
                reason=decision.reason,
            )
            continue

        if memory_limit is None:
            continue
        # Per-device residency of the SHARDED plan: the row slice (2× for
        # the centered/featurized working copy) plus the un-sharded
        # statistics every device carries in full.
        in_bytes = spec_bytes(in_spec)
        if streaming:
            feat = interp.specs.get(("feat", node))
            d = _width(feat) if feat is not None else None
            chunk = decision.chunk_rows or stream_chunk_rows()
            row_bytes = (
                (in_bytes // max(rows, 1)) if (in_bytes and rows) else None
            )
            slice_bytes = (
                2 * chunk * row_bytes // decision.shards if row_bytes else 0
            )
        else:
            d = _width(in_spec)
            slice_bytes = 2 * in_bytes // decision.shards if in_bytes else 0
        k = 1
        if len(deps) > 1:
            k = _width(interp.specs.get(deps[1])) or 1
        # 2-D layouts block the feature-indexed statistics (Gram rows,
        # cross-product rows, feature sums) over the model axis — only
        # the label-sized remainder stays replicated per model shard.
        p_m = max(1, int(getattr(decision, "model_shards", 1) or 1))
        stat_bytes = 2 * 4 * ((d * d + d * k + d) // p_m + k) if d else 0
        per_device = slice_bytes + stat_bytes
        if per_device > memory_limit:
            axis_hint = (
                "raise KEYSTONE_PARTITION_MODEL_SHARDS or use the "
                "sketched tier"
                if p_m > 1
                else "sharding divides rows, not the O(d²) state; use "
                "the sketched tier or a model-axis layout"
            )
            interp.diag(
                "KV304",
                f"{label}: sharded over {decision.shards}"
                + (f"×{p_m}" if p_m > 1 else "")
                + " devices the "
                f"per-device residency is still ~{per_device / 1e9:.2f} GB "
                f"(row slice {slice_bytes / 1e9:.2f} GB + "
                + ("feature-blocked" if p_m > 1 else "replicated")
                + f" statistics {stat_bytes / 1e9:.2f} GB) against a "
                f"{memory_limit / 1e9:.2f} GB budget — " + axis_hint,
                node=node,
                shards=decision.shards,
                model_shards=p_m,
                per_device_bytes=per_device,
                memory_limit=memory_limit,
            )


def _gram_feasibility(
    graph: Graph,
    interp: _Interpreter,
    node: NodeId,
    op: Any,
    memory_limit: Optional[int],
) -> None:
    """O(d²) sufficient statistics must fit next to two chunk buffers —
    the whole point of the streamed fit is bounded residency, so an
    infeasible Gram should be caught at plan time, not as an OOM ten
    minutes into ingest."""
    if memory_limit is None:
        return
    feat_spec = interp.specs.get(("feat", node))
    d = _width(feat_spec) if feat_spec is not None else None
    if d is None:
        return
    label = str(getattr(op, "label", type(op).__name__))
    # carry (gram d², cross d·k, sums) + the donated update's transient
    # double-residency: 2× is the engine's working-set model.
    k = 1
    deps = graph.get_dependencies(node)
    if len(deps) > 1:
        k = _width(interp.specs.get(deps[1])) or 1
    gram_bytes = 2 * 4 * (d * d + d * k + d + k)
    if gram_bytes > memory_limit:
        interp.diag(
            "KV303",
            f"{label}: streamed fit needs ~{gram_bytes / 1e9:.2f} GB of "
            f"Gram state (d={d}, k={k}) but the device memory budget is "
            f"{memory_limit / 1e9:.2f} GB — use the sketched/rematerialized "
            "tier instead",
            node=node,
            d=d,
            k=k,
            gram_bytes=gram_bytes,
            memory_limit=memory_limit,
        )


def _plan_state_kind(interp: _Interpreter, node: NodeId, op: Any) -> str:
    """Which stream-state kind this fit will produce at plan time —
    mirrors the solver ladder's width-based dispatch so the feasibility
    check inspects the rung that will actually run."""
    from ..refit.state import SketchStreamStateMixin

    est = getattr(op, "estimator", None)
    if isinstance(est, SketchStreamStateMixin):
        return "sketch"
    feat_spec = interp.specs.get(("feat", node))
    d = _width(feat_spec) if feat_spec is not None else None
    solver_for = getattr(est, "_stream_solver", None)
    if callable(solver_for) and d is not None:
        try:
            return str(getattr(solver_for(d), "stream_state_kind", "gram"))
        except Exception:
            return "gram"
    return "gram"


def _sketch_feasibility(
    graph: Graph,
    interp: _Interpreter,
    node: NodeId,
    op: Any,
    memory_limit: Optional[int],
) -> None:
    """The sketched tier is the LAST memory rung — below it there is
    nothing to degrade to, so an O(s·d) state that still misses the
    budget, or a sketch size too small for its error bound to mean
    anything (s below the dual-solve / label-width floor), is a plan
    error (KV308), not a warning like the Gram tier's KV303."""
    from ..envknobs import env_int
    from ..sketch.core import sketch_state_bytes
    from ..sketch.solvers import default_sketch_size

    feat_spec = interp.specs.get(("feat", node))
    d = _width(feat_spec) if feat_spec is not None else None
    if d is None:
        return
    label = str(getattr(op, "label", type(op).__name__))
    k = 1
    deps = graph.get_dependencies(node)
    if len(deps) > 1:
        k = _width(interp.specs.get(deps[1])) or 1
    est = getattr(op, "estimator", None)
    s = (
        env_int("KEYSTONE_SKETCH_SIZE", 0)
        or int(getattr(est, "sketch_size", 0) or 0)
        or default_sketch_size(d)
    )
    # Conditioning / bound heuristic: the finish is a dual s×s ridge
    # whose solution spans at most s directions — with s below a small
    # multiple of the label width (or a hard floor) the sketched
    # objective's error bound is vacuous. Checked even without a memory
    # budget: a bad sketch size is wrong on any device.
    floor = max(32, 4 * (k + 1))
    if s < floor:
        interp.diag(
            "KV308",
            f"{label}: sketch size s={s} is below the conditioning floor "
            f"{floor} (max(32, 4*(k+1)) with k={k}) — the dual ridge "
            "finish spans too few directions for the sketch-and-solve "
            "error bound to hold; raise KEYSTONE_SKETCH_SIZE",
            node=node,
            d=d,
            k=k,
            sketch_size=s,
            floor=floor,
        )
        return
    if memory_limit is None:
        return
    # carry (SA s·d, SY s·k, s1, sums) + the donated update's transient
    # double-residency: same 2× working-set model as the Gram check.
    state_bytes = 2 * sketch_state_bytes(s, d, k)
    if state_bytes > memory_limit:
        interp.diag(
            "KV308",
            f"{label}: even the sketched tier needs ~{state_bytes / 1e9:.2f} "
            f"GB of state (s={s}, d={d}, k={k}) against a "
            f"{memory_limit / 1e9:.2f} GB budget — no lower-memory rung "
            "exists; shrink KEYSTONE_SKETCH_SIZE or the feature width",
            node=node,
            d=d,
            k=k,
            sketch_size=s,
            state_bytes=state_bytes,
            memory_limit=memory_limit,
        )


# ------------------------------------------------------------------ memory


def _device_memory_limit() -> Optional[int]:
    """The accelerator's reported capacity (bytes_limit), when the
    backend exposes one. CPU test meshes report none — the memory check
    then only runs with an explicit budget."""
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return None


_AUTO = object()


def verify_graph(
    graph: Graph,
    source_specs: Optional[Dict[SourceId, Any]] = None,
    *,
    buckets: Optional[Sequence[int]] = None,
    warmed_buckets: Optional[Sequence[int]] = None,
    device_memory_bytes: Any = _AUTO,
    probe_objects: bool = False,
    context: str = "graph",
) -> VerifyReport:
    """Statically verify a plan graph. Pure host-side analysis: specs
    propagate via ``out_spec``/``jax.eval_shape`` — no device execution,
    no XLA compiles (asserted by scripts/check_smoke.sh via the compile
    counter)."""
    t0 = time.perf_counter()
    report = VerifyReport(context=context)
    interp = _Interpreter(graph, report.diagnostics, probe_objects)
    memory_limit = (
        _device_memory_limit() if device_memory_bytes is _AUTO
        else device_memory_bytes
    )

    try:
        order = linearize_whole(graph)
    except GraphCycleError as e:
        interp.diag("KV401", str(e))
        report.seconds = time.perf_counter() - t0
        _publish(report, context)
        return report

    peak_node_bytes = 0
    peak_node = None
    for vid in order:
        if isinstance(vid, SourceId):
            interp.specs[vid] = (source_specs or {}).get(vid, UNKNOWN)
            continue
        if isinstance(vid, SinkId):
            interp.specs[vid] = interp.specs.get(
                graph.get_sink_dependency(vid), UNKNOWN
            )
            continue
        op = graph.get_operator(vid)
        in_specs = [
            interp.specs.get(d, UNKNOWN) for d in graph.get_dependencies(vid)
        ]
        out = interp.node_out_spec(vid, op, in_specs)
        interp.specs[vid] = out

        label = str(getattr(op, "label", type(op).__name__))
        out_bytes = spec_bytes(out)
        report.annotations.append(
            NodeAnnotation(
                node=repr(vid),
                label=label,
                spec=_render_spec(out),
                est_bytes=out_bytes,
            )
        )
        # Silent widening: a float64 output from non-float64 inputs.
        import numpy as np

        out_leaves = _leaves(out)
        if out_leaves and any(
            np.dtype(leaf.dtype) == np.float64 for leaf in out_leaves
        ):
            in_leaves = [
                leaf for spec in in_specs for leaf in _leaves(spec)
            ]
            in_has_f64 = any(
                np.dtype(leaf.dtype) == np.float64 for leaf in in_leaves
            )
            # A node with no known input leaves (a source/dataset node,
            # or all-UNKNOWN inputs) cannot have WIDENED anything — f64
            # there is the data's own dtype, not a silent cast.
            if in_leaves and not in_has_f64:
                interp.diag(
                    "KV102",
                    f"{label}: output widens to float64 from narrower "
                    "inputs — 2× the bytes and a silent slow path on "
                    "accelerators",
                    node=vid,
                    op=label,
                )
        live = (out_bytes or 0) + sum(
            spec_bytes(spec) or 0 for spec in in_specs
        )
        if live > peak_node_bytes:
            peak_node_bytes, peak_node = live, (vid, label)

    if memory_limit is not None and peak_node_bytes > memory_limit:
        interp.diag(
            "KV302",
            f"estimated peak residency ~{peak_node_bytes / 1e9:.2f} GB at "
            f"node {peak_node[0]!r} ({peak_node[1]}) exceeds the device "
            f"memory budget {memory_limit / 1e9:.2f} GB",
            node=peak_node[0],
            peak_bytes=peak_node_bytes,
            memory_limit=memory_limit,
        )

    _fusion_diagnostics(graph, interp)
    _streaming_diagnostics(graph, interp, memory_limit)
    _partition_diagnostics(graph, interp, memory_limit, report)

    if buckets:
        # The serving-path partition decision rides the report too, so
        # `check --pipeline --buckets` explains the sharded (or not)
        # serve placement next to the warm-set check below.
        try:
            from ..parallel.partitioner import Partitioner

            report.partition.append(
                Partitioner()
                .decide_serve("serving", buckets, record=False)
                .to_json()
            )
        except Exception:  # pragma: no cover - decision is advisory
            pass
        warmed = set(int(b) for b in (warmed_buckets or ()))
        missing = sorted(set(int(b) for b in buckets) - warmed)
        if missing:
            interp.diag(
                "KV301",
                f"serving buckets {missing} are not in the warmed set "
                f"{sorted(warmed)} — every batch padded onto them compiles "
                "at serve time (steady-state recompile hazard; "
                "utils/aot.warm_buckets)",
                missing=missing,
                warmed=sorted(warmed),
            )

    report.seconds = time.perf_counter() - t0
    _publish(report, context)
    return report


def _apply_out_spec(model: Any, example_spec: Any):
    """Shape-only trace of a fitted model's batch apply on one request
    spec — zero device execution. Returns a ``(kind, rendering)`` pair:
    the two trace engines (``jax.eval_shape`` over ``apply_arrays`` vs
    the graph verifier's sink annotation) render specs differently, so a
    comparison is only meaningful between like kinds — the caller must
    never diff a mapper's repr against a pipeline's annotation string
    (that would flag every cross-kind publish). UNKNOWN when the model's
    apply path isn't statically traceable (bespoke apply_batch etc.)."""
    import jax

    apply_arrays = getattr(model, "apply_arrays", None)
    if apply_arrays is None and hasattr(model, "graph"):
        # FittedPipeline: propagate through the verifier itself and read
        # the sink annotation — the same engine load_fitted uses.
        try:
            report = verify_graph(
                model.graph,
                {model.source: example_spec},
                context="refit-spec-probe",
            )
            sink_dep = model.graph.get_sink_dependency(model.sink)
            for ann in report.annotations:
                if ann.node == repr(sink_dep):
                    return ("graph", ann.spec)
        except Exception:
            return UNKNOWN
        return UNKNOWN
    if apply_arrays is None:
        return UNKNOWN
    try:
        out = jax.eval_shape(apply_arrays, example_spec)
        return ("arrays", repr(out))
    except Exception:
        return UNKNOWN


def verify_refit_publish(
    candidate: Any,
    incumbent: Any,
    example: Any = None,
    buckets: Optional[Sequence[int]] = None,
    warmed_buckets: Optional[Sequence[int]] = None,
    context: str = "refit-publish",
) -> VerifyReport:
    """The publish-path face of the steady-state-recompile hazard
    (docs/REFIT.md, docs/VERIFICATION.md KV305).

    A refit-published candidate serves through the INCUMBENT's warmed
    executables: the fleet re-warms exactly the bucket set it already
    holds, so a candidate whose apply spec (per-bucket output
    shape/dtype) or required bucket set disagrees with what the
    incumbent warmed compiles at serve time — on live traffic, after the
    swap ack said "warm". This check is pure tracing (``jax.eval_shape``
    / spec propagation), zero device execution, and runs before every
    controller publish.
    """
    t0 = time.perf_counter()
    report = VerifyReport(context=context)
    interp = _Interpreter(Graph(), report.diagnostics, probe_objects=False)

    if buckets is not None:
        want = set(int(b) for b in buckets)
        warmed = set(int(b) for b in (warmed_buckets or ()))
        missing = sorted(want - warmed)
        if missing:
            interp.diag(
                "KV305",
                f"candidate's serving buckets {missing} are not in the "
                f"incumbent's warmed set {sorted(warmed)} — every batch "
                "padded onto them compiles at serve time, AFTER the "
                "publish settled (steady-state recompile on the publish "
                "path; re-warm the new buckets before swapping)",
                missing=missing,
                warmed=sorted(warmed),
            )

    if example is not None and incumbent is not None:
        import jax
        import numpy as np

        def leaf_spec(a):
            dtype = getattr(a, "dtype", None)
            if dtype is None:
                dtype = np.asarray(a).dtype
            return jax.ShapeDtypeStruct(
                (1,) + tuple(np.shape(a)), np.dtype(dtype)
            )

        try:
            spec = jax.tree_util.tree_map(leaf_spec, example)
        except Exception:
            spec = None
        if spec is not None:
            cand_out = _apply_out_spec(candidate, spec)
            inc_out = _apply_out_spec(incumbent, spec)
            if (
                cand_out is not UNKNOWN
                and inc_out is not UNKNOWN
                # Same trace engine only: the two renderings are not
                # comparable across kinds (a mapper candidate over a
                # pipeline incumbent would otherwise ALWAYS mismatch).
                and cand_out[0] == inc_out[0]
                and cand_out[1] != inc_out[1]
            ):
                interp.diag(
                    "KV305",
                    "candidate's apply spec "
                    f"{cand_out[1]} != incumbent's {inc_out[1]} for the "
                    "same request — the warmed executables cannot serve "
                    "it (shape/dtype drift in the refit candidate)",
                    candidate_spec=str(cand_out[1]),
                    incumbent_spec=str(inc_out[1]),
                )

    report.seconds = time.perf_counter() - t0
    _publish(report, context)
    return report


def verify_stream_resume(
    cursor: Any,
    current: Dict[str, Any],
    context: str = "stream-resume",
) -> VerifyReport:
    """The durable-fit face of stale-state corruption (docs/RELIABILITY.md
    "Durable fits", docs/VERIFICATION.md KV306).

    A mid-stream resume entry seeds a fold with sufficient statistics
    captured over a PREFIX of the dataset — sound only when the fresh
    process's re-planned pipeline reproduces the exact same features for
    the exact same rows. The resume key is deliberately coarse (it names
    the logical fit, so re-planned pipelines FIND their entry); this
    check is the content-level gate: any disagreement between the
    cursor's fingerprints and the re-planned pipeline's — dataset or
    labels content digest, featurize-chain digest (weights included),
    featurized width or dtype — refuses the entry. Stale resume must be
    a loud refusal and a from-scratch re-ingest, never a silently
    corrupted fit. Pure host-side comparison, zero device execution.

    ``cursor`` is a :class:`~keystone_tpu.reliability.durable.StreamCursor`;
    ``current`` maps the same fingerprint field names to the re-planned
    pipeline's values.
    """
    t0 = time.perf_counter()
    report = VerifyReport(context=context)
    interp = _Interpreter(Graph(), report.diagnostics, probe_objects=False)
    checks = (
        ("dataset_digest", "dataset content digest"),
        ("labels_digest", "labels content digest"),
        ("chain_digest", "featurize-chain digest"),
        ("feature_width", "featurized width"),
        ("feature_dtype", "featurized dtype"),
    )
    for field_name, title in checks:
        have = getattr(cursor, field_name)
        want = current.get(field_name)
        if have != want:
            interp.diag(
                "KV306",
                f"resume entry's {title} ({str(have)[:16]}) disagrees with "
                f"the re-planned pipeline's ({str(want)[:16]}) — seeding "
                "the fold from this entry would silently corrupt the fit; "
                "the entry is refused and the fit re-ingests from scratch",
                field=field_name,
                entry=str(have)[:16],
                planned=str(want)[:16],
            )
    report.seconds = time.perf_counter() - t0
    _publish(report, context)
    return report


#: manifest/environment fields verify_boot_image compares, with the human
#: titles its diagnostics use. serving/bootimage.py builds both sides.
BOOT_IMAGE_FINGERPRINTS: Tuple[Tuple[str, str], ...] = (
    ("format_version", "artifact format version"),
    ("jax_version", "jax version"),
    ("backend", "jax backend"),
    ("device_kind", "device kind"),
    ("weights_digest", "fitted-weights digest"),
)


def verify_boot_image(
    manifest: Dict[str, Any],
    current: Dict[str, Any],
    context: str = "boot-image",
) -> VerifyReport:
    """The serving face of stale-state corruption (docs/SERVING.md
    "Elastic fleet", docs/VERIFICATION.md KV307).

    A boot image carries AOT-serialized bucket executables plus the
    fitted weights they were exported from — sound to serve through only
    when the loading worker's environment matches the builder's: same
    artifact format, same jax version (export/deserialize compatibility),
    same backend and device kind (the serialized executables ride the
    persistent compilation cache, which is environment-keyed exactly like
    ProfileStore entries), and the same weights digest (an image whose
    executables baked different weights than ``model.pkl`` would answer
    with the WRONG model). Any disagreement refuses the image: the worker
    falls back to the classic warm path — slower first request, never
    garbage. Pure host-side comparison, zero device execution.

    ``manifest`` and ``current`` both map the fingerprint field names
    from :data:`BOOT_IMAGE_FINGERPRINTS` to their values (the image's
    recorded environment vs the loading process's observed one).
    """
    t0 = time.perf_counter()
    report = VerifyReport(context=context)
    interp = _Interpreter(Graph(), report.diagnostics, probe_objects=False)
    for field_name, title in BOOT_IMAGE_FINGERPRINTS:
        have = manifest.get(field_name)
        want = current.get(field_name)
        if have != want:
            interp.diag(
                "KV307",
                f"boot image's {title} ({str(have)[:24]}) disagrees with "
                f"this worker's ({str(want)[:24]}) — serving through its "
                "executables could return garbage; the image is refused "
                "and the worker warms through the classic path",
                field=field_name,
                image=str(have)[:24],
                worker=str(want)[:24],
            )
    report.seconds = time.perf_counter() - t0
    _publish(report, context)
    return report


def verify_pipeline(
    pipeline: Any,
    input_spec: Any = None,
    **kwargs: Any,
) -> VerifyReport:
    """Verify a ``Pipeline`` or ``FittedPipeline``: binds ``input_spec``
    (a ShapeDtypeStruct pytree for the pipeline's input batch) to the
    unbound source when given."""
    graph = pipeline.graph
    source_specs = {}
    source = getattr(pipeline, "source", None)
    if input_spec is not None and source is not None and source in graph.sources:
        source_specs[source] = input_spec
    kwargs.setdefault("context", type(pipeline).__name__)
    return verify_graph(graph, source_specs or None, **kwargs)


def _publish(report: VerifyReport, context: str) -> None:
    _names.metric(_names.VERIFY_RUNS).inc(context=context)
    _names.metric(_names.VERIFY_NODES).inc(len(report.annotations))
    _names.metric(_names.VERIFY_SECONDS).observe(report.seconds)
    diag_c = _names.metric(_names.VERIFY_DIAGNOSTICS)
    for d in report.diagnostics:
        diag_c.inc(code=d.code, severity=d.severity)


# ----------------------------------------------------------------- enforcement


def verification_mode() -> str:
    """``KEYSTONE_VERIFY``: ``warn`` (default — log and continue),
    ``strict`` (errors raise :class:`VerificationError`), ``off``."""
    raw = env_str("KEYSTONE_VERIFY", "warn").lower()
    if raw in ("off", "0", "disabled", "none"):
        return "off"
    if raw == "strict":
        return "strict"
    return "warn"


def verify_and_enforce(
    graph: Graph,
    context: str,
    source_specs: Optional[Dict[SourceId, Any]] = None,
    **kwargs: Any,
) -> Optional[VerifyReport]:
    """The fit/load hook: verify under the ``KEYSTONE_VERIFY`` mode.

    ``warn`` logs error/warning diagnostics and never interferes;
    ``strict`` raises :class:`VerificationError` when errors were found.
    An internal verifier failure is logged and swallowed in BOTH modes —
    a bug in the verifier must never take down a fit that would have
    succeeded (only *verified* findings raise).
    """
    mode = verification_mode()
    if mode == "off":
        return None
    try:
        report = verify_graph(
            graph, source_specs, context=context, **kwargs
        )
    except Exception:
        logger.warning(
            "plan-time verification of %s failed internally (ignored)",
            context,
            exc_info=True,
        )
        return None
    for d in report.diagnostics:
        if d.severity == ERROR:
            logger.warning("plan-time verify [%s]: %s", context, d.render())
        elif d.severity == WARNING:
            logger.info("plan-time verify [%s]: %s", context, d.render())
    if mode == "strict" and not report.ok:
        raise VerificationError(report)
    return report
