"""Typed pipeline API: Transformer / Estimator / LabelEstimator / Pipeline.

TPU-native re-design of the reference's public facade
(reference: workflow/Transformer.scala:18-70, workflow/Estimator.scala:10-62,
workflow/LabelEstimator.scala:13-100, workflow/Chainable.scala:13-126,
workflow/Pipeline.scala:22-155, workflow/FittedPipeline.scala:22-48).

Semantics preserved from the reference:

- ``a >> b >> est.with_data(data)`` builds an immutable DAG; nothing runs
  until a result is forced.
- Applying a pipeline yields lazy ``PipelineDataset``/``PipelineDatum``
  handles; forcing ``.get()`` runs the optimizer once, then executes with
  memoization.
- Estimators bound to data fit **once** per process even across repeated
  applications — results are memoized under structural prefixes in the
  process-wide state table.
- ``Pipeline.fit()`` executes every estimator, splices the fit transformers
  in place, prunes fit-time-only branches, and returns a serializable
  ``FittedPipeline`` containing only transformers.

What is different on TPU: datasets are sharded device batches rather than
RDDs, and transformer ``apply_batch`` implementations are jitted XLA
computations over whole batches rather than per-partition JVM loops.
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from ..data.dataset import ArrayDataset, Dataset, ObjectDataset, as_dataset
from .executor import GraphExecutor, PipelineEnv
from .graph import Graph, NodeId, NodeOrSourceId, SinkId, SourceId
from .operators import (
    DatasetOperator,
    DatumOperator,
    DelegatingOperator,
    EstimatorOperator,
    Expression,
    TransformerOperator,
)
from .rules import UnusedBranchRemovalRule


# --------------------------------------------------------------------- results


class PipelineResult:
    """Lazy handle on a pipeline output
    (reference: workflow/PipelineResult.scala:13-20)."""

    def __init__(self, executor: GraphExecutor, sink: SinkId, graph: Graph):
        self._executor = executor
        self._sink = sink
        self.graph = graph  # unoptimized graph, for further composition

    def get(self) -> Any:
        return self._executor.execute(self._sink).get()


class PipelineDataset(PipelineResult):
    """Lazy dataset result; duck-types enough of Dataset for evaluators."""

    def collect(self) -> List[Any]:
        return self.get().collect()

    def __len__(self) -> int:
        return len(self.get())


class PipelineDatum(PipelineResult):
    pass


# -------------------------------------------------------------------- chaining


class Chainable:
    """Mixin providing ``then`` / ``>>`` composition
    (reference: workflow/Chainable.scala:13-126)."""

    def to_pipeline(self) -> "Pipeline":
        raise NotImplementedError

    def then(self, nxt: "Chainable") -> "Pipeline":
        """``self`` then ``nxt`` (reference ``andThen``)."""
        this = self.to_pipeline()
        other = nxt.to_pipeline()
        combined, _, sink_map = this.graph.connect_graph(other.graph, {other.source: this.sink})
        return Pipeline(combined, this.source, sink_map[other.sink])

    def then_estimator(self, est: "Estimator", data: Union[Dataset, PipelineDataset, Any]) -> "Pipeline":
        """Fit ``est`` on this pipeline applied to ``data``; result applies
        self then the fit transformer (reference: Chainable.scala estimator
        overloads of andThen)."""
        return self.then(est.with_data(self.to_pipeline().apply(data)))

    def then_label_estimator(
        self,
        est: "LabelEstimator",
        data: Union[Dataset, PipelineDataset, Any],
        labels: Union[Dataset, PipelineDataset, Any],
    ) -> "Pipeline":
        return self.then(est.with_data(self.to_pipeline().apply(data), labels))

    def __rshift__(self, nxt: "Chainable") -> "Pipeline":
        return self.then(nxt)


# ----------------------------------------------------------------- transformer


class Transformer(TransformerOperator, Chainable):
    """Typed unary transformer (reference: workflow/Transformer.scala:18-70).

    Subclasses implement ``apply`` (one datum) and optionally override
    ``apply_batch`` with a device-batched implementation.
    """

    def apply(self, datum: Any) -> Any:
        raise NotImplementedError

    def apply_batch(self, dataset: Dataset) -> Dataset:
        return dataset.map(self.apply)

    # Operator protocol -----------------------------------------------------
    def single_transform(self, datums: List[Any]) -> Any:
        return self.apply(datums[0])

    def batch_transform(self, datasets: List[Dataset]) -> Dataset:
        return self.apply_batch(datasets[0])

    # Chaining --------------------------------------------------------------
    def to_pipeline(self) -> "Pipeline":
        graph = Graph()
        graph, source = graph.add_source()
        graph, node = graph.add_node(self, [source])
        graph, sink = graph.add_sink(node)
        return Pipeline(graph, source, sink)

    def __call__(self, data: Any) -> Any:
        if isinstance(data, (Dataset, PipelineDataset)):
            return self.to_pipeline().apply(data)
        return self.apply(data)

    @staticmethod
    def from_fn(fn: Callable[[Any], Any], batch_fn: Optional[Callable] = None, name: str = "") -> "Transformer":
        return _FnTransformer(fn, batch_fn, name)


class _FnTransformer(Transformer):
    def __init__(self, fn, batch_fn=None, name=""):
        self.fn = fn
        self.batch_fn = batch_fn
        self.name = name or getattr(fn, "__name__", "fn")

    @property
    def label(self) -> str:
        return self.name

    def apply(self, datum):
        return self.fn(datum)

    def apply_batch(self, dataset):
        if self.batch_fn is not None and isinstance(dataset, ArrayDataset):
            return dataset.map_batched(self.batch_fn)
        return dataset.map(self.fn)


class Identity(Transformer):
    """reference: workflow/Identity.scala:11"""

    def apply(self, datum: Any) -> Any:
        return datum

    def apply_batch(self, dataset: Dataset) -> Dataset:
        return dataset


class BatchTransformer(Transformer):
    """Transformer whose native form is whole-batch array computation.

    Subclasses implement ``apply_arrays(pytree) -> pytree`` (jit-friendly);
    per-datum apply wraps it with a singleton batch dimension.

    Batch application preserves the framework-wide invariant that rows past
    ``num_examples`` (mesh padding) stay exactly zero, so downstream
    Gram/gradient accumulations over the data axis are unaffected by
    padding no matter what elementwise work happens in between.

    ``apply_arrays`` must also be row-independent (output row i depends
    only on input row i) and jit-traceable — the contract the fusion pass
    (workflow/fusion.py) relies on to compose consecutive transformers
    into one compiled dispatch. Ops that manage their own sharding or
    dispatch set ``fusable = False`` to opt out.
    """

    #: Chain-fusion opt-out (see workflow/fusion.py).
    fusable: bool = True
    #: True only on FusedTransformerOperator (dispatch accounting label).
    _is_fused: bool = False

    def apply_arrays(self, data: Any) -> Any:
        raise NotImplementedError

    def apply(self, datum: Any) -> Any:
        import jax
        import jax.numpy as jnp

        # jnp.asarray keeps device arrays on device (np.asarray would force
        # a host round-trip per datum) and still handles scalars/lists.
        batched = jax.tree_util.tree_map(lambda a: jnp.asarray(a)[None], datum)
        out = self.apply_arrays(batched)
        return jax.tree_util.tree_map(lambda a: a[0], out)

    def apply_batch(self, dataset: Dataset) -> Dataset:
        import jax
        import jax.numpy as jnp

        from ..data.dataset import BucketedDataset

        if isinstance(dataset, BucketedDataset):
            # Native-resolution path: one static-shape application per
            # size bucket (each bucket compiles once, like any batch).
            return dataset.map_datasets(self.apply_batch)
        # Dispatch accounting: each batch application of a transformer is
        # one host→device round trip. The fused-vs-unfused split is the
        # direct evidence for the fusion pass (a k-node chain fused into
        # one operator counts 1 here instead of k) — see workflow/fusion.py
        # and the bench `fusion` leg. Bucketed batches count per bucket
        # (each bucket genuinely dispatches), via the recursion above.
        # A fused operator that latched its eager fallback no longer
        # dispatches once — count its members as unfused so the CI-gated
        # 1-dispatch invariant actually detects fusion degrading. (The
        # single batch that triggers the latch is counted fused — the
        # latch flips mid-apply — every batch after it is counted true.)
        from ..obs import names as _names

        counter = _names.metric(_names.FUSION_BATCH_DISPATCHES)
        if self._is_fused and getattr(self, "_eager_fallback", False):
            counter.inc(len(self.members), fused="0")
        else:
            counter.inc(fused="1" if self._is_fused else "0")
        if isinstance(dataset, ObjectDataset):
            dataset = dataset.to_arrays()
        assert isinstance(dataset, ArrayDataset)
        if (
            isinstance(dataset.data, dict)
            and "desc" in dataset.data
            and "valid" in dataset.data
        ):
            # Masked descriptor convention ({"desc": (N, n_pad, d),
            # "valid": (N, n_pad)} from ops.images.native): the op acts on
            # the descriptors, validity flows through untouched. Safe for
            # the chain between extractor and FisherVector (elementwise
            # maps and PCA matmuls keep zero rows zero).
            out = self.apply_arrays(dataset.data["desc"])
            return ArrayDataset(
                {"desc": out, "valid": dataset.data["valid"]},
                dataset.num_examples,
            )
        out = dataset.map_batched(self.apply_arrays)
        if out.physical_rows > out.num_examples:
            real_row = jnp.arange(out.physical_rows) < out.num_examples

            def zero_pad_rows(a):
                # where (not multiply): ops like log/div turn zero pad rows
                # into NaN/Inf, and 0*NaN is NaN — select restores exact 0.
                m = real_row.reshape((-1,) + (1,) * (a.ndim - 1))
                return jnp.where(m, a, jnp.zeros((), dtype=a.dtype))

            out = ArrayDataset(
                jax.tree_util.tree_map(zero_pad_rows, out.data), out.num_examples
            )
        return out


# ------------------------------------------------------------------ estimators


class Estimator(EstimatorOperator):
    """Unsupervised estimator (reference: workflow/Estimator.scala:10-62)."""

    def fit(self, data: Dataset) -> Transformer:
        raise NotImplementedError

    def fit_datasets(self, datasets: List[Dataset]) -> TransformerOperator:
        return self.fit(datasets[0])

    def with_data(self, data: Union[Dataset, PipelineDataset, Any]) -> "Pipeline":
        """Bind training data now; returns a pipeline applying the (lazily)
        fit transformer to its input (reference: Estimator.scala:29-46)."""
        graph = Graph()
        graph, data_dep = _attach_data(graph, data)
        graph, est_node = graph.add_node(self, [data_dep])
        graph, source = graph.add_source()
        graph, delegating = graph.add_node(DelegatingOperator(), [est_node, source])
        graph, sink = graph.add_sink(delegating)
        return Pipeline(graph, source, sink)


class LabelEstimator(EstimatorOperator):
    """Supervised estimator (reference: workflow/LabelEstimator.scala:13-100)."""

    def fit(self, data: Dataset, labels: Dataset) -> Transformer:
        raise NotImplementedError

    def fit_datasets(self, datasets: List[Dataset]) -> TransformerOperator:
        return self.fit(datasets[0], datasets[1])

    def with_data(
        self,
        data: Union[Dataset, PipelineDataset, Any],
        labels: Union[Dataset, PipelineDataset, Any],
    ) -> "Pipeline":
        graph = Graph()
        graph, data_dep = _attach_data(graph, data)
        graph, labels_dep = _attach_data(graph, labels)
        graph, est_node = graph.add_node(self, [data_dep, labels_dep])
        graph, source = graph.add_source()
        graph, delegating = graph.add_node(DelegatingOperator(), [est_node, source])
        graph, sink = graph.add_sink(delegating)
        return Pipeline(graph, source, sink)


def _attach_data(graph: Graph, data: Any):
    """Attach a dataset (or lazy pipeline result graph) to ``graph``."""
    if isinstance(data, PipelineDataset):
        combined, _, sink_map = graph.add_graph(data.graph)
        inner_sink = sink_map[data._sink]
        dep = combined.get_sink_dependency(inner_sink)
        return combined.remove_sink(inner_sink), dep
    dataset = as_dataset(data)
    graph, node = graph.add_node(DatasetOperator(dataset), [])
    return graph, node


# -------------------------------------------------------------------- pipeline


class Pipeline(Chainable):
    """A single-input single-output dataflow with fit-on-demand semantics."""

    def __init__(self, graph: Graph, source: SourceId, sink: SinkId):
        self.graph = graph
        self.source = source
        self.sink = sink

    def to_pipeline(self) -> "Pipeline":
        return self

    # ------------------------------------------------------------------ apply
    def apply(self, data: Any) -> PipelineResult:
        if isinstance(data, PipelineDataset):
            combined, _, sink_map = data.graph.add_graph(self.graph)
            new_source = _find_mapped_source(self.graph, self.source, combined, data.graph)
            inner_dep = combined.get_sink_dependency(data._sink)
            combined = combined.remove_sink(data._sink)
            combined = combined.replace_dependency(new_source, inner_dep)
            combined = combined.remove_source(new_source)
            sink = sink_map[self.sink]
            return PipelineDataset(GraphExecutor(combined), sink, combined)
        if isinstance(data, (Dataset, list, tuple)) or _is_array(data):
            dataset = as_dataset(data)
            graph, node = self.graph.add_node(DatasetOperator(dataset), [])
            graph = graph.replace_dependency(self.source, node)
            graph = graph.remove_source(self.source)
            return PipelineDataset(GraphExecutor(graph), self.sink, graph)
        # single datum
        graph, node = self.graph.add_node(DatumOperator(data), [])
        graph = graph.replace_dependency(self.source, node)
        graph = graph.remove_source(self.source)
        return PipelineDatum(GraphExecutor(graph), self.sink, graph)

    def __call__(self, data: Any) -> PipelineResult:
        return self.apply(data)

    # -------------------------------------------------------------------- fit
    def fit(self) -> "FittedPipeline":
        """Execute all estimator fits and return a transformer-only pipeline
        (reference: Pipeline.scala:38-65).

        Before any fit executes, the OPTIMIZED graph goes through the
        plan-time static verifier (workflow/verify.py): shape/dtype
        mismatches, float64 widening, and infeasible streamed fits are
        diagnosed from specs alone — warn-by-default,
        ``KEYSTONE_VERIFY=strict`` raises ``VerificationError`` here
        instead of failing minutes later inside a jit trace."""
        from .verify import verify_and_enforce

        env = PipelineEnv.get_or_create()
        graph, prefixes = env.optimizer.execute(self.graph)
        verify_and_enforce(graph, context="fit")
        executor = GraphExecutor(graph, optimize=False)
        executor._prefixes = prefixes

        for node in sorted(graph.nodes):
            op = graph.operators.get(node)
            if not isinstance(op, DelegatingOperator):
                continue
            deps = graph.get_dependencies(node)
            transformer_dep, data_deps = deps[0], deps[1:]
            fit_transformer = executor.execute(transformer_dep).get()
            if not isinstance(fit_transformer, TransformerOperator):
                raise TypeError(
                    f"delegating node {node} resolved to {type(fit_transformer).__name__}"
                )
            graph = graph.set_operator(node, fit_transformer)
            graph = graph.set_dependencies(node, data_deps)
            # keep executor and graph views consistent for later delegating nodes
            executor._optimized = graph
            executor._memo.pop(node, None)

        graph, _ = UnusedBranchRemovalRule().apply(graph, {})
        # The spliced graph is transformer-only: newly-adjacent chains
        # (fit transformer next to its featurization) fuse into single
        # compiled dispatches for the apply/serving path. The optimizer's
        # own fusion batch can't see these chains — they exist only after
        # delegating nodes collapse.
        return FittedPipeline(graph, self.source, self.sink).fused()

    # ------------------------------------------------------------------ gather
    @staticmethod
    def gather(branches: Sequence[Chainable]) -> "Pipeline":
        """Merge parallel branches into one pipeline emitting, per input,
        the list of branch outputs (reference: Pipeline.scala:119-154)."""
        from ..ops.util.gather import GatherTransformer

        graph = Graph()
        graph, source = graph.add_source()
        ends: List[NodeOrSourceId] = []
        for branch in branches:
            bp = branch.to_pipeline()
            combined, source_map, sink_map = graph.add_graph(bp.graph)
            mapped_source = source_map[bp.source]
            combined = combined.replace_dependency(mapped_source, source)
            combined = combined.remove_source(mapped_source)
            mapped_sink = sink_map[bp.sink]
            ends.append(combined.get_sink_dependency(mapped_sink))
            graph = combined.remove_sink(mapped_sink)
        graph, gather_node = graph.add_node(GatherTransformer(), ends)
        graph, sink = graph.add_sink(gather_node)
        return Pipeline(graph, source, sink)

    def to_dot(self) -> str:
        return self.graph.to_dot()


def _is_array(x: Any) -> bool:
    import numpy as np

    return hasattr(x, "shape") and hasattr(x, "dtype") and not isinstance(x, (np.generic,))


def _find_mapped_source(
    orig_graph: Graph, orig_source: SourceId, combined: Graph, base_graph: Graph
) -> SourceId:
    """Locate where ``orig_source`` landed after ``base_graph.add_graph(orig)``.

    ``add_graph`` remaps ids deterministically (sorted order past max id), so
    recompute the mapping the same way.
    """
    _, source_map, _ = base_graph.add_graph(orig_graph)
    return source_map[orig_source]


# ------------------------------------------------------------- fitted pipeline


class FittedPipeline(Transformer):
    """Transformer-only pipeline: serializable, no estimators, no re-fitting
    (reference: workflow/FittedPipeline.scala:22-48)."""

    def __init__(self, graph: Graph, source: SourceId, sink: SinkId):
        self.graph = graph
        self.source = source
        self.sink = sink
        # Serving-loop fast path: the datum-bound graph is built once and
        # reused; only the DatumOperator's payload is swapped per call,
        # under a lock so concurrent serving calls can't read each
        # other's datum. Safe because per-datum execution runs with
        # optimize=False — a fresh executor per call, no cross-call memo,
        # no prefix write-back keyed on the (mutated) operator.
        self._datum_op: Optional[DatumOperator] = None
        self._datum_graph: Optional[Graph] = None
        self._datum_lock = threading.Lock()
        self._compiled: Optional["CompiledApply"] = None

    def __getstate__(self):
        # save() must not pickle the last served datum (or the lock, or
        # the serving handle's bound graph/payload).
        state = self.__dict__.copy()
        state["_datum_op"] = None
        state["_datum_graph"] = None
        state["_datum_lock"] = None
        state["_compiled"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._datum_lock = threading.Lock()
        # Artifacts saved before the serving layer existed lack the slot.
        self._compiled = None

    def apply(self, datum: Any) -> Any:
        with self._datum_lock:
            if self._datum_graph is None:
                self._datum_op = DatumOperator(datum)
                graph, node = self.graph.add_node(self._datum_op, [])
                graph = graph.replace_dependency(self.source, node)
                self._datum_graph = graph.remove_source(self.source)
            else:
                self._datum_op.datum = datum
            executor = GraphExecutor(self._datum_graph, optimize=False)
            return executor.execute(self.sink).get()

    def apply_batch(self, dataset: Dataset) -> Dataset:
        graph, node = self.graph.add_node(DatasetOperator(dataset), [])
        graph = graph.replace_dependency(self.source, node)
        graph = graph.remove_source(self.source)
        executor = GraphExecutor(graph, optimize=False)
        return executor.execute(self.sink).get()

    def fused(self) -> "FittedPipeline":
        """This pipeline with transformer chains collapsed into single
        compiled dispatches (workflow/fusion.py). Returns ``self`` when
        fusion is disabled or nothing fuses; otherwise a NEW pipeline
        (graph surgery never mutates in place). ``Pipeline.fit`` calls
        this, and the serving registry re-fuses loaded artifacts that
        were saved before fusion existed."""
        from .fusion import fuse_graph, fusion_enabled

        if not fusion_enabled():
            return self
        graph = fuse_graph(self.graph)
        if graph == self.graph:
            return self
        return FittedPipeline(graph, self.source, self.sink)

    def compiled_apply(self) -> "CompiledApply":
        """The serving-loop batch handle: graph bound once, only the
        dataset payload swapped per call (the batch analog of the datum
        fast path above). Cached on the pipeline — all servers applying
        this fitted pipeline share one handle."""
        if self._compiled is None:
            self._compiled = CompiledApply(self)
        return self._compiled

    # ---------------------------------------------------------- serialization
    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            pickle.dump(self, f)

    @staticmethod
    def load(path: str) -> "FittedPipeline":
        with open(path, "rb") as f:
            out = pickle.load(f)
        if not isinstance(out, FittedPipeline):
            raise TypeError(f"{path} does not contain a FittedPipeline")
        return out


class CompiledApply:
    """Reusable batch-apply handle over a :class:`FittedPipeline`.

    ``apply_batch`` rebuilds the datum-bound graph on every call; a
    serving loop calls apply thousands of times per second, so this
    handle binds the graph ONCE and swaps only the ``DatasetOperator``
    payload per call, under a lock (same contract as the datum fast
    path: per-call execution runs optimize=False with a fresh executor,
    so no cross-call memo or prefix write-back sees the mutation).

    Shape discipline is the caller's job: feeding batches whose padded
    physical shapes cycle through a small bucket set means the jitted
    transformer bodies underneath hit XLA's executable cache instead of
    recompiling — see serving/batcher.py and utils/aot.warm_buckets.

    Multi-device serving: an eligible ``partition`` decision
    (parallel/partitioner.py, installed by ``attach_serving_partition``
    at warmup/load) places each batch's rows ``NamedSharding``-sharded
    over the mesh before binding, so the warmed executables run
    data-parallel. Placement is a pure function of the batch's physical
    rows (a bucket either always shards or never does), so the warmed
    layout set is exactly the steady-state layout set — zero
    steady-state compiles preserved.
    """

    def __init__(self, fitted: FittedPipeline):
        self._fitted = fitted
        self._op: Optional[DatasetOperator] = None
        self._graph: Optional[Graph] = None
        self._lock = threading.Lock()
        self.calls = 0
        #: PartitionDecision or None (parallel/partitioner.py).
        self.partition = None
        self._imbalance_gauge = None

    def __call__(self, dataset: Union[Dataset, Any]) -> Dataset:
        if not isinstance(dataset, Dataset):
            dataset = as_dataset(dataset)
        # One read: the attach path may swap the decision concurrently,
        # and placement + accounting must see the same one.
        partition = self.partition
        if partition is not None and isinstance(dataset, ArrayDataset):
            from ..parallel.partitioner import shard_rows

            physical = dataset.physical_rows
            dataset = ArrayDataset(
                shard_rows(partition, dataset.data),
                num_examples=dataset.num_examples,
            )
            if physical and physical % partition.shards == 0:
                if self._imbalance_gauge is None:
                    from ..obs import names as _names

                    self._imbalance_gauge = _names.metric(
                        _names.PARTITION_IMBALANCE
                    )
                self._imbalance_gauge.set(
                    1.0 - dataset.num_examples / physical, kind="serve"
                )
        fitted = self._fitted
        with self._lock:
            if self._graph is None:
                self._op = DatasetOperator(dataset)
                graph, node = fitted.graph.add_node(self._op, [])
                graph = graph.replace_dependency(fitted.source, node)
                self._graph = graph.remove_source(fitted.source)
            else:
                self._op.dataset = dataset
            self.calls += 1
            executor = GraphExecutor(self._graph, optimize=False)
            return executor.execute(fitted.sink).get()
