"""Data-driven node-level optimization.

TPU-native re-design of the reference's sample-driven operator selection
(reference: workflow/NodeOptimizationRule.scala:14-198,
workflow/OptimizableNodes.scala:7-50). ``Optimizable`` operators inspect a
small sample of their input plus dataset statistics (n, d, k, sparsity,
device count) and swap themselves for a concrete implementation chosen by a
cost model — e.g. the least-squares meta-solver picking exact normal
equations vs L-BFGS vs block coordinate descent
(reference: nodes/learning/LeastSquaresEstimator.scala:26-87).

The sample interpreter executes the node's ancestry with every bound
dataset subsampled to ``sample_size`` items — the analog of the reference's
``SampleCollector`` mini-interpreter that pulled a few items per partition
through the DAG.
"""

from __future__ import annotations

import copy
import logging
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..data.dataset import ArrayDataset, Dataset, ObjectDataset
from .graph import Graph, NodeId, SinkId, SourceId
from .operators import (
    DatasetOperator,
    DatumOperator,
    EstimatorOperator,
    Expression,
    Operator,
    wrap_expression,
)
from .rules import PrefixMap, Rule


@dataclass
class DataStats:
    """Statistics handed to ``Optimizable.optimize``."""

    n_total: int
    num_shards: int
    n_per_shard: List[int]


class Optimizable:
    """Mixin for operators that can self-specialize from data statistics."""

    def optimize(self, samples: List[Dataset], stats: DataStats) -> Operator:
        """Return the concrete operator to use (may be ``self``)."""
        raise NotImplementedError


class NodeOptimizationRule(Rule):
    """Run samples through the plan; let Optimizable nodes pick an impl."""

    def __init__(self, sample_size: int = 100):
        self.sample_size = sample_size

    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        optimizable = [
            n for n in sorted(graph.nodes) if isinstance(graph.get_operator(n), Optimizable)
        ]
        if not optimizable:
            return graph, prefixes

        sampler = _SampleInterpreter(graph, self.sample_size)
        for node in optimizable:
            op = graph.get_operator(node)
            try:
                samples = [sampler.execute(d) for d in graph.get_dependencies(node)]
                sample_datasets = [s for s in samples if isinstance(s, Dataset)]
                stats = sampler.stats_for(graph.get_dependencies(node))
                replacement = op.optimize(sample_datasets, stats)
            except Exception as e:  # sampling must never break planning
                logging.getLogger(__name__).warning(
                    "node optimization skipped for %s (%s): falling back to "
                    "the default operator", node, e,
                )
                continue
            if replacement is not op:
                graph = graph.set_operator(node, replacement)
        return graph, prefixes


class _SampleInterpreter:
    """Executes the graph with all bound datasets truncated to a sample."""

    def __init__(self, graph: Graph, sample_size: int):
        self.graph = graph
        self.sample_size = sample_size
        self._memo: Dict = {}
        self._full_sizes: Dict = {}

    def execute(self, graph_id):
        if graph_id in self._memo:
            return self._memo[graph_id]
        if isinstance(graph_id, SourceId):
            raise ValueError("cannot sample through an unbound source")
        if isinstance(graph_id, SinkId):
            return self.execute(self.graph.get_sink_dependency(graph_id))

        op = self.graph.get_operator(graph_id)
        if isinstance(op, DatasetOperator):
            full = op.dataset
            self._full_sizes[graph_id] = (len(full), full.num_shards)
            result = _subsample(full, self.sample_size)
        else:
            deps = [self.execute(d) for d in self.graph.get_dependencies(graph_id)]
            expressions = [wrap_expression(d) for d in deps]
            result = op.execute(expressions).get()
        self._memo[graph_id] = result
        return result

    def stats_for(self, dep_ids) -> DataStats:
        """Full-data statistics for a node's dependency subtree."""
        n_total, shards = 0, 1
        for dep in dep_ids:
            info = self._lookup_size(dep)
            if info is not None:
                n_total = max(n_total, info[0])
                shards = max(shards, info[1])
        base, extra = divmod(n_total, shards)
        return DataStats(
            n_total=n_total,
            num_shards=shards,
            n_per_shard=[base + (1 if i < extra else 0) for i in range(shards)],
        )

    def _lookup_size(self, graph_id) -> Optional[Tuple[int, int]]:
        if graph_id in self._full_sizes:
            return self._full_sizes[graph_id]
        if isinstance(graph_id, NodeId):
            for dep in self.graph.get_dependencies(graph_id):
                info = self._lookup_size(dep)
                if info is not None:
                    return info
        return None


class PartitionPlanRule(Rule):
    """Consult the :class:`~keystone_tpu.parallel.partitioner.Partitioner`
    for every fit in the plan — the LAST optimizer batch (after
    measured-knobs, so a measured ``chunk_rows`` override is what gets
    rounded to the shard count, docs/PARTITIONING.md).

    Eligible nodes get the decision PINNED onto a copy of their operator
    (``op.partition`` — the same pin-on-copy idiom as MeasuredKnobRule):

    - ``StreamingFitOperator`` — the chunk plan shards data-parallel
      (chunk_rows rounded up to a shard multiple so the one compiled
      chunk shape divides evenly across devices);
    - other estimators — the in-core fit shards rows over the decided
      mesh (``partitioner.fit_mesh``), Gram partials psummed across it.

    Ineligible nodes are still DECIDED — the fallback reason lands in the
    partition report so ``check --pipeline`` and BENCH json can explain
    why a plan runs single-device. The rule never errors a plan.
    """

    def __init__(self, mesh=None):
        self.mesh = mesh

    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        from ..parallel.partitioner import (
            Partitioner,
            partition_enabled,
            reset_partition_report,
        )
        from .streaming import StreamingFitOperator, stream_chunk_rows

        reset_partition_report()
        if not partition_enabled():
            # Disabled = the LEGACY (pre-partitioner) behavior: nothing
            # pinned, in-core fits keep the ambient mesh, and the empty
            # report plus the env knob is the explanation
            # (docs/PARTITIONING.md).
            return graph, prefixes
        part = Partitioner(mesh=self.mesh)
        for node in sorted(graph.nodes):
            op = graph.get_operator(node)
            if not isinstance(op, EstimatorOperator):
                continue
            label = str(getattr(op, "label", type(op).__name__))
            streaming = isinstance(op, StreamingFitOperator)
            # The opt-out lives on the estimator the user wrote — for a
            # streamed fit that is the WRAPPED estimator, not the
            # planner-built StreamingFitOperator around it.
            target = op.estimator if streaming else op
            opt_out = getattr(target, "partitionable", True) is False
            rows = _upstream_rows(graph, node)
            # The model (feature) axis only helps operators whose carry
            # declares a blocked layout; width is the RAW upstream column
            # count — a best-effort floor proxy for the featurized width
            # (streams re-validate against the real width at fold time).
            model_ok = getattr(target, "supports_model_axis", False)
            width = _upstream_width(graph, node)
            if streaming:
                decision = part.decide_stream(
                    label, op.chunk_rows or stream_chunk_rows(), rows=rows,
                    opt_out=opt_out, width=width, model_ok=model_ok,
                )
            else:
                decision = part.decide_fit(
                    label, rows, opt_out=opt_out, width=width,
                    model_ok=model_ok,
                )
            # Pin only ELIGIBLE decisions, and always onto a COPY: the
            # user still holds the original estimator, and a fit that is
            # not partition-managed must run the user's own object on
            # the legacy ambient-mesh path (a fallback is recorded in
            # the report, not pinned — fit_mesh's docstring spells out
            # the semantics).
            if decision.eligible:
                pinned = copy.copy(op)
                pinned.partition = decision
                if streaming:
                    pinned.chunk_rows = decision.chunk_rows
                graph = graph.set_operator(node, pinned)
        return graph, prefixes


def _upstream_rows(graph: Graph, node: NodeId) -> Optional[int]:
    """Row count feeding a fit: walk the first-dependency ancestry to a
    bound dataset (transformers are row-preserving by the framework
    contract, so the head's length IS the fit's row count). ``None``
    when the head is unbound/unsized (a Cacher, a source)."""
    seen = set()
    cur = graph.get_dependencies(node)
    cur = cur[0] if cur else None
    while isinstance(cur, NodeId) and cur not in seen:
        seen.add(cur)
        op = graph.get_operator(cur)
        if isinstance(op, DatasetOperator):
            try:
                return len(op.dataset)
            except Exception:
                return None
        deps = graph.get_dependencies(cur)
        cur = deps[0] if deps else None
    return None


def _upstream_width(graph: Graph, node: NodeId) -> Optional[int]:
    """Column count of the bound dataset feeding a fit — the planner's
    proxy for the featurized width when deciding the model (feature)
    axis. Only a proxy: featurizers may widen or narrow it, so streamed
    fits re-validate against the real featurized width at fold time
    (``demote_model_axis``). ``None`` when the head is unbound or not a
    2-D array dataset."""
    seen = set()
    cur = graph.get_dependencies(node)
    cur = cur[0] if cur else None
    while isinstance(cur, NodeId) and cur not in seen:
        seen.add(cur)
        op = graph.get_operator(cur)
        if isinstance(op, DatasetOperator):
            ds = op.dataset
            if isinstance(ds, ArrayDataset):
                import jax

                for leaf in jax.tree_util.tree_leaves(ds.data):
                    shape = getattr(leaf, "shape", ())
                    if len(shape) >= 2:
                        return int(shape[1])
            return None
        deps = graph.get_dependencies(cur)
        cur = deps[0] if deps else None
    return None


def _subsample(dataset: Dataset, n: int) -> Dataset:
    if len(dataset) <= n:
        return dataset
    if isinstance(dataset, ArrayDataset):
        import jax

        data = jax.tree_util.tree_map(lambda a: a[:n], dataset.data)
        return ArrayDataset(data, num_examples=n)
    return ObjectDataset(dataset.take(n))
