"""Pipeline workflow layer: graph IR, operators, executor, optimizer, typed API."""

from .graph import Graph, NodeId, SinkId, SourceId
from .operators import (
    DatasetOperator,
    DatumOperator,
    DelegatingOperator,
    EstimatorOperator,
    Expression,
    ExpressionOperator,
    Operator,
    TransformerOperator,
)
from .executor import GraphExecutor, PipelineEnv
from .pipeline import (
    BatchTransformer,
    Chainable,
    Estimator,
    FittedPipeline,
    Identity,
    LabelEstimator,
    Pipeline,
    PipelineDataset,
    PipelineDatum,
    PipelineResult,
    Transformer,
)
from .prefix import Prefix, find_prefix
from .rules import (
    Batch,
    EquivalentNodeMergeRule,
    Rule,
    RuleExecutor,
    UnusedBranchRemovalRule,
    auto_caching_optimizer,
    default_optimizer,
)
from .optimize import DataStats, NodeOptimizationRule, Optimizable
from .fusion import (
    FusedTransformerOperator,
    NodeFusionRule,
    fuse_graph,
    fusion_disabled,
    fusion_enabled,
    set_fusion_enabled,
)
from .streaming import (
    ChunkStream,
    StreamingFitOperator,
    StreamingPlanRule,
    last_stream_report,
    set_streaming_enabled,
    stream_pipelined,
    streaming_disabled,
    streaming_enabled,
)
from .knobs import MeasuredKnobRule, knob_mode
from .tracing import PipelineTrace, current_trace, trace
from .tune import RidgeCostModel, Tuner, TuneOutcome, TuneSpace

__all__ = [
    "Graph", "NodeId", "SinkId", "SourceId",
    "Operator", "DatasetOperator", "DatumOperator", "DelegatingOperator",
    "EstimatorOperator", "ExpressionOperator", "TransformerOperator", "Expression",
    "GraphExecutor", "PipelineEnv",
    "Transformer", "BatchTransformer", "Estimator", "LabelEstimator",
    "Pipeline", "FittedPipeline", "Identity", "Chainable",
    "PipelineResult", "PipelineDataset", "PipelineDatum",
    "Prefix", "find_prefix",
    "Rule", "Batch", "RuleExecutor", "EquivalentNodeMergeRule",
    "UnusedBranchRemovalRule", "default_optimizer", "auto_caching_optimizer",
    "DataStats", "NodeOptimizationRule", "Optimizable",
    "FusedTransformerOperator", "NodeFusionRule", "fuse_graph",
    "fusion_enabled", "fusion_disabled", "set_fusion_enabled",
    "ChunkStream", "StreamingFitOperator", "StreamingPlanRule",
    "stream_pipelined", "last_stream_report",
    "streaming_enabled", "streaming_disabled", "set_streaming_enabled",
    "MeasuredKnobRule", "knob_mode",
    "RidgeCostModel", "Tuner", "TuneOutcome", "TuneSpace",
    "PipelineTrace", "current_trace", "trace",
]
