"""Graph analysis: ancestry, reachability, deterministic linearization.

TPU-native re-design of the reference's graph analyses
(reference: workflow/AnalysisUtils.scala:3-122).

Linearization is ITERATIVE (an explicit DFS stack) and cycle-checking:
the graph surgery API (``set_dependencies`` / ``replace_dependency``)
can produce a cyclic "DAG", and before this module detected it the
failure mode was a recursion overflow deep inside an ancestry walk —
or, worse, a silently wrong topological order feeding the executor.
A cycle now raises :class:`GraphCycleError` carrying the exact cycle
path, and the plan-time verifier (workflow/verify.py) surfaces it as a
``KV401`` diagnostic before any data touches a device. Deep linear
chains (thousands of nodes) linearize without hitting the interpreter
recursion limit for the same reason.
"""

from __future__ import annotations

from typing import List, Optional, Set

from .graph import Graph, GraphId, NodeId, SinkId, SourceId


class GraphCycleError(ValueError):
    """A dependency walk found a cycle. ``cycle`` is the closed path
    (first vertex repeated last) in dependency order."""

    def __init__(self, cycle: List[GraphId]):
        self.cycle = list(cycle)
        path = " -> ".join(repr(v) for v in self.cycle)
        super().__init__(
            f"pipeline graph contains a dependency cycle: {path} "
            "(a node transitively depends on its own output; check "
            "set_dependencies/replace_dependency surgery)"
        )


def get_parents(graph: Graph, vid: GraphId) -> List[GraphId]:
    """Direct dependencies of a vertex, in order."""
    if isinstance(vid, SinkId):
        return [graph.get_sink_dependency(vid)]
    if isinstance(vid, NodeId):
        return list(graph.get_dependencies(vid))
    return []


def get_children(graph: Graph, vid: GraphId) -> Set[GraphId]:
    """All vertices that directly consume ``vid``."""
    children: Set[GraphId] = set()
    for node, deps in graph.dependencies.items():
        if vid in deps:
            children.add(node)
    for sink, dep in graph.sink_dependencies.items():
        if dep == vid:
            children.add(sink)
    return children


def get_ancestors(graph: Graph, vid: GraphId) -> Set[GraphId]:
    """Transitive closure of parents (excluding ``vid`` itself)."""
    seen: Set[GraphId] = set()
    stack = get_parents(graph, vid)
    while stack:
        v = stack.pop()
        if v in seen:
            continue
        seen.add(v)
        stack.extend(get_parents(graph, v))
    return seen


def get_descendants(graph: Graph, vid: GraphId) -> Set[GraphId]:
    """Transitive closure of children (excluding ``vid`` itself)."""
    seen: Set[GraphId] = set()
    stack = list(get_children(graph, vid))
    while stack:
        v = stack.pop()
        if v in seen:
            continue
        seen.add(v)
        stack.extend(get_children(graph, v))
    return seen


def find_cycle(graph: Graph) -> Optional[List[GraphId]]:
    """The first dependency cycle found, as a closed path (first vertex
    repeated last), or ``None`` for a genuine DAG. Deterministic: roots
    and dependencies are visited in sorted/declared order."""
    seen: Set[GraphId] = set()
    roots = sorted(graph.sink_dependencies) + sorted(graph.operators)
    for root in roots:
        if root in seen:
            continue
        cycle = _dfs(graph, root, seen, collect=None)
        if cycle is not None:
            return cycle
    return None


def _dfs(
    graph: Graph,
    root: GraphId,
    seen: Set[GraphId],
    collect: Optional[List[GraphId]],
) -> Optional[List[GraphId]]:
    """Iterative post-order DFS from ``root``.

    Appends finished vertices to ``collect`` (when given) in
    topological order; returns a closed cycle path if one is reachable,
    else ``None``. ``seen`` persists across calls so multi-root walks
    share work.
    """
    # Stack of (vertex, parent-iterator); on_stack is the grey set.
    on_stack: Set[GraphId] = set()
    path: List[GraphId] = []
    stack = [(root, iter(get_parents(graph, root)))]
    if root in seen:
        return None
    seen.add(root)
    on_stack.add(root)
    path.append(root)
    while stack:
        vertex, parents = stack[-1]
        advanced = False
        for parent in parents:
            if parent in on_stack:
                # Back edge: close the cycle from parent's position.
                start = path.index(parent)
                return path[start:] + [parent]
            if parent in seen:
                continue
            seen.add(parent)
            on_stack.add(parent)
            path.append(parent)
            stack.append((parent, iter(get_parents(graph, parent))))
            advanced = True
            break
        if not advanced:
            stack.pop()
            on_stack.discard(vertex)
            path.pop()
            if collect is not None:
                collect.append(vertex)
    return None


def linearize(graph: Graph, vid: GraphId) -> List[GraphId]:
    """Deterministic topological order of ``vid``'s ancestors plus ``vid``.

    Depth-first post-order with ordered dependency traversal, so equal
    graphs always linearize identically (reference: AnalysisUtils.scala
    topological linearization). Raises :class:`GraphCycleError` if the
    walk closes a cycle.
    """
    order: List[GraphId] = []
    cycle = _dfs(graph, vid, set(), collect=order)
    if cycle is not None:
        raise GraphCycleError(cycle)
    return order


def linearize_whole(graph: Graph) -> List[GraphId]:
    """Topological order over the entire graph (all sinks, sorted).
    Raises :class:`GraphCycleError` on a cyclic graph."""
    order: List[GraphId] = []
    seen: Set[GraphId] = set()
    for root in sorted(graph.sink_dependencies) + sorted(graph.operators):
        cycle = _dfs(graph, root, seen, collect=order)
        if cycle is not None:
            raise GraphCycleError(cycle)
    return order
