"""MeasuredKnobRule: plan knobs chosen from measured history, not env
defaults.

BENCH_r05 showed per-shape fp32/bf16 spreads of 1.4-8× and MFU cliffs
that no single static default survives — yet chunk rows, solver block
size, and solver precision all default to env vars today. This rule
closes the loop the profile store opens (docs/OBSERVABILITY.md): every
streaming fit and solver fit records what its knob settings achieved per
shape class; this rule, running as the LAST optimizer batch (after
streaming, so the ``StreamingFitOperator`` nodes it tunes exist —
docs/OPTIMIZER.md), overrides the *defaults* from the best recorded
observation:

- **stream chunk rows** — the best-throughput recorded ``chunk_rows``
  for this featurize chain + data shape class is pinned onto the
  ``StreamingFitOperator`` (an explicit ``KEYSTONE_STREAM_CHUNK_ROWS``
  always wins). Semantics-free: chunking is parity-tested at any size.
- **solver precision** — the fastest recorded precision mode for this
  shape class is pinned onto the estimator operator
  (``solver_precision``) and applied ONLY around that operator's fit via
  ``parallel.linalg.solver_mode_scope`` — never as process state, so
  unplanned solves and concurrent fits keep their own default (an
  explicit ``KEYSTONE_SOLVER_PRECISION`` always wins).
- **solver block size** — estimators carrying a ``block_size`` are
  re-created with the best recorded block for the shape class; setting
  ``KEYSTONE_SOLVER_BLOCK`` (to any value — it is consumed only here)
  pins constructor-chosen block sizes against measured overrides.

Precision and block size change *numerics within solver tolerance*
(different Gauss-Seidel block order, different matmul precision), so
they are gated behind ``KEYSTONE_MEASURED_KNOBS=all``; the default
(``on``) applies only the semantics-free chunk-rows override, and
``off`` disables the rule entirely.

Every override is recorded as a span attribute on the
``optimize:measured-knobs`` span and counted in
``keystone_profile_store_knob_overrides_total{knob=...}``.
"""

from __future__ import annotations

import copy
import logging
from typing import Any, Dict, Optional, Tuple

from ..envknobs import env_raw, env_set, env_str
from ..obs import cost as _cost
from ..obs import names as _names
from ..obs import spans as _spans
from ..obs import store as _store
from .graph import Graph
from .operators import DatasetOperator, EstimatorOperator
from .rules import PrefixMap, Rule

logger = logging.getLogger(__name__)


def knob_mode() -> str:
    """``KEYSTONE_MEASURED_KNOBS``: ``on`` (default — semantics-free
    overrides only), ``all`` (also precision/block size), ``off``."""
    mode = env_str("KEYSTONE_MEASURED_KNOBS", "on").lower()
    if mode in ("off", "0", "disabled"):
        return "off"
    return "all" if mode == "all" else "on"


def _best_entry(
    store, key_prefix: str, measure: str, shape: Optional[str] = None,
    rows: Optional[str] = None, maximize: bool = True,
    require: Tuple[str, ...] = (),
) -> Optional[Tuple[str, Dict[str, Any]]]:
    """The (key, measurements) with the best ``measure`` among matching
    entries that also carry every ``require`` field — ties broken by key
    for determinism across runs."""
    best: Optional[Tuple[str, Dict[str, Any]]] = None
    best_v: Optional[float] = None
    for key, _shape, m in sorted(
        store.entries(key_prefix=key_prefix, shape=shape, rows=rows)
    ):
        if measure not in m or any(r not in m for r in require):
            continue
        v = float(m[measure])
        better = (
            best_v is None
            or (v > best_v if maximize else v < best_v)
        )
        if better:
            best, best_v = (key, m), v
    return best


def _unanimous_winner(
    store, key_prefix: str, rows: str, field: str,
    knob: Optional[str] = None, sp=None,
) -> Optional[Tuple[str, str, Dict[str, Any]]]:
    """Group matching entries by their FULL shape class (exact d, not
    just the rows bucket), take the best-wall entry per group, and return
    a winner only when every group agrees on ``field``. Absolute walls
    across different feature widths are incommensurable — a knob measured
    fast on a 64-wide problem must not win a 4096-wide one — but when
    every width in the scale band independently picked the same setting,
    the measurement transfers.

    A drop is never silent: disagreeing widths are counted in
    ``keystone_knob_rejected_total{knob,reason="non_unanimous"}`` and
    recorded as a span event naming the contenders, so a tuning gap
    (more measurements needed before the override can apply) is visible
    instead of an invisible no-op."""
    groups: Dict[str, Tuple[float, str, Dict[str, Any]]] = {}
    for key, shape, m in sorted(
        store.entries(key_prefix=key_prefix, rows=rows)
    ):
        if "wall_s" not in m or field not in m:
            continue
        wall = float(m["wall_s"])
        cur = groups.get(shape)
        if cur is None or wall < cur[0]:
            groups[shape] = (wall, key, m)
    if not groups:
        return None
    winners = {repr(m[field]) for _, _, m in groups.values()}
    if len(winners) != 1:
        _reject_knob(
            knob or field, "non_unanimous", sp=sp,
            contenders=sorted(winners), groups=len(groups), rows=rows,
        )
        return None  # the widths disagree: no defensible override
    shape, (_, key, m) = next(iter(groups.items()))
    return key, shape, m


def _reject_knob(knob: str, reason: str, sp=None, **detail: Any) -> None:
    """Count + trace a measured override that was dropped before it
    could apply (the satellite of docs/AUTOTUNING.md: tuning gaps must
    be observable, not invisible no-ops)."""
    _names.metric(_names.KNOB_REJECTED).inc(knob=knob, reason=reason)
    if sp is not None:
        sp.set_attribute(f"knob_rejected:{knob}", reason)
    _spans.add_span_event("measured_knob_rejected", knob=knob, reason=reason,
                          **{k: repr(v) for k, v in detail.items()})


class MeasuredKnobRule(Rule):
    """Override plan-knob defaults per shape class from the profile
    store's best recorded observations (docs/OPTIMIZER.md)."""

    def __init__(self, profile_store="auto"):
        self.profile_store = profile_store

    def _store(self):
        if self.profile_store == "auto":
            return _store.get_store()
        return self.profile_store

    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        mode = knob_mode()
        store = self._store()
        # This rule never installs thread/process precision state itself —
        # measured precision is pinned onto operators and scoped around
        # their fits (linalg.solver_mode_scope). The clear below is
        # defensive hygiene for MANUAL set_solver_mode_override() calls
        # left unscoped on this thread by embedding code: planning a new
        # pipeline is the natural boundary past which such a leftover
        # default must not silently persist (pinned by
        # test_stale_precision_override_cleared_by_next_plan).
        from ..parallel import linalg

        linalg.set_solver_mode_override(None)
        if mode == "off" or store is None:
            return graph, prefixes
        overrides = _names.metric(_names.PROFILE_STORE_KNOB_OVERRIDES)
        with _spans.span("optimize:measured-knobs", mode=mode) as sp:
            graph = self._tune_stream_chunks(graph, store, overrides, sp)
            if mode == "all":
                graph = self._tune_solver_block(graph, store, overrides, sp)
                graph = self._tune_solver_precision(graph, store, overrides, sp)
                graph = self._tune_sketch_size(graph, store, overrides, sp)
        return graph, prefixes

    # ------------------------------------------------------- chunk rows
    def _tune_stream_chunks(self, graph, store, overrides, sp):
        from .streaming import StreamingFitOperator, chain_class

        if env_set("KEYSTONE_STREAM_CHUNK_ROWS"):
            return graph  # explicit env knob always wins
        for node in sorted(graph.nodes):
            op = graph.operators.get(node)
            if not isinstance(op, StreamingFitOperator) or op.chunk_rows:
                continue
            deps = graph.get_dependencies(node)
            head = graph.operators.get(deps[0]) if deps else None
            if not isinstance(head, DatasetOperator):
                continue
            shape = _store.dataset_shape_class(head.dataset)
            best = _best_entry(
                store,
                f"stream:{chain_class(op.members)}:",
                "rows_per_s",
                shape=shape,
            )
            if best is None:
                continue
            rows = int(best[1].get("chunk_rows", 0))
            if rows <= 0:
                continue
            tuned = StreamingFitOperator(
                op.estimator, op.members,
                chunk_rows=rows, prefetch=op.prefetch,
            )
            # Cost-observatory join (obs/cost.py): the stored winner IS
            # this plan's throughput prediction — measured under the
            # exact (key, shape class) it will be compared at, so the
            # drift sentinel may score it (calibrated=True).
            tuned.predicted_cost = _cost.Prediction(
                model="measured_knob", key=best[0], shape=shape,
                rows_per_s=float(best[1]["rows_per_s"]), calibrated=True,
                source=str(best[1].get("source", "observed")),
            )
            graph = graph.set_operator(node, tuned)
            overrides.inc(knob="stream_chunk_rows")
            sp.set_attribute(f"stream_chunk_rows:{node}", rows)
            _spans.add_span_event(
                "measured_knob", knob="stream_chunk_rows",
                value=rows, shape=shape,
            )
        return graph

    # ------------------------------------------------------- block size
    def _tune_solver_block(self, graph, store, overrides, sp):
        from .streaming import StreamingFitOperator

        if env_set("KEYSTONE_SOLVER_BLOCK"):
            return graph
        for node in sorted(graph.nodes):
            op = graph.operators.get(node)
            target = op
            if isinstance(op, StreamingFitOperator):
                target = op.estimator
            if not isinstance(target, EstimatorOperator):
                continue
            block = getattr(target, "block_size", None)
            if not isinstance(block, int):
                continue
            rows = self._head_rows_bucket(graph, node)
            if rows is None:
                continue
            # Trailing colon: "solver:block_ls:" must NOT match
            # "solver:block_ls_stream:*", whose wall covers the whole
            # ingest+featurize+Gram fold — incommensurable with the
            # solver-only in-core walls this knob selects among. And the
            # winner must be unanimous across feature widths in the
            # bucket: absolute walls from different d never compete.
            best = _unanimous_winner(
                store, "solver:block_ls:", rows, "block_size",
                knob="solver_block_size", sp=sp,
            )
            if best is None:
                continue
            best_key, best_shape, best = best
            best_block = int(best.get("block_size", 0))
            if best_block <= 0 or best_block == block:
                continue
            tuned = copy.copy(target)
            tuned.block_size = best_block
            # Displayed in the ledger/explain, never drift-scored: the
            # winner's wall was measured at ITS feature width, and
            # absolute walls across widths are incommensurable (the
            # unanimity gate above is about the SETTING transferring,
            # not the wall).
            tuned.predicted_cost = _cost.Prediction(
                model="measured_knob", key=best_key, shape=best_shape,
                seconds=float(best["wall_s"]), calibrated=False,
                source=str(best.get("source", "observed")),
            )
            if isinstance(op, StreamingFitOperator):
                new_op = StreamingFitOperator(
                    tuned, op.members,
                    chunk_rows=op.chunk_rows, prefetch=op.prefetch,
                )
            else:
                new_op = tuned
            graph = graph.set_operator(node, new_op)
            overrides.inc(knob="solver_block_size")
            sp.set_attribute(f"solver_block_size:{node}", best_block)
            _spans.add_span_event(
                "measured_knob", knob="solver_block_size",
                value=best_block, was=block,
            )
        return graph

    # ------------------------------------------------------ sketch size
    def _tune_sketch_size(self, graph, store, overrides, sp):
        from ..sketch.solvers import SketchedLeastSquaresEstimator
        from .streaming import StreamingFitOperator

        if env_set("KEYSTONE_SKETCH_SIZE"):
            return graph  # explicit env knob always wins
        for node in sorted(graph.nodes):
            op = graph.operators.get(node)
            target = op.estimator if isinstance(op, StreamingFitOperator) else op
            if not isinstance(target, EstimatorOperator):
                continue
            # Eligible: the sketched rung itself, or a meta-solver whose
            # width dispatch may pick it (_tuned_sketch_size rides the
            # delegation either way; Gram rungs just never read it).
            sketched = isinstance(target, SketchedLeastSquaresEstimator)
            if not sketched and not callable(
                getattr(target, "_stream_solver", None)
            ):
                continue
            if getattr(target, "sketch_size", None):
                continue  # constructor pinned its own choice
            rows = self._head_rows_bucket(graph, node)
            if rows is None:
                continue
            # Same commensurability rules as block size: only sketch_ls
            # entries vote, and the winning s must be unanimous across
            # the bucket's feature widths.
            best = _unanimous_winner(
                store, "solver:sketch_ls:", rows, "sketch_size",
                knob="sketch_size", sp=sp,
            )
            if best is None:
                continue
            best_key, best_shape, best = best
            best_s = int(best.get("sketch_size", 0))
            if best_s <= 0 or best_s == getattr(
                target, "_tuned_sketch_size", None
            ):
                continue
            tuned = copy.copy(target)
            tuned._tuned_sketch_size = best_s
            tuned.predicted_cost = _cost.Prediction(
                model="measured_knob", key=best_key, shape=best_shape,
                seconds=float(best["wall_s"]), calibrated=False,
                source=str(best.get("source", "observed")),
            )
            if isinstance(op, StreamingFitOperator):
                new_op = StreamingFitOperator(
                    tuned, op.members,
                    chunk_rows=op.chunk_rows, prefetch=op.prefetch,
                )
            else:
                new_op = tuned
            graph = graph.set_operator(node, new_op)
            overrides.inc(knob="sketch_size")
            sp.set_attribute(f"sketch_size:{node}", best_s)
            _spans.add_span_event(
                "measured_knob", knob="sketch_size", value=best_s,
            )
        return graph

    # -------------------------------------------------------- precision
    def _tune_solver_precision(self, graph, store, overrides, sp):
        from ..parallel import linalg
        from .streaming import StreamingFitOperator

        if env_raw("KEYSTONE_SOLVER_PRECISION") is not None:
            return graph  # explicit env knob always wins
        for node in sorted(graph.nodes):
            op = graph.operators.get(node)
            target = op.estimator if isinstance(op, StreamingFitOperator) else op
            if not isinstance(target, EstimatorOperator):
                continue
            if getattr(target, "solver_precision", None):
                continue  # operator already pinned its own choice
            rows = self._head_rows_bucket(graph, node)
            if rows is None:
                continue
            # Only in-core block_ls entries participate (same solver
            # family → commensurable walls; streaming-fold walls and the
            # meta-solver's precision-less rung entries never vote), and
            # the winning precision must be unanimous across the bucket's
            # feature widths.
            best = _unanimous_winner(
                store, "solver:block_ls:", rows, "precision",
                knob="solver_precision", sp=sp,
            )
            if best is None:
                continue
            _best_key, _best_shape, best = best
            precision = best.get("precision")
            if not precision:
                continue
            try:
                linalg.precision_for_mode(str(precision))
            except KeyError:
                logger.warning(
                    "measured precision override rejected: unknown mode %r",
                    precision,
                )
                _reject_knob(
                    "solver_precision", "invalid_value", sp=sp,
                    value=precision,
                )
                continue
            # Scoped to THIS operator's fit (operators.py wraps
            # fit_datasets / streaming wraps fit_stream in
            # linalg.solver_mode_scope) — never process state, so solves
            # that were not planned under the measurement keep their own
            # default.
            tuned = copy.copy(target)
            tuned.solver_precision = str(precision)
            if isinstance(op, StreamingFitOperator):
                new_op = StreamingFitOperator(
                    tuned, op.members,
                    chunk_rows=op.chunk_rows, prefetch=op.prefetch,
                )
            else:
                new_op = tuned
            graph = graph.set_operator(node, new_op)
            overrides.inc(knob="solver_precision")
            sp.set_attribute(f"solver_precision:{node}", str(precision))
            _spans.add_span_event(
                "measured_knob", knob="solver_precision",
                value=str(precision),
            )
        return graph

    # ---------------------------------------------------------- helpers
    def _head_rows_bucket(self, graph, node) -> Optional[str]:
        """Rows bucket of the dataset feeding ``node``'s chain head — the
        coarse shape key when featurized width is unknowable at plan
        time (solver entries record exact d; the bucket still confines a
        measurement to its scale band)."""
        seen = set()
        cur = node
        while cur in graph.operators:
            op = graph.operators[cur]
            if isinstance(op, DatasetOperator):
                try:
                    return _store.rows_bucket(
                        _store.shape_class(len(op.dataset))
                    )
                except Exception:
                    return None
            deps = graph.get_dependencies(cur)
            if not deps or deps[0] in seen:
                return None
            seen.add(cur)
            cur = deps[0]
        return None
