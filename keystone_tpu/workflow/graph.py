"""Untyped dataflow-graph IR for the pipeline layer.

This is the TPU-native re-design of the reference's immutable DAG
(reference: workflow/Graph.scala:32-455, workflow/GraphId.scala:1-31).
A ``Graph`` is a persistent (copy-on-write) structure: every surgery
operation returns a new ``Graph``, so optimizer rules can rewrite plans
without aliasing hazards.

Vocabulary (mirrors the reference's semantics, not its code):

- ``SourceId``  — an unbound input of the graph (pipeline input).
- ``NodeId``    — an operator application; has an ordered dependency list.
- ``SinkId``    — a named output; depends on exactly one node or source.

Unlike the reference (JVM objects over Spark RDDs), the operators this
graph carries execute against sharded JAX arrays on a device mesh; the
graph itself is pure host-side Python and never traced by XLA.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple, Union

if TYPE_CHECKING:  # pragma: no cover
    from .operators import Operator


@dataclass(frozen=True, order=True)
class NodeId:
    id: int

    def __repr__(self) -> str:
        return f"n{self.id}"


@dataclass(frozen=True, order=True)
class SourceId:
    id: int

    def __repr__(self) -> str:
        return f"src{self.id}"


@dataclass(frozen=True, order=True)
class SinkId:
    id: int

    def __repr__(self) -> str:
        return f"sink{self.id}"


#: Anything a node or sink may depend on.
NodeOrSourceId = Union[NodeId, SourceId]
#: Any vertex in the graph.
GraphId = Union[NodeId, SourceId, SinkId]


class Graph:
    """Immutable dataflow DAG.

    Parameters
    ----------
    sources:
        Unbound inputs.
    sink_dependencies:
        Mapping sink -> the node/source whose value it exposes.
    operators:
        Mapping node -> operator.
    dependencies:
        Mapping node -> ordered list of nodes/sources it consumes.
    """

    __slots__ = ("sources", "sink_dependencies", "operators", "dependencies", "_max_id")

    def __init__(
        self,
        sources: Iterable[SourceId] = (),
        sink_dependencies: Optional[Mapping[SinkId, NodeOrSourceId]] = None,
        operators: Optional[Mapping[NodeId, "Operator"]] = None,
        dependencies: Optional[Mapping[NodeId, Sequence[NodeOrSourceId]]] = None,
    ):
        self.sources = frozenset(sources)
        self.sink_dependencies = dict(sink_dependencies or {})
        self.operators = dict(operators or {})
        self.dependencies = {k: tuple(v) for k, v in (dependencies or {}).items()}
        ids = [s.id for s in self.sources]
        ids += [s.id for s in self.sink_dependencies]
        ids += [n.id for n in self.operators]
        self._max_id = max(ids) if ids else -1

    # ------------------------------------------------------------------ views
    @property
    def nodes(self) -> frozenset:
        return frozenset(self.operators)

    @property
    def sinks(self) -> frozenset:
        return frozenset(self.sink_dependencies)

    def get_operator(self, node: NodeId) -> "Operator":
        return self.operators[node]

    def get_dependencies(self, node: NodeId) -> Tuple[NodeOrSourceId, ...]:
        return self.dependencies[node]

    def get_sink_dependency(self, sink: SinkId) -> NodeOrSourceId:
        return self.sink_dependencies[sink]

    def _next_ids(self) -> Iterable[int]:
        return itertools.count(self._max_id + 1)

    def dependents(self) -> Dict[NodeId, List[GraphId]]:
        """node → list of consumers (nodes AND sinks — a sink read counts).

        The shared reverse-edge view used by the auto-cache planner
        (reuse counting) and the fusion pass (chain cutting): both must
        agree on what 'consumer' means or their rewrites would disagree
        about node boundaries.
        """
        out: Dict[NodeId, List[GraphId]] = {n: [] for n in self.operators}
        for node, deps in self.dependencies.items():
            for dep in deps:
                if isinstance(dep, NodeId):
                    out[dep].append(node)
        for sink, dep in self.sink_dependencies.items():
            if isinstance(dep, NodeId):
                out[dep].append(sink)
        return out

    # --------------------------------------------------------------- surgery
    def add_node(self, op: "Operator", deps: Sequence[NodeOrSourceId]) -> Tuple["Graph", NodeId]:
        node = NodeId(self._max_id + 1)
        operators = dict(self.operators)
        operators[node] = op
        dependencies = dict(self.dependencies)
        dependencies[node] = tuple(deps)
        return Graph(self.sources, self.sink_dependencies, operators, dependencies), node

    def add_source(self) -> Tuple["Graph", SourceId]:
        source = SourceId(self._max_id + 1)
        return (
            Graph(self.sources | {source}, self.sink_dependencies, self.operators, self.dependencies),
            source,
        )

    def add_sink(self, dep: NodeOrSourceId) -> Tuple["Graph", SinkId]:
        sink = SinkId(self._max_id + 1)
        sink_deps = dict(self.sink_dependencies)
        sink_deps[sink] = dep
        return Graph(self.sources, sink_deps, self.operators, self.dependencies), sink

    def set_operator(self, node: NodeId, op: "Operator") -> "Graph":
        if node not in self.operators:
            raise KeyError(f"{node} not in graph")
        operators = dict(self.operators)
        operators[node] = op
        return Graph(self.sources, self.sink_dependencies, operators, self.dependencies)

    def set_dependencies(self, node: NodeId, deps: Sequence[NodeOrSourceId]) -> "Graph":
        if node not in self.operators:
            raise KeyError(f"{node} not in graph")
        dependencies = dict(self.dependencies)
        dependencies[node] = tuple(deps)
        return Graph(self.sources, self.sink_dependencies, self.operators, dependencies)

    def set_sink_dependency(self, sink: SinkId, dep: NodeOrSourceId) -> "Graph":
        sink_deps = dict(self.sink_dependencies)
        sink_deps[sink] = dep
        return Graph(self.sources, sink_deps, self.operators, self.dependencies)

    def remove_sink(self, sink: SinkId) -> "Graph":
        sink_deps = dict(self.sink_dependencies)
        del sink_deps[sink]
        return Graph(self.sources, sink_deps, self.operators, self.dependencies)

    def remove_source(self, source: SourceId) -> "Graph":
        self._check_unreferenced(source)
        return Graph(self.sources - {source}, self.sink_dependencies, self.operators, self.dependencies)

    def remove_node(self, node: NodeId) -> "Graph":
        self._check_unreferenced(node)
        operators = dict(self.operators)
        del operators[node]
        dependencies = dict(self.dependencies)
        del dependencies[node]
        return Graph(self.sources, self.sink_dependencies, operators, dependencies)

    def _check_unreferenced(self, vid: NodeOrSourceId) -> None:
        for deps in self.dependencies.values():
            if vid in deps:
                raise ValueError(f"cannot remove {vid}: still referenced by a node")
        for dep in self.sink_dependencies.values():
            if dep == vid:
                raise ValueError(f"cannot remove {vid}: still referenced by a sink")

    def replace_dependency(self, old: NodeOrSourceId, new: NodeOrSourceId) -> "Graph":
        """Redirect every reference to ``old`` to ``new``."""
        dependencies = {
            node: tuple(new if d == old else d for d in deps)
            for node, deps in self.dependencies.items()
        }
        sink_deps = {
            sink: (new if d == old else d) for sink, d in self.sink_dependencies.items()
        }
        return Graph(self.sources, sink_deps, self.operators, dependencies)

    # ------------------------------------------------------------ composition
    def add_graph(self, other: "Graph") -> Tuple["Graph", Dict[SourceId, SourceId], Dict[SinkId, SinkId]]:
        """Disjoint union; ``other``'s ids are remapped past this graph's ids.

        Returns the union plus maps from ``other``'s source/sink ids to their
        new ids (reference: workflow/Graph.scala:290 ``addGraph``).
        """
        counter = itertools.count(self._max_id + 1)
        node_map: Dict[NodeId, NodeId] = {n: NodeId(next(counter)) for n in sorted(other.operators)}
        source_map: Dict[SourceId, SourceId] = {s: SourceId(next(counter)) for s in sorted(other.sources)}
        sink_map: Dict[SinkId, SinkId] = {s: SinkId(next(counter)) for s in sorted(other.sink_dependencies)}

        def remap(x: NodeOrSourceId) -> NodeOrSourceId:
            if isinstance(x, NodeId):
                return node_map[x]
            return source_map[x]

        operators = dict(self.operators)
        dependencies = dict(self.dependencies)
        for node, op in other.operators.items():
            operators[node_map[node]] = op
            dependencies[node_map[node]] = tuple(remap(d) for d in other.dependencies[node])
        sink_deps = dict(self.sink_dependencies)
        for sink, dep in other.sink_dependencies.items():
            sink_deps[sink_map[sink]] = remap(dep)
        sources = self.sources | frozenset(source_map.values())
        return Graph(sources, sink_deps, operators, dependencies), source_map, sink_map

    def connect_graph(
        self, other: "Graph", splice: Mapping[SourceId, SinkId]
    ) -> Tuple["Graph", Dict[SourceId, SourceId], Dict[SinkId, SinkId]]:
        """Union with ``other``, binding its sources to this graph's sinks.

        For each ``(other_source -> this_sink)`` pair, the spliced source is
        replaced by whatever the sink exposes, and both the source and the
        sink disappear (reference: workflow/Graph.scala:340 ``connectGraph``,
        the substrate of ``Chainable.andThen``).
        """
        combined, source_map, sink_map = self.add_graph(other)
        for other_source, this_sink in splice.items():
            new_source = source_map[other_source]
            target = combined.get_sink_dependency(this_sink)
            combined = combined.replace_dependency(new_source, target)
            combined = combined.remove_source(new_source)
            combined = combined.remove_sink(this_sink)
            del source_map[other_source]
        return combined, source_map, sink_map

    def replace_nodes(
        self,
        nodes_to_remove: Iterable[NodeId],
        replacement: "Graph",
        replacement_source_splice: Mapping[SourceId, NodeOrSourceId],
        replacement_sink_splice: Mapping[NodeId, SinkId],
    ) -> "Graph":
        """Swap a set of nodes for a replacement subgraph.

        ``replacement_source_splice`` binds the replacement's sources onto
        surviving vertices of this graph; ``replacement_sink_splice`` says
        which replacement sink stands in for each removed node
        (reference: workflow/Graph.scala:379 ``replaceNodes``).
        """
        removed = set(nodes_to_remove)
        combined, source_map, sink_map = self.add_graph(replacement)
        # Bind replacement sources to surviving graph vertices.
        for rsource, target in replacement_source_splice.items():
            new_source = source_map[rsource]
            combined = combined.replace_dependency(new_source, target)
            combined = combined.remove_source(new_source)
        # Redirect consumers of removed nodes to replacement sinks' deps.
        for removed_node, rsink in replacement_sink_splice.items():
            new_sink = sink_map[rsink]
            target = combined.get_sink_dependency(new_sink)
            combined = combined.replace_dependency(removed_node, target)
            combined = combined.remove_sink(new_sink)
        # Drop remaining replacement sinks.
        for rsink, new_sink in sink_map.items():
            if new_sink in combined.sink_dependencies:
                combined = combined.remove_sink(new_sink)
        # Remove the dead nodes (in dependency-safe order: repeatedly strip
        # nodes that nothing references).
        pending = set(removed)
        while pending:
            progressed = False
            for node in list(pending):
                try:
                    combined = combined.remove_node(node)
                except ValueError:
                    continue
                pending.discard(node)
                progressed = True
            if not progressed:
                raise ValueError(f"could not remove nodes {pending}: external references remain")
        return combined

    # ---------------------------------------------------------------- export
    def to_dot(self, name: str = "pipeline") -> str:
        """Graphviz DOT export (reference: workflow/Graph.scala:436-455)."""
        lines = [f"digraph {name} {{", "  rankdir=LR;"]
        for source in sorted(self.sources):
            lines.append(f'  "{source!r}" [shape=oval, label="{source!r}"];')
        for node in sorted(self.operators):
            label = getattr(self.operators[node], "label", type(self.operators[node]).__name__)
            lines.append(f'  "{node!r}" [shape=box, label="{label}"];')
        for sink in sorted(self.sink_dependencies):
            lines.append(f'  "{sink!r}" [shape=diamond, label="{sink!r}"];')
        for node, deps in sorted(self.dependencies.items()):
            for i, dep in enumerate(deps):
                lines.append(f'  "{dep!r}" -> "{node!r}" [label="{i}"];')
        for sink, dep in sorted(self.sink_dependencies.items()):
            lines.append(f'  "{dep!r}" -> "{sink!r}";')
        lines.append("}")
        return "\n".join(lines)

    # ------------------------------------------------------------- equality
    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Graph):
            return NotImplemented
        return (
            self.sources == other.sources
            and self.sink_dependencies == other.sink_dependencies
            and self.operators == other.operators
            and self.dependencies == other.dependencies
        )

    def __hash__(self):  # graphs are not hashable (operators may not be)
        raise TypeError("Graph is not hashable")

    def __repr__(self) -> str:
        return (
            f"Graph(sources={sorted(self.sources)}, nodes={sorted(self.operators)}, "
            f"sinks={sorted(self.sink_dependencies)})"
        )
