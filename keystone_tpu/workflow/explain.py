"""``keystone-tpu explain`` — the "why is this pipeline slow" report.

Runs a pipeline's optimized plan under the cost observatory
(obs/cost.py) and prints it node by node: decision provenance (which
rule drove the node — autocache profile, measured-knob winner, solver
ladder rung, partition decision — and which stored entry/key), predicted
cost vs measured wall, achieved FLOP/s / bytes/s, arithmetic intensity,
and compute-bound/memory-bound roofline placement. The drift sentinel
runs live: a stored cost model that no longer matches reality fires a
``cost_drift`` event, marks the entry ``stale:``, and the report says
so.

Execution shape: the same plan is fitted ``--passes`` times (default 3)
with the pipeline state reset between passes — pass 1 pays compiles
(its walls are marked ``cold`` and never drift-score), later passes
measure steady state. The report is built from the LAST pass's ledger
window. Harvesting rides the jit trace cache — ``harvest_compiles`` in
the JSON is the number of backend compiles cost analysis itself caused
and must be 0 (scripts/explain_smoke.sh gates it).

``--pipeline synthetic`` builds a small featurize→fit chain
(SyntheticDense ×2 → BlockLeastSquaresEstimator) under the auto-caching
optimizer so every decision layer is exercised; ``--pipeline PATH``
loads a ``FittedPipeline.save`` artifact and explains its (re-fused)
apply path instead. ``--seed-drift F`` corrupts the stored autocache
measurements by ``F``× before running — the CI negative control: the
sentinel must flag exactly the seeded corruption, then the stale mark
must force a live re-measure (asserted by the smoke).

Flag wiring lives in cli.py (stdlib-only, jax-free ``--help``); this
module imports jax transitively and is loaded only at dispatch.
"""

from __future__ import annotations

import argparse
import json
import logging
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


# ----------------------------------------------------------------- synthetic


def _synthetic_fit_pipeline(rows: int, dim: int, classes: int, seed: int):
    """data → SyntheticDense ×2 → BlockLeastSquaresEstimator, plus the
    bound eval apply — one pipeline exercising the auto-cache profiler
    (the block estimator's weight makes the featurized node a cache
    candidate), fusion, the streaming planner (when ``rows`` clears the
    chunk floor), measured knobs, and the partitioner."""
    import numpy as np

    from ..data.dataset import ArrayDataset
    from ..ops.learning.block import BlockLeastSquaresEstimator
    from ..serving.synthetic import SyntheticDense

    rng = np.random.default_rng(seed)
    scale = 1.0 / np.sqrt(dim)
    w1 = (rng.standard_normal((dim, dim)) * scale).astype(np.float32)
    w2 = (rng.standard_normal((dim, dim)) * scale).astype(np.float32)
    x = rng.standard_normal((rows, dim)).astype(np.float32)
    w_true = rng.standard_normal((dim, classes)).astype(np.float32)
    y = (np.tanh(x @ w1) @ w_true + 0.01 * rng.standard_normal(
        (rows, classes)
    )).astype(np.float32)

    feat = SyntheticDense([w1]).to_pipeline().then(SyntheticDense([w2]))
    est = BlockLeastSquaresEstimator(
        min(64, dim), num_iter=2, reg=1e-3
    )
    pipe = feat.then_label_estimator(est, ArrayDataset(x), ArrayDataset(y))
    x_eval = ArrayDataset(x[: min(256, rows)])
    return pipe, x_eval


def _corrupt_store_predictions(factor: float) -> int:
    """The seeded mis-prediction: scale ONE autocache entry's
    coefficients AND its measured baseline by ``1/factor`` — the stored
    model now claims that node is ``factor``× cheaper than the wall the
    sentinel will measure. Exactly one entry (the one with the largest
    measured baseline — the most consequential node), so the acceptance
    invariant "exactly one drift event" is assertable. Returns the
    number of entries corrupted (0 or 1)."""
    from ..obs import store as _store
    from ..obs.cost import DriftSentinel

    store = _store.get_store()
    if store is None or factor in (0, 1):
        return 0
    baseline_field = DriftSentinel.BASELINE_FIELD
    candidates = [
        (float(m.get(baseline_field, 0.0) or 0.0), key, shape, m)
        for key, shape, m in store.entries(
            key_prefix="autocache:", include_stale=True
        )
        if not _store.is_stale(m)
    ]
    if not candidates:
        return 0
    _, key, shape, m = max(candidates, key=lambda c: (c[0], c[1]))
    m2 = dict(m)
    for field in ("t0", "t1", "run_time_s", baseline_field):
        if isinstance(m2.get(field), (int, float)):
            m2[field] = float(m2[field]) / factor
    store.record(key, shape, **m2)
    return 1


# --------------------------------------------------------------------- passes


def _explain_optimizer():
    """The auto-caching stack with explain-grade profiling scales: the
    default (2, 4)-item samples are sub-millisecond on CPU — fine for
    RELATIVE cache decisions, useless as absolute predictions (the
    lstsq slope is noise and the clamp floors them at 0). Profiling a
    few hundred rows costs milliseconds and yields extrapolations worth
    printing next to measured walls."""
    from .autocache import AutoCacheRule
    from .rules import auto_caching_optimizer

    stack = auto_caching_optimizer()
    for batch in stack.batches:
        for i, rule in enumerate(batch.rules):
            if isinstance(rule, AutoCacheRule):
                batch.rules[i] = AutoCacheRule(profile_scales=(128, 512))
    return stack


def _run_pass(pipe, x_eval, optimizer_factory):
    """One optimize+fit+apply execution in a fresh pipeline env under a
    synced tracing session; returns (ledger entries, executor)."""
    from ..obs import cost as _cost
    from ..obs import spans as _spans
    from .executor import PipelineEnv

    PipelineEnv.reset()
    PipelineEnv.get_or_create().optimizer = optimizer_factory()
    _cost.reset_plan_predictions()
    cursor = _cost.get_ledger().cursor()
    with _spans.tracing_session("explain", sync_timings=True):
        with _spans.span("explain:pass"):
            handle = pipe.apply(x_eval)
            handle.get()
    return _cost.get_ledger().entries(cursor), handle._executor


def _provenance(entry, partition_by_label: Dict[str, Any]) -> Dict[str, Any]:
    """The decision trail for one node: which model/rule claimed it
    (and from which stored entry), plus the partitioner's recorded
    decision/reason when one names this node."""
    out: Dict[str, Any] = {}
    if entry.predicted_model:
        out["model"] = entry.predicted_model
        if entry.predicted_key:
            out["store_key"] = entry.predicted_key
        if entry.predicted_shape:
            out["shape_class"] = entry.predicted_shape
    if getattr(entry, "predicted_candidates", ()):
        # The whole ladder the argmin saw — every rung's predicted cost
        # with the rejected rungs' reasons, not just the survivor.
        out["candidates"] = [
            {"rung": name, "predicted_s": cost, "reason": reason}
            for name, cost, reason in entry.predicted_candidates
        ]
    decision = partition_by_label.get(entry.node)
    if decision is not None:
        out["partition"] = {
            "eligible": bool(getattr(decision, "eligible", False)),
            "reason": str(getattr(decision, "reason", "")),
            "shards": int(getattr(decision, "shards", 1) or 1),
        }
    if entry.kinds:
        out["computations"] = list(entry.kinds)
    return out


def _render_human(report: Dict[str, Any]) -> str:
    lines = [
        f"explain: {report['pipeline']} — pass {report['passes']} of "
        f"{report['passes']} (steady state), roofline "
        f"{report['roofline']['backend'] if report.get('roofline') else '?'}"
    ]
    if report.get("roofline"):
        r = report["roofline"]
        lines.append(
            f"  roofline[{r['source']}]: "
            f"{r['peak_flops_per_s'] / 1e9:.1f} GFLOP/s, "
            f"{r['peak_bytes_per_s'] / 1e9:.1f} GB/s, "
            f"ridge {r['ridge_intensity']:.2f} flop/byte"
        )
    header = (
        f"  {'node':40s} {'wall ms':>9s} {'pred ms':>9s} "
        f"{'GFLOP/s':>8s} {'int.':>6s} {'bound':>14s}  provenance"
    )
    lines.append(header)
    for node in report["nodes"]:
        wall = node.get("seconds", 0.0) * 1e3
        pred = node.get("predicted_s")
        gflops = node.get("flops_per_s")
        intensity = node.get("intensity")
        prov = node.get("provenance", {})
        prov_text = prov.get("model", "-")
        if prov.get("store_key"):
            prov_text += f" ← {prov['store_key'][:40]}"
        if node.get("drift"):
            prov_text += "  ** DRIFT **"
        lines.append(
            f"  {node['node'][:40]:40s} {wall:9.3f} "
            f"{(pred * 1e3 if pred is not None else float('nan')):9.3f} "
            f"{(gflops / 1e9 if gflops else float('nan')):8.2f} "
            f"{(intensity if intensity is not None else float('nan')):6.2f} "
            f"{node.get('roofline') or 'unmeasured':>14s}  {prov_text}"
        )
        for cand in prov.get("candidates", []):
            cost = cand.get("predicted_s")
            cost_text = f"{cost * 1e3:9.3f}" if cost is not None else "      inf"
            lines.append(
                f"    ∟ rung {cand['rung']:14s} pred ms {cost_text}  "
                f"{cand['reason']}"
            )
    for event in report.get("drift_events", []):
        lines.append(
            f"  DRIFT: {event['model']} mis-predicted {event['node']} "
            f"(ratio {event['ratio']}, key {event['key']}"
            f"{', marked stale' if event.get('stale_marked') else ''})"
        )
    lines.append(
        f"  harvest_compiles={report['harvest_compiles']} "
        f"stale_entries={report['store']['stale_entries']} "
        f"drift_events={len(report.get('drift_events', []))}"
    )
    return "\n".join(lines)


def _render_schedule(evidence: Dict[str, Any]) -> str:
    """The mesh schedule, human-readable: one row per lease — who ran,
    its outcome, what displaced it, predicted vs measured wall and the
    price's provenance rung."""
    lines = [
        "keystone-tpu explain --schedule  (docs/SCHEDULING.md)",
        f"  serial wall {evidence['serial_wall_s']:.3f}s vs co-scheduled "
        f"{evidence['cosched_wall_s']:.3f}s "
        f"(ratio {evidence['cosched_vs_serial_ratio']}), "
        f"p99 {evidence['p99_ms_worst']:.1f}ms / "
        f"target {evidence['slo_target_ms']:.0f}ms, "
        f"dropped {evidence['dropped']}, "
        f"idle harvested {evidence['idle_harvest_s']:.3f}s",
        f"  {'lease':14s} {'work':24s} {'kind':10s} {'outcome':10s} "
        f"{'rows':>6s} {'price':>9s} {'pred ms':>9s} {'meas ms':>9s} "
        f"{'ratio':>6s}  displaced by",
    ]
    for entry in evidence.get("obs", {}).get("schedule", []):
        pred = entry.get("predicted_s")
        meas = entry.get("measured_s")
        ratio = entry.get("ratio")
        displaced = entry.get("displaced_by") or "-"
        if entry.get("preempted_at_chunk") is not None:
            displaced += f" (preempted at chunk {entry['preempted_at_chunk']})"
        if entry.get("resume_of"):
            displaced += f" (resumes {entry['resume_of']})"
        lines.append(
            f"  {entry['lease']:14s} {entry['name'][:24]:24s} "
            f"{entry['kind']:10s} {entry['outcome']:10s} "
            f"{entry['rows']:>6d} {entry['price'].get('source', '-'):>9s} "
            f"{(pred * 1e3 if pred is not None else float('nan')):9.3f} "
            f"{(meas * 1e3 if meas is not None else float('nan')):9.3f} "
            f"{(ratio if ratio is not None else float('nan')):6.2f}  "
            f"{displaced}"
        )
    lines.append(
        f"  leases={evidence['leases']} "
        f"preemptions={evidence['preemptions']} "
        f"publishes={evidence['publishes']} "
        f"parity_max_abs_diff={evidence['parity_max_abs_diff']:.2e}"
    )
    return "\n".join(lines)


def _explain_schedule(args: argparse.Namespace) -> int:
    """``explain --schedule``: run the co-scheduled demo and print who
    got the mesh, what was displaced or deferred, and predicted vs
    measured wall per lease."""
    from ..sched.demo import CoschedDemoConfig, run_cosched_demo

    evidence = run_cosched_demo(CoschedDemoConfig(seed=args.seed))
    body = json.dumps(evidence)
    if args.out:
        with open(args.out, "w") as f:
            f.write(body)
    if args.as_json:
        print("SCHED_JSON:" + body)
    else:
        print(_render_schedule(evidence))
    ok = (
        evidence["dropped"] == 0
        and evidence["parity_ok"]
        and evidence["p99_within_slo"]
    )
    return 0 if ok else 2


def explain_from_args(args: argparse.Namespace) -> int:
    from ..obs import cost as _cost
    from ..utils.compilation_cache import install_compile_counter

    if getattr(args, "schedule", False):
        return _explain_schedule(args)

    install_compile_counter()
    override_before = _cost._enabled_override
    _cost.set_cost_observatory(True)
    _cost.record_all_nodes(True)
    try:
        return _explain(args)
    finally:
        # Embedders calling this in-process get their observatory state
        # back; the CLI process just exits.
        _cost.set_cost_observatory(override_before)
        _cost.record_all_nodes(False)


def _explain(args: argparse.Namespace) -> int:
    from ..obs import cost as _cost
    from ..obs import store as _store
    from ..obs.metrics import get_registry
    from ..obs import names as _names

    # Roofline first: the probe's two tiny compiles are calibration,
    # never attributable to harvesting (whose own compile budget is 0).
    roofline = _cost.get_roofline()

    if args.pipeline == "synthetic":
        pipe, x_eval = _synthetic_fit_pipeline(
            args.rows, args.dim, args.classes, args.seed
        )
    else:
        from .pipeline import FittedPipeline

        import numpy as np

        fitted = FittedPipeline.load(args.pipeline).fused()
        pipe = fitted
        rng = np.random.default_rng(args.seed)
        from ..data.dataset import ArrayDataset

        x_eval = ArrayDataset(
            rng.standard_normal((256, args.dim)).astype(np.float32)
        )

    seed_factor = (
        args.seed_drift if args.seed_drift and args.seed_drift != 1.0 else 0
    )
    seeded = 0

    registry = get_registry()
    harvest_before = registry.snapshot().get(_names.COST_HARVEST_COMPILES, 0)
    drift_before = list(_cost.get_drift_sentinel().events)

    entries: List[Any] = []
    executor = None
    total_passes = max(1, args.passes)
    index = 0
    while index < total_passes:
        entries, executor = _run_pass(pipe, x_eval, _explain_optimizer)
        index += 1
        if (
            seed_factor
            and not seeded
            and _cost.get_drift_sentinel().seen_count()
        ):
            # Corrupt only once the sentinel has re-based baselines to
            # THIS process's walls (cross-process ms-scale walls are
            # load noise, and a cold first pass never observes), so the
            # seeded mis-prediction is measured against in-process
            # reality — then guarantee enough further passes for the
            # sustain threshold to fire.
            seeded = _corrupt_store_predictions(seed_factor)
            total_passes = max(
                total_passes, index + _cost.drift_sustain()
            )

    partition_by_label: Dict[str, Any] = {}
    if executor is not None:
        for decision in getattr(executor, "partition_decisions", []) or []:
            label = getattr(decision, "node", None)
            if label:
                partition_by_label[str(label)] = decision

    drift_events = [
        e for e in _cost.get_drift_sentinel().events if e not in drift_before
    ]
    harvest_compiles = int(
        registry.snapshot().get(_names.COST_HARVEST_COMPILES, 0)
        - harvest_before
    )

    store = _store.get_store()
    stale_keys: List[str] = []
    if store is not None:
        stale_keys = sorted(
            {
                key
                for key, _shape, m in store.entries(
                    any_env=True, include_stale=True
                )
                if _store.is_stale(m)
            }
        )

    nodes = []
    for entry in entries:
        node = entry.to_json()
        node["provenance"] = _provenance(entry, partition_by_label)
        nodes.append(node)

    report: Dict[str, Any] = {
        "pipeline": args.pipeline,
        "passes": index,
        "roofline": roofline.to_json() if roofline else None,
        "nodes": nodes,
        "drift_events": drift_events,
        "seeded_corruptions": seeded,
        "harvest_compiles": harvest_compiles,
        "store": {
            "enabled": store is not None,
            "stale_entries": len(stale_keys),
            "stale_keys": stale_keys,
        },
    }
    body = json.dumps(report)
    if args.out:
        with open(args.out, "w") as f:
            f.write(body)
    if args.as_json:
        print("EXPLAIN_JSON:" + body)
    else:
        print(_render_human(report))
    # Exit code mirrors the sentinel: an explain run that caught live
    # drift should fail a CI step that expected a quiet model (the smoke
    # inverts this for the seeded run).
    return 2 if drift_events else 0
