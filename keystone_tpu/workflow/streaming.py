"""Streaming chunked execution: overlap ingest, transfer, and fused compute.

The Pipeline API materializes every stage's output dataset — correct and
optimizer-visible, but the reason featurization-heavy fits die at scale:
the full feature matrix must exist before the solver sees a single row.
The reference never pays that cost — featurization stays lazy per
partition and feeds the solver incrementally (reference:
ImageNetSiftLcsFV.scala:96-136) — and our hand-rolled flagship module
(pipelines/imagenet_streaming.py) proved the TPU shape of the same idea:
uint8 uploads double-buffered against fused per-chunk dispatches.

This module generalizes that shape into the workflow layer:

- :class:`StreamingPlanRule` (the LAST optimizer batch, after auto-cache
  and fusion) rewrites eligible ``ingest/featurize-chain → estimator``
  graphs: the featurize chain between the data source and a
  ``fit_stream``-capable estimator is absorbed into a
  :class:`StreamingFitOperator` that consumes the RAW dataset directly.
- At fit time the operator drives a chunked plan: a bounded-prefetch
  host pipeline (:class:`~keystone_tpu.data.ingest.PrefetchQueue` —
  multi-worker decode/stack feeding a depth-limited queue), host→device
  uploads that cross at the NARROWEST dtype
  (:func:`~keystone_tpu.data.dataset.transfer_dtype`; uint8 images stay
  uint8, 4× less traffic) and cast on device, and ONE fused XLA dispatch
  per chunk composing cast → featurize chain → the estimator's
  Gram-accumulation step, with the carry donated ping-pong style
  (parallel/linalg.py streaming idiom).
- Upload of chunk i+1 is issued before compute of chunk i completes
  (double-buffering, asserted by scripts/streaming_smoke.sh), and the
  full feature matrix never exists on host or device — only O(chunk)
  host buffers and O(d²) device statistics.

Estimator protocol: operators advertising ``supports_fit_stream = True``
implement ``fit_stream(stream)`` where ``stream`` is a
:class:`ChunkStream`; ``stream.fold(init_fn, step_fn)`` runs the engine
loop with ``step_fn`` traced INTO the per-chunk dispatch. See
``LeastSquaresEstimator`` / ``BlockLeastSquaresEstimator`` /
``LinearMapEstimator`` and docs/STREAMING.md.

Boundaries (mirror fusion's, docs/OPTIMIZER.md): Cacher nodes, saveable
prefixes, multi-consumer intermediates, and bespoke-``apply_batch``
transformers all cut the streamed chain — a cut chain streams from the
boundary's materialized output instead (the Cacher-boundary parity case
in tests/workflow/test_streaming.py).
"""

from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, List, Optional, Sequence, Tuple

from ..envknobs import env_disabled, env_int
from ..data.dataset import (
    ArrayDataset,
    Dataset,
    ObjectDataset,
    default_ingest_workers,
    transfer_dtype,
)
from ..obs import cost as _cost
from ..obs import names as _names
from ..obs import spans as _spans
from ..obs import store as _store
from ..reliability.faultinject import probe
from .graph import Graph, NodeId, SourceId
from .operators import DatasetOperator, EstimatorOperator, TransformerOperator
from .rules import PrefixMap, Rule

logger = logging.getLogger(__name__)


# ------------------------------------------------------------------ enablement

# Tri-state like fusion's: None → env default (on unless
# KEYSTONE_STREAMING=off/0/disabled).
_enabled: Optional[bool] = None
_enabled_lock = threading.Lock()


def streaming_enabled() -> bool:
    if _enabled is not None:
        return _enabled
    return not env_disabled("KEYSTONE_STREAMING")


def set_streaming_enabled(value: Optional[bool]) -> None:
    """Force streaming on/off process-wide; ``None`` restores the env
    default."""
    global _enabled
    with _enabled_lock:
        _enabled = value


@contextmanager
def streaming_disabled():
    """Scoped off-switch (parity tests build the materialized reference
    here, exactly like fusion_disabled())."""
    global _enabled
    with _enabled_lock:
        prev = _enabled
        _enabled = False
    try:
        yield
    finally:
        with _enabled_lock:
            _enabled = prev


def stream_chunk_rows() -> int:
    """Rows per streamed chunk (``KEYSTONE_STREAM_CHUNK_ROWS``, default
    4096 — large enough to amortize dispatch, small enough that two host
    chunk buffers stay far below any realistic feature matrix)."""
    return max(1, env_int("KEYSTONE_STREAM_CHUNK_ROWS", 4096))


def stream_min_rows() -> int:
    """Plan-time eligibility floor for known-size datasets: below
    max(2·chunk, this) the materialized path wins (one dispatch, no
    pipeline overhead). ``KEYSTONE_STREAM_MIN_ROWS`` raises it."""
    return env_int("KEYSTONE_STREAM_MIN_ROWS", 0)


def stream_prefetch_depth() -> int:
    """Host prefetch-queue depth (``KEYSTONE_STREAM_PREFETCH``, default
    1). The engine holds at most depth+1 host chunk buffers live — depth
    queued plus one in hand being uploaded — so the default keeps peak
    host residency at 2× chunk while still hiding decode behind compute."""
    return max(1, env_int("KEYSTONE_STREAM_PREFETCH", 1))


def chain_class(members: Sequence[Any]) -> str:
    """Process-stable identity of a featurize chain for knob keys: the
    member type sequence, hashed. Deliberately coarser than the autocache
    structural digest — a chunk-size observation transfers across fits
    whose chains have the same op sequence even when weights differ."""
    import hashlib

    token = "|".join(
        f"{type(m).__module__}.{type(m).__qualname__}" for m in members
    )
    return hashlib.sha1(token.encode()).hexdigest()[:16]


class StreamingFallback(Exception):
    """Raised (internally, before any chunk is consumed) when a planned
    streaming fit turns out ineligible at run time — the operator falls
    back to the materialized path. Never used for mid-stream failures:
    those propagate to the reliability layer."""


class FoldPreempted(Exception):
    """Raised inside ``ChunkStream.fold``'s dispatch loop when the armed
    scheduler lease yields at a chunk boundary (sustained SLO pressure).
    Caught by the fold itself — it returns normally with the partial
    prefix carry and ``report.preempted_at_chunk`` set; the durable
    cursor was committed before the raise, so the deferred fold resumes
    from the boundary instead of restarting (docs/SCHEDULING.md)."""

    def __init__(self, chunk_index: int):
        self.chunk_index = int(chunk_index)
        super().__init__(f"fold preempted at chunk {chunk_index}")


# ------------------------------------------------------------- pipelined loop


def stream_pipelined(
    items: Iterable[Any],
    stage: Callable[[Any], Any],
    compute: Callable[[Any, Any], Any],
    consume: Callable[[Any, Any], None],
    prefetch: int = 2,
) -> int:
    """The shared double-buffered dispatch loop.

    ``stage(item)`` issues the (async) host→device upload; ``compute``
    dispatches device work on the staged value; ``consume`` forces and
    drains a result ONE item behind the dispatch frontier — so staging
    of item i+1 is always issued before the loop blocks on item i, and
    transfer, device compute, and host copies overlap. This is the
    engine under both the streaming fit path below and the ImageNet
    flagship's per-bucket encode loop
    (pipelines/imagenet_streaming.py), which used to hand-roll it.
    Returns the number of items processed.
    """
    staged: List[Tuple[Any, Any]] = []
    pending: List[Tuple[Any, Any]] = []
    it = iter(items)
    done = 0

    def stage_next() -> bool:
        try:
            item = next(it)
        except StopIteration:
            return False
        staged.append((stage(item), item))
        return True

    for _ in range(max(1, prefetch)):
        stage_next()
    while staged:
        s, item = staged.pop(0)
        pending.append((compute(s, item), item))
        stage_next()
        if len(pending) > 1:
            r, r_item = pending.pop(0)
            consume(r, r_item)
            done += 1
    while pending:
        r, r_item = pending.pop(0)
        consume(r, r_item)
        done += 1
    return done


# ------------------------------------------------------------------- reporting


@dataclass
class StreamReport:
    """What the last streaming fit actually did — the evidence the
    smoke script and tests assert on (overlap, compiles, memory)."""

    chunks: int = 0
    chunk_rows: int = 0
    num_examples: int = 0
    bytes_transferred: int = 0
    prefetch_depth: int = 0
    host_buffer_peak_bytes: int = 0
    stall_s: float = 0.0
    compiles_first_chunk: int = 0
    compiles_steady_state: int = 0
    #: Partitioned (multi-device) chunk plan: row shards the chunk rows
    #: split across, feature-block (model) shards of a 2-D layout, the
    #: mesh shape, and the payload bytes of the finish-time statistics
    #: reductions (docs/PARTITIONING.md; 1/()/0 = single-device).
    #: ``collective_bytes`` totals both axes; the per-axis split and the
    #: per-device carry bytes are what bench-diff exact-gates.
    shards: int = 1
    model_shards: int = 1
    mesh_shape: Tuple[int, ...] = ()
    collective_bytes: int = 0
    collective_bytes_data: int = 0
    collective_bytes_model: int = 0
    state_bytes_per_device: int = 0
    #: Durable-fit evidence (docs/RELIABILITY.md "Durable fits"):
    #: mid-stream checkpoints committed, the absolute chunk a crashed
    #: fit resumed from (None = fresh), chunks re-ingested by resume or
    #: shard-loss recovery, and device losses absorbed mid-stream.
    checkpoints: int = 0
    resumed_from_chunk: Optional[int] = None
    reingested_chunks: int = 0
    shard_losses: int = 0
    #: Scheduler preemption (docs/SCHEDULING.md): the absolute chunk a
    #: leased fold yielded at under sustained SLO pressure (None = ran
    #: to completion). The durable cursor committed at this boundary —
    #: the deferred fold resumes from it instead of restarting.
    preempted_at_chunk: Optional[int] = None
    #: perf_counter at fold start — the event lists below are offsets
    #: from this, so exporters can place chunk slices on a session
    #: timeline (obs/export.py Perfetto view).
    t0_s: float = 0.0
    upload_issued_t: List[float] = field(default_factory=list)
    dispatch_t: List[float] = field(default_factory=list)
    compute_done_t: List[float] = field(default_factory=list)

    def overlap_ok(self) -> bool:
        """True when the upload of chunk i+1 was issued before compute
        of chunk i was observed complete — the double-buffer invariant."""
        if self.chunks < 2:
            return True
        return all(
            self.upload_issued_t[i + 1] <= self.compute_done_t[i]
            for i in range(self.chunks - 1)
        )

    def overlap_efficiency(self) -> float:
        """Fraction of chunk boundaries where the next upload was in
        flight before the previous compute finished — 1.0 is perfect
        double-buffering, the number the profile store remembers per
        shape class."""
        if self.chunks < 2:
            return 1.0
        good = sum(
            1
            for i in range(self.chunks - 1)
            if self.upload_issued_t[i + 1] <= self.compute_done_t[i]
        )
        return good / (self.chunks - 1)


_last_report: Optional[StreamReport] = None
_report_lock = threading.Lock()


def last_stream_report() -> Optional[StreamReport]:
    """The :class:`StreamReport` of the most recent streaming fit in
    this process (None if none ran)."""
    return _last_report


def _publish_report(report: StreamReport) -> None:
    global _last_report
    with _report_lock:
        _last_report = report
    _names.metric(_names.STREAM_HOST_BUFFER_PEAK).set(
        report.host_buffer_peak_bytes
    )


# ----------------------------------------------------------- fused chunk step

# One jitted (cast → chain → re-zero → estimator step) callable per
# (member instances, step_fn) pair, shared across folds — same rationale
# as fusion's _shared_chain_jit: every fit of an unfitted pipeline builds
# a fresh StreamingFitOperator, and a per-fold jit would retrace the
# identical program every time (breaking the zero-steady-state-recompile
# guarantee across repeated fits). Entries keep strong refs to members.
_STEP_JIT_CACHE = None  # type: ignore
_STEP_JIT_MAX = 32
_step_cache_lock = threading.Lock()


def _cast_tree(x):
    import jax
    import jax.numpy as jnp

    def cast(a):
        if jnp.issubdtype(a.dtype, jnp.floating):
            return a
        return a.astype(jnp.float32)  # uint8/int/bool → f32 ON DEVICE

    return jax.tree_util.tree_map(cast, x)


def _apply_chain(members, x, mask):
    import jax
    import jax.numpy as jnp

    x = _cast_tree(x)
    for m in members:
        x = m.apply_arrays(x)

    # Re-zero pad rows once at the end of the chain (valid because
    # apply_arrays is row-independent by the BatchTransformer contract)
    # so the estimator's accumulation sees exact zeros — same discipline
    # as BatchTransformer.apply_batch.
    def zero_pad(a):
        m = mask.reshape((-1,) + (1,) * (a.ndim - 1))
        return jnp.where(m > 0, a, jnp.zeros((), dtype=a.dtype))

    return jax.tree_util.tree_map(zero_pad, x)


def _shared_step_jit(members: tuple, step_fn, partition=None):
    """jit of (carry, x_raw, y, mask) → (carry', probe), cached on
    (member ids, step_fn id, partition mesh). Returns
    (callable, trace_counter_list) — the counter appends at trace time
    only, making 'exactly one compile per chunk shape' directly
    observable.

    With an eligible ``partition`` decision the fused step runs inside
    ``shard_map`` over the decision's mesh: each device featurizes its
    row slice of the chunk and accumulates into its OWN carry block (the
    carry grows a leading ``(shards,)`` axis sharded over the row axes),
    so no collective runs per chunk — the partial statistics are summed
    across shards once, at fold finish (docs/PARTITIONING.md)."""
    global _STEP_JIT_CACHE
    import jax

    key = tuple(id(m) for m in members) + (id(step_fn),)
    if partition is not None:
        key += (
            "sharded", id(partition.mesh), partition.shards,
            getattr(partition, "model_shards", 1),
        )
    with _step_cache_lock:
        if _STEP_JIT_CACHE is None:
            from collections import OrderedDict

            _STEP_JIT_CACHE = OrderedDict()
        hit = _STEP_JIT_CACHE.get(key)
        if hit is not None:
            _STEP_JIT_CACHE.move_to_end(key)
            return hit[1], hit[2]

    traces: List[tuple] = []

    # Index-keyed folds (sketch/core.py) declare needs_mask: the step
    # receives the chunk's pad mask — whose lane holds absolute row
    # indices — as a fourth argument. Gram-family steps keep the 3-arg
    # signature untouched.
    needs_mask = bool(getattr(step_fn, "needs_mask", False))

    if partition is None:

        def fused(carry, x_raw, y, mask):
            traces.append(())  # trace-time side effect: once per new shape
            x = _apply_chain(members, x_raw, mask)
            if needs_mask:
                new_carry = step_fn(carry, x, y, mask)
            else:
                new_carry = step_fn(carry, x, y)
            leaf = jax.tree_util.tree_leaves(new_carry)[0]
            probe = leaf.ravel()[:1]  # tiny, NOT donated: safe to block on
            return new_carry, probe

    else:
        from jax.sharding import PartitionSpec as P

        from ..parallel.collectives import shard_map as _smap
        from ..parallel.mesh import MODEL_AXIS

        mesh = partition.mesh
        model_shards = getattr(partition, "model_shards", 1)
        # Chunks shard rows over the ROW axes only (replicated over a
        # model axis if present); the stacked carry's leading block axis
        # additionally shards over ``model`` in a 2-D layout.
        spec = P(tuple(partition.mesh_axes))
        carry_spec = P(
            tuple(getattr(partition, "carry_axes", partition.mesh_axes))
        )
        block_step = getattr(step_fn, "model_block_step", None)

        def fused(carry, x_raw, y, mask):
            traces.append(())

            def local(c, x, yb, m):
                # One device's view: carry block (1, …) squeezed, the
                # chunk's row slice featurized and accumulated locally —
                # apply_arrays is row-independent (the BatchTransformer
                # contract), so per-shard application is exact.
                c0 = jax.tree_util.tree_map(lambda a: a[0], c)
                feats = _apply_chain(members, x, m)
                # m is this device's row slice of the mask, so an
                # index-keyed step sees exactly its rows' absolute
                # indices — per-shard sketch partials stay exact.
                if model_shards > 1:
                    # 2-D layout: this device accumulates only its
                    # feature block — the step's blocked protocol takes
                    # the (traced) model-axis position and slices its own
                    # columns out of the full-width featurized chunk.
                    j = jax.lax.axis_index(MODEL_AXIS)
                    if needs_mask:
                        c1 = block_step(c0, feats, yb, m, j)
                    else:
                        c1 = block_step(c0, feats, yb, j)
                elif needs_mask:
                    c1 = step_fn(c0, feats, yb, m)
                else:
                    c1 = step_fn(c0, feats, yb)
                return jax.tree_util.tree_map(lambda a: a[None], c1)

            new_carry = _smap(
                local, mesh=mesh,
                in_specs=(carry_spec, spec, spec, spec),
                out_specs=carry_spec,
            )(carry, x_raw, y, mask)
            leaf = jax.tree_util.tree_leaves(new_carry)[0]
            probe = leaf.ravel()[:1]
            return new_carry, probe

    from ..parallel.linalg import donation_safe

    # carry is owned by the fold loop: created by gram_stream_init (or a
    # refit state seed) and threaded only through this step. Donation is
    # suppressed where the persistent cache makes it unsound
    # (linalg.donation_safe — CPU deserialized-executable aliasing).
    # keystone: owns-donated
    jitted = jax.jit(fused, donate_argnums=(0,) if donation_safe() else ())
    with _step_cache_lock:
        _STEP_JIT_CACHE[key] = ((members, step_fn, partition), jitted, traces)
        _STEP_JIT_CACHE.move_to_end(key)
        while len(_STEP_JIT_CACHE) > _STEP_JIT_MAX:
            _STEP_JIT_CACHE.popitem(last=False)
    return jitted, traces


# ------------------------------------------------------------------ the stream


def _tree_nbytes(tree) -> int:
    import jax

    return sum(
        getattr(leaf, "nbytes", 0) for leaf in jax.tree_util.tree_leaves(tree)
    )


def _stack_carry(carry, shards: int, sharding):
    """Per-device carry blocks: a leading ``(shards,)`` axis sharded over
    the row axes. Shard 0 seeds the estimator's initial carry (or a
    salvaged shard-loss merge), the rest start zero — exact for the
    additive accumulation the fit_stream protocol is (final carry =
    seed + Σ partials, summed once at finish)."""
    import jax
    import jax.numpy as jnp

    def stack(a):
        a = jnp.asarray(a)
        z = jnp.zeros((shards,) + tuple(a.shape), a.dtype)
        return jax.device_put(z.at[0].set(a), sharding)

    return jax.tree_util.tree_map(stack, carry)


def _carry_layout(step_fn, carry) -> Optional[Tuple[Optional[int], ...]]:
    """The blocked-carry protocol's per-leaf feature axes, validated
    against the actual carry structure — ``None`` when the step doesn't
    declare the protocol or the declaration doesn't match the carry."""
    import jax

    layout = getattr(step_fn, "model_layout", None)
    if layout is None or getattr(step_fn, "model_block_step", None) is None:
        return None
    leaves = jax.tree_util.tree_leaves(carry)
    if len(leaves) != len(layout):
        return None
    return tuple(layout)


def _stack_carry_2d(carry, row_shards: int, model_shards: int, layout, sharding):
    """2-D per-device carry blocks: leading axis ``row_shards ×
    model_shards`` sharded over ``(row axes, model)`` — flat block index
    ``data_idx·model_shards + model_idx``, row-major. Feature leaves
    (``layout`` axis int) split into model blocks; the SEED therefore
    lands spread over blocks 0..model_shards−1 (data row 0). Feature-free
    leaves (``layout`` None) keep full shape per block and seed only
    block 0 — the finish reduce SUMS them across both axes, so the
    additive contract holds leaf-wise."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    total = row_shards * model_shards
    leaves, treedef = jax.tree_util.tree_flatten(carry)

    def stack(a, ax):
        a = jnp.asarray(a)
        if ax is None:
            z = jnp.zeros((total,) + tuple(a.shape), a.dtype)
            return jax.device_put(z.at[0].set(a), sharding)
        b = a.shape[ax] // model_shards
        block_shape = a.shape[:ax] + (b,) + a.shape[ax + 1:]
        z = jnp.zeros((total,) + block_shape, a.dtype)
        for j in range(model_shards):
            blk = lax.slice_in_dim(a, j * b, (j + 1) * b, axis=ax)
            z = z.at[j].set(blk)
        return jax.device_put(z, sharding)

    return jax.tree_util.tree_unflatten(
        treedef, [stack(a, ax) for a, ax in zip(leaves, layout)]
    )


def _merge_blocks(carry, row_shards: int, model_shards: int, layout, np_mod):
    """Reduce a stacked ``(row_shards·model_shards, …)`` carry back to the
    estimator's single-device shape: partials SUM across the data axis;
    feature leaves then CONCATENATE their model blocks along the layout
    axis, feature-free leaves sum (only model block 0 accumulated them).
    ``np_mod`` is numpy for host merges (checkpoints, salvage) or
    jax.numpy for the on-device finish reduce."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(carry)
    if layout is None:
        layout = (None,) * len(leaves)

    def merge(a, ax):
        a = np_mod.asarray(a)
        a = a.reshape((row_shards, model_shards) + a.shape[1:]).sum(axis=0)
        if ax is None or model_shards == 1:
            return a.sum(axis=0) if ax is None else a[0]
        return np_mod.concatenate(
            [a[j] for j in range(model_shards)], axis=ax
        )

    return jax.tree_util.tree_unflatten(
        treedef, [merge(a, ax) for a, ax in zip(leaves, layout)]
    )


def _labels_host(labels: Dataset):
    """Labels as one host (n, k) float-ready matrix. Labels are O(n·k) —
    'the full feature matrix never materializes' is about features; a
    label matrix is the estimator's RHS and is small by construction."""
    import numpy as np

    if isinstance(labels, ObjectDataset):
        labels = labels.to_arrays()
    if not isinstance(labels, ArrayDataset):
        raise StreamingFallback(f"labels of type {type(labels).__name__}")
    # One-time fit setup, before the chunk loop starts.  # keystone: allow-sync
    y = np.asarray(labels.data)[: labels.num_examples]
    if y.ndim == 1:
        y = y[:, None]
    if y.ndim != 2:
        raise StreamingFallback(f"labels must be rank ≤ 2, got {y.shape}")
    return np.ascontiguousarray(y.astype(transfer_dtype(y.dtype), copy=False))


class ChunkStream:
    """The engine-side handle handed to ``Estimator.fit_stream``.

    ``fold(init_fn, step_fn)`` drives the chunked plan:

    - ``init_fn(feat_aval, y_aval)`` receives jax ShapeDtypeStructs of
      the FEATURIZED chunk (post-chain, computed via ``jax.eval_shape``
      without touching data) and the label chunk, and returns the
      initial carry pytree. Raise :class:`StreamingFallback` here to
      reject the shape (nothing has been prefetched yet).
    - ``step_fn(carry, x_feat, y) -> carry`` is traced INTO the single
      per-chunk dispatch, after the featurize chain, with the carry
      donated — the Gram-accumulation protocol.

    Returns ``(carry, info)`` where info has ``num_examples``, ``d``
    (featurized width) and the :class:`StreamReport`.
    """

    def __init__(
        self,
        data: Dataset,
        labels: Optional[Dataset],
        members: Sequence[TransformerOperator],
        chunk_rows: Optional[int] = None,
        prefetch: Optional[int] = None,
        workers: Optional[int] = None,
        partition=None,
    ):
        self.data = data
        self.labels = labels
        self.members = tuple(members)
        self.chunk_rows = chunk_rows or stream_chunk_rows()
        self.prefetch = prefetch or stream_prefetch_depth()
        self.workers = workers or min(default_ingest_workers(), 4)
        self.num_examples = len(data)
        self._feat_aval = None
        # An eligible PartitionDecision (parallel/partitioner.py) runs the
        # sharded chunk plan; the compiled chunk shape must divide evenly
        # across the shards, so round chunk_rows up to a shard multiple.
        self.partition = (
            partition
            if partition is not None and getattr(partition, "eligible", False)
            else None
        )
        if self.partition is not None:
            s = self.partition.shards
            self.chunk_rows = -(-self.chunk_rows // s) * s
        #: Durability plan (reliability/durable.py DurableFold), armed by
        #: the streaming operator when a checkpoint store is attached.
        #: None = today's fold, byte for byte.
        self.durable = None
        #: Mesh-scheduler lease (sched/scheduler.py), armed by scheduled
        #: callers (the refit daemon under a MeshScheduler): consulted at
        #: every chunk boundary; sustained SLO pressure preempts the fold
        #: there, committing the durable cursor first. None = unscheduled
        #: fold, byte for byte.
        self.lease = None

    def feature_aval(self):
        """Shape/dtype of one FEATURIZED chunk (shape-only trace of the
        chain, no data touched). Raises :class:`StreamingFallback` when
        the chain can't shape-trace or the dataset isn't chunkable."""
        if self._feat_aval is None:
            import jax
            import numpy as np

            x_spec = _chunk_spec(self.data, self.chunk_rows)
            mask_spec = jax.ShapeDtypeStruct((self.chunk_rows, 1), np.float32)
            try:
                self._feat_aval = jax.eval_shape(
                    lambda x, m: _apply_chain(self.members, x, m),
                    x_spec,
                    mask_spec,
                )
            except StreamingFallback:
                raise
            except Exception as e:
                raise StreamingFallback(
                    f"chain not shape-traceable: {e}"
                ) from e
        return self._feat_aval

    # ---------------------------------------------------------------- fold
    def fold(self, init_fn, step_fn):
        import jax
        import numpy as np

        from ..parallel.linalg import _quiet_unused_donation_warnings

        data, chunk_rows = self.data, self.chunk_rows
        n = self.num_examples
        if self.labels is None:
            raise StreamingFallback("no labels bound for a supervised fit")
        y_host = _labels_host(self.labels)
        if y_host.shape[0] < n:
            raise StreamingFallback(
                f"labels rows {y_host.shape[0]} < data rows {n}"
            )

        # Shape-only pass: featurized aval without touching data.
        feat_aval = self.feature_aval()
        y_spec = jax.ShapeDtypeStruct((chunk_rows, y_host.shape[1]), y_host.dtype)
        carry = init_fn(feat_aval, y_spec)

        part = self.partition
        if part is not None and getattr(part, "model_shards", 1) > 1:
            # The plan granted the model axis optimistically (raw-width
            # proxy); re-validate against the REAL carry the estimator
            # built — the step's blocked protocol, the featurized width's
            # divisibility, and the width floor — and demote to row-only
            # (same mesh, replicated over model) when any fail.
            part = self._validate_model_axis(part, step_fn, carry)
        durable = self.durable
        lease = self.lease
        sharding = None
        # Shard-loss recovery must be able to re-add the fold's seed when
        # the device holding carry block 0 dies: keep the PRE-STACK device
        # carry alive (stack() copies, nothing donates it) and fetch it to
        # host only if that loss actually happens.
        seed_carry_dev = carry if part is not None else None
        attempt_seed_host = None

        if part is not None:
            from ..parallel.partitioner import NamedShardingCache

            sharding = NamedShardingCache.get(part.mesh, part.mesh_axes)
            if part.model_shards > 1:
                carry_sharding = NamedShardingCache.get(
                    part.mesh, part.carry_axes
                )
                carry = _stack_carry_2d(
                    carry, part.shards, part.model_shards,
                    _carry_layout(step_fn, carry), carry_sharding,
                )
            else:
                carry = _stack_carry(carry, part.shards, sharding)

        _quiet_unused_donation_warnings()  # carries are donated each step
        step, traces = _shared_step_jit(self.members, step_fn, part)

        if not hasattr(type(data), "fetch_rows") or (
            type(data).fetch_rows is Dataset.fetch_rows
        ):
            raise StreamingFallback(f"{type(data).__name__} is not chunkable")
        windows = [
            (s, min(s + chunk_rows, n)) for s in range(0, n, chunk_rows)
        ]
        start_chunk = (
            min(durable.start_chunk, len(windows)) if durable is not None else 0
        )

        report = StreamReport(
            chunk_rows=chunk_rows,
            num_examples=n,
            prefetch_depth=self.prefetch,
            shards=part.shards if part is not None else 1,
            model_shards=part.model_shards if part is not None else 1,
            mesh_shape=tuple(part.mesh_shape) if part is not None else (),
            # The acceptance number for 2-D layouts: bytes of streamed
            # solver state each device actually holds — shrinks with
            # model shards while the row-only plan replicates it.
            state_bytes_per_device=(
                _tree_nbytes(carry) // part.total_shards
                if part is not None
                else _tree_nbytes(carry)
            ),
        )
        if start_chunk:
            # Crash-resume: chunks before the cursor live in the seeded
            # carry already — only the suffix is re-ingested.
            report.resumed_from_chunk = start_chunk
            report.reingested_chunks = len(windows) - start_chunk
            _names.metric(_names.DURABLE_REINGESTED_CHUNKS).inc(
                report.reingested_chunks
            )
        data_shape = _store.dataset_shape_class(data)
        chunks_c = _names.metric(_names.STREAM_CHUNKS)
        bytes_c = _names.metric(_names.STREAM_BYTES)
        from ..data.ingest import PrefetchQueue
        from ..reliability.durable import ShardLossError, shard_loss_index

        def make_prepare(padded_rows):
            def prepare(window):
                start, stop = window
                # fetch_rows runs inside the prefetch workers — this is
                # the decode/stack work being overlapped with device
                # compute.
                x = data.fetch_rows(start, stop)
                x = jax.tree_util.tree_map(
                    lambda a: _pad_narrow(a, padded_rows), x
                )
                rows = stop - start
                y = y_host[start:stop]
                if rows < padded_rows:  # tail chunk: pad to compiled shape
                    y = np.concatenate(
                        [y, np.zeros((padded_rows - rows,) + y.shape[1:], y.dtype)]
                    )
                # The pad-mask lane carries each row's ABSOLUTE dataset
                # index + 1 (0 = pad). The chain only tests m > 0, so
                # this is backward-compatible; index-keyed folds (the
                # sketch tier) read the value itself, which stays exact
                # in float32 up to 2^24 rows (sketch/core.py refuses
                # longer streams).
                mask = np.zeros((padded_rows, 1), np.float32)
                mask[:rows, 0] = np.arange(start + 1, stop + 1, dtype=np.float32)
                return x, y, mask, rows

            return prepare

        in_hand_peak = 0
        queue_stall_s = 0.0
        queue_peak = 0
        t0 = time.perf_counter()
        report.t0_s = t0

        # ---- durable/elastic bookkeeping --------------------------------
        # rows_folded: ABSOLUTE logical rows fully dispatched (a resumed
        # fold starts at the cursor's count) — what a committed cursor
        # records. dispatched indexes attempt_windows (the ordered
        # PrefetchQueue guarantees windows dispatch in source order);
        # folded_log keeps each window's fold-time geometry so shard-loss
        # salvage can slice exactly the lost device's rows back out.
        rows_folded = durable.resume_rows if durable is not None else 0
        dispatched = 0
        last_committed = -1
        # Recovery windows break the canonical chunk-prefix ordering a
        # cursor describes, so after a shard loss mid-fit checkpoints
        # suspend for the remainder of the fold (docs/RELIABILITY.md).
        ckpt_suspended = False
        folded_log: List[Tuple[int, int, int, int]] = []
        attempt_windows: List[Tuple[int, int]] = windows[start_chunk:]
        steady_accum = 0
        attempt_base: Optional[int] = None

        # The loop below IS stream_pipelined — the same engine that runs
        # the flagship's per-bucket encode — with the carry threaded and
        # the report timestamps recorded through the three callbacks.
        # consume() drains one item behind the dispatch frontier, so the
        # upload of chunk i+1 (stage) is always issued before the loop
        # blocks on chunk i — the double-buffer invariant the smoke
        # script asserts via the event log.
        def stage(chunk):
            nonlocal in_hand_peak
            x, y, mask, rows = chunk
            nbytes = _tree_nbytes(x) + y.nbytes + mask.nbytes
            in_hand_peak = max(in_hand_peak, nbytes)
            report.upload_issued_t.append(time.perf_counter() - t0)
            # Async uploads at transfer (narrow) width; cast happens on
            # device inside the fused step. Under a partition decision
            # every leaf lands row-sharded over the mesh — each device
            # receives only its slice of the chunk.
            put = (
                jax.device_put if sharding is None
                else (lambda a: jax.device_put(a, sharding))
            )
            dev = (
                jax.tree_util.tree_map(put, x),
                put(y),
                put(mask),
                rows,
            )
            report.bytes_transferred += nbytes
            bytes_c.inc(nbytes)
            return dev

        def commit_checkpoint():
            # Commit-before-continue barrier: the carry is host-fetched
            # (device_get blocks until the last dispatch retired) and the
            # atomic store write completes BEFORE the next chunk's
            # dispatch may donate the buffer — a persisted carry is never
            # stale (the linalg.donation_safe discipline applied to
            # persistence).  # keystone: allow-sync
            host = jax.device_get(carry)
            if part is not None:
                # Per-shard partials merge via the additive contract into
                # a mesh-INDEPENDENT snapshot (rows summed, feature
                # blocks reassembled): resume may re-plan on any mesh
                # shape, 1-D or 2-D. Operates on the already-fetched HOST
                # tree, never a device array.  # keystone: allow-sync
                host = _merge_blocks(
                    host, part.shards, part.model_shards,
                    _carry_layout(step_fn, host)
                    if part.model_shards > 1
                    else None,
                    np,
                )
            ok = durable.commit(
                tuple(
                    np.asarray(a)  # host leaves  # keystone: allow-sync
                    for a in jax.tree_util.tree_leaves(host)
                ),
                chunk_index=start_chunk + dispatched,
                rows_consumed=rows_folded,
                chunk_rows=chunk_rows,
                mesh_shape=tuple(part.mesh_shape) if part is not None else (),
                shards=part.shards if part is not None else 1,
                model_shards=part.model_shards if part is not None else 1,
            )
            if ok:
                report.checkpoints += 1

        def compute(staged_chunk, _chunk):
            nonlocal carry, dispatched, rows_folded, last_committed
            x_dev, y_dev, mask_dev, _rows = staged_chunk
            if (
                lease is not None
                and dispatched > 0
                and lease.should_yield()
            ):
                # Preempt-at-chunk-boundary: commit the durable cursor
                # FIRST (the preemption contract — a deferred fold must
                # resume from here, not restart), then unwind. The
                # prefix carry stays valid statistics; the caller reads
                # report.preempted_at_chunk and re-leases later.
                if (
                    durable is not None
                    and not ckpt_suspended
                    and dispatched != last_committed
                ):
                    last_committed = dispatched
                    commit_checkpoint()
                report.preempted_at_chunk = start_chunk + dispatched
                lease.mark_preempted(start_chunk + dispatched)
                raise FoldPreempted(start_chunk + dispatched)
            if (
                durable is not None
                and durable.ckpt_every > 0
                and not ckpt_suspended
                and dispatched > 0
                and dispatched % durable.ckpt_every == 0
                and dispatched != last_committed
            ):
                last_committed = dispatched
                commit_checkpoint()
            if part is not None:
                try:
                    probe("parallel.shard_loss")
                except Exception as exc:
                    # Any injected fault at this site models the runtime
                    # observing a device gone from the mesh before this
                    # chunk could dispatch — the elastic recovery below
                    # owns it.
                    # Indexed over ALL carry blocks (row × model shards,
                    # flat row-major) so a seeded fault can land on
                    # either axis of a 2-D layout.
                    raise ShardLossError(
                        shard_loss_index(part.total_shards),
                        start_chunk + dispatched,
                        part.total_shards,
                    ) from exc
            probe("streaming.chunk")
            if not report.chunks and _cost.current_frame() is not None:
                # Cost-observatory note, once per fold: avals (not the
                # arrays — the carry is donated into the step) so the
                # per-chunk program's flop/byte facts harvest at node
                # finalize through the jit trace cache (obs/cost.py).
                avals = jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    (carry, x_dev, y_dev, mask_dev),
                )
                _cost.note_jit_call("stream_step", step, avals=avals)
            report.dispatch_t.append(time.perf_counter() - t0)
            carry, probe_out = step(carry, x_dev, y_dev, mask_dev)
            chunks_c.inc()
            report.chunks += 1
            if report.chunks == 1:
                report.compiles_first_chunk = len(traces)
            w = attempt_windows[dispatched]
            folded_log.append(
                (w[0], w[1], part.shards if part is not None else 1, chunk_rows)
            )
            dispatched += 1
            rows_folded += _rows
            return probe_out

        def consume(probe_out, _chunk):
            # The overlap engine's completion barrier for chunk i — a
            # one-element un-donated probe leaf, waited on so chunk
            # timings and backpressure are real.  # keystone: allow-sync
            probe_out.block_until_ready()
            report.compute_done_t.append(time.perf_counter() - t0)

        try:
            with _spans.span(
                "stream:fold", chunks=len(windows), chunk_rows=chunk_rows,
                shards=report.shards,
            ):
                while True:
                    queue = PrefetchQueue(
                        iter(attempt_windows),
                        make_prepare(chunk_rows),
                        depth=self.prefetch,
                        workers=min(self.workers, self.prefetch),
                        size_of=lambda c: _tree_nbytes(c[0]) + c[1].nbytes,
                    )
                    try:
                        stream_pipelined(
                            queue, stage=stage, compute=compute,
                            consume=consume, prefetch=1,
                        )
                    except ShardLossError as loss:
                        # Join this attempt's prefetch workers BEFORE
                        # salvage (and before ANY exception leaves the
                        # fold — the finally below covers the abort
                        # paths): an abandoned fold must never leak
                        # decode threads.
                        queue.close()
                        if report.chunks:
                            prev_base = (
                                report.compiles_first_chunk
                                if attempt_base is None
                                else attempt_base
                            )
                            steady_accum += len(traces) - prev_base
                        (
                            part, sharding, carry, step, traces,
                            attempt_windows, chunk_rows, attempt_seed_host,
                        ) = self._salvage_shard_loss(
                            loss, carry, part, step_fn, seed_carry_dev,
                            attempt_seed_host, folded_log, attempt_windows,
                            dispatched, chunk_rows, report,
                        )
                        # A loss before ANY chunk folded means the next
                        # attempt's first chunk IS the fold's first chunk
                        # — leave the baseline to compiles_first_chunk or
                        # its compiles would double-count as steady-state.
                        attempt_base = len(traces) if report.chunks else None
                        folded_log = []
                        dispatched = 0
                        ckpt_suspended = True
                        continue
                    except FoldPreempted:
                        # Graceful yield: fall through to the finish
                        # merge with the prefix carry — the cursor is
                        # already committed, the report already marked.
                        pass
                    finally:
                        queue.close()
                        queue_stall_s += queue.stall_s
                        queue_peak = max(queue_peak, queue.peak_live_bytes)
                    break
                if part is not None:
                    # THE cross-shard reduction of the whole fit, once at
                    # finish — O(d²) payload independent of how many
                    # chunks streamed (docs/PARTITIONING.md): partials
                    # SUM across the data axis; a 2-D layout then
                    # reassembles the feature blocks across the model
                    # axis (concat for feature leaves, sum for the
                    # feature-free remainder). Unconditional on chunk
                    # count: the stacked carry must ALWAYS come back to
                    # the estimator's single-device shape (a zero-chunk
                    # fold reduces to the seeded init carry).
                    import jax.numpy as jnp

                    from ..parallel.partitioner import (
                        record_collective_bytes,
                        record_imbalance,
                    )

                    p_m = part.model_shards
                    layout = (
                        _carry_layout(step_fn, carry) if p_m > 1 else None
                    )
                    carry = _merge_blocks(
                        carry, part.shards, p_m, layout, jnp
                    )
                    if report.chunks:
                        # Per-axis accounting, plan-pure: with reduced
                        # leaf bytes split into feature (B_f, sharded
                        # over model) and remainder (B_r, replicated),
                        # each device block holds B_f/p_m + B_r. The
                        # data-axis sum moves one block per non-root row
                        # shard per model column; the model-axis
                        # reassembly moves one block per non-root model
                        # column. At p_m = 1 the data term reduces to
                        # the historical bytes × (shards − 1).
                        reduced = _tree_nbytes(carry)
                        if layout is not None:
                            leaves = jax.tree_util.tree_leaves(carry)
                            b_f = sum(
                                leaf.nbytes
                                for leaf, ax in zip(leaves, layout)
                                if ax is not None
                            )
                        else:
                            b_f = reduced
                        b_r = reduced - b_f
                        report.collective_bytes_data = (
                            b_f + p_m * b_r
                        ) * (part.shards - 1)
                        report.collective_bytes_model = (
                            b_f // p_m + b_r
                        ) * (p_m - 1)
                        report.collective_bytes = (
                            report.collective_bytes_data
                            + report.collective_bytes_model
                        )
                        record_collective_bytes(
                            report.collective_bytes_data, axis="data"
                        )
                        record_collective_bytes(
                            report.collective_bytes_model, axis="model"
                        )
                        record_imbalance(
                            "fit_stream", n, len(windows) * chunk_rows
                        )
        finally:
            report.stall_s = queue_stall_s
            report.host_buffer_peak_bytes = queue_peak + in_hand_peak
            prev_base = (
                report.compiles_first_chunk
                if attempt_base is None
                else attempt_base
            )
            report.compiles_steady_state = (
                steady_accum + len(traces) - prev_base
            )
            _publish_report(report)

        if durable is not None and report.preempted_at_chunk is None:
            # The fit completed: a resume entry pointing into its middle
            # must not outlive it. A PREEMPTED fold is the opposite case
            # — its cursor IS the resume point the next lease needs.
            durable.complete()

        # A COMPLETED fold is a knob observation: remember what this
        # chunk size achieved on this data shape, so MeasuredKnobRule can
        # prefer the best recorded chunk_rows next plan (a failed fold
        # recorded nothing — its throughput would be a lie; a resumed or
        # shard-loss-recovered fold measured recovery, not steady state).
        if (
            report.chunks == len(windows)
            and report.resumed_from_chunk is None
            and report.preempted_at_chunk is None
            and not report.shard_losses
        ):
            self._record_observation(report, data_shape)
        if (
            report.compute_done_t
            and report.resumed_from_chunk is None
            and report.preempted_at_chunk is None
            and not report.shard_losses
        ):
            # Achieved throughput to the enclosing harvest frame: a
            # rows/s-denominated prediction (the measured-knob chunk
            # winner) is drift-scored in its own unit (obs/cost.py).
            # Resumed/recovered folds measured recovery, not steady
            # state — feeding suffix-only walls against full-dataset
            # rows would inflate rows/s and mis-score the drift
            # sentinel (same guard as _record_observation). A
            # scheduler-PREEMPTED fold is the mirror image — a partial
            # wall against full num_examples would inflate the same way
            # (the PR-15 suffix-wall guard extended to deferrals).
            wall = max(report.compute_done_t[-1], 1e-9)
            _cost.note_stream_result(report.num_examples / wall, n)

        resume_rows = durable.resume_rows if durable is not None else 0
        info = {
            # Rows THIS fold absorbed: a resumed fold re-ingests only the
            # suffix past the cursor — the cursor's rows already live in
            # the seeding state, and estimators add state.num_examples.
            # A preempted fold absorbed only the dispatched prefix.
            "num_examples": (
                rows_folded - resume_rows
                if report.preempted_at_chunk is not None
                else n - resume_rows
            ),
            "chunks": report.chunks,
            "report": report,
        }
        return carry, info

    def _validate_model_axis(self, part, step_fn, carry):
        """Fold-time re-validation of an optimistically-granted model
        axis against ground truth the planner lacked: the step function's
        blocked-carry protocol and the REAL featurized width sitting in
        the estimator's init carry. Any failure demotes to the row-only
        layout on the SAME mesh (``demote_model_axis`` — chunk geometry
        and the armed durable cursor stay valid); a demotion that leaves
        no row axis to shard returns ``None`` (single-device fold)."""
        import jax

        from ..parallel.partitioner import (
            R_BELOW_WIDTH_FLOOR,
            R_MODEL_INDIVISIBLE,
            demote_model_axis,
            partition_min_width_per_shard,
        )

        p_m = part.model_shards
        layout = _carry_layout(step_fn, carry)
        reason = detail = ""
        if layout is None or all(ax is None for ax in layout):
            reason = R_MODEL_INDIVISIBLE
            detail = (
                f"step {getattr(step_fn, '__name__', type(step_fn).__name__)}"
                " declares no blocked-carry protocol"
            )
        else:
            leaves = jax.tree_util.tree_leaves(carry)
            widths = {
                leaf.shape[ax]
                for leaf, ax in zip(leaves, layout)
                if ax is not None
            }
            width = max(widths)
            if any(w % p_m for w in widths):
                reason = R_MODEL_INDIVISIBLE
                detail = (
                    f"featurized width {sorted(widths)} not divisible by "
                    f"{p_m} model shards"
                )
            elif width < p_m * partition_min_width_per_shard():
                reason = R_BELOW_WIDTH_FLOOR
                detail = (
                    f"featurized width {width} < {p_m} shards × "
                    f"{partition_min_width_per_shard()} min cols/shard"
                )
        if not reason:
            return part
        demoted = demote_model_axis(part, reason, detail)
        return demoted if demoted.eligible else None

    def _salvage_shard_loss(
        self,
        loss,
        carry,
        part,
        step_fn,
        seed_carry_dev,
        attempt_seed_host,
        folded_log,
        attempt_windows,
        dispatched,
        chunk_rows,
        report,
    ):
        """Absorb a mid-stream device loss and hand back the context for
        the next fold attempt.

        The lost device's carry block is gone; everything else survives:
        the other shards' partials merge via the additive state contract
        into one host carry, and — when the dead shard was block 0, which
        carries the fold's SEED (the estimator's init or a resume state)
        — the host-side seed copy is added back. The rows only the lost
        shard had folded (its row slice of every chunk dispatched this
        attempt, per ``folded_log``'s geometry) become recovery windows,
        re-ingested ahead of the untouched remainder. The Partitioner is
        re-consulted on the shrunken mesh; an ineligible decision (down
        to one device) continues single-device — elasticity is never an
        error (docs/RELIABILITY.md "Durable fits").
        """
        import jax
        import numpy as np

        from ..parallel.mesh import mesh_without
        from ..parallel.partitioner import (
            NamedShardingCache,
            Partitioner,
            record_decision,
        )
        from ..reliability.recovery import get_recovery_log

        label = f"fit_stream[{len(self.members)}ops]"
        lost, old_rows, p_m = loss.lost_shard, part.shards, part.model_shards
        # The flat block index is row-major over (data, model): a loss on
        # EITHER axis maps to one data row-group, and the whole group is
        # dropped — with feature-sharded blocks no single column holds a
        # complete partial, so group-mates of a lost device contribute
        # nothing usable on their own. Their rows are re-ingested below.
        lost_row = lost // p_m
        get_recovery_log().record(
            "shard_loss",
            label,
            lost_shard=lost,
            shards=part.total_shards,
            chunk_index=loss.chunk_index,
        )
        _names.metric(_names.DURABLE_SHARD_LOSSES).inc()
        report.shard_losses += 1

        # Surviving per-shard partials, merged once on host (O(d²) — the
        # same additive algebra the finish-time reduce runs): sum the
        # surviving data row-groups, then reassemble feature blocks
        # across the model axis.
        # keystone: allow-sync
        host_blocks = jax.device_get(carry)
        layout = _carry_layout(step_fn, host_blocks) if p_m > 1 else None
        leaves, treedef = jax.tree_util.tree_flatten(host_blocks)
        if layout is None:
            layout = (None,) * len(leaves)

        def merge(a, ax):
            # Already device_get above — host data.  # keystone: allow-sync
            a = np.asarray(a)
            a = a.reshape((old_rows, p_m) + a.shape[1:])
            keep = [i for i in range(old_rows) if i != lost_row]
            summed = (
                a[keep].sum(axis=0) if keep else np.zeros_like(a[0])
            )  # (p_m, …)
            if ax is None:
                return summed.sum(axis=0)
            if p_m == 1:
                return summed[0]
            return np.concatenate([summed[j] for j in range(p_m)], axis=ax)

        surviving = jax.tree_util.tree_unflatten(
            treedef, [merge(a, ax) for a, ax in zip(leaves, layout)]
        )
        if lost_row == 0:
            # Data row-group 0 carried the fold's seed (spread over its
            # feature blocks in a 2-D layout) and the whole group was
            # dropped; the seed survives on the host.
            if attempt_seed_host is None:
                # keystone: allow-sync
                attempt_seed_host = jax.device_get(seed_carry_dev)
            surviving = jax.tree_util.tree_map(
                lambda s, a: np.asarray(s) + np.asarray(a),
                surviving,
                attempt_seed_host,
            )

        # Rows only the lost row-group had absorbed: group i held padded
        # rows [i·rps, (i+1)·rps) of each chunk, so the lost LOGICAL rows
        # of a window (s, e) are the contiguous
        # [s+lost_row·rps, min(s+(lost_row+1)·rps, e)).
        recovery: List[Tuple[int, int]] = []
        for (s, e, shards_f, cr_f) in folded_log:
            rps = cr_f // shards_f
            lo = s + lost_row * rps
            hi = min(s + (lost_row + 1) * rps, e)
            if lo < hi:
                recovery.append((lo, hi))
        remaining = list(attempt_windows[dispatched:])

        decision = Partitioner(mesh=mesh_without(part.mesh, lost)).decide_stream(
            label, chunk_rows, rows=self.num_examples, record=False
        )
        # Metrics yes, plan report no: the report is documented as "the
        # last PLAN's decisions" and a mid-fold re-decision is runtime.
        record_decision(decision, to_report=False)

        if decision.eligible:
            new_part = decision
            new_chunk_rows = decision.chunk_rows or chunk_rows
            sharding = NamedShardingCache.get(new_part.mesh, new_part.mesh_axes)
            carry = _stack_carry(surviving, new_part.shards, sharding)
        else:
            import jax.numpy as jnp

            new_part, sharding = None, None
            new_chunk_rows = chunk_rows
            carry = jax.tree_util.tree_map(jnp.asarray, surviving)
        step, traces = _shared_step_jit(self.members, step_fn, new_part)
        report.shards = new_part.shards if new_part is not None else 1
        report.model_shards = (
            new_part.model_shards if new_part is not None else 1
        )
        report.mesh_shape = (
            tuple(new_part.mesh_shape) if new_part is not None else ()
        )
        report.reingested_chunks += len(recovery)
        _names.metric(_names.DURABLE_REINGESTED_CHUNKS).inc(len(recovery))
        _names.metric(_names.DURABLE_RESUMES).inc(kind="shard")
        get_recovery_log().record(
            "shard_resume",
            label,
            shards=report.shards,
            recovery_chunks=len(recovery),
            remaining_chunks=len(remaining),
        )
        return (
            new_part,
            sharding,
            carry,
            step,
            traces,
            recovery + remaining,
            new_chunk_rows,
            surviving,
        )

    def _record_observation(self, report: StreamReport, data_shape: str) -> None:
        store = _store.get_store()
        if store is None or not report.compute_done_t:
            return
        wall = max(report.compute_done_t[-1], 1e-9)
        store.record(
            f"stream:{chain_class(self.members)}:cr{report.chunk_rows}",
            data_shape,
            chunk_rows=report.chunk_rows,
            rows_per_s=report.num_examples / wall,
            overlap_efficiency=report.overlap_efficiency(),
            stall_s=round(report.stall_s, 6),
            prefetch_depth=report.prefetch_depth,
            host_buffer_peak_bytes=report.host_buffer_peak_bytes,
        )


def _chunk_spec(data: Dataset, chunk_rows: int):
    """ShapeDtypeStructs of one padded chunk at TRANSFER dtype."""
    import jax
    import numpy as np

    if isinstance(data, ArrayDataset):
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(
                (chunk_rows,) + tuple(a.shape[1:]),
                transfer_dtype(getattr(a, "dtype", np.float32)),
            ),
            data.data,
        )
    if isinstance(data, ObjectDataset):
        if not len(data):
            raise StreamingFallback("empty dataset")
        first = data.take(1)[0]
        return jax.tree_util.tree_map(
            # Plan-time spec probe on ONE decoded host item, before any
            # chunk flows.  # keystone: allow-sync
            lambda leaf: jax.ShapeDtypeStruct(
                (chunk_rows,) + np.asarray(leaf).shape,
                transfer_dtype(np.asarray(leaf).dtype),
            ),
            first,
        )
    raise StreamingFallback(f"{type(data).__name__} is not chunkable")


def _pad_narrow(a, chunk_rows: int):
    """Narrow a host leaf to its transfer dtype and zero-pad the tail
    chunk to the compiled chunk shape (one shape → one compile)."""
    import numpy as np

    # Operates on the decoded HOST chunk buffer (pre-upload), never a
    # device array.  # keystone: allow-sync
    a = np.asarray(a)
    narrow = transfer_dtype(a.dtype)
    if narrow != a.dtype:
        a = a.astype(narrow)
    rows = a.shape[0]
    if rows < chunk_rows:
        a = np.concatenate(
            [a, np.zeros((chunk_rows - rows,) + a.shape[1:], a.dtype)]
        )
    return np.ascontiguousarray(a)


# ------------------------------------------------------------------- operator


class StreamingFitOperator(EstimatorOperator):
    """An estimator node rewritten onto the streaming engine.

    Wraps the original estimator plus the featurize-chain members that
    were between it and the data source; depends directly on the RAW
    data (plus labels). At force time it streams chunks through ONE
    fused dispatch per chunk into ``estimator.fit_stream``; if run-time
    eligibility fails (small data, unchunkable dataset, untraceable
    chain) it reproduces the materialized path exactly — member-by-member
    batch application then ``fit_datasets`` — so a planned-but-infeasible
    stream can never change results.
    """

    #: PartitionDecision pinned by workflow/optimize.py::PartitionPlanRule
    #: (None = single-device chunk plan; the class default keeps copies
    #: built by MeasuredKnobRule before the partition batch unpinned).
    partition = None

    def __init__(
        self,
        estimator: EstimatorOperator,
        members: Sequence[TransformerOperator],
        chunk_rows: Optional[int] = None,
        prefetch: Optional[int] = None,
    ):
        self.estimator = estimator
        self.members = tuple(members)
        self.chunk_rows = chunk_rows
        self.prefetch = prefetch

    @property
    def label(self) -> str:
        est = getattr(self.estimator, "label", type(self.estimator).__name__)
        return f"StreamFit[{est}+{len(self.members)}ops]"

    @property
    def solver_precision(self):
        """The wrapped estimator's measured precision pin, surfaced so the
        inherited ``EstimatorOperator.execute`` scopes the whole fit
        (stream and materialized-fallback paths alike) under it."""
        return getattr(self.estimator, "solver_precision", None)

    def fit_datasets(self, datasets: List[Dataset]) -> TransformerOperator:
        data = datasets[0]
        labels = datasets[1] if len(datasets) > 1 else None
        chunk_rows = self.chunk_rows or stream_chunk_rows()
        with _spans.span(
            "stream:fit",
            estimator=str(getattr(self.estimator, "label", "")),
            members=len(self.members),
            chunk_rows=chunk_rows,
        ) as span:
            # A planned-but-unknowable head (Cacher etc.) may yield a
            # Dataset subclass without even a length — that is a
            # fallback, not a crash (the materialized path handles it).
            try:
                n_rows = len(data)
            except Exception:
                n_rows = -1
            if streaming_enabled() and n_rows >= max(
                2 * chunk_rows, stream_min_rows()
            ):
                try:
                    stream = ChunkStream(
                        data,
                        labels,
                        self.members,
                        chunk_rows=chunk_rows,
                        prefetch=self.prefetch,
                        partition=self.partition,
                    )
                    # Durable fits (docs/RELIABILITY.md): with a
                    # checkpoint store attached, arm mid-fit cursor
                    # checkpoints and look for a resume entry a killed
                    # predecessor left behind. A valid entry seeds the
                    # fold (fit_stream's state contract) and the fold
                    # re-ingests only chunks past the cursor; a stale
                    # one is refused (KV306 — VerificationError in
                    # strict mode, which must propagate, not fall back).
                    resume_state = None
                    from .executor import PipelineEnv

                    store = PipelineEnv.get_or_create().checkpoint
                    if store is not None:
                        from ..reliability.durable import arm_durable_fold

                        stream.durable, resume_state = arm_durable_fold(
                            stream, self.estimator, store
                        )
                    if resume_state is not None:
                        span.set_attribute(
                            "resumed_from_chunk", stream.durable.start_chunk
                        )
                        return self.estimator.fit_stream(
                            stream, state=resume_state
                        )
                    return self.estimator.fit_stream(stream)
                except StreamingFallback as e:
                    logger.info(
                        "streaming fit of %s fell back to the materialized "
                        "path: %s", self.label, e,
                    )
                    span.set_attribute("fallback", str(e))
            else:
                span.set_attribute("fallback", "below row floor or disabled")
            featurized = data
            for m in self.members:
                featurized = m.batch_transform([featurized])
            rest = [labels] if labels is not None else []
            return self.estimator.fit_datasets([featurized] + rest)


# ----------------------------------------------------------------- the rule


def _streamable_member(op) -> bool:
    from .fusion import FusedTransformerOperator, is_fusable

    return isinstance(op, FusedTransformerOperator) or is_fusable(op)


class StreamingPlanRule(Rule):
    """Rewrite eligible ``data → featurize-chain → estimator`` shapes
    onto the streaming engine.

    Runs LAST (after auto-cache and fusion, docs/OPTIMIZER.md): the
    chain it absorbs is usually already one FusedTransformerOperator,
    whose members it flattens into the per-chunk dispatch. A chain
    member is absorbable under exactly the fusion rules (array-in/
    array-out, single consumer, unary, outside the prefix map); the
    walk stops at Cacher nodes, saveable prefixes, and fan-out — the
    stream then starts from that boundary's materialized output.

    Plan-time gates: the estimator advertises ``supports_fit_stream``;
    a known-size head (a bound ``DatasetOperator``) must hold at least
    max(2·chunk, ``KEYSTONE_STREAM_MIN_ROWS``) rows; an unknown-size
    head (e.g. a Cacher) is rewritten only when there is a featurize
    chain to fuse into the chunk dispatches, and the operator's own
    run-time gate makes the final call.
    """

    def __init__(self, chunk_rows: Optional[int] = None):
        self.chunk_rows = chunk_rows

    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        if not streaming_enabled():
            return graph, prefixes
        chunk_rows = self.chunk_rows or stream_chunk_rows()
        rewrites = 0
        for node in sorted(graph.nodes):
            if node not in graph.operators:
                continue  # absorbed into an earlier rewrite
            op = graph.get_operator(node)
            if isinstance(op, StreamingFitOperator):
                continue
            if not isinstance(op, EstimatorOperator):
                continue
            if not getattr(op, "supports_fit_stream", False):
                continue
            deps = graph.get_dependencies(node)
            if not deps:
                continue
            dependents = graph.dependents()
            chain: List[NodeId] = []
            cur = deps[0]
            while isinstance(cur, NodeId):
                consumers = dependents.get(cur, [])
                if (
                    len(consumers) == 1
                    and cur not in prefixes
                    and len(graph.get_dependencies(cur)) == 1
                    and _streamable_member(graph.get_operator(cur))
                ):
                    chain.append(cur)
                    cur = graph.get_dependencies(cur)[0]
                else:
                    break
            head = cur
            if isinstance(head, SourceId):
                continue  # unbound input: nothing to chunk at plan time
            head_op = graph.get_operator(head)
            if isinstance(head_op, DatasetOperator):
                ds = head_op.dataset
                if not isinstance(ds, (ArrayDataset, ObjectDataset)):
                    continue
                if len(ds) < max(2 * chunk_rows, stream_min_rows()):
                    continue
            elif not chain:
                # Unknown size AND nothing to fuse per chunk: the
                # rewrite could only reproduce the materialized fit.
                continue

            from .fusion import FusedTransformerOperator

            members: List[TransformerOperator] = []
            for cn in reversed(chain):  # head-first application order
                m = graph.get_operator(cn)
                if isinstance(m, FusedTransformerOperator):
                    members.extend(m.members)
                else:
                    members.append(m)
            streaming_op = StreamingFitOperator(
                op, members, chunk_rows=self.chunk_rows
            )
            graph = graph.set_operator(node, streaming_op)
            graph = graph.set_dependencies(node, (head,) + tuple(deps[1:]))
            for cn in chain:  # estimator-adjacent first: now unreferenced
                graph = graph.remove_node(cn)
            rewrites += 1
        if rewrites:
            _names.metric(_names.STREAM_PLANS).inc(rewrites)
        return graph, prefixes
