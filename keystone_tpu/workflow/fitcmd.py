"""``keystone-tpu fit``: one durable streamed fit, end to end.

The CLI face of the durable-fit layer (docs/RELIABILITY.md "Durable
fits") and the engine under ``scripts/elastic_smoke.sh``: build a
deterministic synthetic ``featurize-chain → LinearMapEstimator``
pipeline, attach a :class:`~keystone_tpu.reliability.checkpoint.
CheckpointStore`, and fit through the planned streaming path with
mid-fit cursor checkpoints armed.

The durability loop the smoke drives across PROCESSES:

1. run with ``KEYSTONE_FAULT_SPECS`` carrying a ``kill`` at
   ``streaming.chunk`` call k — a real SIGKILL mid-stream; the store
   holds the last committed cursor;
2. re-run the same command in a fresh process — the re-planned pipeline
   finds the resume entry, validates fingerprints (KV306), seeds the
   fold, and re-ingests only chunks past the cursor;
3. ``--expect-resume`` asserts step 2 actually resumed (exit 2 when it
   silently refit from scratch), and ``--out`` writes the fitted
   predictions on a fixed probe batch so the smoke can check parity
   against an uninterrupted reference numerically.

``--drift-data`` perturbs the training matrix while keeping its shape —
the seeded KV306 case: same resume key, different content digest, and
under ``KEYSTONE_VERIFY=strict`` the refusal exits non-zero.

Everything is deterministic in ``--seed``; the probe batch is drawn
from its own fixed stream so every invocation scores the same rows.
Prints one ``FIT_STATS:`` JSON line (the smoke-script contract).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

import numpy as np

from ..workflow.pipeline import BatchTransformer


class FitDemoScaler(BatchTransformer):
    """A real (content-fingerprinted) featurize-chain member: affine
    rescale. Module-level so its prefix/chain digests are process-stable
    — the property crash-resume keys on."""

    def __init__(self, scale: float = 1.0, shift: float = 0.0):
        self.scale = float(scale)
        self.shift = float(shift)

    def apply_arrays(self, x):
        return x * self.scale + self.shift


def fit_from_args(args) -> int:
    """Run the durable synthetic fit; see module docstring."""
    from ..data.dataset import ArrayDataset
    from ..ops.learning.linear import LinearMapEstimator
    from ..reliability import enable_checkpointing, faultinject
    from ..reliability.recovery import get_recovery_log
    from ..workflow.streaming import last_stream_report

    # Chunk geometry is a plan knob: pin it for every process of the
    # smoke so resume cursors align (the entry point owns its env, same
    # as --device-count owns XLA_FLAGS).
    os.environ["KEYSTONE_STREAM_CHUNK_ROWS"] = str(args.chunk_rows)
    if args.ckpt_chunks is not None:
        os.environ["KEYSTONE_STREAM_CKPT_CHUNKS"] = str(args.ckpt_chunks)
    # Chaos crosses the process boundary through the environment — the
    # same door the serving workers use.
    faultinject.install_from_env()

    rng = np.random.default_rng(args.seed)
    x = rng.normal(size=(args.rows, args.dim)).astype(np.float32)
    w = rng.normal(size=(args.dim, args.classes)).astype(np.float32)
    y = (x @ w + 0.01 * rng.normal(size=(args.rows, args.classes))).astype(
        np.float32
    )
    if args.drift_data:
        # Same shape, same dtype, different CONTENT: the stale-resume
        # hazard KV306 exists for.
        x = x + np.float32(args.drift_data)
    probe = np.random.default_rng(12345).normal(
        size=(64, args.dim)
    ).astype(np.float32)

    enable_checkpointing(args.store_dir)
    if getattr(args, "solver", "gram") == "sketch":
        from ..sketch import SketchedLeastSquaresEstimator

        estimator = SketchedLeastSquaresEstimator(reg=args.reg)
    else:
        estimator = LinearMapEstimator(reg=args.reg)
    pipeline = (
        FitDemoScaler(scale=2.0, shift=0.5)
        .to_pipeline()
        .then_label_estimator(
            estimator,
            ArrayDataset(x),
            ArrayDataset(y),
        )
    )
    fitted = pipeline.fit()
    preds = np.asarray(fitted.apply_batch(ArrayDataset(probe)).data)
    if args.out:
        np.savez(args.out, preds=preds)

    report = last_stream_report()
    ledger = get_recovery_log()
    stats: Dict[str, Any] = {
        "rows": args.rows,
        "dim": args.dim,
        "chunk_rows": args.chunk_rows,
        "streamed": report is not None,
        "preds_norm": float(np.linalg.norm(preds)),
    }
    if report is not None:
        stats.update(
            chunks=report.chunks,
            chunks_total=-(-args.rows // report.chunk_rows),
            shards=report.shards,
            checkpoints=report.checkpoints,
            resumed_from_chunk=report.resumed_from_chunk,
            reingested_chunks=report.reingested_chunks,
            shard_losses=report.shard_losses,
        )
    stats["ledger_kinds"] = sorted(
        {
            e.kind
            for e in ledger.events()
            if e.kind.startswith(("stream_", "shard_", "resume_", "checkpoint_"))
        }
    )
    print("FIT_STATS:" + json.dumps(stats))

    if args.expect_resume and (
        report is None or report.resumed_from_chunk is None
    ):
        print("fit: --expect-resume set but the fit did not resume")
        return 2
    return 0
