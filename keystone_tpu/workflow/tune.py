"""Offline autotuner: active search over the plan-knob space.

PR 6 built the measurement loop — the profile store remembers what every
knob setting achieved, ``MeasuredKnobRule`` replays the best recorded
observation — but nothing ever *explored*: chunk rows, solver block
sizes, precision modes, and the block-sparse dispatch threshold were
replays of whatever defaults happened to run, while bench r05 shows
1.4-8× fp32/bf16 spreads and per-shape MFU cliffs no single default
survives. This module closes the loop in the spirit of ML-driven BLAS
runtime tuning (arXiv:2406.19621): ``keystone-tpu tune`` actively
measures candidate configurations per shape class, a learned cost model
(ridge regression on log-scaled knob features, warm-started from the
store's own measured history) proposes which candidate to measure next,
and every measurement — winner included — is persisted to the
:class:`~keystone_tpu.obs.store.ProfileStore` under the SAME keys
``MeasuredKnobRule`` already reads. Tuned configs therefore flow into
plans with **zero plan-semantics change**: the rule's replay machinery is
untouched; it simply has better observations to replay. Tuner-written
entries carry ``source: "tune"`` provenance (vs ``"observed"`` for
passive measurements) so searched and replayed decisions stay
distinguishable post-hoc (``keystone-tpu check --store``, bench json).

Search tasks (docs/AUTOTUNING.md):

- ``stream`` — chunk_rows × prefetch depth (× shard count on multi-device
  meshes) for the streaming engine, measured as real ``fit_stream`` runs
  on synthetic data at the target shape; keys ``stream:<chain>:cr<rows>``.
- ``solver`` — block_size × precision mode for the in-core block
  least-squares solver, measured as whole estimator fits (the same wall
  passive observations carry, so tuned and observed entries stay
  commensurable), plus a donate-on/off probe on the winner (reported,
  not persisted — no plan knob consumes donation); keys
  ``solver:block_ls:bs<b>:prec<mode>``.
- ``blocksparse`` — the block-density threshold below which fits dispatch
  onto the block-sparse Gram kernels (``ops/pallas/blocksparse.py``): a
  density sweep measures the sparse-vs-dense crossover; key
  ``blocksparse:threshold``.

Budget knobs (all via ``envknobs``):

  KEYSTONE_TUNE_BUDGET     max measured candidates per task (default 12)
  KEYSTONE_TUNE_EXPLORE    random-exploration fraction of proposals (0.25)
  KEYSTONE_TUNE_SEED       exploration RNG seed (default 0)
  KEYSTONE_TUNE_TIME_S     wall-clock budget per task in seconds (120)

The search core (:class:`Tuner`, :class:`RidgeCostModel`,
:class:`TuneSpace`) is numpy-only and jax-free — the synthetic-surface
convergence tests run without a backend; only the task measure functions
touch jax.
"""

from __future__ import annotations

import itertools
import json
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..envknobs import env_float, env_int
from ..obs import names as _names
from ..obs import spans as _spans

logger = logging.getLogger(__name__)

#: Cap on the expanded candidate grid — a tune space is a short menu,
#: not an exhaustive sweep; the cost model interpolates the rest.
_MAX_GRID = 512


def tune_budget() -> int:
    """``KEYSTONE_TUNE_BUDGET``: max measured candidates per task."""
    return max(1, env_int("KEYSTONE_TUNE_BUDGET", 12))


def tune_explore() -> float:
    """``KEYSTONE_TUNE_EXPLORE``: fraction of model proposals replaced by
    random exploration (keeps the surrogate from tunnel-visioning)."""
    return min(1.0, max(0.0, env_float("KEYSTONE_TUNE_EXPLORE", 0.25)))


def tune_seed() -> int:
    """``KEYSTONE_TUNE_SEED``: exploration RNG seed."""
    return env_int("KEYSTONE_TUNE_SEED", 0)


def tune_time_budget_s() -> float:
    """``KEYSTONE_TUNE_TIME_S``: per-task wall-clock budget."""
    return env_float("KEYSTONE_TUNE_TIME_S", 120.0)


# ----------------------------------------------------------------- the space


@dataclass
class TuneSpace:
    """A named grid of knob axes. Numeric axes are encoded log2 for the
    cost model; categorical axes one-hot over their candidate values."""

    name: str
    axes: Dict[str, Sequence[Any]]

    def grid(self) -> List[Dict[str, Any]]:
        names = sorted(self.axes)
        combos = itertools.product(*(self.axes[n] for n in names))
        return [dict(zip(names, c)) for c in itertools.islice(combos, _MAX_GRID)]

    def encode(self, cand: Dict[str, Any]) -> List[float]:
        feats: List[float] = []
        for name in sorted(self.axes):
            values = list(self.axes[name])
            v = cand[name]
            if all(isinstance(x, (int, float)) and not isinstance(x, bool)
                   for x in values):
                # log2 + its square: a ridge fit becomes a log-space
                # parabola, the shape a knob sweep's basin actually has
                # (too-small chunks pay dispatch, too-large pay memory).
                lg = float(np.log2(1.0 + float(v)))
                feats.extend((lg, lg * lg))
            else:
                feats.extend(1.0 if v == x else 0.0 for x in values)
        return feats


# ------------------------------------------------------------ the cost model


class RidgeCostModel:
    """Closed-form ridge regression on encoded knob features → log cost.

    Small-sample-friendly on purpose: after 3-4 measurements on a smooth
    knob surface the log-linear fit already ranks unmeasured candidates
    well enough to steer the budget toward the optimum — the point is to
    spend measured runs near the winner, not to be a perfect model."""

    def __init__(self, l2: float = 1e-2):
        self.l2 = l2
        self.coef: Optional[np.ndarray] = None
        self._mu: Optional[np.ndarray] = None
        self._sigma: Optional[np.ndarray] = None

    def fit(self, features: Sequence[Sequence[float]], cost: Sequence[float]):
        x = np.asarray(features, dtype=np.float64)
        y = np.log(np.maximum(np.asarray(cost, dtype=np.float64), 1e-12))
        # Standardize: the quadratic log2 features are ~100× the one-hot
        # ones, and an un-scaled ridge penalty would crush exactly the
        # curvature term the basin fit needs.
        self._mu = x.mean(axis=0)
        self._sigma = np.where((s := x.std(axis=0)) > 1e-9, s, 1.0)
        xb = self._design(x)
        a = xb.T @ xb + self.l2 * np.eye(xb.shape[1])
        self.coef = np.linalg.solve(a, xb.T @ y)
        return self

    def _design(self, x: np.ndarray) -> np.ndarray:
        z = (x - self._mu) / self._sigma
        return np.hstack([z, np.ones((len(z), 1))])

    def predict(self, features: Sequence[Sequence[float]]) -> np.ndarray:
        if self.coef is None:
            raise RuntimeError("model not fitted")
        return self._design(np.asarray(features, dtype=np.float64)) @ self.coef


# ---------------------------------------------------------------- the search


@dataclass
class Measurement:
    knobs: Dict[str, Any]
    objective: float
    extra: Dict[str, Any] = field(default_factory=dict)
    proposed_by: str = "explore"


@dataclass
class TuneOutcome:
    task: str
    winner: Optional[Measurement]
    default: Optional[Measurement]
    measured: List[Measurement]
    maximize: bool
    seconds: float

    @property
    def improved(self) -> bool:
        """Winner strictly better than the env-default candidate ON THE
        SAME measurement runs — deterministic, no noise window: the
        default is always one of the measured candidates."""
        if self.winner is None or self.default is None:
            return False
        if self.maximize:
            return self.winner.objective > self.default.objective
        return self.winner.objective < self.default.objective

    def to_json(self) -> Dict[str, Any]:
        return {
            "task": self.task,
            "maximize": self.maximize,
            "winner": None if self.winner is None else self.winner.knobs,
            "winner_objective": None
            if self.winner is None else self.winner.objective,
            "default": None if self.default is None else self.default.knobs,
            "default_objective": None
            if self.default is None else self.default.objective,
            "improved": self.improved,
            "candidates_measured": len(self.measured),
            "seconds": round(self.seconds, 3),
            "measured": [
                {"knobs": m.knobs, "objective": m.objective,
                 "proposed_by": m.proposed_by, **m.extra}
                for m in self.measured
            ],
        }


class Tuner:
    """Budgeted model-guided search over a :class:`TuneSpace`.

    Loop: measure the env-default candidate first (the baseline any
    winner must beat), seed with one random candidate, then alternate —
    fit the ridge model on everything measured so far (plus warm-start
    rows from prior profile-store history), measure its best predicted
    unmeasured candidate, with an ``explore`` fraction of proposals
    replaced by uniform random picks. Stops at the candidate budget, the
    wall-clock budget, or grid exhaustion, whichever first."""

    def __init__(
        self,
        budget: Optional[int] = None,
        explore: Optional[float] = None,
        seed: Optional[int] = None,
        time_budget_s: Optional[float] = None,
        model: Optional[RidgeCostModel] = None,
    ):
        self.budget = budget if budget is not None else tune_budget()
        self.explore = explore if explore is not None else tune_explore()
        self.time_budget_s = (
            time_budget_s if time_budget_s is not None else tune_time_budget_s()
        )
        self.rng = np.random.RandomState(seed if seed is not None else tune_seed())
        self.model = model or RidgeCostModel()

    def search(
        self,
        space: TuneSpace,
        measure: Callable[[Dict[str, Any]], Any],
        default: Optional[Dict[str, Any]] = None,
        maximize: bool = False,
        warm: Sequence[Tuple[Dict[str, Any], float]] = (),
    ) -> TuneOutcome:
        """Run the budgeted search; ``measure(candidate)`` returns the
        objective (float) or ``(objective, extra_dict)``. ``warm`` rows
        — (knobs, objective) from prior store history — train the model
        without costing budget."""
        t0 = time.perf_counter()
        grid = space.grid()
        if default is not None and default not in grid:
            grid.insert(0, dict(default))
        measured: List[Measurement] = []
        seen: set = set()
        candidates_metric = _names.metric(_names.TUNE_CANDIDATES)

        def key(c: Dict[str, Any]) -> str:
            return json.dumps(c, sort_keys=True, default=repr)

        def run(cand: Dict[str, Any], proposed_by: str) -> Optional[Measurement]:
            seen.add(key(cand))
            try:
                # Each probe is mesh time stolen from serving: under a
                # process scheduler it runs as a cost-tagged lease — a
                # pressured mesh defers the probe (skipping a candidate
                # costs accuracy of the tune, not correctness), an idle
                # one admits it (docs/SCHEDULING.md).
                from ..sched.scheduler import LeaseRequest, get_scheduler

                scheduler = get_scheduler()
                if scheduler is None:
                    result = measure(cand)
                else:
                    with scheduler.lease(
                        LeaseRequest(
                            name=f"tune:{space.name}", kind="tune_probe"
                        )
                    ) as probe_lease:
                        if probe_lease is None:  # deferred: skip candidate
                            _spans.add_span_event(
                                "tune_candidate_deferred", task=space.name
                            )
                            return None
                        result = measure(cand)
            except Exception as e:
                logger.warning(
                    "tune[%s]: candidate %s failed (%s)", space.name, cand, e
                )
                _spans.add_span_event(
                    "tune_candidate_failed", task=space.name, error=str(e)[:200]
                )
                return None
            objective, extra = (
                result if isinstance(result, tuple) else (result, {})
            )
            m = Measurement(dict(cand), float(objective), dict(extra), proposed_by)
            measured.append(m)
            candidates_metric.inc(task=space.name)
            _spans.add_span_event(
                "tune_candidate", task=space.name,
                objective=float(objective), proposed_by=proposed_by,
                **{f"knob:{k}": repr(v) for k, v in cand.items()},
            )
            return m

        def out_of_budget() -> bool:
            return (
                len(measured) >= self.budget
                or time.perf_counter() - t0 > self.time_budget_s
            )

        with _spans.span("tune:search", task=space.name, budget=self.budget):
            default_m = run(default, "default") if default is not None else None
            remaining = [c for c in grid if key(c) not in seen]
            if remaining and not out_of_budget():
                pick = remaining[self.rng.randint(len(remaining))]
                run(pick, "explore")
            while not out_of_budget():
                remaining = [c for c in grid if key(c) not in seen]
                if not remaining:
                    break
                proposed_by = "explore"
                cand = remaining[self.rng.randint(len(remaining))]
                if measured and self.rng.random_sample() >= self.explore:
                    try:
                        rows = [
                            (space.encode(m.knobs), self._cost(m.objective, maximize))
                            for m in measured
                        ] + [
                            (space.encode(k), self._cost(o, maximize))
                            for k, o in warm
                        ]
                        self.model.fit([r[0] for r in rows], [r[1] for r in rows])
                        preds = self.model.predict(
                            [space.encode(c) for c in remaining]
                        )
                        cand = remaining[int(np.argmin(preds))]
                        proposed_by = "model"
                    except Exception as e:  # singular fits etc: explore
                        logger.debug("tune[%s]: model propose failed (%s)",
                                     space.name, e)
                run(cand, proposed_by)
        seconds = time.perf_counter() - t0
        _names.metric(_names.TUNE_SECONDS).observe(seconds, task=space.name)
        winner = None
        if measured:
            winner = (max if maximize else min)(
                measured, key=lambda m: m.objective
            )
        return TuneOutcome(
            task=space.name, winner=winner, default=default_m,
            measured=measured, maximize=maximize, seconds=seconds,
        )

    @staticmethod
    def _cost(objective: float, maximize: bool) -> float:
        """The model always minimizes a positive cost: walls directly,
        throughputs reciprocally."""
        return 1.0 / max(objective, 1e-12) if maximize else max(objective, 1e-12)


# ------------------------------------------------------------- measure tasks
#
# Everything below touches jax: real measured runs on synthetic data at
# the caller's target shape. Each task persists EVERY measured candidate
# to the profile store under the keys MeasuredKnobRule / the block-sparse
# dispatch already read, with source="tune" provenance — the rule's
# best-entry queries then naturally select the winner.


def _warm_from_store(
    store,
    key_prefix: str,
    shape: str,
    space: TuneSpace,
    field_map: Dict[str, str],
    objective_field: str,
    maximize: bool,
) -> List[Tuple[Dict[str, Any], float]]:
    """Warm-start rows for the cost model from the store's own measured
    history: entries under ``key_prefix`` at the exact shape class whose
    measurements carry EVERY space axis (via ``field_map``:
    axis → measurement field) and the objective. Entries missing an axis
    (older schema, other writers) are skipped — partial rows would force
    fabricated knob values into the training set."""
    if store is None:
        return []
    rows: List[Tuple[Dict[str, Any], float]] = []
    try:
        for _key, _shape, m in store.entries(
            key_prefix=key_prefix, shape=shape
        ):
            if objective_field not in m:
                continue
            knobs: Dict[str, Any] = {}
            for axis, field_name in field_map.items():
                if field_name not in m:
                    knobs = {}
                    break
                knobs[axis] = m[field_name]
            if knobs:
                rows.append((knobs, float(m[objective_field])))
    except Exception:  # a broken store must never block tuning
        return []
    # sanity: encodable under this space (unknown categorical values
    # would silently one-hot to all-zeros)
    usable = []
    for knobs, objective in rows:
        try:
            space.encode(knobs)
        except Exception:
            continue
        if objective > 0 or not maximize:
            usable.append((knobs, objective))
    return usable


def _synthetic_problem(rows: int, dim: int, classes: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    x = rng.randn(rows, dim).astype(np.float32)
    w = rng.randn(dim, classes).astype(np.float32)
    y = (x @ w + 0.01 * rng.randn(rows, classes)).astype(np.float32)
    return x, y


def tune_stream(
    tuner: Tuner,
    store,
    rows: int = 8192,
    dim: int = 256,
    classes: int = 4,
) -> TuneOutcome:
    """Search chunk_rows × prefetch (× shards on multi-device meshes) for
    the streaming engine at the target shape; measured as real
    ``fit_stream`` runs (second of two, so per-chunk-shape XLA compiles
    don't pollute the comparison). The objective is the fold's OWN
    rows/s — the exact number the engine auto-records and
    ``MeasuredKnobRule._best_entry`` maximizes — so the tuner's winner
    and the rule's replay choice can never disagree.

    SCOPE: entries land under the EMPTY featurize-chain class
    (``chain_class(())`` — a dataset fed straight into the estimator,
    the shape this task measures). Chunk observations deliberately do
    not transfer across chain classes (a chain changes per-chunk
    compute), so pipelines with featurize members keep their passively
    observed entries; tuning a specific chain offline means measuring
    that chain (docs/AUTOTUNING.md, follow-on)."""
    import jax

    from ..data.dataset import ArrayDataset
    from ..obs.store import dataset_shape_class
    from ..ops.learning.block import BlockLeastSquaresEstimator
    from .streaming import (
        StreamingFitOperator,
        chain_class,
        last_stream_report,
        stream_chunk_rows,
    )

    x, y = _synthetic_problem(rows, dim, classes)
    data, labels = ArrayDataset(x), ArrayDataset(y)
    shape = dataset_shape_class(data)
    est = BlockLeastSquaresEstimator(min(128, dim), num_iter=1, reg=1e-3)
    ndev = len(jax.devices())
    chunk_cands = sorted(
        {c for c in (256, 512, 1024, 2048, 4096, 8192) if c <= max(rows // 2, 256)}
    )
    axes: Dict[str, Sequence[Any]] = {
        "chunk_rows": chunk_cands,
        "prefetch": [1, 2],
        "shards": [1] if ndev == 1 else [1, ndev],
    }
    default = {
        "chunk_rows": min(stream_chunk_rows(), max(chunk_cands)),
        "prefetch": 1,
        "shards": 1,
    }

    def measure(cand):
        wall = rows_per_s = None
        chunk_actual = int(cand["chunk_rows"])
        shards_actual = 1
        for _ in range(2):  # second run: compile excluded
            op = StreamingFitOperator(
                est, (), chunk_rows=int(cand["chunk_rows"]),
                prefetch=int(cand["prefetch"]),
            )
            if int(cand["shards"]) > 1:
                from ..parallel.partitioner import Partitioner

                decision = Partitioner().decide_stream(
                    op.label, int(cand["chunk_rows"]), rows=rows, record=False
                )
                if not decision.eligible:
                    # An unsharded run must not be scored (and later
                    # persisted) as a shards=N configuration — same
                    # persisted-lie rule as the materialized fallback.
                    raise RuntimeError(
                        "partition decision ineligible "
                        f"({decision.reason}): shards={cand['shards']} "
                        "candidate never ran sharded"
                    )
                op.partition = decision
                op.chunk_rows = decision.chunk_rows
                # the measurement describes what actually ran: the
                # shard-rounded chunk and the decided shard count
                chunk_actual = int(decision.chunk_rows)
                shards_actual = int(decision.shards)
            before = last_stream_report()
            t0 = time.perf_counter()
            op.fit_datasets([data, labels])
            wall = time.perf_counter() - t0
            report = last_stream_report()
            # Identity check: a materialized fallback publishes NO
            # report. Scoring such a run — with the previous candidate's
            # stale report, or with an end-to-end rows/wall number that
            # is incommensurable with fold-own rows/s — would persist a
            # lie the knob rule then replays into real plans. A
            # fallback candidate FAILS instead (tuner skips it).
            if (
                report is None
                or report is before
                or not report.compute_done_t
            ):
                raise RuntimeError(
                    "streamed fit fell back to the materialized path — "
                    "no fold throughput to score this candidate with"
                )
            rows_per_s = report.num_examples / max(
                report.compute_done_t[-1], 1e-9
            )
        return rows_per_s, {
            "wall_s": round(wall, 6),
            "chunk_rows_actual": chunk_actual,
            "shards_actual": shards_actual,
        }

    space = TuneSpace("stream", axes)
    warm = _warm_from_store(
        store, f"stream:{chain_class(())}:", shape, space,
        {"chunk_rows": "chunk_rows", "prefetch": "prefetch_depth",
         "shards": "shards"},
        "rows_per_s", maximize=True,
    )
    outcome = tuner.search(
        space, measure, default=default, maximize=True, warm=warm
    )
    if store is not None:
        for m in outcome.measured:
            # keyed/recorded by what actually ran (the partitioner may
            # shard-round chunk_rows), never the requested candidate
            chunk = int(m.extra.get("chunk_rows_actual", m.knobs["chunk_rows"]))
            store.record(
                f"stream:{chain_class(())}:cr{chunk}",
                shape,
                chunk_rows=chunk,
                rows_per_s=m.objective,
                prefetch_depth=int(m.knobs["prefetch"]),
                shards=int(m.extra.get("shards_actual", 1)),
                wall_s=m.extra.get("wall_s"),
                source="tune",
            )
        if outcome.winner is not None:
            _names.metric(_names.TUNE_WINNERS).inc(task="stream")
    return outcome


def tune_solver(
    tuner: Tuner,
    store,
    rows: int = 8192,
    dim: int = 256,
    classes: int = 4,
) -> TuneOutcome:
    """Search block_size × precision for the in-core block least-squares
    solver, measured as FULL estimator fits under ``solver_mode_scope``
    — the same whole-fit wall passive ``_record_solver_observation``
    entries carry, so tuned and observed measurements at a
    ``solver:block_ls:`` key stay commensurable (a bare-BCD wall merged
    into whole-fit history would flip the knob on merge). Donation is
    probed separately on the winner via direct
    ``linalg.block_coordinate_descent`` calls and reported in the
    outcome JSON only — there is no plan knob for it to flow into, so
    persisting it would be a dark measurement."""
    import jax.numpy as jnp

    from ..data.dataset import ArrayDataset
    from ..obs.store import shape_class
    from ..ops.learning.block import BlockLeastSquaresEstimator
    from ..parallel import linalg
    from ..parallel.mesh import get_mesh

    x, y = _synthetic_problem(rows, dim, classes)
    data, labels = ArrayDataset(x), ArrayDataset(y)
    # Tiny --dim still gets a non-empty grid: one block spanning the
    # whole feature width.
    blocks = sorted({b for b in (32, 64, 128, 256, 512) if b <= dim}) or [
        max(1, dim)
    ]
    axes: Dict[str, Sequence[Any]] = {
        "block_size": blocks,
        "precision": ["default", "high", "highest"],
    }
    default = {
        "block_size": min(128, max(blocks)),
        "precision": linalg.solver_mode(),
    }
    if default["precision"] not in axes["precision"]:
        axes["precision"] = list(axes["precision"]) + [default["precision"]]

    def measure(cand):
        est = BlockLeastSquaresEstimator(
            int(cand["block_size"]), num_iter=1, reg=1e-3
        )
        wall = None
        with linalg.solver_mode_scope(str(cand["precision"])):
            for _ in range(2):  # second run: compile excluded
                t0 = time.perf_counter()
                est.fit(data, labels)
                wall = time.perf_counter() - t0
        return wall

    space = TuneSpace("solver", axes)
    shape = shape_class(rows, (dim,), "float32")
    warm = _warm_from_store(
        store, "solver:block_ls:", shape, space,
        {"block_size": "block_size", "precision": "precision"},
        "wall_s", maximize=False,
    )
    outcome = tuner.search(
        space, measure, default=default, maximize=False, warm=warm
    )
    if store is not None:
        for m in outcome.measured:
            b = int(m.knobs["block_size"])
            p = str(m.knobs["precision"])
            store.record(
                f"solver:block_ls:bs{b}:prec{p}",
                shape,
                wall_s=round(m.objective, 6),
                block_size=b,
                precision=p,
                source="tune",
            )
        if outcome.winner is not None:
            _names.metric(_names.TUNE_WINNERS).inc(task="solver")
    if outcome.winner is not None:
        outcome.winner.extra["donation_probe"] = _probe_donation(
            x, y, int(outcome.winner.knobs["block_size"]),
            str(outcome.winner.knobs["precision"]), get_mesh(),
        )
    return outcome


def _probe_donation(x, y, block: int, precision: str, mesh) -> Dict[str, Any]:
    """Winner-config donate-on/off walls via direct BCD calls —
    informational only (no plan knob consumes donation today), so it is
    reported in the tune JSON and never persisted to the store."""
    import jax.numpy as jnp

    from ..parallel import linalg

    xc = x - x.mean(axis=0, keepdims=True)
    yc = y - y.mean(axis=0, keepdims=True)
    out: Dict[str, Any] = {}
    with linalg.solver_mode_scope(precision):
        for donate in (True, False):
            wall = None
            for _ in range(2):  # second run: compile excluded
                a = linalg.prepare_row_sharded(jnp.asarray(xc), mesh)
                b = linalg.prepare_row_sharded(jnp.asarray(yc), mesh)
                t0 = time.perf_counter()
                w = linalg.block_coordinate_descent(
                    a, b, reg=1e-3, num_epochs=1, block_size=block,
                    mesh=mesh, donate_xy=donate,
                )
                w.block_until_ready()
                wall = time.perf_counter() - t0
            out["donate_wall_s" if donate else "no_donate_wall_s"] = round(
                wall, 6
            )
    return out


def tune_blocksparse(
    tuner: Tuner,
    store,
    rows: int = 4096,
    dim: int = 1024,
    classes: int = 4,
) -> TuneOutcome:
    """Measure the block-sparse-vs-dense ESTIMATOR crossover: a density
    sweep where each candidate's objective is the ratio of the sparse
    Gram fit wall to the legacy in-core fit wall (< 1 means dispatching
    sparse wins). This is the decision the threshold actually guards —
    the in-core solver never forms the full d×d Gram, so the fit-level
    crossover sits far below the Gram-kernel-level one. The persisted
    ``threshold`` is the highest swept density at which sparse still
    wins with ≥10% margin — 0.0 (never dispatch on this backend/shape)
    is a legitimate, recorded verdict."""
    from ..data.dataset import ArrayDataset
    from ..obs.store import shape_class
    from ..ops.learning.block import BlockLeastSquaresEstimator
    from ..ops.pallas import blocksparse as _bs
    from ..parallel.mesh import get_mesh
    from ..utils.sparse import BlockSparseMatrix

    rng = np.random.RandomState(tune_seed())
    # Fine enough a tile grid that low densities EXIST: ≥16 block
    # columns regardless of dim (the estimator path's tile choice is the
    # user's; this sweep measures the dispatch decision).
    bm = 8
    bn = max(8, min(_bs.default_block_shape(dim)[1], dim // 16))
    y = rng.randn(rows, classes).astype(np.float32)
    labels = ArrayDataset(y)
    densities = [0.01, 0.02, 0.05, 0.1, 0.2, 0.35]
    est = BlockLeastSquaresEstimator(min(128, dim), num_iter=1, reg=1e-3)
    mesh = get_mesh()

    def build(density: float) -> BlockSparseMatrix:
        nbr = max(1, rows // bm)
        nbc = max(1, dim // bn)
        keep = rng.rand(nbr, nbc) < density
        keep[0, 0] = True  # never fully empty
        vals = rng.randn(nbr, bm, nbc, bn).astype(np.float32)
        mask = keep[:, None, :, None]
        dense = (vals * mask).reshape(nbr * bm, nbc * bn)[:rows, :dim]
        return BlockSparseMatrix.from_dense(dense, (bm, bn))

    def measure(cand):
        bsr = build(float(cand["density"]))
        dense = bsr.to_dense()
        features = ArrayDataset(dense)
        sparse_wall = dense_wall = None
        for _ in range(2):  # second run: compile excluded
            t0 = time.perf_counter()
            est._fit_blocksparse(bsr, labels, 1.0, a_dense=dense)
            sparse_wall = time.perf_counter() - t0
            t0 = time.perf_counter()
            est._fit_in_core(features, labels, mesh, est.block_size)
            dense_wall = time.perf_counter() - t0
        ratio = sparse_wall / max(dense_wall, 1e-9)
        return ratio, {
            "sparse_fit_wall_s": round(sparse_wall, 6),
            "dense_fit_wall_s": round(dense_wall, 6),
            "actual_density": round(bsr.density(), 4),
        }

    outcome = tuner.search(
        TuneSpace("blocksparse", {"density": densities}),
        measure,
        default={"density": _bs.DEFAULT_DENSITY_THRESHOLD},
        maximize=False,
    )
    if store is not None and outcome.measured:
        winning = [
            m for m in outcome.measured if m.objective < 1.0 / 1.1
        ]
        threshold = (
            max(float(m.knobs["density"]) for m in winning) if winning else 0.0
        )
        best = min(outcome.measured, key=lambda m: m.objective)
        store.record(
            "blocksparse:threshold",
            shape_class(rows, (dim,), "float32"),
            threshold=threshold,
            speedup=round(1.0 / max(best.objective, 1e-9), 3),
            block_shape=f"{bm}x{bn}",
            source="tune",
        )
        _names.metric(_names.TUNE_WINNERS).inc(task="blocksparse")
    return outcome


TASKS: Dict[str, Callable[..., TuneOutcome]] = {
    "stream": tune_stream,
    "solver": tune_solver,
    "blocksparse": tune_blocksparse,
}


# ----------------------------------------------------------------------- CLI
# (Flag wiring lives in cli.py::add_tune_arguments — the CLI's help/list
# paths must not import this package, whose __init__ imports jax.)


def tune_from_args(args) -> int:
    from ..obs import store as _store

    store = _store.get_store()
    if store is None:
        print("keystone-tpu tune: profile store disabled "
              "(KEYSTONE_PROFILE_STORE=off) — nowhere to persist winners")
        return 2
    tuner = Tuner(
        budget=args.budget, seed=args.seed, time_budget_s=args.time_budget_s
    )
    tasks = [t.strip() for t in args.tasks.split(",") if t.strip()]
    unknown = [t for t in tasks if t not in TASKS]
    if unknown:
        print(f"keystone-tpu tune: unknown tasks {unknown} "
              f"(expected {sorted(TASKS)})")
        return 2
    results: Dict[str, Any] = {}
    ok = True
    for task in tasks:
        outcome = TASKS[task](
            tuner, store,
            rows=args.rows, dim=args.dim, classes=args.classes,
        )
        results[task] = outcome.to_json()
        win = outcome.winner.knobs if outcome.winner else None
        print(
            f"tune[{task}]: {len(outcome.measured)} candidates in "
            f"{outcome.seconds:.1f}s; winner {win} "
            f"({'beats' if outcome.improved else 'matches'} default)"
        )
        ok = ok and outcome.winner is not None
    payload = {
        "store": store.stats(),
        "by_source": store.by_source(),
        "tasks": results,
    }
    print("TUNE_JSON:" + json.dumps(payload))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1)
    return 0 if ok else 1
