"""CSV loading (reference: loaders/CsvDataLoader.scala:90-120,
loaders/LabeledData.scala:256-266).

Rows of comma-separated numbers become one (n, d) device-ready array —
the TPU-native form of the reference's RDD[DenseVector].
"""

from __future__ import annotations

import glob
import os
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from ..dataset import ArrayDataset


def load_csv(path: str, dtype=np.float32) -> ArrayDataset:
    """Load one CSV file, a directory of them, or a glob pattern.

    Malformed rows (unparsable fields, wrong column count) are
    skipped-and-quarantined instead of aborting the load: the fast
    ``np.loadtxt`` path runs first, and only a file that trips it is
    re-parsed line-by-line. The returned dataset carries a ``.quarantine``
    dict with counts, and totals land in the process recovery log. A file
    with NO parsable rows still raises — an entirely-garbage input is a
    wrong-path error, not a degraded read.
    """
    from ...reliability.recovery import QuarantineCounts

    files = _expand(path)
    quarantine = QuarantineCounts()
    parts = [_load_one(f, dtype, quarantine) for f in files]
    quarantine.publish("load_csv", source=path)
    out = ArrayDataset(np.concatenate(parts, axis=0))
    out.quarantine = quarantine.as_dict()
    return out


def _load_one(path: str, dtype, quarantine) -> np.ndarray:
    try:
        return np.loadtxt(path, delimiter=",", dtype=dtype, ndmin=2)
    except ValueError:
        return _tolerant_parse(path, dtype, quarantine)


def _tolerant_parse(path: str, dtype, quarantine) -> np.ndarray:
    """Line-by-line fallback parse. The row width is the MAJORITY width of
    the parsable rows (a truncated first row must not redefine the file's
    shape and quarantine everything after it); rows that disagree — and
    rows with unparsable fields — are quarantined."""
    from collections import Counter

    parsed = []  # (lineno, row)
    with open(path) as f:
        for lineno, line in enumerate(f, start=1):
            # Skip what np.loadtxt skips (blank lines, '#' comments —
            # including inline ones): the fallback must not quarantine
            # lines the fast path accepts.
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            try:
                parsed.append((lineno, [dtype(v) for v in line.split(",")]))
            except ValueError:
                quarantine.add("unparsable_row", f"{path}:{lineno}")
    if not parsed:
        raise ValueError(
            f"{path}: no parsable CSV rows ({quarantine.total} malformed)"
        )
    width = Counter(len(row) for _, row in parsed).most_common(1)[0][0]
    rows = []
    for lineno, row in parsed:
        if len(row) == width:
            rows.append(row)
        else:
            quarantine.add("wrong_width", f"{path}:{lineno}")
    return np.asarray(rows, dtype=dtype)


def _expand(path: str):
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "*")))
    else:
        matches = sorted(glob.glob(path))
        files = matches if matches else [path]
    if not files:
        raise FileNotFoundError(path)
    return files


@dataclass
class LabeledData:
    """(labels, features) pair of aligned datasets
    (reference: loaders/LabeledData.scala)."""

    labels: ArrayDataset
    data: ArrayDataset


def load_labeled_csv(path: str, label_col: int = 0, label_offset: int = 0) -> LabeledData:
    """CSV where one column is an integer label (reference MNIST format is
    1-indexed label first; pass label_offset=-1 to 0-index)."""
    raw = load_csv(path)
    arr = np.asarray(raw.data)
    labels = arr[:, label_col].astype(np.int32) + label_offset
    features = np.delete(arr, label_col, axis=1)
    return LabeledData(ArrayDataset(labels), ArrayDataset(features))
