"""Tar-of-images ingestion shared by the ImageNet and VOC loaders.

TPU-native re-design of the reference's Spark tar streaming
(reference: loaders/ImageLoaderUtils.scala:23-96 ``getFilePathsRDD`` /
``loadFiles``). The reference parallelizes by making each tar file one RDD
partition and streaming entries through commons-compress + ImageIO on the
executors. Here ingestion is a host-side concern feeding the chip: tar
entries are read sequentially (tar has no index) while JPEG decode +
resize — the actual CPU cost — fans out over a thread pool (PIL releases
the GIL during decode). When the native ingest library is built
(``native/``), decode is delegated to the C++ libjpeg path instead.

Ragged image sizes are the TPU impedance mismatch (SURVEY.md §7 hard part
5): batched XLA computations need static shapes, so loaders take an
optional ``resize=(x, y)`` that produces uniform arrays ready for
``ArrayDataset`` stacking. Without it they return per-image dict records
in an ``ObjectDataset``.
"""

from __future__ import annotations

import glob
import itertools
import os
import tarfile
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..dataset import ObjectDataset
from ...reliability.faultinject import probe
from ...reliability.recovery import QuarantineCounts
from ...utils.image import load_image


def list_archives(data_path: str) -> List[str]:
    """All regular files under a directory, or the path itself if it is a
    file (reference: ImageLoaderUtils.scala:33-40 getFilePathsRDD)."""
    if os.path.isfile(data_path):
        return [data_path]
    if os.path.isdir(data_path):
        return sorted(
            p for p in glob.glob(os.path.join(data_path, "*")) if os.path.isfile(p)
        )
    raise FileNotFoundError(f"no archive(s) at {data_path}")


def _resize_image(arr: np.ndarray, resize: Tuple[int, int]) -> np.ndarray:
    """Bilinear resize an (X, Y, C) float array to (resize[0], resize[1], C)."""
    from PIL import Image as PILImage

    x_dim, y_dim = resize
    if arr.shape[0] == x_dim and arr.shape[1] == y_dim:
        return arr
    chans = []
    for c in range(arr.shape[2]):
        pil = PILImage.fromarray(arr[..., c].astype(np.float32), mode="F")
        # PIL sizes are (width, height) = (second axis, first axis).
        chans.append(np.asarray(pil.resize((y_dim, x_dim), PILImage.BILINEAR)))
    return np.stack(chans, axis=-1).astype(np.float64)


def iter_tar_entries(
    archive_path: str, name_prefix: Optional[str] = None
) -> Iterator[Tuple[str, bytes]]:
    """Yield (entry_name, raw_bytes) for regular entries, optionally
    filtered by prefix (reference: ImageLoaderUtils.scala:70-90). Files
    that are not tar archives are skipped (a data directory may hold label
    files next to its shards)."""
    try:
        tar_cm = tarfile.open(archive_path, mode="r:*")
    except tarfile.ReadError:
        return
    with tar_cm as tar:
        for entry in tar:
            if not entry.isfile():
                continue
            if name_prefix is not None and not entry.name.startswith(name_prefix):
                continue
            fobj = tar.extractfile(entry)
            if fobj is None:
                continue
            yield entry.name, fobj.read()


def native_decode_batch(
    raw: List[bytes], resize: Tuple[int, int]
) -> Optional[Tuple["np.ndarray", "np.ndarray"]]:
    """Decode a batch of JPEGs through the native libjpeg kernel
    (keystone_tpu/native/src/decode.cpp). Returns (images, ok_mask) or
    None when the native library isn't built."""
    import ctypes

    from ... import native

    lib = native.load()
    if lib is None or not raw:
        return None
    n = len(raw)
    x_dim, y_dim = resize
    bufs = (ctypes.POINTER(ctypes.c_ubyte) * n)()
    lens = (ctypes.c_longlong * n)()
    keepalive = []
    for i, b in enumerate(raw):
        arr = np.frombuffer(b, dtype=np.uint8)
        keepalive.append(arr)
        bufs[i] = arr.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte))
        lens[i] = len(b)
    out = np.zeros((n, x_dim, y_dim, 3), dtype=np.float32)
    ok = np.zeros(n, dtype=np.uint8)
    lib.ks_decode_jpeg_batch(
        bufs, lens, n, x_dim, y_dim,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ok.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
    )
    return out, ok.astype(bool)


def load_image_archives(
    data_path: str,
    label_fn: Callable[[str], Any],
    name_prefix: Optional[str] = None,
    resize: Optional[Tuple[int, int]] = None,
    num_workers: Optional[int] = None,
    label_key: str = "label",
    use_native: Optional[bool] = None,
) -> ObjectDataset:
    """Stream every image out of the tar(s) at ``data_path`` into records
    ``{"image": (X, Y, C) float array, label_key: label_fn(entry_name),
    "filename": entry_name}``.

    Entries whose ``label_fn`` raises KeyError or whose bytes fail to
    decode are skipped-and-quarantined, matching the reference's
    Option-typed loader (reference: ImageLoaderUtils.scala:84-88) — but
    with the counts surfaced: the returned dataset carries a
    ``.quarantine`` dict and the totals land in the process recovery log,
    so a corrupt shard degrades a run's coverage visibly instead of
    silently (or, pre-quarantine, fatally).

    With ``resize`` set and the native library built, decode+resize runs
    through the OpenMP libjpeg kernel (``use_native=None`` auto-detects;
    True requires it; False forces the PIL path).

    ``num_workers=None`` resolves through
    :func:`~keystone_tpu.data.dataset.default_ingest_workers`
    (``KEYSTONE_INGEST_WORKERS``) — one knob shared with
    ``ObjectDataset.map`` and the streaming prefetch pipeline.
    """
    from ..dataset import default_ingest_workers

    if num_workers is None:
        num_workers = default_ingest_workers()
    quarantine = QuarantineCounts()

    def decode(item: Tuple[str, bytes]) -> Optional[Dict[str, Any]]:
        name, raw = item
        try:
            label = label_fn(name)
        except KeyError:
            quarantine.add("label_missing", name)
            return None
        img = load_image(raw)
        if img is None:
            quarantine.add("decode_failed", name)
            return None
        if resize is not None:
            img = _resize_image(img, resize)
        return {"image": img, label_key: label, "filename": name}

    records: List[Dict[str, Any]] = []
    archives = [p for p in list_archives(data_path) if tarfile.is_tarfile(p)]

    if use_native is None:
        from ... import native

        use_native = resize is not None and native.available()
    if use_native and resize is None:
        raise ValueError("native decode requires a resize target")

    # Chunked submission keeps only ~2 decode-rounds of raw bytes in
    # flight — draining the raw generator into queued futures would pull
    # the whole tar into memory before the first decode finishes.
    chunk = max(1, 2 * num_workers)
    if use_native:
        for archive in archives:
            entries = iter_tar_entries(archive, name_prefix)
            while True:
                batch = list(itertools.islice(entries, chunk * 8))
                if not batch:
                    break
                probe("ingest.decode_batch")
                labeled = []
                for name, raw in batch:
                    try:
                        labeled.append((name, raw, label_fn(name)))
                    except KeyError:
                        quarantine.add("label_missing", name)
                        continue
                if not labeled:
                    continue
                decoded = native_decode_batch([r for _, r, _ in labeled], resize)
                if decoded is None:
                    raise RuntimeError(
                        "use_native=True but the native library is not built; "
                        "run make -C keystone_tpu/native"
                    )
                images, ok = decoded
                for i, (name, raw, label) in enumerate(labeled):
                    if ok[i]:
                        records.append(
                            {"image": images[i], label_key: label, "filename": name}
                        )
                    else:
                        # libjpeg only handles JPEG; PNG/BMP/CMYK entries
                        # fall back to the PIL path so dataset contents do
                        # not depend on whether the native build exists.
                        rec = decode((name, raw))
                        if rec is not None:
                            rec["image"] = rec["image"].astype(np.float32)
                            records.append(rec)
        return _finish(records, archives, quarantine)

    with ThreadPoolExecutor(max_workers=num_workers) as pool:
        for archive in archives:
            entries = iter_tar_entries(archive, name_prefix)
            while True:
                batch = list(itertools.islice(entries, chunk))
                if not batch:
                    break
                probe("ingest.decode_batch")
                for rec in pool.map(decode, batch):
                    if rec is not None:
                        records.append(rec)
    return _finish(records, archives, quarantine)


def _finish(records, archives, quarantine: QuarantineCounts) -> ObjectDataset:
    quarantine.publish("load_image_archives")
    ds = ObjectDataset(records, num_shards=max(1, len(archives)))
    ds.quarantine = quarantine.as_dict()
    return ds
