"""ImageNet tar-of-JPEG loader.

TPU-native re-design of reference: loaders/ImageNetLoader.scala:11-39.
Each tar file contains JPEGs inside one directory per class; the directory
name keys into a space-separated ``className label`` map file.

Records are ``{"image": (X, Y, C) float BGR array, "label": int,
"filename": str}``; with ``resize`` set they stack directly into an
``ArrayDataset`` for whole-batch XLA featurization.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from ..dataset import ObjectDataset
from .archive import load_image_archives

NUM_CLASSES = 1000


def read_label_map(labels_path: str) -> Dict[str, int]:
    """``className label`` lines → dict
    (reference: ImageNetLoader.scala:27-32)."""
    out: Dict[str, int] = {}
    with open(labels_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            parts = line.split()
            out[parts[0]] = int(parts[1])
    return out


def load_imagenet(
    data_path: str,
    labels_path: str,
    resize: Optional[Tuple[int, int]] = None,
    num_workers: Optional[int] = None,  # None → KEYSTONE_INGEST_WORKERS default
) -> ObjectDataset:
    """Load every image under ``data_path`` (a tar file or a directory of
    tar files), labeling by the entry's leading directory name
    (reference: ImageNetLoader.scala:34-38)."""
    label_map = read_label_map(labels_path)

    def label_fn(entry_name: str) -> int:
        return label_map[entry_name.split("/")[0]]

    return load_image_archives(
        data_path, label_fn, resize=resize, num_workers=num_workers
    )
