"""VOC 2007 multi-label tar loader.

TPU-native re-design of reference: loaders/VOCLoader.scala:15-52. Images
live in a tar under ``VOCdevkit/VOC2007/JPEGImages/``; labels come from a
CSV whose rows carry a 1-based class id in column 1 and a quoted filename
in column 4 (header skipped). One image can carry several labels, so
records are ``{"image": arr, "labels": [int, ...], "filename": str}``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..dataset import ObjectDataset
from .archive import load_image_archives

NUM_CLASSES = 20  # fixed by the VOC 2007 dataset
DEFAULT_NAME_PREFIX = "VOCdevkit/VOC2007/JPEGImages/"


def read_voc_labels(labels_path: str) -> Dict[str, List[int]]:
    """CSV (with header) → filename → sorted list of 0-based class ids
    (reference: VOCLoader.scala:34-46)."""
    out: Dict[str, List[int]] = {}
    with open(labels_path) as f:
        lines = f.read().splitlines()
    for line in lines[1:]:
        if not line.strip():
            continue
        parts = line.split(",")
        fname = parts[4].replace('"', "")
        label = int(parts[1]) - 1
        out.setdefault(fname, []).append(label)
    return {k: sorted(set(v)) for k, v in out.items()}


def load_voc(
    data_path: str,
    labels_path: str,
    name_prefix: str = DEFAULT_NAME_PREFIX,
    resize: Optional[Tuple[int, int]] = None,
    num_workers: Optional[int] = None,  # None → KEYSTONE_INGEST_WORKERS default
) -> ObjectDataset:
    """Load the VOC tar(s); entries are matched to labels by basename so
    the label CSV's bare filenames line up with tar paths under
    ``name_prefix`` (reference: VOCLoader.scala:30,50 — the reference keys
    the map by ``entry.getName`` which includes the prefix; the CSV is
    preprocessed to match, here basename matching covers both layouts)."""
    label_map = read_voc_labels(labels_path)

    def label_fn(entry_name: str) -> List[int]:
        if entry_name in label_map:
            return label_map[entry_name]
        return label_map[entry_name.rsplit("/", 1)[-1]]

    return load_image_archives(
        data_path,
        label_fn,
        name_prefix=name_prefix,
        resize=resize,
        num_workers=num_workers,
        label_key="labels",
    )
