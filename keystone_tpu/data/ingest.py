"""Host-ingest throughput: tar-of-JPEG → device-ready batches.

The input pipeline is the classic host-side bottleneck feeding the chip
(SURVEY §7 hard part 5; reference: loaders/ImageLoaderUtils.scala:133-211
streams tar entries through executor-side ImageIO at cluster scale).
This module measures OUR ingest path — ``iter_tar_entries`` +
``native_decode_batch`` (OpenMP libjpeg, ``native/src/decode.cpp``) — and
optionally overlaps it with device featurization so the bench can state
whether the host can feed the device featurize rate.

Also provides the synthetic tar fixture builder the bench uses (cached:
writing 10k JPEGs once is ~1 min of pure PIL encode time).
"""

from __future__ import annotations

import io
import os
import tarfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Iterable, Optional

import numpy as np

from ..obs import names as _names
from ..obs import spans as _spans
from .loaders.archive import iter_tar_entries, native_decode_batch


class PrefetchQueue:
    """Bounded, ordered, multi-worker host prefetch pipeline.

    The host side of the streaming execution engine
    (workflow/streaming.py): ``workers`` threads pull raw items from
    ``source`` (under a lock — iterators aren't thread-safe), run
    ``prepare`` (decode/stack — the GIL-releasing work) concurrently,
    and publish results IN SOURCE ORDER into a depth-limited buffer.
    ``depth`` bounds the number of prepared-or-in-flight chunks, which
    is what makes host memory O(chunk) instead of O(dataset): a fast
    producer blocks instead of ballooning.

    Error handling mirrors the streaming contract: an exception from
    ``source``/``prepare`` is re-raised at the consumer in order, and
    ``close()`` (idempotent, called on ANY consumer exit including
    mid-stream estimator failure) unblocks and joins every worker — no
    leaked threads, verified by the reliability fault-injection tests.
    """

    def __init__(
        self,
        source: Iterable[Any],
        prepare: Optional[Callable[[Any], Any]] = None,
        depth: int = 1,
        workers: Optional[int] = None,
        size_of: Optional[Callable[[Any], int]] = None,
        name: str = "stream",
    ):
        self._source = iter(source)
        self._prepare = prepare or (lambda x: x)
        self._depth = max(1, int(depth))
        self._size_of = size_of
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._buffer: Dict[int, tuple] = {}
        self._next_pull = 0
        self._next_emit = 0
        self._exhausted_at: Optional[int] = None
        self._closed = False
        self.live_bytes = 0
        self.peak_live_bytes = 0
        self.stall_s = 0.0
        self._sem = threading.Semaphore(self._depth)
        nworkers = max(1, workers if workers is not None else 1)
        self._threads = [
            threading.Thread(
                target=self._run, name=f"keystone-{name}-prefetch-{i}", daemon=True
            )
            for i in range(nworkers)
        ]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------- workers
    def _run(self) -> None:
        depth_gauge = _names.metric(_names.STREAM_PREFETCH_DEPTH)
        while True:
            self._sem.acquire()
            with self._lock:
                if self._closed or self._exhausted_at is not None:
                    self._sem.release()
                    return
                seq = self._next_pull
                try:
                    item = next(self._source)
                except StopIteration:
                    self._exhausted_at = seq
                    self._cond.notify_all()
                    self._sem.release()
                    return
                except Exception as e:  # source error: surfaced in order
                    self._buffer[seq] = ("err", e, 0)
                    self._next_pull += 1
                    self._cond.notify_all()
                    continue
                self._next_pull += 1
            try:
                entry = ("ok", self._prepare(item), 0)
            except Exception as e:
                entry = ("err", e, 0)
            if entry[0] == "ok" and self._size_of is not None:
                try:
                    entry = ("ok", entry[1], int(self._size_of(entry[1])))
                except Exception:
                    pass
            with self._lock:
                if self._closed:
                    return
                self._buffer[seq] = entry
                self.live_bytes += entry[2]
                self.peak_live_bytes = max(self.peak_live_bytes, self.live_bytes)
                depth_gauge.set(len(self._buffer))
                self._cond.notify_all()

    # ------------------------------------------------------------ consumer
    def __iter__(self):
        return self

    def __next__(self) -> Any:
        t0 = time.perf_counter()
        depth_gauge = _names.metric(_names.STREAM_PREFETCH_DEPTH)
        with self._cond:
            while True:
                if self._closed:
                    raise RuntimeError("prefetch queue closed")
                if self._next_emit in self._buffer:
                    kind, value, nbytes = self._buffer.pop(self._next_emit)
                    self._next_emit += 1
                    self.live_bytes -= nbytes
                    depth_gauge.set(len(self._buffer))
                    waited = time.perf_counter() - t0
                    self.stall_s += waited
                    _names.metric(_names.STREAM_STALL_SECONDS).inc(waited)
                    self._sem.release()
                    if kind == "err":
                        raise value
                    return value
                if (
                    self._exhausted_at is not None
                    and self._next_emit >= self._exhausted_at
                ):
                    raise StopIteration
                self._cond.wait(0.05)

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        for _ in self._threads:
            self._sem.release()  # unblock workers parked on the bound
        for t in self._threads:
            t.join(timeout=10)

    def __enter__(self) -> "PrefetchQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def build_jpeg_tar_fixture(
    path: str,
    num_images: int,
    size: int = 256,
    quality: int = 87,
    seed: int = 0,
    deadline_left_fn: Optional[Callable[[], Optional[float]]] = None,
    deadline_margin_s: float = 60.0,
) -> str:
    """Write a tar of ``num_images`` synthetic JPEGs (block-textured so
    file sizes land near real photo entropy, ~20-40 KB at 256²). Cached:
    an existing file at ``path`` with the right entry count is reused.

    ``deadline_left_fn`` makes the build TIME-BUDGETED: the serial PIL
    encode loop is the single longest uninterruptible phase of the bench
    ingest leg (BENCH_r05 died inside it with a bare child timeout), so
    when fewer than ``deadline_margin_s`` seconds remain the tar is
    finalized with however many images were written — the measuring
    phases downstream then report partial results instead of nothing.
    """
    from PIL import Image

    if os.path.exists(path):
        try:
            with tarfile.open(path) as t:
                if sum(1 for m in t if m.isfile()) == num_images:
                    return path
        except tarfile.ReadError:
            pass
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    rng = np.random.default_rng(seed)
    tmp = path + ".tmp"
    with tarfile.open(tmp, "w") as tar:
        for i in range(num_images):
            if deadline_left_fn is not None and i and i % 128 == 0:
                left = deadline_left_fn()
                if left is not None and left <= deadline_margin_s:
                    break  # finalize a partial (still valid) fixture
            # Low-res random field upsampled ×8 + noise: JPEG-compressible
            # structure, photo-like size on disk.
            low = rng.integers(0, 256, (size // 8, size // 8, 3), dtype=np.uint8)
            img = np.repeat(np.repeat(low, 8, axis=0), 8, axis=1)
            img = np.clip(
                img.astype(np.int16) + rng.integers(-12, 13, img.shape), 0, 255
            ).astype(np.uint8)
            buf = io.BytesIO()
            Image.fromarray(img).save(buf, format="JPEG", quality=quality)
            data = buf.getvalue()
            info = tarfile.TarInfo(name=f"synset{i % 16:04d}/img_{i:06d}.JPEG")
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
    os.replace(tmp, path)
    return path


def measure_ingest(
    tar_path: str,
    resize: tuple = (256, 256),
    batch: int = 256,
    threads: Optional[int] = None,
    featurize: Optional[Callable[[np.ndarray], object]] = None,
    max_images: Optional[int] = None,
) -> Dict[str, float]:
    """Stream ``tar_path`` through the native decode kernel; returns
    images/sec plus byte counts. With ``featurize`` given, decode of
    batch i+1 overlaps ``featurize(batch_i)`` (device work) through a
    one-slot pipeline — the shape of a real training input pipeline —
    and the overlapped rate is reported separately."""
    from .. import native

    lib = native.load()
    if lib is None:
        return {"error": "native library not built"}
    if threads:
        lib.ks_set_threads(int(threads))

    t0 = time.perf_counter()
    done = 0
    corrupt = 0  # undecodable entries: quarantined, never abort the stream
    raw_bytes = 0
    pending = None  # in-flight featurize result to force
    pool = ThreadPoolExecutor(max_workers=1)
    decode_s = 0.0
    feat_wait_s = 0.0

    def decode(chunk):
        return native_decode_batch([r for _, r in chunk], resize)

    with _spans.span("ingest:read", source=tar_path):
        entries = iter_tar_entries(tar_path)
        chunk: list = []
        futures = []
        for name, raw in entries:
            chunk.append((name, raw))
            raw_bytes += len(raw)
            if len(chunk) == batch:
                futures.append(chunk)
                chunk = []
                if max_images and sum(len(c) for c in futures) + done >= max_images:
                    break
        if chunk:
            futures.append(chunk)

    read_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    with _spans.span(
        "ingest:decode", batches=len(futures), overlapped=featurize is not None
    ):
        for c in futures:
            td = time.perf_counter()
            images, ok = decode(c)
            decode_s += time.perf_counter() - td
            done += int(ok.sum())
            corrupt += len(c) - int(ok.sum())
            if featurize is not None:
                tw = time.perf_counter()
                if pending is not None:
                    pending.result()  # force previous device batch
                feat_wait_s += time.perf_counter() - tw
                pending = pool.submit(featurize, images)
        if pending is not None:
            pending.result()
    total_s = time.perf_counter() - t0
    pool.shutdown()

    _names.metric(_names.INGEST_IMAGES).inc(done)
    _names.metric(_names.INGEST_BYTES).inc(raw_bytes)
    _names.metric(_names.INGEST_DECODE_SECONDS).inc(decode_s)

    if corrupt:
        from ..reliability.recovery import get_recovery_log

        _names.metric(_names.INGEST_CORRUPT).inc(corrupt)
        get_recovery_log().record(
            "quarantine", "measure_ingest", count=corrupt, source=tar_path
        )
    out = {
        "images": done,
        "corrupt_skipped": corrupt,
        "tar_read_s": round(read_s, 2),
        "decode_s": round(decode_s, 2),
        "images_per_sec_decode": round(done / max(decode_s, 1e-9), 1),
        "mb_per_sec_jpeg": round(raw_bytes / 1e6 / max(decode_s + read_s, 1e-9), 1),
    }
    if featurize is not None:
        out["images_per_sec_overlapped"] = round(done / max(total_s, 1e-9), 1)
        out["featurize_wait_s"] = round(feat_wait_s, 2)
    return out
