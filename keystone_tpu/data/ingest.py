"""Host-ingest throughput: tar-of-JPEG → device-ready batches.

The input pipeline is the classic host-side bottleneck feeding the chip
(SURVEY §7 hard part 5; reference: loaders/ImageLoaderUtils.scala:133-211
streams tar entries through executor-side ImageIO at cluster scale).
This module measures OUR ingest path — ``iter_tar_entries`` +
``native_decode_batch`` (OpenMP libjpeg, ``native/src/decode.cpp``) — and
optionally overlaps it with device featurization so the bench can state
whether the host can feed the device featurize rate.

Also provides the synthetic tar fixture builder the bench uses (cached:
writing 10k JPEGs once is ~1 min of pure PIL encode time).
"""

from __future__ import annotations

import io
import os
import tarfile
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Optional

import numpy as np

from ..obs import names as _names
from ..obs import spans as _spans
from .loaders.archive import iter_tar_entries, native_decode_batch


def build_jpeg_tar_fixture(
    path: str, num_images: int, size: int = 256, quality: int = 87, seed: int = 0
) -> str:
    """Write a tar of ``num_images`` synthetic JPEGs (block-textured so
    file sizes land near real photo entropy, ~20-40 KB at 256²). Cached:
    an existing file at ``path`` with the right entry count is reused."""
    from PIL import Image

    if os.path.exists(path):
        try:
            with tarfile.open(path) as t:
                if sum(1 for m in t if m.isfile()) == num_images:
                    return path
        except tarfile.ReadError:
            pass
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    rng = np.random.default_rng(seed)
    tmp = path + ".tmp"
    with tarfile.open(tmp, "w") as tar:
        for i in range(num_images):
            # Low-res random field upsampled ×8 + noise: JPEG-compressible
            # structure, photo-like size on disk.
            low = rng.integers(0, 256, (size // 8, size // 8, 3), dtype=np.uint8)
            img = np.repeat(np.repeat(low, 8, axis=0), 8, axis=1)
            img = np.clip(
                img.astype(np.int16) + rng.integers(-12, 13, img.shape), 0, 255
            ).astype(np.uint8)
            buf = io.BytesIO()
            Image.fromarray(img).save(buf, format="JPEG", quality=quality)
            data = buf.getvalue()
            info = tarfile.TarInfo(name=f"synset{i % 16:04d}/img_{i:06d}.JPEG")
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
    os.replace(tmp, path)
    return path


def measure_ingest(
    tar_path: str,
    resize: tuple = (256, 256),
    batch: int = 256,
    threads: Optional[int] = None,
    featurize: Optional[Callable[[np.ndarray], object]] = None,
    max_images: Optional[int] = None,
) -> Dict[str, float]:
    """Stream ``tar_path`` through the native decode kernel; returns
    images/sec plus byte counts. With ``featurize`` given, decode of
    batch i+1 overlaps ``featurize(batch_i)`` (device work) through a
    one-slot pipeline — the shape of a real training input pipeline —
    and the overlapped rate is reported separately."""
    from .. import native

    lib = native.load()
    if lib is None:
        return {"error": "native library not built"}
    if threads:
        lib.ks_set_threads(int(threads))

    t0 = time.perf_counter()
    done = 0
    corrupt = 0  # undecodable entries: quarantined, never abort the stream
    raw_bytes = 0
    pending = None  # in-flight featurize result to force
    pool = ThreadPoolExecutor(max_workers=1)
    decode_s = 0.0
    feat_wait_s = 0.0

    def decode(chunk):
        return native_decode_batch([r for _, r in chunk], resize)

    with _spans.span("ingest:read", source=tar_path):
        entries = iter_tar_entries(tar_path)
        chunk: list = []
        futures = []
        for name, raw in entries:
            chunk.append((name, raw))
            raw_bytes += len(raw)
            if len(chunk) == batch:
                futures.append(chunk)
                chunk = []
                if max_images and sum(len(c) for c in futures) + done >= max_images:
                    break
        if chunk:
            futures.append(chunk)

    read_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    with _spans.span(
        "ingest:decode", batches=len(futures), overlapped=featurize is not None
    ):
        for c in futures:
            td = time.perf_counter()
            images, ok = decode(c)
            decode_s += time.perf_counter() - td
            done += int(ok.sum())
            corrupt += len(c) - int(ok.sum())
            if featurize is not None:
                tw = time.perf_counter()
                if pending is not None:
                    pending.result()  # force previous device batch
                feat_wait_s += time.perf_counter() - tw
                pending = pool.submit(featurize, images)
        if pending is not None:
            pending.result()
    total_s = time.perf_counter() - t0
    pool.shutdown()

    _names.metric(_names.INGEST_IMAGES).inc(done)
    _names.metric(_names.INGEST_BYTES).inc(raw_bytes)
    _names.metric(_names.INGEST_DECODE_SECONDS).inc(decode_s)

    if corrupt:
        from ..reliability.recovery import get_recovery_log

        _names.metric(_names.INGEST_CORRUPT).inc(corrupt)
        get_recovery_log().record(
            "quarantine", "measure_ingest", count=corrupt, source=tar_path
        )
    out = {
        "images": done,
        "corrupt_skipped": corrupt,
        "tar_read_s": round(read_s, 2),
        "decode_s": round(decode_s, 2),
        "images_per_sec_decode": round(done / max(decode_s, 1e-9), 1),
        "mb_per_sec_jpeg": round(raw_bytes / 1e6 / max(decode_s + read_s, 1e-9), 1),
    }
    if featurize is not None:
        out["images_per_sec_overlapped"] = round(done / max(total_s, 1e-9), 1)
        out["featurize_wait_s"] = round(feat_wait_s, 2)
    return out
