"""Dataset substrate: the TPU-native replacement for the reference's RDDs.

The reference moves every collection through Spark ``RDD[T]``s; featurizers
run ``mapPartitions`` over JVM objects and solvers batch partition rows into
local BLAS matrices (reference: utils/MatrixUtils.scala:17-205
``rowsToMatrixIter``; workflow/Operator.scala:10-177).

On TPU the idiomatic substrate is different, so this is a re-design, not a
port:

- ``ArrayDataset`` — a pytree of arrays with a leading example axis, the
  device-resident form. Solvers and batched featurizers consume it whole
  (one XLA computation over the sharded batch), replacing the reference's
  partition-wise GEMM idiom.
- ``ObjectDataset`` — a host-side list of Python objects (raw images,
  strings, token lists); the staging ground before padding/batching onto
  device. Replaces ``RDD[LabeledImage]``-style collections.

Both expose ``map``/``collect``/``cache`` so the untyped operator layer can
treat them uniformly. Sharding over a ``jax.sharding.Mesh`` happens when an
``ArrayDataset`` is placed with :func:`ArrayDataset.shard`; zero-row padding
makes the example count divisible by the mesh's data axis (zero rows are
harmless to Gram/gradient accumulation and are masked out of statistics via
``num_examples``).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..envknobs import env_str

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def default_ingest_workers() -> int:
    """Host-side worker count shared by every ingest-adjacent pool:
    ``ObjectDataset.map``, the archive decode pool, and the streaming
    engine's prefetch pipeline. ``KEYSTONE_INGEST_WORKERS`` overrides;
    the default derives from the host's core count (capped — tar decode
    pools past ~32 threads just fight the GIL/page cache)."""
    raw = env_str("KEYSTONE_INGEST_WORKERS").strip()
    if raw:
        return max(1, int(raw))
    return max(2, min(32, os.cpu_count() or 4))


def transfer_dtype(dtype) -> np.dtype:
    """The dtype a host array should CROSS the host→device link as.

    Narrow dtypes (uint8 images, int16 audio, bool masks) stay narrow —
    transfer scales with bytes, and uint8 is 4× less traffic than the
    float32 the math eventually wants (measured fact backing
    pipelines/imagenet_streaming.py); the consumer casts ON DEVICE.
    64-bit host types squeeze to 32-bit: jax (x64 disabled) would
    canonicalize them to 32-bit anyway, so shipping 8 bytes/element is
    pure waste.
    """
    dtype = np.dtype(dtype)
    if dtype == np.float64:
        return np.dtype(np.float32)
    if dtype == np.int64:
        return np.dtype(np.int32)
    if dtype == np.uint64:
        return np.dtype(np.uint32)
    if dtype == np.complex128:
        return np.dtype(np.complex64)
    return dtype


class Dataset:
    """Abstract logical collection of examples."""

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        raise NotImplementedError

    def collect(self) -> List[Any]:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    def take(self, n: int) -> List[Any]:
        return self.collect()[:n]

    def cache(self) -> "Dataset":
        """Materialization point (reference: nodes/util/Cacher.scala:15-25).

        ``ArrayDataset`` is already materialized in HBM; ``ObjectDataset``
        forces any lazy source. Returns self for chaining.
        """
        return self

    def fetch_rows(self, start: int, stop: int) -> Any:
        """Host numpy pytree of the ``[start, stop)`` example window,
        stored dtype preserved. The one chunk-windowing primitive: both
        :meth:`iter_chunks` and the streaming engine's parallel prefetch
        workers (workflow/streaming.py) go through it, so window
        semantics can't diverge. Subclasses without a chunkable physical
        layout don't implement it — the streaming planner falls back to
        the materialized path for them."""
        raise NotImplementedError(f"{type(self).__name__} is not chunkable")

    def iter_chunks(self, chunk_rows: int) -> Iterator[Tuple[Any, int]]:
        """Yield ``(host_pytree, num_valid_rows)`` windows of at most
        ``chunk_rows`` examples, in order, as host numpy arrays with
        their stored dtype preserved (the streaming engine narrows via
        :func:`transfer_dtype` at upload time)."""
        n = len(self)
        for start in range(0, n, chunk_rows):
            stop = min(start + chunk_rows, n)
            yield self.fetch_rows(start, stop), stop - start

    @property
    def num_shards(self) -> int:
        return 1

    def per_shard_counts(self) -> List[int]:
        """Analog of the reference's ``WorkflowUtils.numPerPartition``."""
        n = len(self)
        k = self.num_shards
        base, extra = divmod(n, k)
        return [base + (1 if i < extra else 0) for i in range(k)]


class ObjectDataset(Dataset):
    """Host-side list of arbitrary Python objects."""

    def __init__(self, items: Sequence[Any], num_shards: Optional[int] = None):
        self._items = list(items)
        self._num_shards = num_shards or 1

    def map(self, fn: Callable[[Any], Any], parallel: Optional[bool] = None) -> "ObjectDataset":
        """Per-item host map, fanned over a thread pool for larger
        datasets (the RDD-map analog; pays off when ``fn`` releases the
        GIL — numpy, PIL, the native kernels — which is what host-side
        featurizer fallbacks do). Order is preserved.

        ``fn`` must be safe to call concurrently (the RDD-map contract);
        pass ``parallel=False`` for functions with shared mutable state,
        ``parallel=True`` to force the pool for small datasets. Pool
        width comes from :func:`default_ingest_workers`
        (``KEYSTONE_INGEST_WORKERS``), shared with the archive decode
        pool and the streaming prefetch pipeline."""
        if parallel is None:
            parallel = len(self._items) >= 64
        if parallel:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=default_ingest_workers()) as pool:
                return ObjectDataset(list(pool.map(fn, self._items)), self._num_shards)
        return ObjectDataset([fn(x) for x in self._items], self._num_shards)

    def collect(self) -> List[Any]:
        return list(self._items)

    def __len__(self) -> int:
        return len(self._items)

    @property
    def num_shards(self) -> int:
        return self._num_shards

    def to_arrays(self) -> "ArrayDataset":
        """Stack items (arrays or pytrees of equal shape) into an ArrayDataset."""
        if not self._items:
            raise ValueError("cannot stack an empty dataset")
        stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *self._items)
        return ArrayDataset(stacked)

    def fetch_rows(self, start: int, stop: int) -> Any:
        """Stack one window of items on demand — only the window is ever
        stacked, so host residency stays O(chunk) no matter the dataset
        size; the streaming prefetch workers call this concurrently."""
        window = self._items[start:stop]
        return jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]), *window
        )

    def __repr__(self) -> str:
        return f"ObjectDataset(n={len(self._items)}, shards={self._num_shards})"


def _leading_dim(tree: Any) -> int:
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        raise ValueError("empty pytree")
    n = leaves[0].shape[0]
    for leaf in leaves:
        if leaf.shape[0] != n:
            raise ValueError("inconsistent leading dimensions in dataset pytree")
    return n


class ArrayDataset(Dataset):
    """A pytree of arrays with a shared leading example axis.

    ``num_examples`` is the *logical* row count; the physical arrays may be
    zero-padded past it so the leading axis divides the mesh's data axis.
    """

    def __init__(self, data: Any, num_examples: Optional[int] = None):
        self.data = data
        physical = _leading_dim(data)
        self.num_examples = num_examples if num_examples is not None else physical
        if self.num_examples > physical:
            raise ValueError("num_examples exceeds physical leading dim")

    # ------------------------------------------------------------- protocol
    def __len__(self) -> int:
        return self.num_examples

    @property
    def physical_rows(self) -> int:
        return _leading_dim(self.data)

    def collect(self) -> List[Any]:
        host = jax.tree_util.tree_map(np.asarray, self.data)
        return [
            jax.tree_util.tree_map(lambda a: a[i], host) for i in range(self.num_examples)
        ]

    def map(self, fn: Callable[[Any], Any]) -> "ObjectDataset":
        """Per-item host map. Prefer :meth:`map_batched` on the device path."""
        return ObjectDataset([fn(x) for x in self.collect()])

    def map_batched(self, fn: Callable[[Any], Any], num_examples: Optional[int] = None) -> "ArrayDataset":
        """Apply ``fn`` to the whole batched pytree — one XLA computation."""
        out = fn(self.data)
        return ArrayDataset(out, num_examples if num_examples is not None else self.num_examples)

    def take(self, n: int) -> List[Any]:
        n = min(n, self.num_examples)
        host = jax.tree_util.tree_map(lambda a: np.asarray(a[:n]), self.data)
        return [jax.tree_util.tree_map(lambda a: a[i], host) for i in range(n)]

    def fetch_rows(self, start: int, stop: int) -> Any:
        """Host-side row window of the logical (unpadded) examples.
        Device-resident leaves are pulled per window, never whole —
        a chunked read of an HBM-resident dataset stays O(chunk)."""
        return jax.tree_util.tree_map(
            lambda a: np.asarray(a[start:stop]), self.data
        )

    # ------------------------------------------------------------- sharding
    def padded_to(self, multiple: int) -> "ArrayDataset":
        """Zero-pad the leading axis up to the next multiple of ``multiple``.

        Dtype-preserving by contract: a uint8 image batch pads to uint8 —
        narrowing to the storage dtype and casting on DEVICE is what
        keeps host→device traffic at 1 byte/px (see
        :func:`transfer_dtype`); an upcast here would silently 4× it.
        """
        physical = self.physical_rows
        target = ((physical + multiple - 1) // multiple) * multiple
        if target == physical:
            return self
        pad = target - physical

        def pad_leaf(a):
            widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            return jnp.pad(a, widths) if isinstance(a, jnp.ndarray) else np.pad(a, widths)

        return ArrayDataset(jax.tree_util.tree_map(pad_leaf, self.data), self.num_examples)

    def shard(self, mesh: jax.sharding.Mesh, axis: str = "data") -> "ArrayDataset":
        """Place on ``mesh`` sharded along the leading axis.

        Zero-pads so the leading axis divides the mesh axis size — the
        TPU-native analog of the reference's row-partitioned RDDs.
        Host leaves cross the link at :func:`transfer_dtype` width
        (uint8 stays uint8, float64 squeezes to float32) so the
        placement never silently widens the transfer.
        """
        n_dev = mesh.shape[axis]
        ds = self.padded_to(n_dev)

        def place(a):
            if isinstance(a, np.ndarray):
                narrow = transfer_dtype(a.dtype)
                if narrow != a.dtype:
                    a = a.astype(narrow)
            spec = P(axis, *([None] * (a.ndim - 1)))
            return jax.device_put(a, NamedSharding(mesh, spec))

        return ArrayDataset(jax.tree_util.tree_map(place, ds.data), self.num_examples)

    @property
    def num_shards(self) -> int:
        leaves = jax.tree_util.tree_leaves(self.data)
        leaf = leaves[0]
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and hasattr(sharding, "num_devices"):
            try:
                return sharding.num_devices
            except Exception:
                return 1
        return 1

    def mask(self) -> jnp.ndarray:
        """1.0 for real rows, 0.0 for padding — shape (physical_rows,)."""
        return (jnp.arange(self.physical_rows) < self.num_examples).astype(jnp.float32)

    def __repr__(self) -> str:
        shapes = jax.tree_util.tree_map(lambda a: tuple(a.shape), self.data)
        return f"ArrayDataset(n={self.num_examples}, shapes={shapes})"


class BucketedDataset(Dataset):
    """A logical dataset physically stored as static-shape groups.

    The native-resolution path (SURVEY §7 hard part 4) groups images by
    padded size so each group is one XLA compilation; this class makes
    those groups a first-class Dataset the workflow layer can execute —
    batched transformers map per bucket, estimators consume the
    concatenation — so native-resolution pipelines flow through the
    optimizer/autocache/prefix-reuse machinery instead of a bespoke host
    loop. Example order is bucket-major and stable across ops, so labels
    aligned to ``concat()`` order stay aligned downstream.
    """

    def __init__(self, buckets: Sequence["ArrayDataset"]):
        if not buckets:
            raise ValueError("BucketedDataset needs at least one bucket")
        self.buckets = list(buckets)

    # ------------------------------------------------------------- protocol
    def __len__(self) -> int:
        return sum(len(b) for b in self.buckets)

    def collect(self) -> List[Any]:
        out: List[Any] = []
        for b in self.buckets:
            out.extend(b.collect())
        return out

    def map(self, fn: Callable[[Any], Any]) -> "ObjectDataset":
        return ObjectDataset([fn(x) for x in self.collect()])

    def map_datasets(self, fn: Callable[["ArrayDataset"], "ArrayDataset"]) -> "BucketedDataset":
        """Apply a per-bucket Dataset→Dataset function (the workflow-layer
        entry point: one static-shape computation per bucket)."""
        return BucketedDataset([fn(b) for b in self.buckets])

    def map_batched(self, fn: Callable[[Any], Any]) -> "BucketedDataset":
        return BucketedDataset([b.map_batched(fn) for b in self.buckets])

    @property
    def num_shards(self) -> int:
        return len(self.buckets)

    def per_shard_counts(self) -> List[int]:
        return [len(b) for b in self.buckets]

    def concat(self) -> "ArrayDataset":
        """Concatenate buckets along the example axis (valid once trailing
        shapes agree — e.g. after Fisher encoding collapses per-bucket
        descriptor grids to fixed-width features)."""
        datas = [
            jax.tree_util.tree_map(lambda a: a[: len(b)], b.data)
            for b in self.buckets
        ]
        joined = jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0), *datas
        )
        return ArrayDataset(joined)

    def __repr__(self) -> str:
        return f"BucketedDataset(buckets={[len(b) for b in self.buckets]})"


def as_dataset(value: Any) -> Dataset:
    """Coerce lists/arrays into a Dataset."""
    if isinstance(value, Dataset):
        return value
    if isinstance(value, (list, tuple)):
        return ObjectDataset(list(value))
    if isinstance(value, (np.ndarray, jnp.ndarray)):
        return ArrayDataset(value)
    raise TypeError(f"cannot interpret {type(value)} as a Dataset")
