"""Failure taxonomy: classify an exception into the recovery path it gets.

KeystoneML inherited fault tolerance from Spark RDD lineage — a lost
partition was recomputed from its parents, and the framework never had to
name its failure modes. The TPU-native port executes through a memoizing
in-process interpreter, so failures must be classified explicitly:

- ``TRANSIENT``   — relay/coordinator hiccups, preemptions, dropped
                    connections. Worth retrying with backoff (retry.py).
- ``OOM``         — RESOURCE_EXHAUSTED / allocator failures. Retrying the
                    same shape re-OOMs; the recovery is a
                    :class:`~keystone_tpu.reliability.degrade.DegradationLadder`
                    rung at a smaller block/batch size.
- ``DEADLINE``    — a node ran past its execution deadline (a hung relay
                    looks like an infinite compile). Retryable: the retry
                    re-dispatches, usually onto a healthy channel.
- ``CORRUPT_DATA``— undecodable / malformed input records. Neither retry
                    nor shrinking helps; the recovery is skip-and-quarantine
                    at the ingest layer (data/ingest.py, data/loaders/*).
- ``PERMANENT``   — user/programming errors (bad shapes, bad config).
                    Never retried; they must propagate unchanged.

Classification is message-pattern first (an XLA RESOURCE_EXHAUSTED can
surface as several exception types depending on the dispatch path), then
exception-type. The pattern table is data (`CLASSIFICATION_TABLE`) so tests
and docs/RELIABILITY.md state the taxonomy from the same source.
"""

from __future__ import annotations

import enum
from typing import Tuple


class ErrorClass(enum.Enum):
    TRANSIENT = "transient"
    OOM = "oom"
    DEADLINE = "deadline"
    CORRUPT_DATA = "corrupt_data"
    PERMANENT = "permanent"


class DeadlineExceeded(TimeoutError):
    """A unit of work ran past its execution deadline."""


class CorruptRecordError(ValueError):
    """An input record failed validation/decoding (quarantine, don't abort)."""


# (class, uppercase substrings of str(exc)) — first match wins, in order.
# OOM before TRANSIENT: an OOM raised through a relay RPC can carry both
# RESOURCE_EXHAUSTED and connection noise in one message, and shrinking is
# the recovery that actually converges.
CLASSIFICATION_TABLE: Tuple[Tuple[ErrorClass, Tuple[str, ...]], ...] = (
    (
        ErrorClass.OOM,
        (
            "RESOURCE_EXHAUSTED",
            "OUT OF MEMORY",
            "OUT-OF-MEMORY",
            "ALLOCATION FAILURE",
            "HBM OOM",
        ),
    ),
    (
        ErrorClass.DEADLINE,
        ("DEADLINE_EXCEEDED", "EXECUTION DEADLINE"),
    ),
    (
        ErrorClass.CORRUPT_DATA,
        ("DATA_LOSS", "CORRUPT RECORD", "CORRUPTED RECORD"),
    ),
    (
        ErrorClass.TRANSIENT,
        (
            "UNAVAILABLE",
            "CONNECTION RESET",
            "CONNECTION REFUSED",
            "BROKEN PIPE",
            "SOCKET CLOSED",
            "COORDINATOR",
            "PREEMPT",
            "HEARTBEAT",
            "BARRIER TIMED OUT",
            "TRANSIENT",
            "TEMPORARILY",
        ),
    ),
)


def classify_error(exc: BaseException) -> ErrorClass:
    """Map an exception to its :class:`ErrorClass`.

    Message patterns win over exception type — the same XLA failure
    surfaces as XlaRuntimeError, RuntimeError, or ValueError depending on
    where in the dispatch stack it is raised.
    """
    if isinstance(exc, DeadlineExceeded):
        return ErrorClass.DEADLINE
    if isinstance(exc, CorruptRecordError):
        return ErrorClass.CORRUPT_DATA
    if isinstance(exc, MemoryError):
        return ErrorClass.OOM

    message = str(exc).upper()
    for error_class, patterns in CLASSIFICATION_TABLE:
        if any(p in message for p in patterns):
            return error_class

    if isinstance(exc, (ConnectionError, TimeoutError)):
        return ErrorClass.TRANSIENT
    if isinstance(exc, OSError):
        # I/O flakiness on data paths (NFS hiccups, EINTR); user errors on
        # data paths raise FileNotFoundError before any device work starts.
        if isinstance(exc, (FileNotFoundError, PermissionError, IsADirectoryError)):
            return ErrorClass.PERMANENT
        return ErrorClass.TRANSIENT
    return ErrorClass.PERMANENT


def is_oom(exc: BaseException) -> bool:
    return classify_error(exc) is ErrorClass.OOM
