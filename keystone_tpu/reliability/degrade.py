"""Degradation ladders: walk a sequence of progressively cheaper
configurations until one fits on the chip.

Generalized from the three ad-hoc OOM ladders bench.py grew (halve n on
RESOURCE_EXHAUSTED in timit_exact / timit_wide_block / cifar, plus the
explicit imagenet_fv rung list) into one reusable component that solvers
and pipelines share. The Panther mindset (PAPERS.md — randomized NLA:
a cheap approximation beats no answer) applied to memory: when the
full-precision / full-size solve won't fit, take the best rung that does
and SAY SO — every degraded result carries ``reduced_from`` and
``reduction_reason`` so a reader can't mistake it for the full-size run.

Memory discipline: between rungs the failed attempt's buffers must die
before the next allocation (holding both is itself an OOM source — the
bench r5 on-chip failure mode). ``run`` therefore keeps only the formatted
error string, never the exception object, so the attempt frame (and the
device buffers its locals pin) is freed when the except block ends.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Sequence

from .errors import is_oom
from .recovery import get_recovery_log


class LadderExhausted(RuntimeError):
    """Every rung of a degradation ladder failed with a degradable error."""


def halving_rungs(full: int, floor: int, align: int = 1) -> List[int]:
    """The halving rung sequence the bench ladders walk: ``full``, then
    repeated halvings (each aligned DOWN to a multiple of ``align``),
    ending with the first value ≤ ``floor`` — that last rung still gets
    attempted; only a failure AT it exhausts the ladder."""
    if full <= 0:
        raise ValueError(f"halving_rungs: full={full} must be positive")
    rungs = [full]
    v = full
    while v > floor:
        v = v // 2
        v -= v % align
        if v <= 0:
            break
        rungs.append(v)
    return rungs


class DegradationLadder:
    """Run an attempt across rungs, degrading on OOM-class failures.

    ``rungs`` are opaque configs (ints, tuples, estimator factories — the
    attempt callable interprets them). After a successful ``run``,
    ``record`` describes what happened; ``annotate`` stamps the standard
    reduction fields onto a result dict.
    """

    def __init__(
        self,
        rungs: Sequence[Any],
        should_degrade: Callable[[BaseException], bool] = is_oom,
        label: str = "ladder",
        on_degrade: Optional[Callable[[Any, str], None]] = None,
    ):
        if not rungs:
            raise ValueError(f"{label}: empty rung list")
        self.rungs = list(rungs)
        self.should_degrade = should_degrade
        self.label = label
        self.on_degrade = on_degrade
        self.last_error: Optional[str] = None
        self.record: Dict[str, Any] = {}

    def run(self, attempt: Callable[[Any], Any]) -> Any:
        self.last_error = None
        for index, rung in enumerate(self.rungs):
            try:
                value = attempt(rung)
            except Exception as exc:
                if not self.should_degrade(exc):
                    raise
                # Keep the STRING only: holding `exc` (and its traceback's
                # frames) across the next rung pins the failed attempt's
                # buffers — see module docstring.
                self.last_error = f"{type(exc).__name__}: {exc}"
                if self.on_degrade is not None:
                    self.on_degrade(rung, self.last_error)
                continue
            self.record = {
                "rung": rung,
                "rung_index": index,
                "first_rung": self.rungs[0],
                "reduced": index > 0,
            }
            if index > 0:
                self.record["reduction_reason"] = (self.last_error or "")[:200]
                get_recovery_log().record(
                    "degrade",
                    self.label,
                    rung_index=index,
                    rung=_printable(rung),
                    first_rung=_printable(self.rungs[0]),
                    reason=self.record["reduction_reason"],
                )
            return value
        raise LadderExhausted(
            f"{self.label}: OOM at every ladder rung: {self.last_error}"
        )

    @property
    def reduced(self) -> bool:
        return bool(self.record.get("reduced"))

    def annotate(self, out: Dict[str, Any]) -> Dict[str, Any]:
        """Stamp the standard degradation fields onto a result dict (the
        bench convention: ``extrapolated`` + ``reduced_from`` +
        ``reduction_reason``)."""
        if self.reduced:
            out["extrapolated"] = True
            out["reduced_from"] = _printable(self.record["first_rung"])
            out["reduction_reason"] = self.record["reduction_reason"]
        return out


def _printable(rung: Any) -> Any:
    if isinstance(rung, (int, float, str, bool)) or rung is None:
        return rung
    if isinstance(rung, dict):
        return {k: _printable(v) for k, v in rung.items()}
    if isinstance(rung, (list, tuple)):
        return [_printable(v) for v in rung]
    if callable(rung):
        return getattr(rung, "__qualname__", type(rung).__name__)
    # Default reprs embed per-process addresses ("<... at 0x7f...>") —
    # strip them so recovery-log events compare equal across identical runs.
    return re.sub(r" at 0x[0-9a-fA-F]+", "", repr(rung))
