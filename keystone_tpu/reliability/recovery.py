"""Process-wide recovery ledger: every retry, degradation, checkpoint hit,
and quarantined record lands here so a run can report HOW it survived, not
just that it did.

The log is module-global (like ``PipelineEnv``) and reset alongside it —
``PipelineEnv.reset()`` clears both, so tests stay isolated without a
second fixture.

Since the observability PR the ledger is also a *publisher*: every
``record()`` increments the ``keystone_reliability_events_total{kind=...}``
counter and, when a span session is active, attaches a
``reliability:<kind>`` event to the current span — so retries, ladder rung
transitions, and checkpoint save/restores show up inline in Chrome traces
and Prometheus snapshots, not only in ledger summaries
(docs/OBSERVABILITY.md; cross-linked from docs/RELIABILITY.md).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List

from ..obs import flight as _flight
from ..obs import names as _names
from ..obs import spans as _spans


@dataclass
class RecoveryEvent:
    kind: str  # "retry" | "degrade" | "checkpoint_hit" | "quarantine" | "fault"
    label: str
    detail: Dict[str, Any] = field(default_factory=dict)


class RecoveryLog:
    """Thread-safe append-only event list with a summarizing view."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: List[RecoveryEvent] = []

    def record(self, kind: str, label: str, **detail: Any) -> None:
        with self._lock:
            self._events.append(RecoveryEvent(kind, label, dict(detail)))
        # Publish beyond the ledger: counter always (cheap), span event
        # only under an active trace session (free otherwise).
        _names.metric(_names.RELIABILITY_EVENTS).inc(kind=kind)
        _spans.add_span_event(f"reliability:{kind}", label=label, **{
            k: v for k, v in detail.items()
            if isinstance(v, (bool, int, float, str))
        })
        # Flight recorder (obs/flight.py): ring-append, and crash-class
        # kinds (worker_crash, fault, refit_rollback, slo degrade) dump
        # the post-mortem artifact. Single global read when uninstalled.
        _flight.observe_ledger(kind, label, detail)

    def events(self, kind: str = None) -> List[RecoveryEvent]:
        with self._lock:
            return [e for e in self._events if kind is None or e.kind == kind]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def summary(self) -> Dict[str, Any]:
        """The shape run results embed: counts per kind plus compact events.

        ``quarantined_records`` sums record counts (one quarantine event may
        cover a whole batch of skipped records).
        """
        with self._lock:
            events = list(self._events)
        out: Dict[str, Any] = {
            "retries": sum(1 for e in events if e.kind == "retry"),
            "degradations": sum(1 for e in events if e.kind == "degrade"),
            "checkpoint_hits": sum(1 for e in events if e.kind == "checkpoint_hit"),
            "quarantined_records": sum(
                int(e.detail.get("count", 1)) for e in events if e.kind == "quarantine"
            ),
        }
        out["events"] = [
            {"kind": e.kind, "label": e.label, **e.detail} for e in events[-50:]
        ]
        return out


_log = RecoveryLog()


def get_recovery_log() -> RecoveryLog:
    return _log


def reset_recovery_log() -> None:
    _log.clear()


class QuarantineCounts:
    """Skip-and-quarantine tally shared by the data loaders: per-reason
    counts plus the first few offending names for the audit trail.
    Attach ``as_dict()`` to the returned dataset and ``publish`` the total
    into the recovery log so run results surface how many records a
    'successful' ingest actually dropped."""

    def __init__(self, max_examples: int = 8):
        self.counts: Dict[str, int] = {}
        self.examples: List[str] = []
        self._max_examples = max_examples
        # add() runs from loader thread pools (archive.py decodes on 8
        # workers); an unlocked read-modify-write would drop counts.
        self._lock = threading.Lock()

    def add(self, reason: str, name: str) -> None:
        with self._lock:
            self.counts[reason] = self.counts.get(reason, 0) + 1
            if len(self.examples) < self._max_examples:
                self.examples.append(name)

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    def as_dict(self) -> Dict[str, Any]:
        return {
            "quarantined": self.total,
            **self.counts,
            "examples": list(self.examples),
        }

    def publish(self, label: str, **extra: Any) -> None:
        if self.total:
            get_recovery_log().record(
                "quarantine", label, count=self.total,
                examples=list(self.examples), **self.counts, **extra,
            )
