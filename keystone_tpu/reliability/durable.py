"""Durable elastic fits: the mid-stream resume contract.

The reference got mid-job durability from Spark lineage — a killed job
re-ran and already-materialized blocks short-circuited. Our equivalents
so far cover the *edges* of a fit: completed prefixes restore from the
:class:`~keystone_tpu.reliability.checkpoint.CheckpointStore`, and the
refit state contract (refit/state.py) persists sufficient statistics
*between* folds. What neither covers is the inside of one long
``fit_stream``: a SIGKILL at chunk 4000 of 5000 used to discard every
chunk already folded, and a device lost from the mesh mid-fit had no
recovery path at all.

This module is the contract both recoveries share (docs/RELIABILITY.md
"Durable fits"):

- :class:`StreamCursor` — WHERE a streamed fit was: absolute chunk
  index, rows consumed, the compiled chunk geometry, and the identity
  fingerprints (dataset/labels content digests, featurize-chain digest,
  featurized width/dtype) that make resuming safe.
- :class:`ResumeEntry` — cursor + the mesh-independent
  :class:`~keystone_tpu.refit.state.StreamState` snapshot (per-shard
  partials already merged via the additive contract), persisted in the
  CheckpointStore under :func:`resume_key`.
- :func:`resume_key` is deliberately COARSER than the cursor's
  fingerprints: it names the logical fit (estimator × chain class ×
  row count) so a fresh process re-planning the same pipeline *finds*
  the entry — and the verifier (``verify_stream_resume``, KV306) then
  refuses it when any content fingerprint disagrees. Stale resume must
  be a loud refusal, never silent corruption.
- :class:`ShardLossError` — the mid-stream signal that a device left
  the mesh (raised by the ``parallel.shard_loss`` probe site); the
  streaming engine catches it, salvages surviving per-shard partials,
  and re-plans on the shrunken mesh (workflow/streaming.py).

The contract is solver-agnostic on purpose: envelopes carry an opaque
host-numpy carry (whatever ``kind`` the estimator accumulates), so the
sketch-state tier the ROADMAP names inherits durability for free.

Import discipline: stdlib + numpy only at module scope (same rule as
refit/state.py) — the control plane imports this without paying for a
backend.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..envknobs import env_int
from ..refit.state import FORMAT_VERSION, StreamState
from .checkpoint import _MISS
from .recovery import get_recovery_log

#: Resume-entry layout version — bumped independently of the StreamState
#: format; loads refuse unknown versions (a miss, never a mis-resume).
RESUME_FORMAT_VERSION = 1


class ShardLossError(RuntimeError):
    """A device left the mesh mid-stream. Raised at the
    ``parallel.shard_loss`` probe site (one call per sharded chunk
    dispatch) and caught by ``ChunkStream.fold``, which salvages the
    surviving shards' partials and continues on the shrunken mesh."""

    def __init__(self, lost_shard: int, chunk_index: int, shards: int):
        self.lost_shard = int(lost_shard)
        self.chunk_index = int(chunk_index)
        self.shards = int(shards)
        super().__init__(
            f"shard {lost_shard}/{shards} lost at chunk {chunk_index}"
        )


# ----------------------------------------------------------------- knobs


def stream_ckpt_chunks(n_rows: int) -> int:
    """Chunks between mid-fit checkpoint commits; 0 = off.

    ``KEYSTONE_STREAM_CKPT_CHUNKS`` set explicitly wins (0 disables even
    for huge fits). Unset, checkpointing auto-arms at every
    ``KEYSTONE_STREAM_CKPT_AUTO_EVERY`` (default 32) chunks once the
    dataset holds at least ``KEYSTONE_STREAM_CKPT_AUTO_ROWS`` rows
    (default 1e6) — small fits are cheaper to redo than to checkpoint.
    """
    explicit = env_int("KEYSTONE_STREAM_CKPT_CHUNKS", -1)
    if explicit >= 0:
        return explicit
    if n_rows >= env_int("KEYSTONE_STREAM_CKPT_AUTO_ROWS", 1_000_000):
        return max(1, env_int("KEYSTONE_STREAM_CKPT_AUTO_EVERY", 32))
    return 0


def shard_loss_index(shards: int) -> int:
    """Which shard a *simulated* loss removes (default: the last).
    ``KEYSTONE_SHARD_LOSS_INDEX`` overrides so tests can exercise the
    seed-bearing shard-0 path. Real device loss would carry the failed
    device's identity instead of this knob."""
    idx = env_int("KEYSTONE_SHARD_LOSS_INDEX", shards - 1)
    return min(max(idx, 0), shards - 1)


# ------------------------------------------------------------- identity


def content_digest(value: Any) -> str:
    """Process-stable content digest of a dataset/operator attribute —
    the checkpoint layer's ``_value_token`` hashed, so the rules (array
    content, dataset payload + length, scalar config) stay in one place."""
    from .checkpoint import _value_token

    return hashlib.sha1(repr(_value_token(value)).encode()).hexdigest()


#: Above this, array leaves fingerprint by shape/dtype + a deterministic
#: strided row sample instead of a full-content pass — the fits where
#: durability auto-arms are exactly the ones where an O(n·d) host hash
#: at plan time would betray the streaming path's no-full-pass design.
FULL_HASH_MAX_BYTES = 64 << 20
#: Rows sampled (first + last always included) for oversized leaves.
FINGERPRINT_SAMPLE_ROWS = 257


def dataset_fingerprint(ds: Any) -> str:
    """Process-stable fingerprint of a dataset for resume validation.

    Small payloads hash in full (identical to :func:`content_digest`
    semantics); array leaves past :data:`FULL_HASH_MAX_BYTES` hash their
    shape/dtype plus a deterministic evenly-strided row sample — bounded
    work at plan time, at the cost of missing a drift confined entirely
    to unsampled rows (a deliberate trade: KV306 is a stale-RESUME
    guard, not a data-integrity audit; the full-content prefix digests
    still govern completed-fit checkpoints)."""
    data = getattr(ds, "data", None)
    n = getattr(ds, "num_examples", None)
    if data is None or n is None:
        return content_digest(ds)
    h = hashlib.sha1(f"ds:n{int(n)}".encode())
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(data)
    except Exception:
        leaves = [data]
    for leaf in leaves:
        if not isinstance(leaf, np.ndarray):
            h.update(content_digest(leaf).encode())
            continue
        h.update(f"{leaf.dtype}{leaf.shape}".encode())
        if leaf.nbytes <= FULL_HASH_MAX_BYTES or leaf.ndim == 0:
            h.update(np.ascontiguousarray(leaf))
        else:
            rows = np.unique(
                np.linspace(
                    0, leaf.shape[0] - 1, FINGERPRINT_SAMPLE_ROWS
                ).astype(np.int64)
            )
            h.update(rows.tobytes())
            h.update(np.ascontiguousarray(leaf[rows]))
    return h.hexdigest()


def chain_digest(members: Tuple[Any, ...]) -> str:
    """Content digest of the featurize chain BETWEEN the data source and
    the estimator — operator class identity plus content-hashed state
    (weights included: a chain with different weights produces different
    features, so resuming across it would corrupt the fit)."""
    from .checkpoint import _op_token

    return hashlib.sha1(
        repr([_op_token(m) for m in members]).encode()
    ).hexdigest()


def resume_key(estimator: Any, members: Tuple[Any, ...], n_rows: int) -> str:
    """Checkpoint-store digest naming the LOGICAL fit. Coarser than the
    cursor's validation fingerprints by design (module docstring): same
    estimator class, same chain op sequence, same row count → same key,
    so a re-planned pipeline finds the entry and the KV306 validation
    gets to rule on whether the contents still agree."""
    from ..workflow.streaming import chain_class

    est = f"{type(estimator).__module__}.{type(estimator).__qualname__}"
    token = f"keystone-stream-resume:{est}:{chain_class(members)}:n{n_rows}"
    return hashlib.sha1(token.encode()).hexdigest()


# -------------------------------------------------------------- envelope


@dataclass
class StreamCursor:
    """Where a streamed fit stood when its state was committed."""

    chunk_index: int          # absolute chunks fully folded
    rows_consumed: int        # logical dataset rows those chunks held
    chunk_rows: int           # compiled chunk geometry (must match to resume)
    dataset_digest: str
    labels_digest: str
    chain_digest: str
    feature_width: int
    feature_dtype: str
    mesh_shape: Tuple[int, ...] = ()
    shards: int = 1
    # Layout METADATA only (like mesh_shape/shards): the snapshot carry
    # itself is always merged to the mesh-independent single-device
    # shape, so resume re-plans freely across 1-D and 2-D meshes.
    model_shards: int = 1


@dataclass
class ResumeEntry:
    """One persisted mid-fit snapshot: cursor + mesh-independent state."""

    cursor: StreamCursor
    state: StreamState
    #: rows the fold's SEED state held that did not come from this
    #: dataset (a refit-seeded fold); the resume arithmetic needs them
    #: separated from ``rows_consumed`` so totals stay exact.
    seed_rows: int = 0
    format_version: int = RESUME_FORMAT_VERSION


def save_resume_entry(store: Any, key: str, entry: ResumeEntry) -> bool:
    return store.save(None, entry, digest=key)


def load_resume_entry(store: Any, key: str) -> Optional[ResumeEntry]:
    """The persisted entry, or None (missing/torn/foreign versions are
    misses — the checkpoint-store contract)."""
    value = store.lookup(None, digest=key)
    if value is _MISS or not isinstance(value, ResumeEntry):
        return None
    if value.format_version != RESUME_FORMAT_VERSION:
        return None
    if value.state.format_version != FORMAT_VERSION:
        return None
    return value


def clear_resume_entry(store: Any, key: str) -> None:
    store.delete(key)


# --------------------------------------------------------- fold-side plan


@dataclass
class DurableFold:
    """The durability plan ``ChunkStream.fold`` executes (built by the
    streaming operator's arm step; ``None`` on a stream = today's
    behavior, byte for byte)."""

    store: Any                      # reliability CheckpointStore
    key: str                        # resume-entry digest
    kind: str                       # stream-state kind ("gram", ...)
    estimator: str                  # estimator qualname for the envelope
    ckpt_every: int                 # chunks between commits (0 = never)
    #: Extra envelope meta the committed StreamState must carry (e.g. the
    #: sketch tier's {sketch_variant, sketch_seed} — what a resumed fold
    #: needs to keep accumulating under the SAME sketch map).
    state_meta: Dict[str, Any] = field(default_factory=dict)
    fingerprints: Dict[str, Any] = field(default_factory=dict)
    start_chunk: int = 0            # chunks to skip (resumed fold)
    resume_rows: int = 0            # rows those skipped chunks held
    seed_rows: int = 0              # non-dataset rows in the seed state

    def cursor(
        self,
        chunk_index: int,
        rows_consumed: int,
        chunk_rows: int,
        mesh_shape: Tuple[int, ...],
        shards: int,
        model_shards: int = 1,
    ) -> StreamCursor:
        return StreamCursor(
            chunk_index=chunk_index,
            rows_consumed=rows_consumed,
            chunk_rows=chunk_rows,
            mesh_shape=tuple(mesh_shape),
            shards=shards,
            model_shards=model_shards,
            **self.fingerprints,
        )

    def commit(
        self,
        host_carry: Tuple[np.ndarray, ...],
        chunk_index: int,
        rows_consumed: int,
        chunk_rows: int,
        mesh_shape: Tuple[int, ...] = (),
        shards: int = 1,
        model_shards: int = 1,
    ) -> bool:
        """Persist one mid-fit snapshot (atomic tmp+rename underneath).
        Called by the fold with the carry ALREADY host-fetched and
        shard-merged — the commit-before-continue barrier is the fold's
        job; this is just the write. Best-effort: a failed write is
        ledgered and the fit continues (durability must never fail a
        fit that would have succeeded)."""
        state = StreamState(
            kind=self.kind,
            estimator=self.estimator,
            num_examples=int(self.seed_rows + rows_consumed),
            carry=tuple(np.asarray(a) for a in host_carry),
            meta={**self.state_meta, "durable": True},
        )
        entry = ResumeEntry(
            cursor=self.cursor(
                chunk_index, rows_consumed, chunk_rows, mesh_shape, shards,
                model_shards,
            ),
            state=state,
            seed_rows=self.seed_rows,
        )
        ok = save_resume_entry(self.store, self.key, entry)
        if ok:
            from ..obs import names as _names

            _names.metric(_names.DURABLE_CHECKPOINTS).inc()
            get_recovery_log().record(
                "stream_checkpoint",
                self.estimator,
                chunk_index=chunk_index,
                rows_consumed=rows_consumed,
                key=self.key[:12],
            )
        else:
            get_recovery_log().record(
                "stream_checkpoint_failed",
                self.estimator,
                chunk_index=chunk_index,
                key=self.key[:12],
            )
        return ok

    def complete(self) -> None:
        """The fit finished: a resume entry pointing into its middle
        must not outlive it (a later identical fit would 'resume' work
        that is already done and persisted whole by the prefix store)."""
        clear_resume_entry(self.store, self.key)


# -------------------------------------------------------------------- arming


def arm_durable_fold(
    stream: Any, estimator: Any, store: Any,
    ckpt_every: Optional[int] = None,
):
    """Build a stream's durability plan and, when a valid resume entry
    exists, the :class:`StreamState` that seeds the fold.

    Returns ``(durable, resume_state)`` — ``(None, None)`` when
    durability stays off (no store, checkpointing off for this size and
    no entry to resume). Called by ``StreamingFitOperator`` after the
    chunk geometry is final (partition rounding included).

    ``ckpt_every`` overrides the size-based :func:`stream_ckpt_chunks`
    cadence — the mesh scheduler arms checkpoints on folds far below the
    auto-arm row threshold because its preemption contract (yield at a
    chunk boundary, resume from the cursor) needs a committable cursor
    regardless of fold size (docs/SCHEDULING.md).

    Refusal ladder for an existing entry:

    - geometry drift (a re-planned/tuned ``chunk_rows`` that no longer
      matches the cursor's) — the entry is *discarded* with a
      ``resume_discard`` ledger event: chunk boundaries can't realign,
      but nothing is corrupt;
    - fingerprint drift (dataset/labels/chain content, featurized
      width/dtype) — the entry is *refused* via ``verify_stream_resume``
      (KV306): warn mode re-ingests from scratch, ``KEYSTONE_VERIFY=
      strict`` raises :class:`~keystone_tpu.workflow.verify.
      VerificationError` — stale resume is corruption, not a knob.
    """
    from ..obs import names as _names
    from ..workflow.verify import (
        VerificationError,
        verification_mode,
        verify_stream_resume,
    )

    members = stream.members
    n = stream.num_examples
    every = ckpt_every if ckpt_every is not None else stream_ckpt_chunks(n)
    key = resume_key(estimator, members, n)
    entry = load_resume_entry(store, key)
    if every <= 0 and entry is None:
        return None, None

    # Content fingerprints — the KV306 validation surface. feature_aval
    # raises StreamingFallback for unchunkable shapes, which the caller
    # already treats as "stream ineligible".
    import jax

    leaves = jax.tree_util.tree_leaves(stream.feature_aval())
    if len(leaves) == 1 and len(leaves[0].shape) == 2:
        width, dtype = int(leaves[0].shape[1]), str(leaves[0].dtype)
    else:
        width, dtype = -1, "|".join(str(l.dtype) for l in leaves)
    fingerprints = {
        "dataset_digest": dataset_fingerprint(stream.data),
        "labels_digest": dataset_fingerprint(stream.labels),
        "chain_digest": chain_digest(members),
        "feature_width": width,
        "feature_dtype": dtype,
    }
    # Meta-estimators pick their concrete rung per stream (width-based
    # ladder), so the committed state's kind/meta must come from the
    # CHOSEN rung, not the class default — the optional *_for(stream)
    # protocol resolves both after the geometry is final.
    kind_for = getattr(estimator, "stream_state_kind_for", None)
    kind = (
        kind_for(stream) if callable(kind_for)
        else getattr(estimator, "stream_state_kind", "gram")
    )
    meta_for = getattr(estimator, "stream_state_meta_for", None)
    if callable(meta_for):
        state_meta = dict(meta_for(stream) or {})
    else:
        state_meta = dict(getattr(estimator, "stream_state_meta", {}) or {})
    durable = DurableFold(
        store=store,
        key=key,
        kind=kind,
        estimator=f"{type(estimator).__module__}.{type(estimator).__qualname__}",
        ckpt_every=every,
        state_meta=state_meta,
        fingerprints=fingerprints,
    )
    if entry is None:
        return durable, None

    if entry.cursor.chunk_rows != stream.chunk_rows:
        get_recovery_log().record(
            "resume_discard",
            durable.estimator,
            reason="chunk-geometry-drift",
            entry_chunk_rows=entry.cursor.chunk_rows,
            planned_chunk_rows=stream.chunk_rows,
        )
        _names.metric(_names.DURABLE_RESUME_REFUSED).inc(reason="geometry")
        clear_resume_entry(store, key)
        return durable, None

    report = verify_stream_resume(entry.cursor, fingerprints)
    if not report.ok:
        get_recovery_log().record(
            "resume_refused",
            durable.estimator,
            codes=sorted({d.code for d in report.errors()}),
            fields=sorted(
                {str(d.details.get("field")) for d in report.errors()}
            ),
        )
        _names.metric(_names.DURABLE_RESUME_REFUSED).inc(reason="kv306")
        if verification_mode() == "strict":
            # Strict refuses the FIT, not the entry: the mismatch may be
            # THIS run's mistake (wrong dataset), and deleting here would
            # destroy the legitimate job's checkpoint work. Only the warn
            # path — which proceeds to a from-scratch refit that will
            # overwrite the entry anyway — retires it.
            raise VerificationError(report)
        clear_resume_entry(store, key)
        return durable, None

    durable.start_chunk = int(entry.cursor.chunk_index)
    durable.resume_rows = int(entry.cursor.rows_consumed)
    durable.seed_rows = int(entry.seed_rows)
    _names.metric(_names.DURABLE_RESUMES).inc(kind="crash")
    get_recovery_log().record(
        "stream_resume",
        durable.estimator,
        chunk_index=entry.cursor.chunk_index,
        rows_consumed=entry.cursor.rows_consumed,
        key=key[:12],
    )
    return durable, entry.state
