"""Deterministic fault injection: make any graph node (or any probed code
site) raise OOM, hang past a deadline, raise a transient error, or return
corrupt data on chosen calls — so every recovery path in this package is
exercised by ordinary tier-1 tests instead of waiting for a real
preemption.

Two integration points:

1. **Graph nodes** — ``GraphExecutor.execute`` wraps every node forcing
   with :meth:`FaultInjector.wrap` while an injector is active; specs
   match on the node's operator label.
2. **Probe sites** — long-running library code calls ``probe("site-name")``
   at its retryable boundaries (solver ladder attempts, ingest decode). A no-op (one global ``is None`` check) unless
   an injector is active, so production paths pay nothing.

Faults are deterministic: specs name exact 1-based call numbers (or a
``first_n`` prefix) per matched label, and the injector counts calls —
including retried ones, which is exactly what lets a test say "fail the
first two attempts, succeed on the third".

Process-level chaos (docs/RELIABILITY.md, docs/SERVING.md): the
``kill`` kind SIGKILLs the *current process* at a probed call — from
inside a serving worker that is a real ``kill -9`` mid-load, the crash
the :class:`~keystone_tpu.serving.supervisor.WorkerSupervisor` must
survive. Because the injector is per-process, specs cross the
supervisor → worker boundary through the environment:
:func:`specs_to_env` serializes a spec list to JSON and
:func:`install_from_env` (called by the worker at startup) installs a
process-lifetime injector from ``KEYSTONE_FAULT_SPECS``. Env-carried
specs can't ship a ``corrupt`` callable; the default corruption garbles
strings into non-JSON bytes, which at the worker's heartbeat site is
exactly the wire corruption the supervisor has to treat as a dead
heartbeat.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

FAULT_SPECS_ENV = "KEYSTONE_FAULT_SPECS"

from ..envknobs import env_raw
from .recovery import get_recovery_log


class InjectedOOM(RuntimeError):
    """Injected allocator failure; message classifies as OOM."""

    def __init__(self, label: str):
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected OOM at {label} (faultinject)"
        )


class InjectedTransient(ConnectionError):
    """Injected relay/coordinator failure; message classifies as transient."""

    def __init__(self, label: str):
        super().__init__(f"UNAVAILABLE: injected transient fault at {label}")


@dataclass(frozen=True)
class FaultSpec:
    """What to inject, where, and on which calls.

    ``match``   — substring of the node label / probe site ("*" = every site).
    ``kind``    — "oom" | "transient" | "hang" | "corrupt" | "kill".
    ``calls``   — exact 1-based call numbers to fault at.
    ``first_n`` — alternative to ``calls``: fault calls 1..first_n.
    ``hang_s``  — sleep length for kind="hang" (pair with a policy whose
                  ``deadline_s`` is shorter to exercise the watchdog; at a
                  worker's apply site a long hang IS the straggler fault).
    ``corrupt`` — value transform for kind="corrupt" (default NaN-fills
                  array leaves, the shape-preserving corruption an XLA
                  consumer actually notices; strings garble into non-JSON
                  bytes — the heartbeat-corruption fault).
    ``kind="kill"`` SIGKILLs the current process — un-catchable, exactly
    a ``kill -9`` of a serving worker mid-load.
    """

    match: str
    kind: str = "oom"
    calls: Tuple[int, ...] = (1,)
    first_n: Optional[int] = None
    hang_s: float = 60.0
    corrupt: Optional[Callable[[Any], Any]] = None

    def applies(self, label: str, call_number: int) -> bool:
        if self.match != "*" and self.match not in label:
            return False
        if self.first_n is not None:
            return call_number <= self.first_n
        return call_number in self.calls


def _nan_corrupt(value: Any) -> Any:
    # Strings garble into bytes that cannot parse as JSON (or decode as
    # UTF-8 text cleanly) — wire-level corruption for line protocols like
    # the serving worker's heartbeat channel.
    if isinstance(value, str):
        return "\x00garbled\x00" + value[::-1][: max(len(value) // 2, 1)]

    import numpy as np

    # Dataset-like wrappers (ArrayDataset & friends): poison the payload,
    # keep the wrapper type so downstream dispatch is unchanged.
    data = getattr(value, "data", None)
    if data is not None and hasattr(value, "num_examples"):
        try:
            return type(value)(_nan_corrupt(data), value.num_examples)
        except Exception:
            pass

    def poison(leaf):
        if hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
            arr = np.array(leaf, copy=True)
            if np.issubdtype(arr.dtype, np.floating):
                arr.fill(np.nan)
            return arr
        return leaf

    try:
        import jax

        return jax.tree_util.tree_map(poison, value)
    except Exception:
        return poison(value)


class FaultInjector:
    """Holds specs + per-label call counts; install via :func:`injected`."""

    def __init__(self, *specs: FaultSpec, sleep: Callable[[float], None] = time.sleep):
        self.specs = specs
        self._sleep = sleep
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def calls(self, label: str) -> int:
        with self._lock:
            return self._counts.get(label, 0)

    def _bump(self, label: str) -> int:
        with self._lock:
            self._counts[label] = self._counts.get(label, 0) + 1
            return self._counts[label]

    def check(self, label: str) -> None:
        """Raise/hang if a spec targets this call of ``label`` (corrupt
        specs are handled by :meth:`wrap`, which sees the value)."""
        n = self._bump(label)
        for spec in self.specs:
            if spec.kind == "corrupt" or not spec.applies(label, n):
                continue
            get_recovery_log().record(
                "fault", label, fault_kind=spec.kind, call_number=n
            )
            if spec.kind == "oom":
                raise InjectedOOM(label)
            if spec.kind == "transient":
                raise InjectedTransient(label)
            if spec.kind == "hang":
                self._sleep(spec.hang_s)
                return
            if spec.kind == "kill":
                # Flush whatever this process has said so far — the
                # supervisor's reader must see everything emitted BEFORE
                # the kill, and nothing after.
                import sys

                for stream in (sys.stdout, sys.stderr):
                    try:
                        stream.flush()
                    except Exception:
                        pass
                os.kill(os.getpid(), signal.SIGKILL)
            raise ValueError(f"unknown fault kind {spec.kind!r}")

    def wrap(self, label: str, thunk: Callable[[], Any]) -> Callable[[], Any]:
        def faulted():
            self.check(label)
            value = thunk()
            n = self.calls(label)
            for spec in self.specs:
                if spec.kind == "corrupt" and spec.applies(label, n):
                    get_recovery_log().record(
                        "fault", label, fault_kind="corrupt", call_number=n
                    )
                    value = (spec.corrupt or _nan_corrupt)(value)
            return value

        return faulted


_current: Optional[FaultInjector] = None

#: Every probe site the library exposes, by its exact label. The failure
#: suite (scripts/run_failure_suite.sh) and chaos specs target sites by
#: these names, so an unregistered ``probe("...")`` call is dead chaos
#: surface nobody can aim at — ``keystone-tpu check --lint`` (rule KV504,
#: docs/VERIFICATION.md) fails on any call whose label is missing here.
#: Registering a site is a one-line diff reviewed next to the code that
#: adds it.
KNOWN_PROBE_SITES = frozenset(
    {
        "serving.apply",               # serving/server.py: per-batch apply
        "serving.worker.request",      # serving/worker.py: request handling
        "serving.worker.heartbeat",    # serving/worker.py: heartbeat wire
        "streaming.chunk",             # workflow/streaming.py: per-chunk dispatch
        "parallel.shard_loss",         # workflow/streaming.py: sharded chunk plan —
                                       # a fault here models a device lost from the
                                       # mesh; the elastic fold recovers, never raises
        "refit.fold",                  # refit/daemon.py: incremental fold
        "refit.candidate",             # refit/daemon.py: candidate, post-eval
        "refit.publish",               # refit/publish.py: registry/fleet swap
        "ingest.decode_batch",         # data/loaders/archive.py: decode pool
        "BlockLeastSquaresEstimator.solve",
        "LeastSquaresEstimator.solve",
        "KernelRidgeRegression.solve",
        "sketch.finish",               # sketch/solvers.py: finish-solve ladder
                                       # (dual s×s ridge → lstsq fallback)
    }
)


def current() -> Optional[FaultInjector]:
    return _current


def probe(label: str) -> None:
    """Library-side injection point: no-op unless an injector is active."""
    injector = _current
    if injector is not None:
        injector.check(label)


@contextmanager
def injected(*specs: FaultSpec, sleep: Callable[[float], None] = time.sleep):
    """Activate a :class:`FaultInjector` for the dynamic extent of the
    block (process-wide — pipeline execution may cross threads)."""
    global _current
    if _current is not None:
        raise RuntimeError("fault injector already active (no nesting)")
    injector = FaultInjector(*specs, sleep=sleep)
    _current = injector
    try:
        yield injector
    finally:
        _current = None


# ------------------------------------------------------- cross-process specs

_ENV_FIELDS = ("match", "kind", "calls", "first_n", "hang_s")


def specs_to_env(specs: Tuple[FaultSpec, ...]) -> str:
    """Serialize specs for a child process's ``KEYSTONE_FAULT_SPECS``.
    ``corrupt`` callables don't cross the boundary — env-carried corrupt
    specs use the default corruption (NaN arrays / garbled strings)."""
    return json.dumps(
        [
            {k: getattr(s, k) for k in _ENV_FIELDS if getattr(s, k) is not None}
            for s in specs
        ]
    )


def specs_from_env(value: str) -> List[FaultSpec]:
    out = []
    for obj in json.loads(value):
        if "calls" in obj:
            obj["calls"] = tuple(int(c) for c in obj["calls"])
        out.append(FaultSpec(**obj))
    return out


def install_from_env(env_var: str = FAULT_SPECS_ENV) -> Optional[FaultInjector]:
    """Install a process-LIFETIME injector from the environment (no
    context manager — the process is the scope). Called by worker-process
    entry points before serving; a no-op when the variable is unset/empty
    or an injector is already active. Chaos-in-env is how the supervisor
    arms faults inside the worker it spawns."""
    global _current
    raw = (env_raw(env_var) or "").strip()
    if not raw or _current is not None:
        return None
    injector = FaultInjector(*specs_from_env(raw))
    _current = injector
    return injector
