"""Deterministic fault injection: make any graph node (or any probed code
site) raise OOM, hang past a deadline, raise a transient error, or return
corrupt data on chosen calls — so every recovery path in this package is
exercised by ordinary tier-1 tests instead of waiting for a real
preemption.

Two integration points:

1. **Graph nodes** — ``GraphExecutor.execute`` wraps every node forcing
   with :meth:`FaultInjector.wrap` while an injector is active; specs
   match on the node's operator label.
2. **Probe sites** — long-running library code calls ``probe("site-name")``
   at its retryable boundaries (solver ladder attempts, ingest decode). A no-op (one global ``is None`` check) unless
   an injector is active, so production paths pay nothing.

Faults are deterministic: specs name exact 1-based call numbers (or a
``first_n`` prefix) per matched label, and the injector counts calls —
including retried ones, which is exactly what lets a test say "fail the
first two attempts, succeed on the third".
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

from .recovery import get_recovery_log


class InjectedOOM(RuntimeError):
    """Injected allocator failure; message classifies as OOM."""

    def __init__(self, label: str):
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected OOM at {label} (faultinject)"
        )


class InjectedTransient(ConnectionError):
    """Injected relay/coordinator failure; message classifies as transient."""

    def __init__(self, label: str):
        super().__init__(f"UNAVAILABLE: injected transient fault at {label}")


@dataclass(frozen=True)
class FaultSpec:
    """What to inject, where, and on which calls.

    ``match``   — substring of the node label / probe site ("*" = every site).
    ``kind``    — "oom" | "transient" | "hang" | "corrupt".
    ``calls``   — exact 1-based call numbers to fault at.
    ``first_n`` — alternative to ``calls``: fault calls 1..first_n.
    ``hang_s``  — sleep length for kind="hang" (pair with a policy whose
                  ``deadline_s`` is shorter to exercise the watchdog).
    ``corrupt`` — value transform for kind="corrupt" (default NaN-fills
                  array leaves, the shape-preserving corruption an XLA
                  consumer actually notices).
    """

    match: str
    kind: str = "oom"
    calls: Tuple[int, ...] = (1,)
    first_n: Optional[int] = None
    hang_s: float = 60.0
    corrupt: Optional[Callable[[Any], Any]] = None

    def applies(self, label: str, call_number: int) -> bool:
        if self.match != "*" and self.match not in label:
            return False
        if self.first_n is not None:
            return call_number <= self.first_n
        return call_number in self.calls


def _nan_corrupt(value: Any) -> Any:
    import numpy as np

    # Dataset-like wrappers (ArrayDataset & friends): poison the payload,
    # keep the wrapper type so downstream dispatch is unchanged.
    data = getattr(value, "data", None)
    if data is not None and hasattr(value, "num_examples"):
        try:
            return type(value)(_nan_corrupt(data), value.num_examples)
        except Exception:
            pass

    def poison(leaf):
        if hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
            arr = np.array(leaf, copy=True)
            if np.issubdtype(arr.dtype, np.floating):
                arr.fill(np.nan)
            return arr
        return leaf

    try:
        import jax

        return jax.tree_util.tree_map(poison, value)
    except Exception:
        return poison(value)


class FaultInjector:
    """Holds specs + per-label call counts; install via :func:`injected`."""

    def __init__(self, *specs: FaultSpec, sleep: Callable[[float], None] = time.sleep):
        self.specs = specs
        self._sleep = sleep
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def calls(self, label: str) -> int:
        with self._lock:
            return self._counts.get(label, 0)

    def _bump(self, label: str) -> int:
        with self._lock:
            self._counts[label] = self._counts.get(label, 0) + 1
            return self._counts[label]

    def check(self, label: str) -> None:
        """Raise/hang if a spec targets this call of ``label`` (corrupt
        specs are handled by :meth:`wrap`, which sees the value)."""
        n = self._bump(label)
        for spec in self.specs:
            if spec.kind == "corrupt" or not spec.applies(label, n):
                continue
            get_recovery_log().record(
                "fault", label, fault_kind=spec.kind, call_number=n
            )
            if spec.kind == "oom":
                raise InjectedOOM(label)
            if spec.kind == "transient":
                raise InjectedTransient(label)
            if spec.kind == "hang":
                self._sleep(spec.hang_s)
                return
            raise ValueError(f"unknown fault kind {spec.kind!r}")

    def wrap(self, label: str, thunk: Callable[[], Any]) -> Callable[[], Any]:
        def faulted():
            self.check(label)
            value = thunk()
            n = self.calls(label)
            for spec in self.specs:
                if spec.kind == "corrupt" and spec.applies(label, n):
                    get_recovery_log().record(
                        "fault", label, fault_kind="corrupt", call_number=n
                    )
                    value = (spec.corrupt or _nan_corrupt)(value)
            return value

        return faulted


_current: Optional[FaultInjector] = None


def current() -> Optional[FaultInjector]:
    return _current


def probe(label: str) -> None:
    """Library-side injection point: no-op unless an injector is active."""
    injector = _current
    if injector is not None:
        injector.check(label)


@contextmanager
def injected(*specs: FaultSpec, sleep: Callable[[float], None] = time.sleep):
    """Activate a :class:`FaultInjector` for the dynamic extent of the
    block (process-wide — pipeline execution may cross threads)."""
    global _current
    if _current is not None:
        raise RuntimeError("fault injector already active (no nesting)")
    injector = FaultInjector(*specs, sleep=sleep)
    _current = injector
    try:
        yield injector
    finally:
        _current = None
