"""Fault-tolerant execution layer.

KeystoneML's fault tolerance was Spark's: RDD lineage recomputed lost
partitions, task retries absorbed flaky executors, and nobody had to name
a failure mode. The TPU-native executor has no lineage, so this package
makes failure handling explicit and test-injectable:

- :mod:`errors`      — the failure taxonomy (`classify_error`).
- :mod:`retry`       — `RetryPolicy` (classified retries, deterministic
                       backoff), `Deadline` / `run_with_deadline` /
                       `wait_until` watchdogs.
- :mod:`degrade`     — `DegradationLadder`: shrink block/batch sizes on
                       OOM, annotate results with what was given up.
- :mod:`checkpoint`  — persist fitted prefix state; a killed run resumes
                       past already-fit estimators in a fresh process.
- :mod:`durable`     — the mid-STREAM resume contract: `ResumeEntry` /
                       `StreamCursor` snapshots committed every K chunks
                       of a `fit_stream`, plus `ShardLossError` — the
                       shard-loss elasticity signal (docs/RELIABILITY.md
                       "Durable fits").
- :mod:`faultinject` — deterministic fault injection for tests.
- :mod:`recovery`    — the process-wide ledger of how a run survived.

Everything here is stdlib-only at import time (no jax) so bench.py and
launch scripts can import it before any backend initializes.

See docs/RELIABILITY.md for semantics and examples.
"""

from .checkpoint import CheckpointStore, enable_checkpointing, prefix_digest
from .degrade import DegradationLadder, LadderExhausted, halving_rungs
from .durable import (
    DurableFold,
    ResumeEntry,
    ShardLossError,
    StreamCursor,
    clear_resume_entry,
    load_resume_entry,
    resume_key,
    save_resume_entry,
)
from .errors import (
    CLASSIFICATION_TABLE,
    CorruptRecordError,
    DeadlineExceeded,
    ErrorClass,
    classify_error,
    is_oom,
)
from .faultinject import (
    FaultInjector,
    FaultSpec,
    InjectedOOM,
    InjectedTransient,
    injected,
    install_from_env,
    probe,
    specs_from_env,
    specs_to_env,
)
from .recovery import RecoveryLog, get_recovery_log, reset_recovery_log
from .retry import Deadline, RetryPolicy, run_with_deadline, wait_until

__all__ = [
    "CLASSIFICATION_TABLE",
    "CheckpointStore",
    "CorruptRecordError",
    "Deadline",
    "DeadlineExceeded",
    "DegradationLadder",
    "DurableFold",
    "ErrorClass",
    "FaultInjector",
    "FaultSpec",
    "InjectedOOM",
    "InjectedTransient",
    "LadderExhausted",
    "RecoveryLog",
    "ResumeEntry",
    "RetryPolicy",
    "ShardLossError",
    "StreamCursor",
    "classify_error",
    "clear_resume_entry",
    "enable_checkpointing",
    "load_resume_entry",
    "resume_key",
    "save_resume_entry",
    "get_recovery_log",
    "halving_rungs",
    "injected",
    "install_from_env",
    "is_oom",
    "prefix_digest",
    "probe",
    "reset_recovery_log",
    "run_with_deadline",
    "specs_from_env",
    "specs_to_env",
    "wait_until",
]
