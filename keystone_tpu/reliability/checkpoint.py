"""Checkpoint / restore of fitted pipeline state.

The reference got resumability from Spark lineage: a killed job re-ran,
and already-materialized RDD blocks short-circuited recomputation. Here
the equivalent unit is the ``PipelineEnv`` prefix table — fitted estimator
outputs keyed by the structural prefix of everything that produced them.
This module persists those fitted transformers to disk so a killed run,
restarted in a FRESH process, resumes past already-fit prefixes instead of
refitting them.

The in-memory table keys on :class:`~keystone_tpu.workflow.prefix.Prefix`,
whose operators hash by object identity — useless across processes. The
on-disk key is a *stable digest* of the same tree: operator class identity
plus content-hashed state (ndarray bytes, dataset payloads, scalar config).
Two structurally identical pipelines built in different processes over
equal data produce equal digests; any attribute change (different reg,
different training data) changes the digest and forces a refit.

Values are pickled fitted transformers (the same contract as
``FittedPipeline.save``). Writes are atomic (tmp + rename) so a kill
mid-checkpoint never leaves a truncated entry — a torn file is treated as
a miss and refit.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import pickle
import tempfile
import threading
from typing import Any, Callable, Dict, Optional

from ..obs import names as _names
from .recovery import get_recovery_log

_MISS = object()


def _store_counters():
    return (
        _names.metric(_names.CHECKPOINT_HITS),
        _names.metric(_names.CHECKPOINT_MISSES),
        _names.metric(_names.CHECKPOINT_WRITES),
    )


# ------------------------------------------------------------ stable digests

_token_memo_local = threading.local()


@contextlib.contextmanager
def token_memo():
    """Memoize expensive :func:`_value_token` results by object identity
    for the duration of one multi-node digest pass.

    Digesting N node prefixes of one pipeline re-tokenizes the SAME
    dataset object N times — each pass content-hashes the full training
    matrix (or worse, ``collect()``s an ObjectDataset). Within a single
    plan the objects are unchanged, so the autocache warm-start loop
    wraps its digest pass in this scope and pays each hash once. The memo
    holds a strong reference to every memoized value, which also pins its
    ``id`` against reuse; it dies with the scope, so nothing outlives the
    plan. Nested scopes reuse the outermost memo."""
    fresh = getattr(_token_memo_local, "memo", None) is None
    if fresh:
        _token_memo_local.memo = {}
    try:
        yield
    finally:
        if fresh:
            _token_memo_local.memo = None


def _value_token(value: Any) -> Any:
    """Deterministic, process-independent token for an operator attribute."""
    if value is None or isinstance(value, (bool, int, str)):
        return ("s", repr(value))
    if isinstance(value, float):
        return ("f", value.hex())
    memo = getattr(_token_memo_local, "memo", None)
    if memo is not None:
        hit = memo.get(id(value))
        if hit is not None and hit[0] is value:
            return hit[1]
        token = _value_token_uncached(value)
        memo[id(value)] = (value, token)
        return token
    return _value_token_uncached(value)


def _value_token_uncached(value: Any) -> Any:
    if isinstance(value, bytes):
        return ("b", hashlib.sha1(value).hexdigest())
    if isinstance(value, (list, tuple)):
        return ("t", tuple(_value_token(v) for v in value))
    if isinstance(value, (set, frozenset)):
        # Explicit sorted branch: set iteration order follows per-process
        # PYTHONHASHSEED, so letting sets reach the pickle fallback would
        # silently defeat cross-process resume.
        return ("set", tuple(sorted(repr(_value_token(v)) for v in value)))
    if isinstance(value, dict):
        return (
            "d",
            tuple(sorted((repr(k), _value_token(v)) for k, v in value.items())),
        )
    if callable(value) and hasattr(value, "__qualname__"):
        return ("fn", getattr(value, "__module__", ""), value.__qualname__)
    # Array-likes (numpy / jax / anything with shape+dtype): content hash.
    # sha1 consumes the array's buffer directly — tobytes() would make a
    # second full copy of a possibly multi-GB training matrix.
    if hasattr(value, "dtype") and hasattr(value, "shape"):
        import numpy as np

        arr = np.ascontiguousarray(np.asarray(value))
        return (
            "arr",
            str(arr.dtype),
            tuple(arr.shape),
            hashlib.sha1(arr).hexdigest(),
        )
    # Datasets: payload token + logical length.
    data = getattr(value, "data", None)
    if data is not None and hasattr(value, "num_examples"):
        return ("ds", _value_token(data), int(value.num_examples))
    if hasattr(value, "items") and hasattr(value, "collect"):
        try:
            return ("ods", tuple(_value_token(v) for v in value.collect()))
        except Exception:
            pass
    try:
        return ("pkl", hashlib.sha1(pickle.dumps(value)).hexdigest())
    except Exception:
        # Last resort: type identity only. Weaker than content hashing but
        # still process-stable; collisions across *differently configured*
        # operators of the same class are possible only when every other
        # attribute also matches.
        return ("type", type(value).__module__, type(value).__qualname__)


def _op_token(op: Any) -> Any:
    attrs = tuple(
        sorted(
            (name, _value_token(v))
            for name, v in vars(op).items()
            if not name.startswith("_")
        )
    )
    return ("op", type(op).__module__, type(op).__qualname__, attrs)


def prefix_digest(prefix: Any) -> str:
    """Stable hex digest of a :class:`Prefix`'s operator tree."""

    def walk(tree):
        op, children = tree
        return (_op_token(op), tuple(walk(c) for c in children))

    token = walk(prefix.tree)
    return hashlib.sha1(repr(token).encode()).hexdigest()


# ------------------------------------------------------------------- store


class CheckpointStore:
    """Directory of ``<digest>.pkl`` fitted-state entries with hit/miss
    accounting. Lookups tolerate torn/unreadable entries (treated as
    misses); writes are atomic."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.hits = 0
        self.misses = 0
        self.writes = 0

    def _entry(self, digest: str) -> str:
        return os.path.join(self.path, f"{digest}.pkl")

    def lookup(self, prefix: Any, digest: Optional[str] = None) -> Any:
        """Stored value for ``prefix``, or the module ``_MISS`` sentinel.
        Pass ``digest`` when already computed — digesting walks the prefix
        tree and content-hashes its datasets, which is not free."""
        hits_c, misses_c, _ = _store_counters()
        entry = self._entry(digest or prefix_digest(prefix))
        if not os.path.exists(entry):
            self.misses += 1
            misses_c.inc()
            return _MISS
        try:
            with open(entry, "rb") as f:
                value = pickle.load(f)
        except Exception:
            self.misses += 1
            misses_c.inc()
            return _MISS
        self.hits += 1
        hits_c.inc()
        return value

    def save(self, prefix: Any, value: Any, digest: Optional[str] = None) -> bool:
        """Persist ``value`` under ``prefix``; returns False (and leaves no
        entry) when the value isn't picklable — unpicklable fits simply
        don't resume."""
        digest = digest or prefix_digest(prefix)
        try:
            blob = pickle.dumps(value)
        except Exception:
            return False
        fd, tmp = tempfile.mkstemp(dir=self.path, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, self._entry(digest))
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        self.writes += 1
        _store_counters()[2].inc()
        return True

    def delete(self, digest: str) -> bool:
        """Remove an entry by digest (used by the durable-fit layer to
        retire resume entries and round journals once the work they
        describe completed). Missing entries are a no-op."""
        try:
            os.unlink(self._entry(digest))
            return True
        except OSError:
            return False

    def get_or_compute(
        self, prefix: Any, thunk: Callable[[], Any], label: str = "node"
    ) -> Any:
        digest = prefix_digest(prefix)  # once per force: lookup + save share it
        value = self.lookup(prefix, digest=digest)
        if value is not _MISS:
            get_recovery_log().record("checkpoint_hit", label, digest=digest[:12])
            return value
        value = thunk()
        if self.save(prefix, value, digest=digest):
            # Saves are recovery-relevant state changes too: a resumed run
            # reads them back, so surface them next to hits in traces.
            get_recovery_log().record("checkpoint_save", label, digest=digest[:12])
        return value

    def stats(self) -> Dict[str, int]:
        return {"hits": self.hits, "misses": self.misses, "writes": self.writes}


def enable_checkpointing(path: str, env: Optional[Any] = None) -> CheckpointStore:
    """Attach a :class:`CheckpointStore` at ``path`` to the process
    ``PipelineEnv`` (or a given env). Subsequent estimator fits write
    through; fits whose prefix digest is already on disk are restored
    without refitting."""
    from ..workflow.executor import PipelineEnv

    env = env or PipelineEnv.get_or_create()
    store = CheckpointStore(path)
    env.checkpoint = store
    return store
