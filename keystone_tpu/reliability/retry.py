"""Retry engine: classified retries, exponential backoff with deterministic
jitter, and per-call execution deadlines.

The replacement for Spark's task-level retry (``spark.task.maxFailures``)
that the reference leaned on: here the unit of retry is one graph-node
forcing (or any callable), the decision to retry comes from
``errors.classify_error``, and hung work — which Spark's scheduler would
have speculatively re-launched — is bounded by a deadline watchdog.

Jitter is drawn from a ``random.Random`` seeded per ``call`` (policy
``seed``), so a backoff schedule is reproducible in tests and two policies
with different seeds decorrelate their retry storms in production.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, List, Optional, Tuple

from .errors import DeadlineExceeded, ErrorClass, classify_error
from .recovery import get_recovery_log


class Deadline:
    """A fixed point in (monotonic) time work must finish by."""

    def __init__(self, seconds: float, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._expires = clock() + seconds

    @classmethod
    def after(cls, seconds: float, **kw) -> "Deadline":
        return cls(seconds, **kw)

    def remaining(self) -> float:
        return self._expires - self._clock()

    def expired(self) -> bool:
        return self.remaining() <= 0.0


def run_with_deadline(fn: Callable[[], Any], seconds: float, label: str = "work") -> Any:
    """Run ``fn()`` in a watchdog-joined worker thread; raise
    :class:`DeadlineExceeded` if it runs past ``seconds``.

    Python can't kill a thread, so on timeout the worker is abandoned
    (daemon) — same contract as a hung XLA dispatch: the caller moves on,
    the stuck work dies with the process. Use only around units of work
    whose results are idempotent to recompute (graph-node forcings are).
    """
    box: List[Any] = []
    error: List[BaseException] = []

    def worker():
        try:
            box.append(fn())
        except BaseException as e:  # propagated below, incl. KeyboardInterrupt
            error.append(e)

    t = threading.Thread(target=worker, daemon=True, name=f"deadline-{label}")
    t.start()
    t.join(seconds)
    if t.is_alive():
        raise DeadlineExceeded(
            f"{label}: execution deadline of {seconds:g}s exceeded (worker abandoned)"
        )
    if error:
        raise error[0]
    return box[0]


def wait_until(
    predicate: Callable[[], Any],
    deadline: Deadline,
    interval: float = 0.1,
    label: str = "condition",
    sleep: Callable[[float], None] = time.sleep,
) -> Any:
    """Poll ``predicate`` until it returns truthy; :class:`DeadlineExceeded`
    if the deadline passes first — the generic poll-with-deadline
    primitive for launch scripts and external-resource waits."""
    while True:
        value = predicate()
        if value:
            return value
        left = deadline.remaining()
        if left <= 0:
            raise DeadlineExceeded(f"{label}: not satisfied within deadline")
        sleep(min(interval, max(left, 0.0)))


@dataclass(frozen=True)
class RetryPolicy:
    """Classified retry with exponential backoff.

    ``retry_on`` defaults to transient + deadline failures only: retrying an
    OOM at the same shape re-OOMs (that's ``DegradationLadder``'s job), and
    permanent errors must propagate on the first attempt.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.1
    max_delay_s: float = 30.0
    multiplier: float = 2.0
    jitter: float = 0.25  # ± fraction of the computed delay
    seed: Optional[int] = 0  # None → nondeterministic jitter
    retry_on: Tuple[ErrorClass, ...] = (ErrorClass.TRANSIENT, ErrorClass.DEADLINE)
    deadline_s: Optional[float] = None  # per-attempt execution deadline
    sleep: Callable[[float], None] = field(default=time.sleep, repr=False)

    def with_(self, **kw) -> "RetryPolicy":
        return replace(self, **kw)

    def backoff_schedule(self, attempts: Optional[int] = None) -> List[float]:
        """The delays ``call`` would sleep between attempts — deterministic
        for a given seed, so tests can assert it and operators can read it."""
        rng = random.Random(self.seed)
        n = (attempts if attempts is not None else self.max_attempts) - 1
        return [self._delay(i, rng) for i in range(max(n, 0))]

    def _delay(self, retry_index: int, rng: random.Random) -> float:
        delay = min(self.base_delay_s * (self.multiplier**retry_index), self.max_delay_s)
        if self.jitter:
            delay *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(delay, 0.0)

    def call(
        self,
        fn: Callable[..., Any],
        *args: Any,
        label: str = None,
        deadline: Optional[Deadline] = None,
        **kwargs: Any,
    ) -> Any:
        """Invoke ``fn(*args, **kwargs)`` under this policy.

        Each attempt runs under ``deadline_s`` (when set). A failure is
        classified; classes outside ``retry_on`` — and the final attempt —
        re-raise unchanged. Retries are recorded in the recovery log.

        ``deadline`` bounds the WHOLE retry loop by the caller's budget:
        once backing off + retrying cannot finish inside what remains of
        the deadline, the last error re-raises instead of retrying past
        it (a serving request's retry clock must never outlive the
        request — docs/SERVING.md). The retry budget and the per-attempt
        ``deadline_s`` watchdog compose: one bounds attempts, the other
        bounds the loop.
        """
        label = label or getattr(fn, "__name__", "call")
        rng = random.Random(self.seed)
        for attempt in range(1, self.max_attempts + 1):
            try:
                if self.deadline_s is not None:
                    return run_with_deadline(
                        lambda: fn(*args, **kwargs), self.deadline_s, label=label
                    )
                return fn(*args, **kwargs)
            except BaseException as exc:
                error_class = classify_error(exc)
                if error_class not in self.retry_on or attempt >= self.max_attempts:
                    raise
                delay = self._delay(attempt - 1, rng)
                if deadline is not None and deadline.remaining() <= delay:
                    get_recovery_log().record(
                        "retry_abandoned",
                        label,
                        attempt=attempt,
                        error_class=error_class.value,
                        remaining_s=round(max(deadline.remaining(), 0.0), 4),
                        delay_s=round(delay, 4),
                    )
                    raise
                get_recovery_log().record(
                    "retry",
                    label,
                    attempt=attempt,
                    error_class=error_class.value,
                    error=f"{type(exc).__name__}: {exc}"[:200],
                    delay_s=round(delay, 4),
                )
                self.sleep(delay)
