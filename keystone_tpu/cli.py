"""Command-line workload runner.

The scopt analog (reference: each workload object carries an
``OptionParser`` over its config case class, e.g.
pipelines/images/cifar/RandomPatchCifar.scala:101-114,
pipelines/images/imagenet/ImageNetSiftLcsFV.scala:171-207). Here one
argparse subcommand per workload is generated from the workload's config
dataclass: field names become ``--flags``, field types become parsers,
dataclass defaults become defaults — so pipeline authors only declare the
dataclass, exactly as reference authors only declared the case class.

Mesh/runtime knobs the reference put in the launcher environment
(KEYSTONE_MEM, OMP_NUM_THREADS; reference: bin/run-pipeline.sh:9-42) map
to ``--platform`` / ``--device-count`` here.

Usage:
    python -m keystone_tpu <workload> [--flag value ...]
    python -m keystone_tpu --list
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import sys
import typing
from typing import Any, Callable, Dict, Optional, Tuple


def _field_parser(field_type: Any) -> Optional[Callable[[str], Any]]:
    """Map a dataclass field annotation to an argparse type callable."""
    origin = typing.get_origin(field_type)
    if origin is typing.Union:  # Optional[T]
        args = [a for a in typing.get_args(field_type) if a is not type(None)]
        return _field_parser(args[0]) if len(args) == 1 else str
    if origin in (tuple, Tuple):
        inner = typing.get_args(field_type)

        def parse_tuple(text: str):
            parts = [p for p in text.replace("x", ",").split(",") if p]
            caster = inner[0] if inner else int
            return tuple(caster(p) for p in parts)

        return parse_tuple
    if field_type is bool:
        return lambda s: s.lower() in ("1", "true", "yes")
    if field_type in (int, float, str):
        return field_type
    return None


def add_config_arguments(parser: argparse.ArgumentParser, config_cls) -> None:
    """Generate ``--flag`` options from a config dataclass."""
    for field in dataclasses.fields(config_cls):
        caster = _field_parser(field.type if not isinstance(field.type, str)
                               else typing.get_type_hints(config_cls)[field.name])
        if caster is None:
            continue
        default = (
            field.default
            if field.default is not dataclasses.MISSING
            else field.default_factory()  # type: ignore[misc]
        )
        parser.add_argument(
            "--" + field.name.replace("_", "-"),
            dest=field.name,
            type=caster,
            default=default,
            help=f"(default: {default!r})",
        )


def build_config(config_cls, args: argparse.Namespace):
    names = {f.name for f in dataclasses.fields(config_cls)}
    return config_cls(**{k: v for k, v in vars(args).items() if k in names})


def add_refit_arguments(parser: argparse.ArgumentParser) -> None:
    """Flags for ``keystone-tpu refit`` — wired here (stdlib-only) so the
    CLI's --help/--list paths never import the refit/workflow packages
    (whose fold path imports jax); ``refit.daemon.refit_from_args``
    consumes the parsed namespace at dispatch time."""
    parser.add_argument(
        "--rounds", type=int, default=6,
        help="drifting-workload rounds to run",
    )
    parser.add_argument(
        "--dim", type=int, default=16, help="synthetic feature width",
    )
    parser.add_argument(
        "--classes", type=int, default=4, help="synthetic class count",
    )
    parser.add_argument(
        "--rows-per-round", type=int, default=1024,
        help="labeled rows fed to the tap per round",
    )
    parser.add_argument(
        "--serve-requests", type=int, default=192,
        help="live requests served through the pipeline per round",
    )
    parser.add_argument(
        "--chunk-rows", type=int, default=256,
        help="chunk rows for the incremental fold",
    )
    parser.add_argument(
        "--drift", type=float, default=0.2,
        help="per-round drift of the true weights",
    )
    parser.add_argument(
        "--quiet-round", type=int, default=2,
        help="round that feeds too few rows (a ledgered skip); 0 disables",
    )
    parser.add_argument(
        "--bad-round", type=int, default=4,
        help="round whose candidate is corrupted post-eval (exercises "
        "auto-rollback); 0 disables",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--store-dir", default=None,
        help="checkpoint-store directory for the stream state "
        "(default: a fresh temp dir)",
    )
    parser.add_argument(
        "--watch-gate", choices=("margin", "sequential"),
        default="margin", dest="watch_gate",
        help="post-publish watch rule: fixed margin floor, or the "
        "anytime-valid sequential gate (docs/OBSERVABILITY.md "
        "\"Quality plane\")",
    )
    parser.add_argument(
        "--adaptive-decay", action="store_true", dest="adaptive_decay",
        help="let the quality plane's drift detector shrink state_decay "
        "under detected score drift",
    )


def add_fit_arguments(parser: argparse.ArgumentParser) -> None:
    """Flags for ``keystone-tpu fit`` — wired here (stdlib-only) so the
    CLI's --help/--list paths never import the workflow package (whose
    __init__ imports jax); ``workflow.fitcmd.fit_from_args`` consumes
    the parsed namespace at dispatch time."""
    parser.add_argument(
        "--rows", type=int, default=1024, help="synthetic training rows",
    )
    parser.add_argument(
        "--dim", type=int, default=16, help="synthetic feature width",
    )
    parser.add_argument(
        "--classes", type=int, default=3, help="synthetic label width",
    )
    parser.add_argument(
        "--chunk-rows", type=int, default=128,
        help="streamed chunk rows (pinned so resume cursors align "
        "across processes)",
    )
    parser.add_argument(
        "--ckpt-chunks", type=int, default=None,
        help="chunks between mid-fit checkpoint commits "
        "(default KEYSTONE_STREAM_CKPT_CHUNKS; 0 disables)",
    )
    parser.add_argument("--reg", type=float, default=1e-3)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--store-dir", required=True,
        help="checkpoint-store directory (resume entries + fitted "
        "prefixes live here)",
    )
    parser.add_argument(
        "--out", default=None,
        help="write fitted predictions on the fixed probe batch here "
        "(.npz; the smoke's parity artifact)",
    )
    parser.add_argument(
        "--expect-resume", action="store_true",
        help="exit 2 unless this fit resumed from a persisted cursor",
    )
    parser.add_argument(
        "--drift-data", type=float, default=0.0,
        help="perturb the training matrix by this constant (same shape, "
        "different content — the seeded KV306 stale-resume case)",
    )
    parser.add_argument(
        "--solver", choices=("gram", "sketch"), default="gram",
        help="streamed state family: 'gram' accumulates the O(d²) "
        "sufficient statistics, 'sketch' the O(s·d) randomized sketch "
        "(docs/SOLVERS.md — the very-wide rung under test in "
        "scripts/sketch_smoke.sh)",
    )


def add_explain_arguments(parser: argparse.ArgumentParser) -> None:
    """Flags for ``keystone-tpu explain`` — wired here (stdlib-only) so
    --help/--list never import the workflow package (whose __init__
    imports jax); ``workflow.explain.explain_from_args`` consumes the
    parsed namespace at dispatch time."""
    parser.add_argument(
        "--pipeline", default="synthetic", metavar="PATH|synthetic",
        help="FittedPipeline.save artifact to explain, or 'synthetic' "
        "(featurize chain + block solve under the auto-cache optimizer)",
    )
    parser.add_argument(
        "--rows", type=int, default=2048,
        help="synthetic training rows (fit cost scales with this)",
    )
    parser.add_argument(
        "--dim", type=int, default=64,
        help="feature width: the synthetic pipeline's, or — for "
        "--pipeline PATH — the loaded artifact's expected input width "
        "(the eval batch is built at this width)",
    )
    parser.add_argument(
        "--classes", type=int, default=4, help="synthetic label width",
    )
    parser.add_argument(
        "--passes", type=int, default=3,
        help="plan executions: pass 1 pays compiles (cold, never "
        "drift-scored), later passes measure steady state",
    )
    parser.add_argument(
        "--seed-drift", type=float, default=0.0, metavar="FACTOR",
        help="corrupt stored autocache measurements by FACTOR× before "
        "running (CI negative control: the drift sentinel must flag it)",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--out", default=None, help="write report JSON here")
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="print EXPLAIN_JSON: line instead of the human table",
    )
    parser.add_argument(
        "--schedule", action="store_true",
        help="run the co-scheduled serving+refit demo and print the mesh "
        "schedule instead: per lease — who ran, what displaced or "
        "deferred it, predicted vs measured wall, price provenance "
        "(docs/SCHEDULING.md)",
    )


def add_tune_arguments(parser: argparse.ArgumentParser) -> None:
    """Flags for ``keystone-tpu tune`` — wired here (stdlib-only) so the
    CLI's --help/--list paths never import the workflow package (whose
    __init__ imports jax); ``workflow.tune.tune_from_args`` consumes the
    parsed namespace at dispatch time."""
    parser.add_argument(
        "--tasks", default="stream,solver,blocksparse",
        help="comma-separated tune tasks (stream, solver, blocksparse)",
    )
    parser.add_argument(
        "--rows", type=int, default=8192,
        help="synthetic problem rows (pick the shape class you serve)",
    )
    parser.add_argument(
        "--dim", type=int, default=256, help="synthetic feature width",
    )
    parser.add_argument(
        "--classes", type=int, default=4, help="synthetic label width",
    )
    parser.add_argument(
        "--budget", type=int, default=None,
        help="max measured candidates per task (default KEYSTONE_TUNE_BUDGET)",
    )
    parser.add_argument(
        "--seed", type=int, default=None,
        help="exploration seed (default KEYSTONE_TUNE_SEED)",
    )
    parser.add_argument(
        "--time-budget-s", type=float, default=None,
        help="per-task wall budget (default KEYSTONE_TUNE_TIME_S)",
    )
    parser.add_argument("--out", default=None, help="write result JSON here")


# ----------------------------------------------------------------- registry


# name → (module, config class name, run callable name, kwargs, description).
# Static strings only: --list and help must not import jax/pipelines.
WORKLOADS: Dict[str, Tuple[str, str, str, Dict[str, Any], str]] = {
    "mnist-random-fft": (
        "mnist_random_fft", "MnistRandomFFTConfig", "run", {},
        "MNIST random-FFT featurization + linear solve",
    ),
    "timit": (
        "timit", "TimitConfig", "run", {},
        "TIMIT cosine random features + block solve",
    ),
    "voc-sift-fisher": (
        "voc", "SIFTFisherConfig", "run", {},
        "VOC 2007 SIFT + Fisher Vector + block least squares",
    ),
    "imagenet-sift-lcs-fv": (
        "imagenet", "ImageNetSiftLcsFVConfig", "run", {},
        "ImageNet dual-branch SIFT+LCS Fisher Vector pipeline",
    ),
    "imagenet-native": (
        "imagenet", "ImageNetSiftLcsFVConfig", "run_native_resolution", {},
        "ImageNet SIFT+LCS+FV with per-image native-resolution featurization",
    ),
    "imagenet-native-streaming": (
        "imagenet_streaming", "ImageNetSiftLcsFVConfig",
        "run_native_resolution_streaming", {},
        "Native-resolution flagship via the fused streaming path (at-scale)",
    ),
    "amazon-reviews": (
        "text", "AmazonReviewsConfig", "run_amazon", {},
        "Amazon reviews n-gram logistic/LBFGS text pipeline",
    ),
    "newsgroups": (
        "text", "NewsgroupsConfig", "run_newsgroups", {},
        "20 Newsgroups n-gram naive-bayes/least-squares pipeline",
    ),
    "stupid-backoff": (
        "stupid_backoff", "StupidBackoffConfig", "run", {},
        "Stupid Backoff n-gram language model",
    ),
    **{
        "cifar-" + v.replace("_", "-"): (
            "cifar", "RandomCifarConfig", "run", {"variant": v},
            f"CIFAR-10 {v} workload",
        )
        for v in (
            "linear_pixels", "random", "random_patch", "random_patch_fused",
            "random_patch_kernel", "random_patch_augmented",
            "random_patch_kernel_augmented",
        )
    },
}


def _resolve(name: str) -> Tuple[Any, Callable[..., dict]]:
    """Import one workload's module and bind (config_cls, run_fn)."""
    import importlib

    module_name, config_name, run_name, kwargs, _desc = WORKLOADS[name]
    module = importlib.import_module(
        f".pipelines.{module_name}", package="keystone_tpu"
    )
    config_cls = getattr(module, config_name)
    run_fn = getattr(module, run_name)
    if kwargs:
        bound = run_fn

        def run_fn(config, _bound=bound, _kw=kwargs):
            return _bound(config, **_kw)

    return config_cls, run_fn


def _apply_platform_flags(argv: list) -> None:
    """Apply --platform / --device-count from raw argv before jax loads."""
    import os

    def flag_value(flag: str) -> Optional[str]:
        for i, a in enumerate(argv):
            if a == flag and i + 1 < len(argv):
                return argv[i + 1]
            if a.startswith(flag + "="):
                return a.split("=", 1)[1]
        return None

    from .envknobs import env_str

    device_count = flag_value("--device-count")
    if device_count:
        flags = env_str("XLA_FLAGS")
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={device_count}"
        ).strip()
    platform = flag_value("--platform")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)


def main(argv: Optional[list] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = argparse.ArgumentParser(
        prog="keystone_tpu",
        description="TPU-native ML pipeline framework — workload runner",
    )
    parser.add_argument("--list", action="store_true", help="list workloads")
    parser.add_argument(
        "--platform",
        default=None,
        help="force a JAX platform (cpu/tpu) before device init",
    )
    parser.add_argument(
        "--device-count",
        type=int,
        default=None,
        help="virtual CPU device count (XLA_FLAGS host platform override)",
    )
    parser.add_argument("--log-level", default="INFO")
    sub = parser.add_subparsers(dest="workload")

    # Platform knobs must land before anything imports jax — pre-scan argv
    # since resolving the selected workload imports its pipeline module.
    _apply_platform_flags(argv)

    # Only the selected workload's module is imported; --list and top-level
    # --help stay jax-free.
    selected = next((a for a in argv if a in WORKLOADS), None)
    resolved: Dict[str, Tuple[Any, Callable[..., dict]]] = {}
    for name, entry in WORKLOADS.items():
        sp = sub.add_parser(name, help=entry[-1])
        if name == selected:
            config_cls, run_fn = _resolve(name)
            resolved[name] = (config_cls, run_fn)
            add_config_arguments(sp, config_cls)

    # The online serving front-end (docs/SERVING.md): JSON requests on
    # stdin, responses on stdout. Flag wiring is plain argparse from the
    # serving package (stdlib-only import — help stays jax-free).
    from .serving.server import add_serve_arguments

    serve_parser = sub.add_parser(
        "serve",
        help="serve a fitted pipeline: micro-batched inference over stdin/JSON",
    )
    add_serve_arguments(serve_parser)

    # Observability harness (docs/OBSERVABILITY.md): run the synthetic
    # pipeline under full instrumentation, write a Perfetto-loadable
    # Chrome trace + a Prometheus snapshot. Stdlib-only flag wiring.
    from .obs.profile import add_profile_arguments

    profile_parser = sub.add_parser(
        "profile",
        help="profile a pipeline: spans + metrics → Chrome trace + Prometheus",
    )
    add_profile_arguments(profile_parser)

    # Fleet observability plane (docs/OBSERVABILITY.md "Fleet tracing"):
    # drive a traffic sweep against a real multiworker fleet under
    # cross-process tracing, emit the merged Perfetto trace + /metrics
    # scrape artifacts. Stdlib-only flag wiring; the default stub
    # backend keeps the whole run jax-free.
    from .obs.fleet import add_trace_arguments

    trace_parser = sub.add_parser(
        "trace",
        help="fleet trace: multiworker traffic sweep → merged Perfetto "
        "trace + Prometheus scrape + flight-recorder artifacts",
    )
    add_trace_arguments(trace_parser)

    # Perf-regression gate (docs/OBSERVABILITY.md): compare two BENCH
    # json artifacts leg by leg with noise-aware tolerances. Entirely
    # stdlib — CI runs it without a backend.
    from .obs.benchdiff import add_bench_diff_arguments

    bench_diff_parser = sub.add_parser(
        "bench-diff",
        help="compare two BENCH_*.json artifacts; exit 1 on perf regression",
    )
    add_bench_diff_arguments(bench_diff_parser)

    # Static tier (docs/VERIFICATION.md): keystone-lint over the
    # codebase and/or plan-time graph verification of a pipeline —
    # all before any data touches a device. Stdlib-only flag wiring.
    from .lint.check import add_check_arguments

    check_parser = sub.add_parser(
        "check",
        help="static checks: --lint the codebase, --concurrency the lock "
        "discipline, --pipeline verify a plan graph, --store the profile "
        "store's provenance",
    )
    add_check_arguments(check_parser)

    # Cost observatory (docs/OBSERVABILITY.md "Cost observatory"): run a
    # plan under per-node roofline attribution and the predicted-vs-
    # measured drift sentinel — the "why is this pipeline slow" report.
    # Stdlib-only flag wiring, same rule as tune.
    explain_parser = sub.add_parser(
        "explain",
        help="cost observatory: per-node predicted vs measured cost, "
        "roofline placement, decision provenance, drift sentinel",
    )
    add_explain_arguments(explain_parser)

    # Offline autotuner (docs/AUTOTUNING.md): budgeted measured search
    # over the plan-knob space, winners persisted to the profile store
    # under the keys MeasuredKnobRule replays. Flag wiring lives HERE,
    # not in workflow/tune.py: importing any workflow submodule executes
    # the package __init__, which imports jax — and --list/--help must
    # stay jax-free (pinned by tests/lint/test_check_cli.py).
    tune_parser = sub.add_parser(
        "tune",
        help="offline autotuner: search chunk/block/precision/threshold "
        "knobs per shape class, persist winners to the profile store",
    )
    add_tune_arguments(tune_parser)

    # Quality plane (docs/OBSERVABILITY.md "Quality plane"): the
    # operator-facing report over score streams, drift state, and
    # anytime-valid decision gates — run on a deterministic seeded
    # scenario so scripts/quality_smoke.sh can assert its decisions.
    # Stdlib-only flag wiring AND dispatch (the plane itself is jax-free).
    from .obs.quality_cli import add_quality_arguments

    quality_parser = sub.add_parser(
        "quality",
        help="quality-plane report: score streams, drift state, open "
        "sequential tests, decisions with evidence",
    )
    add_quality_arguments(quality_parser)

    # Continuous refit (docs/REFIT.md): the drifting-workload closed
    # loop — serve, tap, incremental fold, shadow-eval, publish, watch,
    # auto-rollback — with a final REFIT_STATS: JSON line the chaos
    # smoke asserts on. Stdlib-only flag wiring, same rule as tune.
    refit_parser = sub.add_parser(
        "refit",
        help="continuous-refit demo loop: drifting traffic absorbed by "
        "incremental refits with shadow eval and auto-rollback",
    )
    add_refit_arguments(refit_parser)

    # Durable fits (docs/RELIABILITY.md "Durable fits"): one streamed
    # fit with mid-fit cursor checkpoints; killed runs resume in a
    # fresh process via the same command. The engine under
    # scripts/elastic_smoke.sh. Stdlib-only flag wiring, same rule as
    # tune.
    fit_parser = sub.add_parser(
        "fit",
        help="durable streamed fit: mid-stream checkpoints, crash "
        "resume (--expect-resume), KV306 stale-entry refusal",
    )
    add_fit_arguments(fit_parser)

    args = parser.parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    if args.list or not args.workload:
        for name, entry in sorted(WORKLOADS.items()):
            print(f"{name:28s} {entry[-1]}")
        print(f"{'serve':28s} online serving front-end (micro-batched, stdin/JSON)")
        print(f"{'profile':28s} instrumented run → Chrome trace + Prometheus snapshot")
        print(
            f"{'trace':28s} fleet trace: multiworker sweep → merged "
            "Perfetto trace + /metrics scrape"
        )
        print(f"{'bench-diff':28s} compare two BENCH json artifacts, fail on regression")
        print(
            f"{'check':28s} static tier: keystone-lint + concurrency "
            "analysis + plan-time graph verification"
        )
        print(
            f"{'explain':28s} cost observatory: predicted vs measured "
            "per node, roofline placement, drift sentinel"
        )
        print(
            f"{'tune':28s} offline autotuner: measured knob search → "
            "profile-store winners"
        )
        print(
            f"{'quality':28s} quality-plane report: score streams, drift "
            "state, anytime-valid decision gates"
        )
        print(
            f"{'refit':28s} continuous-refit loop: incremental retrain + "
            "shadow eval + auto-rollback"
        )
        print(
            f"{'explain --schedule':28s} mesh co-scheduler: serving + "
            "leased background folds on one mesh, preempt/resume proof"
        )
        print(
            f"{'fit':28s} durable streamed fit: mid-stream checkpoints + "
            "crash resume + KV306 stale-entry refusal"
        )
        return 0

    # Multi-host launch (bin/launch-pod.sh sets KEYSTONE_DISTRIBUTED=1;
    # runbook: docs/MULTIHOST.md): join the pod's distributed runtime
    # BEFORE any device use so every host sees the global device set.
    from .envknobs import env_set

    if env_set("KEYSTONE_DISTRIBUTED"):
        from .parallel.mesh import distributed_init

        distributed_init()

    if args.workload == "serve":
        from .serving.server import serve_from_args

        return serve_from_args(args)

    if args.workload == "trace":
        from .obs.fleet import trace_from_args

        return trace_from_args(args)

    if args.workload == "bench-diff":
        from .obs.benchdiff import bench_diff_from_args

        return bench_diff_from_args(args)

    if args.workload == "check":
        from .lint.check import check_from_args

        return check_from_args(args)

    if args.workload == "explain":
        from .utils.compilation_cache import enable_persistent_cache
        from .workflow.explain import explain_from_args

        enable_persistent_cache()  # later passes/runs measure steady state
        return explain_from_args(args)

    if args.workload == "tune":
        from .utils.compilation_cache import enable_persistent_cache
        from .workflow.tune import tune_from_args

        enable_persistent_cache()  # measured runs warm the same cache
        return tune_from_args(args)

    if args.workload == "quality":
        from .obs.quality_cli import quality_from_args

        return quality_from_args(args)

    if args.workload == "refit":
        from .refit.daemon import refit_from_args
        from .utils.compilation_cache import enable_persistent_cache

        enable_persistent_cache()  # warm folds/warmups across runs
        return refit_from_args(args)

    if args.workload == "fit":
        from .utils.compilation_cache import enable_persistent_cache
        from .workflow.fitcmd import fit_from_args

        enable_persistent_cache()  # resumed processes re-use warm steps
        return fit_from_args(args)

    if args.workload == "profile":
        from .obs.profile import profile_from_args
        from .utils.compilation_cache import (
            enable_persistent_cache,
            install_compile_counter,
        )

        enable_persistent_cache()
        install_compile_counter()  # compile counts belong in the profile
        return profile_from_args(args)

    # Warm repeat runs: compiled XLA programs persist across processes
    # (KEYSTONE_COMPILATION_CACHE=off to disable). Enabled only on the
    # workload path so --list / --help stay jax-free.
    from .utils.compilation_cache import enable_persistent_cache

    enable_persistent_cache()

    config_cls, run_fn = resolved[args.workload]
    config = build_config(config_cls, args)
    results = run_fn(config)
    print(json.dumps({"workload": args.workload, **printable_results(results)}))
    return 0


def printable_results(results: dict) -> dict:
    """JSON-serializable view of a workload's results dict: true scalars
    become floats, small arrays become lists (e.g. the VOC run's (20,)
    per-class AP), large arrays and non-serializable objects are skipped."""
    import numpy as _np

    printable = {}
    for k, v in results.items():
        if isinstance(v, (int, float, str)):
            printable[k] = v
        elif hasattr(v, "item"):
            if _np.ndim(v) == 0 or getattr(v, "size", 0) == 1:
                printable[k] = float(_np.asarray(v).reshape(()))
            elif getattr(v, "size", 0) <= 64:
                printable[k] = _np.asarray(v).tolist()
    return printable


if __name__ == "__main__":
    sys.exit(main())
