"""Always-on flight recorder: a bounded per-process ring of recent
telemetry, dumped to a post-mortem artifact when something goes wrong.

"Worker died, requeued" is a healthy-system log line and a terrible
post-mortem: by the time a human looks, the spans, ledger events, and
counter values that explain *why* are gone. This module keeps the last
few hundred of each in bounded ring buffers — cheap enough to run
permanently in every process (supervisor, workers, the refit daemon) —
and writes one ``flightrec-<role>-<pid>.json`` artifact the moment a
trigger fires:

- ``worker_crash``   — the supervisor declared a worker dead (its view:
  the crash ledger event, last heartbeat stats, dispatch spans).
- ``fault_probe``    — an armed fault-injection probe fired in THIS
  process. A ``kill`` spec records the fault to the ledger *before*
  SIGKILLing, so the dump lands on disk and the killed worker leaves its
  own post-mortem.
- ``slo_degrade``    — the SLO controller stepped the admission ladder
  down (the latency objective was violated; capture why).
- ``refit_rollback`` — the post-publish watch window rolled a candidate
  back.
- ``quality_drift`` / ``quality_rollback`` — the quality plane detected
  an input/score drift, or its sequential gate decided ``rollback``
  (docs/OBSERVABILITY.md "Quality plane"). Quality events live in their
  own ``quality`` ring so the dump separates statistical evidence from
  the recovery ledger.

Triggers ride the recovery ledger: :func:`observe_ledger` is called by
``RecoveryLog.record`` for every event (a single global read when no
recorder is installed), appends to the ring, and auto-dumps on the
trigger kinds above. Dumps are rate-limited per trigger so a fault storm
produces one artifact, not a disk full of them.

Artifact schema (one JSON object)::

    {"flightrec": 1, "role": ..., "pid": ..., "trigger": ...,
     "written_unix": ..., "detail": {...},
     "spans": [<fleet span fragments, absolute-unix times>],
     "ledger": [{"kind", "label", "unix", ...detail}],
     "quality": [{"kind", "unix", ...evidence}],
     "metric_snapshots": [{"unix", "metrics": {...}}],
     "metrics": {<full registry snapshot at dump time>},
     "marks": [{"label", "unix", ...}], "dropped_spans": N}

Stdlib-only at import, like the rest of ``obs/``. The artifact directory
is ``KEYSTONE_FLIGHT_DIR`` (default: the system temp dir), documented in
docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..envknobs import env_raw
from . import names as _names
from . import spans as _spans
from .metrics import get_registry

#: ledger kind → dump trigger for unconditional triggers; ``slo`` events
#: trigger only on direction="degrade" (handled in observe_ledger).
TRIGGER_KINDS: Dict[str, str] = {
    "fault": "fault_probe",
    "worker_crash": "worker_crash",
    "refit_rollback": "refit_rollback",
}

FLIGHT_DIR_ENV = "KEYSTONE_FLIGHT_DIR"


def _json_safe_detail(detail: Dict[str, Any]) -> Dict[str, Any]:
    return {
        k: (v if isinstance(v, (bool, int, float, str)) or v is None else str(v))
        for k, v in detail.items()
    }


class FlightRecorder:
    """Bounded rings of recent ledger events / metric snapshots / marks,
    plus a dump method that also captures the active span session's
    tail. One per process, installed via :func:`install_flight_recorder`."""

    def __init__(
        self,
        role: str,
        capacity: int = 512,
        out_dir: Optional[str] = None,
        min_dump_interval_s: float = 1.0,
        metrics_interval_s: float = 1.0,
    ):
        self.role = role
        self.capacity = capacity
        self.out_dir = (
            out_dir or env_raw(FLIGHT_DIR_ENV) or tempfile.gettempdir()
        )
        self.min_dump_interval_s = min_dump_interval_s
        self.metrics_interval_s = metrics_interval_s
        self._lock = threading.Lock()
        self._ledger: "deque[Dict[str, Any]]" = deque(maxlen=capacity)
        self._quality: "deque[Dict[str, Any]]" = deque(maxlen=128)
        self._marks: "deque[Dict[str, Any]]" = deque(maxlen=64)
        self._metric_ring: "deque[Dict[str, Any]]" = deque(maxlen=8)
        self._last_metrics_at = -float("inf")
        self._last_dump_at: Dict[str, float] = {}
        #: dump history (trigger + path), for tests and TRACE_STATS lines.
        self.dumps: List[Dict[str, str]] = []
        self._m_records = _names.metric(_names.FLIGHT_RECORDS)
        self._m_dumps = _names.metric(_names.FLIGHT_DUMPS)
        self._m_dump_bytes = _names.metric(_names.FLIGHT_DUMP_BYTES)

    # -------------------------------------------------------------- recording
    def observe_ledger(self, kind: str, label: str, detail: Dict[str, Any]) -> None:
        entry = {
            "kind": kind,
            "label": label,
            "unix": round(time.time(), 6),
            **_json_safe_detail(detail),
        }
        with self._lock:
            self._ledger.append(entry)
        self._m_records.inc(kind="ledger")
        trigger = TRIGGER_KINDS.get(kind)
        if kind == "slo" and detail.get("direction") == "degrade":
            trigger = "slo_degrade"
        if trigger is not None:
            self.dump(trigger, detail={"kind": kind, "label": label})

    def observe_quality(self, event: Dict[str, Any]) -> None:
        """Append a quality-plane event (drift firing, gate decision) to
        the ``quality`` ring; a ``drift`` event or a ``rollback`` gate
        decision is a post-mortem moment and dumps immediately."""
        entry = {"unix": round(time.time(), 6), **_json_safe_detail(event)}
        with self._lock:
            self._quality.append(entry)
        self._m_records.inc(kind="quality")
        kind = event.get("kind")
        if kind == "drift":
            self.dump("quality_drift",
                      detail={"kind": "quality_drift",
                              "model": event.get("model", "")})
        elif kind == "gate_decision" and event.get("decision") == "rollback":
            self.dump("quality_rollback",
                      detail={"kind": "quality_rollback",
                              "model": event.get("model", "")})

    def quality_ring(self) -> List[Dict[str, Any]]:
        """A copy of the quality ring — the Perfetto exporter's
        ``quality`` track source (obs/export.py quality_events)."""
        with self._lock:
            return list(self._quality)

    def mark(self, label: str, **data: Any) -> None:
        """Append a caller-defined waypoint (heartbeat seq, round index)
        to the mark ring — breadcrumbs for the dump reader."""
        with self._lock:
            self._marks.append(
                {"label": label, "unix": round(time.time(), 6),
                 **_json_safe_detail(data)}
            )
        self._m_records.inc(kind="mark")

    def observe_metrics(self) -> bool:
        """Snapshot the metrics registry into the bounded snapshot ring,
        rate-limited to one per ``metrics_interval_s`` (worker heartbeat
        loops call this every beat; most beats are a no-op)."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_metrics_at < self.metrics_interval_s:
                return False
            self._last_metrics_at = now
        snapshot = {"unix": round(time.time(), 6),
                    "metrics": get_registry().snapshot()}
        with self._lock:
            self._metric_ring.append(snapshot)
        self._m_records.inc(kind="metrics")
        return True

    # ------------------------------------------------------------------ dump
    def dump(
        self,
        trigger: str,
        detail: Optional[Dict[str, Any]] = None,
        force: bool = False,
    ) -> Optional[str]:
        """Write the post-mortem artifact for ``trigger``; returns its
        path, or None when rate-limited. Never raises — a flight-recorder
        bug must not take down the process it exists to explain."""
        try:
            return self._dump(trigger, detail, force)
        except Exception:
            return None

    def _dump(
        self, trigger: str, detail: Optional[Dict[str, Any]], force: bool
    ) -> Optional[str]:
        now = time.monotonic()
        with self._lock:
            last = self._last_dump_at.get(trigger, -float("inf"))
            if not force and now - last < self.min_dump_interval_s:
                return None
            self._last_dump_at[trigger] = now
            ledger = list(self._ledger)
            quality = list(self._quality)
            marks = list(self._marks)
            metric_ring = list(self._metric_ring)
        session = _spans.active_session()
        span_tail: List[Dict[str, Any]] = []
        dropped = 0
        if session is not None:
            from .fleet import span_fragment  # lazy: fleet imports spans too

            span_tail = [
                span_fragment(s, session)
                for s in session.spans()[-self.capacity:]
            ]
            dropped = session.dropped
        perf_ledger: List[Dict[str, Any]] = []
        try:
            # The cost observatory's recent per-node entries: a crash
            # snapshot carries the perf picture (predicted vs measured,
            # roofline placement) alongside the events that explain it.
            from . import cost as _cost

            perf_ledger = [
                e.to_json() for e in _cost.get_ledger().tail(32)
            ]
        except Exception:
            pass
        payload = {
            "flightrec": 1,
            "role": self.role,
            "pid": os.getpid(),
            "trigger": trigger,
            "written_unix": round(time.time(), 6),
            "detail": _json_safe_detail(detail or {}),
            "spans": span_tail,
            "ledger": ledger,
            "quality": quality,
            "perf_ledger": perf_ledger,
            "metric_snapshots": metric_ring,
            "metrics": get_registry().snapshot(),
            "marks": marks,
            "dropped_spans": dropped,
        }
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(
            self.out_dir, f"flightrec-{self.role}-{os.getpid()}.json"
        )
        body = json.dumps(payload)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(body)
        os.replace(tmp, path)  # readers never see a torn artifact
        self._m_dumps.inc(trigger=trigger)
        self._m_dump_bytes.set(len(body))
        with self._lock:
            self.dumps.append({"trigger": trigger, "path": path})
        return path


# --------------------------------------------------------- process singleton

_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def install_flight_recorder(role: str, **kwargs: Any) -> FlightRecorder:
    """Install the process-wide recorder (idempotent — the first
    installer's role wins; a supervisor and a frontend sharing a process
    share one recorder)."""
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = FlightRecorder(role, **kwargs)
        return _recorder


def get_flight_recorder() -> Optional[FlightRecorder]:
    return _recorder


def reset_flight_recorder() -> None:
    """Testing hook: drop the installed recorder."""
    global _recorder
    with _recorder_lock:
        _recorder = None


def observe_ledger(kind: str, label: str, detail: Dict[str, Any]) -> None:
    """RecoveryLog.record's hook: one global read when no recorder is
    installed; otherwise ring-append + auto-dump on trigger kinds.
    Exceptions are swallowed — the ledger write must always win."""
    recorder = _recorder
    if recorder is None:
        return
    try:
        recorder.observe_ledger(kind, label, detail)
    except Exception:
        pass
