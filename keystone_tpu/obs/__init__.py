"""keystone_tpu.obs — the unified observability layer.

One subsystem answering "where did this pipeline spend its time and
memory" end to end, replacing the three telemetry fragments the system
grew (flat per-op tracing, serving-local percentiles, the reliability
ledger's counts):

- :mod:`.spans` — hierarchical spans with trace ids, attributes, events,
  and cross-thread context handoff; free when no session is active.
- :mod:`.metrics` — process-wide registry of labeled counters / gauges /
  histograms; :mod:`.names` declares the stable, tested name schema.
- :mod:`.device` — device/host memory sampling, per-stage peak
  attribution, optional ``jax.profiler.TraceAnnotation`` wrapping.
- :mod:`.export` — Chrome trace-event JSON (Perfetto), Prometheus text,
  and a human span-tree report.
- :mod:`.profile` — the ``keystone-tpu profile`` harness.

The package is stdlib-only at import time (jax is imported lazily inside
functions), so bench.py and the CLI can import it before any backend
initializes. See docs/OBSERVABILITY.md.
"""

from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    percentile,
    reset_registry,
)
from .spans import (
    NOOP_SPAN,
    Span,
    TraceSession,
    active_session,
    add_span_event,
    attach,
    current_context,
    current_span,
    record_span,
    span,
    tracing_session,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    "percentile", "reset_registry",
    "NOOP_SPAN", "Span", "TraceSession", "active_session", "add_span_event",
    "attach", "current_context", "current_span", "record_span", "span",
    "tracing_session",
]
