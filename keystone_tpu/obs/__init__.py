"""keystone_tpu.obs — the unified observability layer.

One subsystem answering "where did this pipeline spend its time and
memory" end to end, replacing the three telemetry fragments the system
grew (flat per-op tracing, serving-local percentiles, the reliability
ledger's counts):

- :mod:`.spans` — hierarchical spans with trace ids, attributes, events,
  and cross-thread context handoff; free when no session is active.
- :mod:`.metrics` — process-wide registry of labeled counters / gauges /
  histograms; :mod:`.names` declares the stable, tested name schema.
- :mod:`.device` — device/host memory sampling, per-stage peak
  attribution, optional ``jax.profiler.TraceAnnotation`` wrapping.
- :mod:`.export` — Chrome trace-event JSON (Perfetto), Prometheus text,
  and a human span-tree report.
- :mod:`.store` — the persistent profile store: measurements keyed by
  structural digest + shape class + backend, persisted next to the XLA
  cache, consumed by the optimizer (autocache warm-start, measured
  knobs) and the bench-diff gate.
- :mod:`.benchdiff` — ``keystone-tpu bench-diff``: run-over-run BENCH
  comparison with a regression verdict.
- :mod:`.profile` — the ``keystone-tpu profile`` harness.

The package is stdlib-only at import time (jax is imported lazily inside
functions), so bench.py and the CLI can import it before any backend
initializes. See docs/OBSERVABILITY.md.
"""

from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    percentile,
    reset_registry,
)
from .spans import (
    NOOP_SPAN,
    Span,
    TraceSession,
    active_session,
    add_span_event,
    attach,
    current_context,
    current_span,
    record_span,
    span,
    tracing_session,
)
from .store import (
    ProfileStore,
    dataset_shape_class,
    get_store,
    set_store,
    shape_class,
    store_enabled,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    "percentile", "reset_registry",
    "NOOP_SPAN", "Span", "TraceSession", "active_session", "add_span_event",
    "attach", "current_context", "current_span", "record_span", "span",
    "tracing_session",
    "ProfileStore", "get_store", "set_store", "store_enabled",
    "shape_class", "dataset_shape_class",
]
