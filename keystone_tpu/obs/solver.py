"""Solver instrumentation helpers: fit spans, ladder-rung iteration spans,
and host-loop iteration counting.

The solvers' inner loops live in three places with different shapes: the
compiled BCD/KRR epoch×block scans (one XLA computation — only the whole
solve is observable from the host), the degradation-ladder rung loop
(host-level: each rung attempt is a real iteration of the solve-or-shrink
loop), and scipy's L-BFGS callback (host-level per-step). These helpers
give all three one vocabulary:

- :func:`fit_span` — ``solver:fit`` span + ``keystone_solver_fit_seconds``
  histogram around a whole fit;
- :func:`rung_span` — ``solver:iteration`` child span +
  ``keystone_solver_rung_attempts_total`` per ladder rung attempt;
- :func:`count_iteration` — ``keystone_solver_iterations_total`` +
  a span event per host-level optimizer step.

All are free when neither a span session nor the metric has consumers —
counters are cheap dict increments; spans no-op without a session.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Any, Iterator

from . import names, spans


@contextmanager
def fit_span(solver: str, **attributes: Any) -> Iterator[None]:
    t0 = time.perf_counter()
    try:
        with spans.span("solver:fit", solver=solver, **attributes):
            yield
    finally:
        names.metric(names.SOLVER_FIT_SECONDS).observe(
            time.perf_counter() - t0, solver=solver
        )


@contextmanager
def rung_span(solver: str, rung: Any, index: int) -> Iterator[None]:
    names.metric(names.SOLVER_RUNG_ATTEMPTS).inc(solver=solver)
    with spans.span(
        "solver:iteration", solver=solver, rung=str(rung), rung_index=index
    ):
        yield


def count_iteration(solver: str, n: int = 1, **attributes: Any) -> None:
    names.metric(names.SOLVER_ITERATIONS).inc(n, solver=solver)
    spans.add_span_event("solver:step", solver=solver, **attributes)


def predicted_attrs(estimator: Any) -> dict:
    """Span attributes for the cost prediction pinned on an estimator
    (``predicted_cost``, an :class:`~keystone_tpu.obs.cost.Prediction`
    from the solver ladder's argmin or MeasuredKnobRule's winner) — the
    cost-observatory join surface on ``solver:fit`` spans: a solver span
    in any trace names the model/key that predicted it, next to the wall
    it actually took (docs/OBSERVABILITY.md "Cost observatory")."""
    prediction = getattr(estimator, "predicted_cost", None)
    if prediction is None:
        return {}
    out: dict = {"predicted_model": prediction.model}
    if getattr(prediction, "seconds", None) is not None:
        out["predicted_cost_ms"] = round(prediction.seconds * 1e3, 3)
    if getattr(prediction, "rows_per_s", None):
        out["predicted_rows_per_s"] = round(prediction.rows_per_s, 1)
    if getattr(prediction, "key", ""):
        out["predicted_key"] = prediction.key
    return out
