"""Cost observatory: measured-vs-modeled accounting for every compiled
node the plan executes.

Four cost models drive decisions in this tree — the solver ladder's
``CostModel`` rungs, ``AutoCacheRule``'s per-node linear fits,
``MeasuredKnobRule``'s recorded winners, and the tuner's ridge model —
and before this module nothing ever checked a prediction against what
XLA actually executed. A drifting model silently degrades every
decision downstream (the ROADMAP's measure-or-delete discipline). This
module closes that loop:

- **Harvest** — ``jax.stages.Lowered.cost_analysis()`` gives per-program
  flop and byte counts. On jax 0.4.37, ``jitted.lower(*args)`` after the
  function has executed hits the jit's trace cache: no re-trace, no
  backend compile (``keystone_cost_harvest_compiles_total`` counts any
  violation of that invariant and must stay 0 — the explain smoke gates
  it). ``cost_analysis`` can return ``None``, a list, or a dict with
  missing keys depending on backend — every read is guarded here, and a
  KV506 lint rule keeps *all* ``cost_analysis()`` call sites in this
  module so the guarding lives exactly once.
- **Roofline** — a tiny probe pair (one matmul, one copy) measures this
  process's achievable FLOP/s and bytes/s once, cached in the
  ProfileStore under ``roofline:<backend>`` so later processes skip the
  probe. Each harvested node is classified compute-bound or
  memory-bound by its arithmetic intensity against the ridge point.
- **Perf ledger** — ``workflow/tracing.timed_execute`` opens a harvest
  frame around each node's forcing; operators note their jitted
  computations into it (fused chains, streaming chunk steps); the frame
  is finalized into one :class:`PerfLedgerEntry` joining predicted cost
  (whichever model drove the decision), measured wall, achieved rates,
  intensity, and roofline placement. Entries ride flight-recorder dumps
  and export as Perfetto counter tracks (obs/export.py).
- **Drift sentinel** — predicted-vs-measured per ``(key, shape class)``
  with a noise-tolerant ratio test (symmetric band, consecutive-miss
  sustain). Sustained drift publishes ``keystone_cost_drift_*`` metrics,
  lands a ``cost_drift`` recovery-ledger event (which the flight
  recorder rings), and marks the offending ProfileStore entry
  ``stale:`` so ``AutoCacheRule``/``MeasuredKnobRule`` re-measure
  instead of replaying a stale winner. Only *calibrated* predictions —
  ones measured under the exact (key, shape class) they are compared at
  (autocache fits, measured-knob stream winners) — are drift-scored;
  the solver ladder's constants are relative (its argmin is what
  matters), so its predictions are displayed but never flagged.

Everything is off unless the observatory is enabled
(``KEYSTONE_COST_OBS=1`` or :func:`set_cost_observatory`): harvesting
re-lowers nothing on cache hits, but the no-op path must stay a single
thread-local read for serving hot paths. The explain CLI
(``keystone-tpu explain``, workflow/explain.py), ``keystone-tpu
profile``, and bench legs turn it on for their runs.

Stdlib-only at import, like the rest of ``obs/``. docs/OBSERVABILITY.md
"Cost observatory" documents the ledger schema, calibration, and the
drift knobs.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..envknobs import env_flag, env_float, env_int
from . import names as _names
from . import spans as _spans

logger = logging.getLogger(__name__)

#: Facts cache bound: one entry per (jitted fn, input signature) —
#: generously above the live executable count of any real process.
_FACTS_CACHE_MAX = 256

#: Perf-ledger ring bound (overridable per-instance).
_LEDGER_MAX_DEFAULT = 256


# ------------------------------------------------------------------ enablement

_enabled_override: Optional[bool] = None
_enabled_lock = threading.Lock()


def cost_observatory_enabled() -> bool:
    """Master switch: ``set_cost_observatory()`` override, else the
    ``KEYSTONE_COST_OBS`` env flag (default OFF — harvesting re-traces
    nothing on cache hits, but the observatory is an analysis plane, not
    a steady-state tax; explain/profile/bench enable it for their runs)."""
    if _enabled_override is not None:
        return _enabled_override
    return env_flag("KEYSTONE_COST_OBS", False)


def set_cost_observatory(value: Optional[bool]) -> None:
    """Force the observatory on/off process-wide; ``None`` restores the
    env default."""
    global _enabled_override
    with _enabled_lock:
        _enabled_override = value


def drift_ratio_tolerance() -> float:
    """Symmetric ratio band half-width: a prediction is in-band while
    ``max(ratio, 1/ratio) <= tol``. Default 4.0 — sub-second CPU walls
    on a loaded box swing ~4× run to run (docs/OBSERVABILITY.md), and a
    drift gate tighter than the noise floor would cry wolf."""
    return max(1.0, env_float("KEYSTONE_COST_DRIFT_RATIO", 4.0))


def drift_sustain() -> int:
    """Consecutive out-of-band observations of one (key, shape) before
    the sentinel fires (``KEYSTONE_COST_DRIFT_SUSTAIN``, default 2)."""
    return max(1, env_int("KEYSTONE_COST_DRIFT_SUSTAIN", 2))


def drift_enabled() -> bool:
    return env_flag("KEYSTONE_COST_DRIFT", True)


# ----------------------------------------------------------------- predictions


@dataclass(frozen=True)
class Prediction:
    """One model's cost claim for a node, carried to the ledger join.

    ``calibrated`` marks predictions measured under the exact
    (key, shape class) they will be compared at — only those are
    drift-scored. ``seconds`` and ``rows_per_s`` are alternative units;
    whichever is set is what the sentinel compares."""

    model: str  # solver_ladder | autocache | measured_knob | tune | roofline
    key: str = ""  # the ProfileStore key that backed it ("" = none)
    shape: str = ""  # the shape class it was recorded under
    seconds: Optional[float] = None
    rows_per_s: Optional[float] = None
    calibrated: bool = False
    source: str = "observed"  # store provenance (observed | tune)
    #: Every candidate an argmin choice considered, as (name,
    #: seconds-or-None, reason) tuples — "chosen" for the winner,
    #: the rejection reason otherwise. Lets explain audit the whole
    #: ladder, not just the surviving rung.
    candidates: Tuple = ()


# Plan-scoped prediction book: node label → Prediction, filled by the
# optimizer passes that predict per-NODE costs (AutoCacheRule's linear
# fits) and read back by finalize_node when the executed operator has no
# pinned prediction of its own. Label-keyed (labels can collide across
# plans) — best-effort attribution, reset per plan by the harnesses.
_plan_predictions: Dict[str, Prediction] = {}
_plan_lock = threading.Lock()


def note_plan_prediction(label: str, prediction: Prediction) -> None:
    if not cost_observatory_enabled():
        return
    with _plan_lock:
        _plan_predictions[str(label)] = prediction


def reset_plan_predictions() -> None:
    with _plan_lock:
        _plan_predictions.clear()


def plan_prediction(label: str) -> Optional[Prediction]:
    with _plan_lock:
        return _plan_predictions.get(str(label))


# -------------------------------------------------------------------- harvest


@dataclass(frozen=True)
class CostFacts:
    """What one compiled program is, per XLA: flop count, bytes
    accessed, and the lowering digest (sha1 of the StableHLO text) that
    joins ledger entries to spans and ProfileStore keys
    deterministically."""

    flops: Optional[float]
    bytes_accessed: Optional[float]
    lowering_digest: str = ""

    @property
    def intensity(self) -> Optional[float]:
        if not self.flops or not self.bytes_accessed:
            return None
        return self.flops / self.bytes_accessed


# (id(fn), signature) → (fn strong ref, CostFacts). The ref pins the id
# against recycling, same discipline as fusion's chain-jit cache.
_facts_cache: "OrderedDict[Tuple[int, str], Tuple[Any, Optional[CostFacts]]]" = (
    OrderedDict()
)
_facts_lock = threading.Lock()


def _aval_signature(tree: Any) -> str:
    import jax

    parts = []
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            shape = tuple(leaf.shape)
            dtype = getattr(leaf.dtype, "name", str(leaf.dtype))
            parts.append(f"{dtype}{list(shape)}")
        else:
            # Static/python operands (epoch counts, block sizes) are part
            # of the compiled identity — different values, different
            # programs, different flop counts.
            parts.append(repr(leaf)[:32])
    return ";".join(parts)


def _normalize_cost_analysis(raw: Any) -> Tuple[Optional[float], Optional[float]]:
    """Flops / bytes-accessed out of whatever shape ``cost_analysis``
    returned: None, a dict, or a list of per-program dicts (backends
    differ; CPU returns both keys, some TPU paths return partial or
    nothing). Missing or negative values degrade to None, never raise."""
    entries: Sequence[Any]
    if raw is None:
        return None, None
    if isinstance(raw, dict):
        entries = [raw]
    elif isinstance(raw, (list, tuple)):
        entries = [e for e in raw if isinstance(e, dict)]
    else:
        return None, None
    flops = 0.0
    bytes_accessed = 0.0
    saw_flops = saw_bytes = False
    for entry in entries:
        f = entry.get("flops")
        b = entry.get("bytes accessed")
        if isinstance(f, (int, float)) and f >= 0:
            flops += float(f)
            saw_flops = True
        if isinstance(b, (int, float)) and b >= 0:
            bytes_accessed += float(b)
            saw_bytes = True
    return (flops if saw_flops else None), (bytes_accessed if saw_bytes else None)


def _harvest_compile_counter():
    return _names.metric(_names.COST_HARVEST_COMPILES)


def harvest_cost_facts(fn: Any, args: Any = None) -> Optional[CostFacts]:
    """Flop/byte facts for one compiled computation — THE sanctioned
    ``cost_analysis()`` call site (lint rule KV506 flags any other).

    ``fn`` is a ``jax.stages.Compiled``, a ``jax.stages.Lowered``, or a
    jitted callable (then ``args`` — concrete arrays or
    ``ShapeDtypeStruct`` avals — selects the signature and
    ``fn.lower(*args)`` resolves through the jit trace cache: zero
    backend compiles when the signature already executed, asserted by
    ``keystone_cost_harvest_compiles_total``). Any failure returns None
    — a backend without cost analysis must not break a fit."""
    from ..utils.compilation_cache import compile_count

    before = compile_count()
    facts: Optional[CostFacts] = None
    try:
        lowered = fn
        if hasattr(fn, "lower") and not hasattr(fn, "cost_analysis"):
            lowered = fn.lower(*tuple(args or ()))
        raw = lowered.cost_analysis()  # the ONE call site (KV506)
        flops, bytes_accessed = _normalize_cost_analysis(raw)
        digest = ""
        try:
            text = lowered.as_text()
            digest = hashlib.sha1(text.encode()).hexdigest()[:16]
        except Exception:
            pass
        facts = CostFacts(flops, bytes_accessed, digest)
    except Exception as e:
        logger.debug("cost harvest failed (%s)", e)
        facts = None
    extra = compile_count() - before
    if extra > 0:
        # The zero-extra-compiles invariant broke (a signature was
        # lowered before it ever executed, or AOT drifted) — count it
        # loudly; the explain smoke asserts this stays 0.
        _harvest_compile_counter().inc(extra)
    return facts


def _cached_facts(fn: Any, args: Any = None, avals: Any = None) -> Optional[CostFacts]:
    """Facts for (fn, signature) through the bounded cache — the steady
    state pays one dict lookup per node execution."""
    try:
        sig = _aval_signature(avals if avals is not None else args)
    except Exception:
        return None
    key = (id(fn), sig)
    with _facts_lock:
        hit = _facts_cache.get(key)
        if hit is not None:
            _facts_cache.move_to_end(key)
            return hit[1]
    facts = harvest_cost_facts(fn, avals if avals is not None else args)
    with _facts_lock:
        _facts_cache[key] = (fn, facts)
        _facts_cache.move_to_end(key)
        while len(_facts_cache) > _FACTS_CACHE_MAX:
            _facts_cache.popitem(last=False)
    return facts


# ------------------------------------------------------------------- roofline


@dataclass(frozen=True)
class Roofline:
    """Per-backend achievable peaks, probe-measured (docs/OBSERVABILITY.md
    "Cost observatory"): the ridge point ``peak_flops/peak_bytes``
    separates compute-bound from memory-bound intensities."""

    peak_flops_per_s: float
    peak_bytes_per_s: float
    backend: str = "unknown"
    source: str = "probe"  # probe | store

    @property
    def ridge_intensity(self) -> float:
        if self.peak_bytes_per_s <= 0:
            return float("inf")
        return self.peak_flops_per_s / self.peak_bytes_per_s

    def classify(self, intensity: Optional[float]) -> Optional[str]:
        if intensity is None:
            return None
        return (
            "compute-bound" if intensity >= self.ridge_intensity
            else "memory-bound"
        )

    def predicted_seconds(
        self, flops: Optional[float], bytes_accessed: Optional[float]
    ) -> Optional[float]:
        """First-principles roofline time: max of the compute and the
        memory floor — the fallback prediction for nodes no model
        claimed."""
        terms = []
        if flops and self.peak_flops_per_s > 0:
            terms.append(flops / self.peak_flops_per_s)
        if bytes_accessed and self.peak_bytes_per_s > 0:
            terms.append(bytes_accessed / self.peak_bytes_per_s)
        return max(terms) if terms else None

    def to_json(self) -> Dict[str, Any]:
        return {
            "peak_flops_per_s": self.peak_flops_per_s,
            "peak_bytes_per_s": self.peak_bytes_per_s,
            "ridge_intensity": self.ridge_intensity,
            "backend": self.backend,
            "source": self.source,
        }


ROOFLINE_SHAPE = "probe:v1"

_roofline: Optional[Roofline] = None
_roofline_lock = threading.Lock()


def _roofline_store_key(backend: str) -> str:
    return f"roofline:{backend}"


def _probe_roofline(backend: str) -> Optional[Roofline]:
    """Measure achievable peaks with one matmul (compute roof) and one
    copy-scale (bandwidth roof): warm once, min-of-3 timed — ambient
    load inflates walls, never deflates them, so min-of-N is the
    honest calibration on a shared box. Flop/byte counts come from the
    probes' own harvested facts (self-consistent units)."""
    try:
        import jax
        import jax.numpy as jnp

        n = 384
        a = jnp.ones((n, n), jnp.float32)
        matmul = jax.jit(lambda x: x @ x)
        big = jnp.ones((4 * 1024 * 1024,), jnp.float32)  # 16 MiB
        copy = jax.jit(lambda x: x * 1.00001 + 1.0)

        def timed(fn, arg) -> float:
            fn(arg).block_until_ready()  # warm/compile
            walls = []
            for _ in range(3):
                t0 = time.perf_counter()
                fn(arg).block_until_ready()
                walls.append(time.perf_counter() - t0)
            return max(min(walls), 1e-9)

        mat_wall = timed(matmul, a)
        copy_wall = timed(copy, big)
        mat_facts = harvest_cost_facts(matmul, (a,))
        copy_facts = harvest_cost_facts(copy, (big,))
        flops = (mat_facts and mat_facts.flops) or float(2 * n**3)
        traffic = (copy_facts and copy_facts.bytes_accessed) or float(
            2 * big.size * 4
        )
        return Roofline(
            peak_flops_per_s=flops / mat_wall,
            peak_bytes_per_s=traffic / copy_wall,
            backend=backend,
            source="probe",
        )
    except Exception as e:
        logger.warning("roofline probe failed (%s)", e)
        return None


def get_roofline(refresh: bool = False) -> Optional[Roofline]:
    """The process roofline: cached in-process, warm-started from the
    ProfileStore's ``roofline:<backend>`` entry (fingerprinted like any
    other measurement), probe-measured and recorded back on a cold
    store. None when no backend is importable."""
    global _roofline
    if _roofline is not None and not refresh:
        return _roofline
    with _roofline_lock:
        if _roofline is not None and not refresh:
            return _roofline
        from . import store as _store

        backend = _store.environment_fingerprint()["backend"]
        store = _store.get_store()
        if store is not None and not refresh:
            m = store.lookup(_roofline_store_key(backend), ROOFLINE_SHAPE)
            if m and m.get("peak_flops_per_s") and m.get("peak_bytes_per_s"):
                _roofline = Roofline(
                    float(m["peak_flops_per_s"]),
                    float(m["peak_bytes_per_s"]),
                    backend=backend,
                    source="store",
                )
                _publish_roofline(_roofline)
                return _roofline
        probed = _probe_roofline(backend)
        if probed is None:
            return None
        if store is not None:
            store.record(
                _roofline_store_key(backend),
                ROOFLINE_SHAPE,
                peak_flops_per_s=probed.peak_flops_per_s,
                peak_bytes_per_s=probed.peak_bytes_per_s,
            )
        _roofline = probed
        _publish_roofline(probed)
        return probed


def _publish_roofline(roofline: Roofline) -> None:
    gauge = _names.metric(_names.COST_ROOFLINE_PEAK)
    gauge.set(roofline.peak_flops_per_s, resource="flops_per_s")
    gauge.set(roofline.peak_bytes_per_s, resource="bytes_per_s")


def set_roofline(roofline: Optional[Roofline]) -> None:
    """Pin a roofline (tests); None drops the cache so the next
    :func:`get_roofline` re-resolves."""
    global _roofline
    with _roofline_lock:
        _roofline = roofline


# ------------------------------------------------------------------ the ledger


@dataclass
class PerfLedgerEntry:
    """One node execution, measured and attributed — the perf ledger's
    record (docs/OBSERVABILITY.md "Cost observatory" schema)."""

    node: str
    seconds: float
    synced: bool
    t_s: float  # perf_counter at finalize (session-relative export anchor)
    t_unix: float
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    intensity: Optional[float] = None
    flops_per_s: Optional[float] = None
    bytes_per_s: Optional[float] = None
    roofline: Optional[str] = None  # compute-bound | memory-bound | None
    bound_frac: Optional[float] = None  # achieved / peak on the binding axis
    lowering_digest: str = ""
    kinds: Tuple[str, ...] = ()
    predicted_s: Optional[float] = None
    predicted_model: Optional[str] = None
    predicted_key: str = ""
    predicted_shape: str = ""
    predicted_calibrated: bool = False
    #: (name, seconds-or-None, reason) per ladder candidate, when the
    #: prediction came from an argmin over alternatives.
    predicted_candidates: Tuple = ()
    ratio: Optional[float] = None  # measured-vs-predicted, >1 = slower
    drift: bool = False
    cold: bool = False  # compiles observed during the forcing
    rows_per_s: Optional[float] = None  # streaming folds only

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"node": self.node}
        for key in (
            "seconds", "synced", "t_unix", "flops", "bytes_accessed",
            "intensity", "flops_per_s", "bytes_per_s", "roofline",
            "bound_frac", "lowering_digest", "predicted_s",
            "predicted_model", "predicted_key", "predicted_shape",
            "predicted_calibrated", "ratio", "drift", "cold", "rows_per_s",
        ):
            value = getattr(self, key)
            if value is not None and value != "":
                out[key] = value
        if self.kinds:
            out["kinds"] = list(self.kinds)
        if self.predicted_candidates:
            out["predicted_candidates"] = [
                list(c) for c in self.predicted_candidates
            ]
        return out


class PerfLedger:
    """Bounded ring of :class:`PerfLedgerEntry` with a monotonic cursor
    so consumers (bench legs, flight dumps, explain) read their own
    windows."""

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = capacity or env_int(
            "KEYSTONE_COST_LEDGER_MAX", _LEDGER_MAX_DEFAULT
        )
        self._lock = threading.Lock()
        self._ring: "deque[PerfLedgerEntry]" = deque(maxlen=self.capacity)
        self._seq = 0

    def record(self, entry: PerfLedgerEntry) -> None:
        with self._lock:
            self._ring.append(entry)
            self._seq += 1
        _names.metric(_names.COST_LEDGER_ENTRIES).inc(
            roofline=entry.roofline or "unknown"
        )

    def cursor(self) -> int:
        with self._lock:
            return self._seq

    def entries(self, since: int = 0) -> List[PerfLedgerEntry]:
        """Entries recorded after cursor ``since`` (ring-bounded: at most
        the last ``capacity`` survive)."""
        with self._lock:
            fresh = max(0, self._seq - since)
            return list(self._ring)[-fresh:] if fresh else []

    def tail(self, n: int) -> List[PerfLedgerEntry]:
        with self._lock:
            return list(self._ring)[-n:]

    def summary(self, since: int = 0) -> Dict[str, Any]:
        """Aggregate view for bench leg payloads: entry count, total
        flops/bytes, roofline split."""
        entries = self.entries(since)
        flops = sum(e.flops or 0.0 for e in entries)
        bytes_accessed = sum(e.bytes_accessed or 0.0 for e in entries)
        bound: Dict[str, int] = {}
        for e in entries:
            bound[e.roofline or "unknown"] = bound.get(e.roofline or "unknown", 0) + 1
        return {
            "nodes": len(entries),
            "flops": flops,
            "bytes_accessed": bytes_accessed,
            "roofline": bound,
            "drift": sum(1 for e in entries if e.drift),
        }


_ledger = PerfLedger()


def get_ledger() -> PerfLedger:
    return _ledger


# ------------------------------------------------------------- harvest frames


class _Note:
    __slots__ = ("kind", "fn", "args", "avals")

    def __init__(self, kind: str, fn: Any, args: Any, avals: Any):
        self.kind = kind
        self.fn = fn
        self.args = args
        self.avals = avals


@dataclass
class HarvestFrame:
    label: str
    notes: List[_Note] = field(default_factory=list)
    rows_per_s: Optional[float] = None
    num_examples: Optional[int] = None
    #: backend compiles observed while the node forced — a cold wall
    #: (compile-inflated) is recorded but never anchors or scores drift.
    compiles: int = 0


_frames = threading.local()


def _frame_stack() -> List[HarvestFrame]:
    stack = getattr(_frames, "stack", None)
    if stack is None:
        stack = []
        _frames.stack = stack
    return stack


def push_frame(label: str) -> HarvestFrame:
    frame = HarvestFrame(label)
    _frame_stack().append(frame)
    return frame


def pop_frame(frame: HarvestFrame) -> HarvestFrame:
    stack = _frame_stack()
    if stack and stack[-1] is frame:
        stack.pop()
    elif frame in stack:  # defensive: unwind past it
        while stack and stack.pop() is not frame:
            pass
    return frame


def current_frame() -> Optional[HarvestFrame]:
    stack = getattr(_frames, "stack", None)
    return stack[-1] if stack else None


def note_jit_call(
    kind: str, fn: Any, args: Any = None, avals: Any = None
) -> None:
    """Operators call this as they dispatch a jitted computation so the
    enclosing node's harvest frame can attribute flop/byte facts to it.
    A single thread-local read when no frame is active (serving hot
    paths never pay more). Pass ``avals`` instead of ``args`` when the
    arguments will be donated/freed before the node finalizes."""
    frame = current_frame()
    if frame is None:
        return
    frame.notes.append(_Note(kind, fn, args if avals is None else None, avals))


def note_solver_call(kind: str, fn: Any, args: Sequence[Any]) -> None:
    """Note a solver-layer jitted call, substituting avals for array
    operands (solver jits donate their inputs — the buffers may be
    deleted before the node finalizes) while passing static/python
    operands verbatim (``lower`` needs the actual static values). A
    single thread-local read when no frame is active."""
    frame = current_frame()
    if frame is None:
        return
    try:
        import jax

        lower_args = tuple(
            jax.ShapeDtypeStruct(a.shape, a.dtype)
            if hasattr(a, "shape") and hasattr(a, "dtype")
            else a
            for a in args
        )
    except Exception:
        return
    frame.notes.append(_Note(kind, fn, None, lower_args))


def note_stream_result(
    rows_per_s: Optional[float], num_examples: Optional[int] = None
) -> None:
    """The streaming fold reports its achieved throughput so a
    rows/s-denominated prediction (MeasuredKnobRule's chunk winner) can
    be drift-scored in its own unit."""
    frame = current_frame()
    if frame is None:
        return
    frame.rows_per_s = rows_per_s
    frame.num_examples = num_examples


def note_lease_result(
    name: str,
    kind: str,
    predicted_s: Optional[float],
    measured_s: Optional[float],
    source: str,
) -> None:
    """The mesh scheduler joins a retired lease's predicted wall (by
    pricing provenance — tune/store/roofline/default) to the wall it
    measured, inside whatever harvest frame is open: ``explain`` and the
    bench legs read the observatory, not the scheduler's internals
    (docs/SCHEDULING.md "Observability")."""
    frame = current_frame()
    if frame is None:
        return
    leases = getattr(frame, "leases", None)
    if leases is None:
        leases = frame.leases = []  # type: ignore[attr-defined]
    leases.append(
        {
            "name": name,
            "kind": kind,
            "predicted_s": predicted_s,
            "measured_s": measured_s,
            "source": source,
        }
    )


# --------------------------------------------------------------- the sentinel


class DriftSentinel:
    """Noise-tolerant measured-vs-expected watchdog per (key, shape).

    What it scores depends on the prediction's unit:

    - ``rows_per_s`` predictions (MeasuredKnobRule's stream winners) are
      measurements in the exact unit and shape class they are compared
      at — scored directly: ``predicted_rate / achieved_rate``.
    - ``seconds`` predictions (autocache's linear fits) are
      extrapolations — a model is allowed constant bias, so the sentinel
      baselines on REALITY instead: the first warm (compile-free)
      execution writes ``measured_wall_s`` onto the backing ProfileStore
      entry, and later fits are scored ``measured / baseline``. Drift
      means the world moved while the stored decision stood still —
      exactly when replaying it stops being defensible. A legit
      re-measurement re-records the entry without the baseline field,
      so self-correcting paths re-baseline instead of false-firing.

    Compound-key predictions (a fused chain summing member claims) are
    never scored — their walls cannot be attributed to one entry — but a
    fire on any component marks every component stale.

    One out-of-band observation is noise; ``sustain`` consecutive ones
    are drift. Firing publishes ``keystone_cost_drift_events_total``,
    records a ``cost_drift`` recovery-ledger event (flight-recorder
    ringed), marks the backing ProfileStore entry ``stale:`` (so the
    consumer rules re-measure instead of replaying a stale winner), and
    resets the streak — one sustained drift is one event until fresh
    measurements land."""

    BASELINE_FIELD = "measured_wall_s"

    def __init__(self):
        self._lock = threading.Lock()
        self._streak: Dict[Tuple[str, str], int] = {}
        #: (key, shape) already observed by THIS process. The first
        #: sight of a key re-bases its stored baseline to the wall this
        #: process just measured instead of scoring it: ms-scale CPU
        #: walls jump several-fold between processes with ambient load
        #: (the bench-diff noise floor), so cross-process baselines are
        #: noise — drift is judged within a process, where the
        #: long-running consumers (serving, the refit daemon, a
        #: multi-pass explain) actually live.
        self._seen: set = set()
        self.events: List[Dict[str, Any]] = []

    def observe(
        self,
        node: str,
        prediction: Prediction,
        measured_s: Optional[float] = None,
        measured_rate: Optional[float] = None,
    ) -> Optional[Dict[str, Any]]:
        if (
            not drift_enabled()
            or not prediction.calibrated
            or not prediction.key
            or "," in prediction.key  # compound: unattributable
        ):
            return None
        from . import store as _store

        store = _store.get_store()
        if store is None:
            return None  # the sentinel rides the store (its marks live there)
        m = store.lookup(prediction.key, prediction.shape, include_stale=True)
        if m is None or _store.is_stale(m):
            return None  # evicted, or already flagged and awaiting re-measure

        base: Optional[float] = None
        ident = (prediction.key, prediction.shape)
        if prediction.rows_per_s and measured_rate:
            ratio = prediction.rows_per_s / max(measured_rate, 1e-12)
        elif prediction.seconds is not None and measured_s:
            base = m.get(self.BASELINE_FIELD)
            with self._lock:
                first_sight = ident not in self._seen
                self._seen.add(ident)
            if (
                first_sight
                or not isinstance(base, (int, float))
                or base <= 0
            ):
                # First warm execution this process (or since a
                # re-measurement): reality becomes the baseline; no
                # drift judgment yet (see _seen — cross-process walls
                # are noise at ms scale).
                baselined = dict(m)
                baselined[self.BASELINE_FIELD] = round(measured_s, 6)
                store.record(prediction.key, prediction.shape, **baselined)
                _names.metric(_names.COST_DRIFT_RATIO).set(
                    1.0, model=prediction.model
                )
                return None
            base = float(base)
            ratio = measured_s / base
        else:
            return None

        tol = drift_ratio_tolerance()
        _names.metric(_names.COST_DRIFT_RATIO).set(
            ratio, model=prediction.model
        )
        out_of_band = max(ratio, 1.0 / max(ratio, 1e-12)) > tol
        with self._lock:
            if not out_of_band:
                self._streak.pop(ident, None)
                # In-band observations smooth the baseline toward
                # current reality (EMA): a badly-timed first baseline
                # self-corrects instead of false-firing later, at the
                # documented cost that drift *slower than the band per
                # step* is absorbed — the sentinel hunts regime changes,
                # not creep.
                if (
                    base is not None
                    and measured_s
                    and abs(measured_s - float(base)) > 0.05 * float(base)
                ):
                    smoothed = dict(m)
                    smoothed[self.BASELINE_FIELD] = round(
                        0.7 * float(base) + 0.3 * measured_s, 6
                    )
                    store.record(
                        prediction.key, prediction.shape, **smoothed
                    )
                return None
            streak = self._streak.get(ident, 0) + 1
            if streak < drift_sustain():
                self._streak[ident] = streak
                return None
            self._streak.pop(ident, None)
        return self._fire(node, prediction, ratio)

    def _fire(
        self, node: str, prediction: Prediction, ratio: float
    ) -> Dict[str, Any]:
        event = {
            "node": node,
            "model": prediction.model,
            "key": prediction.key,
            "shape": prediction.shape,
            "ratio": round(ratio, 4),
            "stale_marked": False,
        }
        _names.metric(_names.COST_DRIFT_EVENTS).inc(model=prediction.model)
        if prediction.key:
            try:
                from . import store as _store

                store = _store.get_store()
                if store is not None:
                    marked = [
                        store.mark_stale(
                            key, prediction.shape, reason="cost_drift"
                        )
                        for key in prediction.key.split(",")
                    ]
                    event["stale_marked"] = any(marked)
            except Exception:
                pass
        try:
            # The recovery ledger is the event bus the flight recorder
            # rings — a drift lands in every post-mortem dump.
            from ..reliability.recovery import get_recovery_log

            get_recovery_log().record(
                "cost_drift", node,
                model=prediction.model, key=prediction.key,
                shape=prediction.shape, ratio=event["ratio"],
                stale_marked=event["stale_marked"],
            )
        except Exception:
            pass
        _spans.add_span_event("cost_drift", **event)
        with self._lock:
            self.events.append(event)
            del self.events[:-64]
        logger.warning(
            "cost-model drift: %s predicted %s under %s ratio=%.2f "
            "(entry %smarked stale)", prediction.model, node,
            prediction.key or "<unkeyed>", ratio,
            "" if event["stale_marked"] else "NOT ",
        )
        return event

    def seen_count(self) -> int:
        """Keys this process has observed (and therefore re-based) —
        the explain CLI's gate for when a seeded corruption is
        meaningful (a corruption before any in-process baseline exists
        is clobbered by the first re-base)."""
        with self._lock:
            return len(self._seen)

    def drain_events(self) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self.events)
            self.events.clear()
        return out


_sentinel = DriftSentinel()


def get_drift_sentinel() -> DriftSentinel:
    return _sentinel


# ------------------------------------------------------------------- finalize


def _label_of(op: Any) -> str:
    return str(getattr(op, "label", type(op).__name__))


def _sum_predictions(labels: Sequence[str]) -> Optional[Prediction]:
    resolved = [plan_prediction(m) for m in labels]
    parts = [p for p in resolved if p is not None and p.seconds is not None]
    if not parts:
        return None
    # Calibrated only with FULL member coverage: a partial sum both
    # understates the chain's claim and — when it collapses to a single
    # key — would slip past the sentinel's compound-key guard and score
    # the whole chain's wall against one member's entry.
    complete = len(parts) == len(labels)
    return Prediction(
        model=parts[0].model,
        key=",".join(p.key for p in parts if p.key),
        shape=parts[0].shape,
        seconds=sum(p.seconds for p in parts),
        calibrated=complete and all(p.calibrated for p in parts),
        source=parts[0].source,
    )


def _resolve_prediction(op: Any, label: str) -> Optional[Prediction]:
    pinned = getattr(op, "predicted_cost", None)
    if isinstance(pinned, Prediction):
        return pinned
    # Fused chains: the autocache profiler predicted the MEMBERS; their
    # per-node claims sum to the chain's (same work, one dispatch).
    members = getattr(op, "member_labels", None)
    if members:
        return _sum_predictions(list(members))
    # A streaming absorb (StreamingFitOperator) replaced estimator +
    # featurize members with one node: their plan-book claims sum the
    # same way (pinned measured-knob predictions, above, win over this).
    estimator = getattr(op, "estimator", None)
    absorbed = getattr(op, "members", None)
    if estimator is not None and absorbed is not None:
        return _sum_predictions(
            [_label_of(estimator)] + [_label_of(m) for m in absorbed]
        )
    return plan_prediction(label)


def finalize_node(
    label: str,
    seconds: float,
    synced: bool,
    op: Any = None,
    span: Any = None,
    frame: Optional[HarvestFrame] = None,
) -> Optional[PerfLedgerEntry]:
    """Close one node's harvest: resolve noted computations to flop/byte
    facts (cache-hit cheap), classify against the roofline, join the
    prediction that drove the plan, drift-score it, and land the ledger
    entry (plus span attributes for the trace view). Called by
    ``timed_execute`` AFTER the wall measurement so first-shape harvest
    cost never inflates node timings. Never raises."""
    try:
        return _finalize_node(label, seconds, synced, op, span, frame)
    except Exception as e:
        logger.debug("cost finalize failed for %s (%s)", label, e)
        return None


def _finalize_node(label, seconds, synced, op, span, frame):
    notes = frame.notes if frame is not None else []
    prediction = _resolve_prediction(op, label) if op is not None else (
        plan_prediction(label)
    )
    if not notes and prediction is None and not _record_all:
        return None

    flops_total: Optional[float] = None
    bytes_total: Optional[float] = None
    digest = ""
    kinds: List[str] = []
    for note in notes:
        facts = _cached_facts(note.fn, note.args, note.avals)
        note.args = None  # drop array refs promptly
        if facts is None:
            continue
        kinds.append(note.kind)
        if facts.flops is not None:
            flops_total = (flops_total or 0.0) + facts.flops
        if facts.bytes_accessed is not None:
            bytes_total = (bytes_total or 0.0) + facts.bytes_accessed
        digest = digest or facts.lowering_digest

    intensity = (
        flops_total / bytes_total if flops_total and bytes_total else None
    )
    roofline = get_roofline() if (flops_total or bytes_total) else _roofline
    classification = roofline.classify(intensity) if roofline else None

    flops_per_s = bytes_per_s = bound_frac = None
    if synced and seconds > 0:
        if flops_total:
            flops_per_s = flops_total / seconds
        if bytes_total:
            bytes_per_s = bytes_total / seconds
        if roofline and classification == "compute-bound" and flops_per_s:
            bound_frac = flops_per_s / max(roofline.peak_flops_per_s, 1e-9)
        elif roofline and classification == "memory-bound" and bytes_per_s:
            bound_frac = bytes_per_s / max(roofline.peak_bytes_per_s, 1e-9)

    predicted_s = predicted_model = None
    predicted_key = predicted_shape = ""
    calibrated = False
    predicted_candidates: Tuple = ()
    ratio = None
    drift = False
    cold = frame is not None and frame.compiles > 0
    if prediction is not None:
        predicted_model = prediction.model
        predicted_key = prediction.key
        predicted_shape = prediction.shape
        calibrated = prediction.calibrated
        predicted_candidates = tuple(getattr(prediction, "candidates", ()))
        if prediction.seconds is not None:
            predicted_s = prediction.seconds
        elif (
            prediction.rows_per_s
            and frame is not None
            and frame.num_examples
        ):
            predicted_s = frame.num_examples / prediction.rows_per_s
        # Display ratio in the prediction's own unit, >1 = slower than
        # predicted. (The sentinel scores its own baseline-relative
        # ratio — a model is allowed constant bias; see DriftSentinel.)
        if prediction.rows_per_s and frame is not None and frame.rows_per_s:
            ratio = prediction.rows_per_s / max(frame.rows_per_s, 1e-12)
        elif prediction.seconds and synced and seconds > 0:
            ratio = seconds / prediction.seconds
        if not cold:
            drift = (
                _sentinel.observe(
                    label,
                    prediction,
                    measured_s=seconds if synced and seconds > 0 else None,
                    measured_rate=(
                        frame.rows_per_s if frame is not None else None
                    ),
                ) is not None
            )
    elif roofline is not None:
        # No model claimed this node: the roofline's first-principles
        # floor is the displayed prediction (never drift-scored).
        predicted_s = roofline.predicted_seconds(flops_total, bytes_total)
        predicted_model = "roofline" if predicted_s is not None else None

    entry = PerfLedgerEntry(
        node=label,
        seconds=round(seconds, 6),
        synced=synced,
        cold=cold,
        t_s=time.perf_counter(),
        t_unix=round(time.time(), 6),
        flops=flops_total,
        bytes_accessed=bytes_total,
        intensity=intensity,
        flops_per_s=flops_per_s,
        bytes_per_s=bytes_per_s,
        roofline=classification,
        bound_frac=bound_frac,
        lowering_digest=digest,
        kinds=tuple(kinds),
        predicted_s=predicted_s,
        predicted_model=predicted_model,
        predicted_key=predicted_key,
        predicted_shape=predicted_shape,
        predicted_calibrated=calibrated,
        predicted_candidates=predicted_candidates,
        ratio=ratio,
        drift=drift,
        rows_per_s=frame.rows_per_s if frame is not None else None,
    )
    _ledger.record(entry)

    if span is not None:
        if flops_total is not None:
            span.set_attribute("flops", flops_total)
        if bytes_total is not None:
            span.set_attribute("bytes_accessed", bytes_total)
        if classification is not None:
            span.set_attribute("roofline", classification)
        if digest:
            # The executable fingerprint: joins this span to ledger
            # entries and ProfileStore keys deterministically (the
            # fused-member-names attr alone never could).
            span.set_attribute("lowering_digest", digest)
        if predicted_s is not None:
            span.set_attribute("predicted_s", round(predicted_s, 6))
            span.set_attribute("predicted_model", predicted_model)
    return entry


# Record-all mode: explain wants a ledger entry for EVERY executed plan
# node (host-side ops included), not just harvested/predicted ones.
_record_all = False


def record_all_nodes(value: bool) -> None:
    global _record_all
    _record_all = bool(value)


# ---------------------------------------------------------------------- reset


def reset_cost_observatory() -> None:
    """Testing hook: drop ledger entries, sentinel state, plan
    predictions, facts cache, and the cached roofline."""
    global _ledger, _sentinel, _record_all
    with _facts_lock:
        _facts_cache.clear()
    reset_plan_predictions()
    set_roofline(None)
    _ledger = PerfLedger()
    _sentinel = DriftSentinel()
    _record_all = False
