"""Fleet aggregation: merge per-process telemetry into one trace and one
scrape.

The multi-worker runtime (docs/SERVING.md) is a fleet of processes —
HTTP frontend + supervisor in one, N workers — each with its own
:class:`~keystone_tpu.obs.spans.TraceSession` and metrics registry.
This module is the plane that makes them one system:

- **Span fragments** (:func:`span_fragment`): a span serialized with
  *absolute unix* timestamps (``session.started_unix`` anchors the
  perf_counter offsets), so fragments from different processes merge
  without exchanging clock bases — processes on one host share the wall
  clock, and the residual skew estimate from the heartbeat handshake is
  published as ``keystone_fleet_clock_skew_seconds`` (the alignment
  model docs/OBSERVABILITY.md documents).
- **FleetTraceCollector**: the supervisor-side sink. Workers ship
  fragments + metric-registry deltas on the existing heartbeat channel
  (bounded per beat); the collector files them per (role, pid), folds
  metric deltas monotonically across worker *incarnations* (a restarted
  worker's counters restart from zero; the fleet's must not), and
  :meth:`merge`\\ s everything — worker fragments plus the local
  session — into one Perfetto-loadable Chrome trace with per-process
  tracks.
- **Fleet Prometheus** (:func:`fleet_prometheus_text`): the frontend's
  ``GET /metrics`` body — the local registry (the supervisor's own
  ``keystone_serving_*`` series live here) plus ``keystone_fleet_*``
  counters published from the supervisor's restart-safe high-water
  aggregation.
- **``keystone-tpu trace``** (:func:`trace_from_args`): drive a traffic
  sweep against a real multiworker fleet (stub or synthetic backend,
  optional seeded worker kill) and emit the merged trace + scrape
  artifacts — the CI face (scripts/trace_smoke.sh).

Stdlib-only at import time, like the rest of ``obs/``.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import names as _names
from .export import _json_safe, prometheus_text
from .metrics import get_registry
from .spans import Span, TraceSession

#: env flag: workers (and the serve front-end) install a process-lifetime
#: span session and ship fragments on heartbeats when set.
FLEET_TRACE_ENV = "KEYSTONE_FLEET_TRACE"

#: fragments shipped per heartbeat at most — one beat must stay one
#: cheap line; a burst drains over the next beats.
FRAGMENTS_PER_BEAT = 128

#: per-process fragment retention in the collector (drop-oldest).
MAX_FRAGMENTS_PER_PROCESS = 20_000

#: worker counters aggregated monotonically across incarnations
#: (supervisor high-water marks; docs/SERVING.md).
MONOTONIC_WORKER_COUNTERS = (
    "served", "batches", "sheds", "timeouts", "retries", "failures",
)


# ------------------------------------------------------------ span fragments


def span_fragment(span: Span, session: TraceSession) -> Dict[str, Any]:
    """One span as a compact wire fragment with ABSOLUTE unix times —
    ``a``/``b`` are start/end seconds since the epoch, so fragments from
    any process merge on a shared axis. Keys are short on purpose: these
    ride heartbeat lines."""
    origin = session.started_unix - session.started_s
    end = span.end_s if span.end_s is not None else span.start_s
    fragment: Dict[str, Any] = {
        "n": span.name,
        "t": span.trace_id,
        "s": span.span_id,
        "a": round(origin + span.start_s, 6),
        "b": round(origin + end, 6),
        "tid": span.thread_id or 0,
        "tn": span.thread_name,
    }
    if span.parent_id:
        fragment["p"] = span.parent_id
    if span.status != "ok":
        fragment["st"] = span.status
    if span.attributes:
        fragment["at"] = {
            k: _json_safe(v) for k, v in span.attributes.items()
        }
    return fragment


def drain_fragments(
    session: TraceSession, cursor: int, limit: int = FRAGMENTS_PER_BEAT
) -> Tuple[List[Dict[str, Any]], int]:
    """Fragments for the session's spans past ``cursor`` (bounded by
    ``limit``), plus the advanced cursor. ``cursor`` is an ABSOLUTE
    accepted-span index (``TraceSession.added``), so it stays a stable
    ship-once iterator even for ring sessions: spans evicted before
    they could ship are skipped (the ring outran the heartbeat), never
    re-shipped or double-shipped."""
    buffer, total = session.tail()
    base = total - len(buffer)  # absolute index of buffer[0]
    start = max(cursor, base)
    fresh = buffer[start - base:start - base + limit]
    return [span_fragment(s, session) for s in fresh], start + len(fresh)


# ---------------------------------------------------------------- collector


class FleetTraceCollector:
    """Supervisor-side sink for worker span fragments, clock anchors,
    and metric-registry deltas."""

    def __init__(self):
        self._lock = threading.Lock()
        self._fragments: Dict[Tuple[str, int], List[Dict[str, Any]]] = {}
        self._fragment_drops = 0
        self._clocks: Dict[Tuple[str, int], Dict[str, float]] = {}
        #: worker_id → (incarnation, live cumulative series values)
        self._metric_live: Dict[str, Tuple[int, Dict[str, float]]] = {}
        #: worker_id → folded totals from dead incarnations
        self._metric_base: Dict[str, Dict[str, float]] = {}
        self._m_fragments = _names.metric(_names.FLEET_SPAN_FRAGMENTS)
        self._m_bytes = _names.metric(_names.FLEET_TRACE_BYTES)
        self._m_skew = _names.metric(_names.FLEET_CLOCK_SKEW)

    # ------------------------------------------------------------- ingestion
    def add_fragments(
        self,
        role: str,
        pid: int,
        fragments: List[Dict[str, Any]],
        raw_bytes: Optional[int] = None,
    ) -> None:
        """File one shipment of fragments. ``raw_bytes`` is the wire
        size the caller already knows (the heartbeat line length —
        supervisor reader threads must not re-serialize every fragment
        just to count bytes); without it, fall back to measuring."""
        if not fragments:
            return
        if raw_bytes is None:
            raw_bytes = sum(len(json.dumps(f)) for f in fragments)
        with self._lock:
            bucket = self._fragments.setdefault((role, int(pid or 0)), [])
            bucket.extend(fragments)
            overflow = len(bucket) - MAX_FRAGMENTS_PER_PROCESS
            if overflow > 0:
                del bucket[:overflow]
                self._fragment_drops += overflow
        self._m_fragments.inc(len(fragments), role=role)
        self._m_bytes.inc(raw_bytes)

    def observe_clock(
        self, role: str, pid: int, clock: Dict[str, Any]
    ) -> None:
        """Heartbeat/ready handshake: the shipper's wall+perf anchors at
        emit time. ``time.time() - unix`` at receipt bounds skew from
        above by the pipe latency — on one host that residual IS the
        alignment error of the merged trace."""
        unix = clock.get("unix")
        if not isinstance(unix, (int, float)):
            return
        skew = time.time() - float(unix)
        with self._lock:
            self._clocks[(role, int(pid or 0))] = {
                "unix": float(unix),
                "perf": float(clock.get("perf") or 0.0),
                "received_unix": time.time(),
                "skew_s": round(skew, 6),
            }
        self._m_skew.set(round(skew, 6), role=role)

    def observe_metrics(
        self, worker_id: str, incarnation: int, delta: Dict[str, Any]
    ) -> None:
        """Fold one heartbeat's metric-registry delta. Deltas accumulate
        per (worker, incarnation); a new incarnation folds the previous
        one's cumulative values into the worker's base, so
        :meth:`metric_totals` stays monotonic through restarts."""
        with self._lock:
            live_incarnation, live = self._metric_live.get(
                worker_id, (None, {})
            )
            if live_incarnation != incarnation:
                base = self._metric_base.setdefault(worker_id, {})
                for key, value in live.items():
                    base[key] = base.get(key, 0.0) + value
                live = {}
            for key, value in delta.items():
                if isinstance(value, (int, float)):
                    live[key] = live.get(key, 0.0) + float(value)
            self._metric_live[worker_id] = (incarnation, live)

    # ----------------------------------------------------------------- views
    def fragments(self) -> Dict[Tuple[str, int], List[Dict[str, Any]]]:
        with self._lock:
            return {k: list(v) for k, v in self._fragments.items()}

    def clocks(self) -> Dict[Tuple[str, int], Dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._clocks.items()}

    def metric_totals(self) -> Dict[str, float]:
        """Fleet-cumulative series values: sum over workers of folded
        base + live incarnation. Monotonic by construction."""
        out: Dict[str, float] = {}
        with self._lock:
            for worker_id, (_, live) in self._metric_live.items():
                for key, value in live.items():
                    out[key] = out.get(key, 0.0) + value
            for base in self._metric_base.values():
                for key, value in base.items():
                    out[key] = out.get(key, 0.0) + value
        return out

    # ----------------------------------------------------------------- merge
    def merge(
        self,
        local_session: Optional[TraceSession] = None,
        local_role: str = "supervisor",
    ) -> Dict[str, Any]:
        """One Perfetto-loadable Chrome trace over every process: worker
        fragments plus the local session's spans, pid-mapped tracks with
        process_name/thread_name metadata, timestamps normalized to the
        earliest fragment."""
        import os

        per_process = self.fragments()
        if local_session is not None:
            local = [
                span_fragment(s, local_session)
                for s in local_session.spans()
            ]
            key = (local_role, os.getpid())
            per_process[key] = per_process.get(key, []) + local

        starts = [
            f["a"] for frags in per_process.values() for f in frags
        ]
        t0 = min(starts) if starts else 0.0
        events: List[Dict[str, Any]] = []
        processes: Dict[int, str] = {}
        trace_ids: set = set()
        threads_seen: Dict[Tuple[int, int], str] = {}
        for (role, pid), frags in sorted(per_process.items()):
            processes[pid] = role
            for f in frags:
                trace_ids.add(f["t"])
                tid = int(f.get("tid") or 0)
                if (pid, tid) not in threads_seen:
                    threads_seen[(pid, tid)] = f.get("tn") or f"thread-{tid}"
                args: Dict[str, Any] = dict(f.get("at") or {})
                args["trace_id"] = f["t"]
                args["span_id"] = f["s"]
                if f.get("p"):
                    args["parent_id"] = f["p"]
                if f.get("st"):
                    args["status"] = f["st"]
                events.append(
                    {
                        "name": f["n"],
                        "cat": f["n"].split(":", 1)[0] or "span",
                        "ph": "X",
                        "ts": round((f["a"] - t0) * 1e6, 3),
                        "dur": round(max(f["b"] - f["a"], 0.0) * 1e6, 3),
                        "pid": pid,
                        "tid": tid,
                        "args": args,
                    }
                )
        for pid, role in processes.items():
            events.append(
                {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                 "args": {"name": role}}
            )
        for (pid, tid), name in threads_seen.items():
            events.append(
                {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                 "args": {"name": name}}
            )
        clock_skews = {
            f"{role}:{pid}": anchors.get("skew_s")
            for (role, pid), anchors in self.clocks().items()
        }
        with self._lock:
            dropped = self._fragment_drops
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "trace_ids": sorted(trace_ids),
                "processes": {str(pid): role for pid, role in processes.items()},
                "base_unix": t0,
                "clock_skew_s": clock_skews,
                "dropped_fragments": dropped,
            },
        }


def write_fleet_trace(
    collector: FleetTraceCollector,
    path: str,
    local_session: Optional[TraceSession] = None,
    local_role: str = "supervisor",
) -> str:
    with open(path, "w") as f:
        json.dump(
            collector.merge(local_session=local_session, local_role=local_role), f
        )
    return path


# ------------------------------------------------------------- /metrics body


# Serializes the read-compare-raise below: two concurrent /metrics
# scrapes racing it would BOTH inc by (target - current) and inflate
# the counter forever (each metric's own lock only makes value() and
# inc() individually atomic, not the pair).
_publish_lock = threading.Lock()


def publish_fleet_metrics(supervisor: Any) -> None:
    """Fold the supervisor's restart-safe per-worker counter totals into
    the ``keystone_fleet_*`` registry series. Counter-safe: each series
    is raised to its new high-water value by a non-negative increment,
    so the exposition stays monotonic through worker restarts."""
    totals = supervisor.fleet_counter_totals()
    m_requests = _names.metric(_names.FLEET_REQUESTS)
    m_failures = _names.metric(_names.FLEET_FAILURES)
    with _publish_lock:
        for worker_id, counters in totals.items():
            for metric_obj, key in (
                (m_requests, "served"), (m_failures, "failures")
            ):
                target = float(counters.get(key, 0.0) or 0.0)
                current = metric_obj.value(worker=worker_id)
                if target > current:
                    metric_obj.inc(target - current, worker=worker_id)
    # The heartbeat-shipped metric-registry deltas, folded monotonically
    # per incarnation by the collector, surface as one gauge family
    # keyed by the worker-side series name — the worker processes' OWN
    # counters (their in-process servers' retries, bucket hits, ...)
    # are otherwise invisible to a frontend scrape.
    collector = getattr(supervisor, "fleet", None)
    if collector is not None:
        gauge = _names.metric(_names.FLEET_WORKER_SERIES)
        for series, value in collector.metric_totals().items():
            gauge.set(round(value, 6), series=series)
    # Quality plane: the supervisor's fleet-merged sketch/stream state
    # surfaces as keystone_quality_* gauges on the same scrape.
    quality = getattr(supervisor, "quality", None)
    if quality is not None:
        quality.publish_metrics()


def fleet_prometheus_text(supervisor: Any) -> str:
    """The frontend's ``GET /metrics`` body: the full local registry
    (pre-registered so the schema exports completely) plus the fleet
    counters above."""
    _names.register_all()
    if supervisor is not None and hasattr(supervisor, "fleet_counter_totals"):
        publish_fleet_metrics(supervisor)
    return prometheus_text(get_registry())


# ----------------------------------------------------------------- trace CLI


def add_trace_arguments(parser) -> None:
    """Flags for ``keystone-tpu trace`` (plain argparse — the CLI's
    --help path must stay jax-free; the default stub backend keeps the
    whole run jax-free too)."""
    parser.add_argument(
        "--workers", type=int, default=2, help="worker processes in the fleet"
    )
    parser.add_argument(
        "--requests", type=int, default=64, help="HTTP requests to sweep"
    )
    parser.add_argument(
        "--synthetic", type=int, default=None, metavar="D",
        help="serve a synthetic D-dim jax pipeline (default: the jax-free "
             "stub echo backend — the pipe layer is what fleet tracing "
             "instruments)",
    )
    parser.add_argument(
        "--stub-delay-ms", type=float, default=0.0,
        help="per-request delay of the stub backend",
    )
    parser.add_argument(
        "--kill-request", type=int, default=0,
        help="SIGKILL worker 0 at its Nth request (0 = no chaos); the "
             "killed worker leaves a flight-recorder dump and its "
             "in-flight work requeues under the same trace id",
    )
    parser.add_argument(
        "--concurrency", type=int, default=4,
        help="parallel HTTP client threads",
    )
    parser.add_argument(
        "--deadline-ms", type=float, default=60000.0,
        help="per-request deadline for the sweep",
    )
    parser.add_argument(
        "--out-dir", default="tracedir",
        help="directory for fleet_trace.json / fleet_metrics.prom / "
             "flightrec-*.json",
    )
    parser.add_argument("--listen", default="127.0.0.1:0")


def trace_from_args(args) -> int:
    """Drive a traffic sweep against a real multiworker fleet under full
    fleet tracing; write the merged Perfetto trace and two /metrics
    scrapes; print one ``TRACE_STATS:`` JSON line (the smoke-script
    contract, scripts/trace_smoke.sh)."""
    import os
    import queue as queue_mod
    import urllib.request

    from ..reliability.retry import RetryPolicy
    from ..serving.frontend import ServingFrontend, parse_listen
    from ..serving.supervisor import (
        FAULT_SPECS_WORKER_ENV,
        SupervisorConfig,
        WorkerSupervisor,
    )
    from . import spans as _spans
    from .flight import install_flight_recorder

    out_dir = os.path.abspath(args.out_dir)
    os.makedirs(out_dir, exist_ok=True)
    recorder = install_flight_recorder("frontend", out_dir=out_dir)
    session = _spans.install_session("fleet-trace", sync_timings=False)

    d = args.synthetic or 4
    spec: Dict[str, Any] = (
        {"synthetic": {"d": args.synthetic}}
        if args.synthetic
        else {"stub": {"delay_ms": args.stub_delay_ms}}
    )
    env = {FLEET_TRACE_ENV: "1", "KEYSTONE_FLIGHT_DIR": out_dir}
    if args.kill_request:
        env[FAULT_SPECS_WORKER_ENV + "0"] = json.dumps(
            [{"match": "serving.worker.request", "kind": "kill",
              "calls": [args.kill_request]}]
        )
    supervisor = WorkerSupervisor(
        spec,
        SupervisorConfig(
            workers=args.workers,
            heartbeat_s=0.1,
            hang_timeout_s=10.0,
            ready_timeout_s=240.0,
            queue_depth=args.requests + 64,
            worker_queue_depth=args.requests + 32,
            restart_policy=RetryPolicy(
                max_attempts=4, base_delay_s=0.2, max_delay_s=2.0
            ),
        ),
        env=env,
    ).start()
    host, port = parse_listen(args.listen)
    frontend = None
    errors = 0
    scrapes: List[str] = []
    try:
        supervisor.wait_ready()
        frontend = ServingFrontend(
            supervisor, host, port,
            default_deadline_s=args.deadline_ms / 1e3,
        ).start()
        base_url = f"http://{frontend.host}:{frontend.port}"

        def scrape() -> str:
            with urllib.request.urlopen(base_url + "/metrics", timeout=30) as r:
                return r.read().decode()

        work: "queue_mod.Queue" = queue_mod.Queue()
        for i in range(args.requests):
            work.put(i)
        error_lock = threading.Lock()
        error_box = [0]

        def client() -> None:
            while True:
                try:
                    i = work.get_nowait()
                except queue_mod.Empty:
                    return
                body = json.dumps(
                    {"x": [float(i % 7)] * d, "deadline_ms": args.deadline_ms}
                ).encode()
                request = urllib.request.Request(
                    base_url + "/v1/apply", data=body,
                    headers={"Content-Type": "application/json"},
                )
                try:
                    with urllib.request.urlopen(request, timeout=120) as r:
                        json.loads(r.read())
                except Exception:
                    with error_lock:
                        error_box[0] += 1

        with _spans.span("trace:sweep", requests=args.requests):
            threads = [
                threading.Thread(target=client, name=f"trace-client-{t}")
                for t in range(max(args.concurrency, 1))
            ]
            for t in threads:
                t.start()
            # Mid-sweep scrape: with the after-sweep scrape below it is
            # the monotonic-through-restart evidence the smoke asserts.
            time.sleep(0.2)
            scrapes.append(scrape())
            for t in threads:
                t.join()
        errors = error_box[0]

        # Let straggling heartbeats ship the tail fragments, then scrape
        # again and merge.
        time.sleep(max(supervisor.config.heartbeat_s * 4, 0.4))
        scrapes.append(scrape())
        merged = supervisor.fleet.merge(
            local_session=session, local_role="frontend"
        )
        stats = supervisor.stats()
    finally:
        if frontend is not None:
            frontend.stop()
        supervisor.stop()

    trace_path = os.path.join(out_dir, "fleet_trace.json")
    with open(trace_path, "w") as f:
        json.dump(merged, f)
    prom_path = os.path.join(out_dir, "fleet_metrics.prom")
    with open(prom_path, "w") as f:
        f.write(scrapes[-1])

    def fleet_served(text: str) -> float:
        total = 0.0
        for line in text.splitlines():
            if line.startswith(_names.FLEET_REQUESTS + "{"):
                try:
                    total += float(line.rsplit(" ", 1)[1])
                except ValueError:
                    pass
        return total

    span_counts: Dict[str, int] = {}
    for event in merged["traceEvents"]:
        if event.get("ph") == "X":
            role = merged["otherData"]["processes"].get(str(event["pid"]), "?")
            span_counts[role] = span_counts.get(role, 0) + 1
    flight_dumps = sorted(
        name for name in os.listdir(out_dir) if name.startswith("flightrec-")
    )
    summary = {
        "trace_path": trace_path,
        "prom_path": prom_path,
        "requests": args.requests,
        "errors": errors,
        "trace_ids": merged["otherData"]["trace_ids"][:8],
        "processes": merged["otherData"]["processes"],
        "span_counts": span_counts,
        "clock_skew_s": merged["otherData"]["clock_skew_s"],
        "metric_families": scrapes[-1].count("# HELP"),
        "fleet_served_mid": fleet_served(scrapes[0]),
        "fleet_served_final": fleet_served(scrapes[-1]),
        "requeued": stats["supervisor"]["requeued"],
        "restarts": stats["supervisor"]["restarts"],
        "flight_dumps": flight_dumps,
        "local_flight_dumps": [d["trigger"] for d in recorder.dumps],
    }
    print("TRACE_STATS:" + json.dumps(summary))
    return 0
