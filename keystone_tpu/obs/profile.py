"""``keystone-tpu profile``: run a pipeline under full instrumentation and
write both export formats.

Drives the synthetic MNIST random-FFT workload (featurize → block least
squares) through fit, batch apply, and a burst of online serving — the
three execution modes the system has — inside one
:class:`~keystone_tpu.obs.spans.TraceSession` with the full metric schema
pre-registered. Outputs, into ``--out``:

- ``profile_trace.json`` — Chrome trace-event JSON; open in Perfetto
  (https://ui.perfetto.dev) to see pipeline → node → solver spans nested
  on their threads.
- ``profile_metrics.prom`` — Prometheus text exposition of every metric,
  executor/autocache/reliability/serving included.

plus a span-tree table on stdout. The flag surface stays stdlib-only
(:func:`add_profile_arguments`); everything heavy imports inside
:func:`run_profile`.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict

from . import device, export, metrics, names, spans

logger = logging.getLogger(__name__)


def add_profile_arguments(parser) -> None:
    """Flags for the ``keystone-tpu profile`` subcommand (plain argparse —
    the CLI's --help path must stay jax-free)."""
    parser.add_argument(
        "--rows", type=int, default=512,
        help="synthetic training rows (default: 512)",
    )
    parser.add_argument(
        "--num-ffts", type=int, default=2,
        help="featurizer branches (default: 2)",
    )
    parser.add_argument(
        "--block-size", type=int, default=256,
        help="solver block size (default: 256)",
    )
    parser.add_argument(
        "--serve-requests", type=int, default=32,
        help="online requests to fire through PipelineServer (default: 32)",
    )
    parser.add_argument(
        "--out", default=None,
        help="deprecated alias of --out-dir",
    )
    parser.add_argument(
        "--out-dir", default=None, dest="out_dir",
        help="directory for profile_trace.json / profile_metrics.prom "
             "(default: current directory)",
    )
    parser.add_argument(
        "--no-autocache", action="store_true",
        help="skip the profile-driven auto-cache planner during fit",
    )
    parser.add_argument(
        "--no-serve", action="store_true",
        help="skip the serving phase",
    )
    parser.add_argument(
        "--device-annotations", action="store_true",
        help="wrap node execution in jax.profiler.TraceAnnotation "
             "(useful under an active XLA profiler capture)",
    )


def profile_from_args(args) -> int:
    result = run_profile(
        rows=args.rows,
        num_ffts=args.num_ffts,
        block_size=args.block_size,
        serve_requests=0 if args.no_serve else args.serve_requests,
        out_dir=args.out_dir or args.out or ".",
        autocache=not args.no_autocache,
        annotations=args.device_annotations,
    )
    # Store round-trip evidence (asserted by scripts/profile_smoke.sh):
    # hits prove a previous run's measurements were read back, writes
    # prove this run's were persisted.
    print("PROFILE_STORE:" + json.dumps(result["summary"].get(
        "profile_store", {"enabled": False}
    )))
    print("PROFILE_JSON:" + json.dumps(result["summary"]))
    return 0


def run_profile(
    rows: int = 512,
    num_ffts: int = 2,
    block_size: int = 256,
    serve_requests: int = 32,
    out_dir: str = ".",
    autocache: bool = True,
    annotations: bool = False,
) -> Dict[str, Any]:
    """Fit + apply + serve the synthetic pipeline under instrumentation;
    returns ``{"summary": ..., "session": TraceSession, "report": str}``."""
    from ..pipelines.mnist_random_fft import (
        MnistRandomFFTConfig,
        build_pipeline,
        synthetic_mnist,
    )
    from ..workflow.executor import PipelineEnv
    from ..workflow.rules import auto_caching_optimizer
    from . import store as obs_store

    names.register_all()
    # Save the raw override (None = following the env), not the resolved
    # bool: restoring a resolved False would PIN annotations off
    # process-wide and re-introduce the stale-env bug device.py fixed.
    annotations_before = device._annotations_enabled
    if annotations:
        device.set_device_annotations(True)
    os.makedirs(out_dir, exist_ok=True)
    # The profile harness is an analysis run: the cost observatory rides
    # along (per-node flop/byte facts + the cost-ledger counter track in
    # the exported trace), restored to the prior override afterwards.
    # Flipped inside the try below so no exception path can leak the
    # forced-on observatory process-wide.
    from . import cost as _cost

    cost_override_before = _cost._enabled_override

    registry = metrics.get_registry()
    before = registry.snapshot()
    config = MnistRandomFFTConfig(
        num_ffts=max(1, num_ffts), block_size=max(8, block_size)
    )
    summary: Dict[str, Any] = {
        "rows": rows,
        "num_ffts": config.num_ffts,
        "block_size": config.block_size,
    }

    # Profile-store round trip: remember this harness run's phase walls
    # per workload shape, and surface the PREVIOUS run's next to them —
    # the CLI's own run-over-run comparison (docs/OBSERVABILITY.md).
    store = obs_store.get_store()
    store_key = f"profile:mnist_fft:ffts{config.num_ffts}"
    store_shape = obs_store.shape_class(rows, (config.block_size,))
    if store is not None:
        previous = store.lookup(store_key, store_shape)
        if previous is not None:
            summary["previous"] = previous

    env = PipelineEnv.get_or_create()
    optimizer_before = env._optimizer  # restore below: run_profile is a
    try:                               # library API, not a process owner
        _cost.set_cost_observatory(True)
        with spans.tracing_session("profile") as session:
            with spans.span("profile", rows=rows):
                if autocache:
                    env.optimizer = auto_caching_optimizer()

                with spans.span("phase:fit"), device.stage_memory("fit"):
                    train = synthetic_mnist(rows, seed=0)
                    t0 = time.perf_counter()
                    fitted = build_pipeline(config, train).fit()
                    summary["fit_s"] = round(time.perf_counter() - t0, 3)

                with spans.span("phase:apply", rows=min(rows, 128)), \
                        device.stage_memory("apply"):
                    test = synthetic_mnist(min(rows, 128), seed=1)
                    t0 = time.perf_counter()
                    fitted(test.data).get()
                    summary["apply_s"] = round(time.perf_counter() - t0, 3)

                if serve_requests > 0:
                    with spans.span("phase:serve", requests=serve_requests), \
                            device.stage_memory("serve"):
                        summary["serve"] = _serve_burst(fitted, serve_requests)
    finally:
        env._optimizer = optimizer_before
        device.set_device_annotations(annotations_before)
        _cost.set_cost_observatory(cost_override_before)

    if store is not None:
        store.record(
            store_key, store_shape,
            fit_s=summary.get("fit_s"), apply_s=summary.get("apply_s"),
        )
        summary["profile_store"] = {"enabled": True, **store.stats()}
    else:
        summary["profile_store"] = {"enabled": False}

    from ..workflow.streaming import last_stream_report

    from .flight import get_flight_recorder

    recorder = get_flight_recorder()
    trace_path = export.write_chrome_trace(
        session, os.path.join(out_dir, "profile_trace.json"),
        stream_report=last_stream_report(),
        cost_ledger=_cost.get_ledger().tail(_cost.get_ledger().capacity),
        quality_ring=recorder.quality_ring() if recorder is not None else None,
    )
    prom_path = export.write_prometheus(
        os.path.join(out_dir, "profile_metrics.prom"), registry
    )
    summary["spans"] = len(session)
    summary["metrics_delta_keys"] = len(metrics.delta(registry.snapshot(), before))
    summary["trace_path"] = trace_path
    summary["prometheus_path"] = prom_path
    text = export.report(session)
    print(text)
    return {"summary": summary, "session": session, "report": text}


def _serve_burst(fitted, n_requests: int) -> Dict[str, Any]:
    """Fire a burst through PipelineServer so request traces and the full
    serving metric set land in the profile."""
    import numpy as np

    from ..serving import PipelineServer, ServingConfig
    from ..pipelines.mnist_random_fft import MNIST_IMAGE_SIZE

    rng = np.random.default_rng(7)
    example = np.zeros((MNIST_IMAGE_SIZE,), np.float32)
    server = PipelineServer(
        fitted,
        config=ServingConfig(
            max_batch=8, max_wait_ms=2.0, queue_depth=n_requests + 16
        ),
    ).start()
    try:
        server.warmup(example)
        payloads = [
            rng.standard_normal(MNIST_IMAGE_SIZE).astype(np.float32)
            for _ in range(n_requests)
        ]
        t0 = time.perf_counter()
        futures = server.submit_many(payloads)
        errors = sum(1 for f in futures if f.exception(timeout=120) is not None)
        elapsed = time.perf_counter() - t0
        stats = server.stats()
    finally:
        server.stop()
    return {
        "requests": n_requests,
        "errors": errors,
        "rps": round((n_requests - errors) / max(elapsed, 1e-9), 1),
        "p99_ms": stats.get("p99_ms"),
        "xla_compiles_since_warmup": stats.get("xla_compiles_since_warmup"),
    }
