"""bench-diff: run-over-run BENCH comparison with a regression verdict.

The bench suite has produced six ``BENCH_*.json`` snapshots and nothing
has ever compared run N to run N−1 — perf regressions accrete silently.
This module is the gate: given a baseline artifact and a current one, it
compares every shared leg with *noise-aware* rules and emits a verdict
(exit code 1 on regression) that tier-1 CI runs on every push.

Comparison rules, per flattened leg key:

- **counts** (``*dispatches*``, ``compiles_first_chunk``,
  ``compiles_steady_state``, ``chunks``, ``*dropped*``) are compared
  **exactly** — a fused chain that suddenly dispatches twice, a steady-
  state compile appearing, or a serving leg dropping a request under
  chaos is a structural regression no tolerance should forgive.
- **timings** (``*_ms``, ``*_s``, ``*_seconds``) are compared as ratios
  with a configurable tolerance (default ±50% — CI machines are noisy)
  and an absolute floor (default 50 ms — jitter on a 3 ms leg is not a
  regression). Skipped entirely unless BOTH artifacts declare the SAME
  platform (a TPU baseline says nothing about CPU CI walls, and a
  truncated wrapper with no platform key may carry either).
- **parity** (``parity_rel_err``) is bounded: worse than 10× baseline
  AND above 1e-3 flags a numerical regression.
- **booleans** (``overlap_ok``) regress on true→false.
- **config** keys (``n``, ``d``, ``k``, ``shape``, ``iters``, …) must
  match for a leg to be comparable at all; mismatched legs are reported
  ``incomparable`` and skipped (they measured different problems).
- legs that errored/skipped in the BASELINE are skipped; a leg that was
  healthy in the baseline but errors NOW is itself a regression.

Artifact formats accepted: the driver wrapper (``{"tail": ...}`` with
the result JSON inside the tail — possibly truncated, in which case
whole-leg objects are still recovered line-by-line), the bench's own
single-line result / ``BENCH_PARTIAL.json`` dump, and a raw
``BENCH_CHILD_JSON`` report. Stdlib-only: the CLI help path and CI can
run this without jax.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

# Leg-level keys that are run metadata, never measurements.
_META_KEYS = {
    "platform", "device_kind", "backend_init_s", "small_shapes",
    "compilation_cache", "diagnostics", "metric", "value", "unit",
    "vs_baseline", "partial", "phase", "best_onchip_run",
}
_CONFIG_KEYS = {
    "n", "d", "k", "shape", "iters", "chain_nodes", "num_epochs",
    "chunks", "chunk_rows", "block_size", "mode", "method",
    "requests", "solver_precision",
}
_EXACT_SUBSTRINGS = (
    "dispatches", "compiles_first_chunk", "compiles_steady_state",
    "bytes_transferred",  # deterministic for a pinned dataset + dtype plan
    "dropped",  # serving chaos invariant: a dropped request is never OK
    # Partitioner invariants (docs/PARTITIONING.md): shard counts and the
    # finish-reduce payload are pure functions of the pinned plan.
    "collective_bytes", "shards_chosen",
    # Block-sparse invariants (docs/AUTOTUNING.md): density and skipped
    # tiles are pure functions of the deterministic corpus + hash.
    "density", "blocks_skipped",
    # Continuous-refit invariants (docs/REFIT.md): the deterministic
    # drifting workload publishes, skips, and rolls back EXACTLY the
    # same rounds every run — a changed count is a changed loop.
    "publishes", "rollbacks", "skips",
    # Cost-observatory invariant (docs/OBSERVABILITY.md "Cost
    # observatory"): harvesting rides the jit trace cache and must
    # compile NOTHING — any nonzero count is a broken harvest path.
    "harvest_compiles",
    # Quality-plane invariant (docs/OBSERVABILITY.md "Quality plane"):
    # the sequential gate's decision count is deterministic in the
    # seeded loop — a pure serving sweep decides nothing, the refit
    # demo decides exactly its seeded rounds. (quality_sketch_bytes
    # stays under the skip list's generic "bytes" — heartbeat timing
    # shapes what a killed worker managed to ship.)
    "quality_decisions",
    # Sketched-tier invariant (docs/SOLVERS.md): the sketch/Gram state
    # footprints are pure functions of (s, d, k) — a changed byte count
    # is a changed state layout, not noise. (Matched before the skip
    # list's generic "bytes".)
    "state_bytes",
    # Co-scheduler invariants (docs/SCHEDULING.md): the cosched leg's
    # seeded pressure window admits, defers, preempts, and resumes
    # EXACTLY the same leases every run — a changed count is a changed
    # admission policy, not noise.
    "leases", "preemptions",
)
_SKIP_SUBSTRINGS = (
    # Environment-dependent measurements no two runs share: compile
    # counts depend on persistent-cache warmth, RSS/memory on the host.
    "xla_compiles", "rss", "memory", "bytes", "obs.",
    "adopted_from_capture", "stall_s",  # prefetch stalls are scheduler noise
    # Block-sparse leg kernel walls: sub-second and observed swinging
    # ≥4× with ambient load on shared CI boxes. The verdict rides the
    # IN-RUN ratios instead (speedup_ok bool + exact density counts),
    # where both paths see the same ambient load.
    "_gram_wall_s", "_fit_wall_s",
    # Refit leg fold walls: same story — the gate is the in-run
    # refit_speedup ratio (speedup_ok bool), not sub-second absolutes.
    "_refit_wall_s",
)


# ------------------------------------------------------------------ loading


def _iter_json_objects(text: str):
    """Yield every parseable top-level JSON object embedded in ``text``
    (driver tails mix logs and JSON, and may truncate the head)."""
    decoder = json.JSONDecoder()
    i = 0
    while True:
        start = text.find("{", i)
        if start < 0:
            return
        try:
            obj, consumed = decoder.raw_decode(text[start:])
        except json.JSONDecodeError:
            i = start + 1
            continue
        yield obj
        i = start + consumed


def _looks_like_report(obj: Any) -> bool:
    return isinstance(obj, dict) and (
        "platform" in obj
        or "metric" in obj
        or any(
            isinstance(v, dict) and ("wall_s" in v or "error" in v)
            for v in obj.values()
        )
    )


def load_bench_report(path: str) -> Dict[str, Any]:
    """Best-effort extraction of a ``{leg: {...}}`` report from any of
    the artifact shapes the bench ecosystem produces."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(f"{path}: not a JSON object")
    if "tail" in data and isinstance(data.get("tail"), str):
        # Driver wrapper: the report is embedded in (possibly truncated)
        # stdout. Prefer the largest report-shaped object; fall back to
        # stitching whole-leg objects out of a truncated head.
        candidates = [
            o for o in _iter_json_objects(data["tail"]) if _looks_like_report(o)
        ]
        if candidates:
            return max(candidates, key=lambda o: len(json.dumps(o)))
        report: Dict[str, Any] = {}
        for key, obj in _iter_leg_fragments(data["tail"]):
            report[key] = obj
        if report:
            return report
        raise ValueError(f"{path}: no report JSON recoverable from tail")
    return data


def _iter_leg_fragments(tail: str):
    """Recover ``"leg": {...}`` fragments from a truncated JSON tail —
    the committed driver artifacts keep only the last N bytes, which
    beheads the outer object but leaves whole legs intact."""
    decoder = json.JSONDecoder()
    i = 0
    while True:
        q = tail.find('": {', i)
        if q < 0:
            return
        # backtrack to the opening quote of the key
        k = tail.rfind('"', 0, q)
        if k < 0:
            i = q + 1
            continue
        key = tail[k + 1:q]
        try:
            obj, consumed = decoder.raw_decode(tail[q + 3:])
        except json.JSONDecodeError:
            i = q + 1
            continue
        if isinstance(obj, dict) and ("wall_s" in obj or "error" in obj
                                      or "fit_ms" in obj):
            yield key, obj
        i = q + 3 + consumed


def report_legs(report: Dict[str, Any]) -> List[str]:
    return sorted(
        k for k, v in report.items()
        if k not in _META_KEYS and isinstance(v, dict)
    )


# ---------------------------------------------------------------- comparison


def _flatten(leg: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in leg.items():
        name = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, name + "."))
        else:
            out[name] = v
    return out


def _classify(key: str) -> str:
    leaf = key.rsplit(".", 1)[-1]
    # obs.* keys are whole-registry deltas spanning warmups and incidental
    # applies — environment-shaped even when they mention dispatches; the
    # pinned invariants live at leg level (fused_dispatches_per_apply,
    # streaming_report.*), so the skip wins over the exact substrings here.
    if key.startswith("obs.") or ".obs." in key:
        return "skip"
    if any(s in key for s in _EXACT_SUBSTRINGS):
        return "exact"
    if leaf == "source" or leaf.endswith("_source"):
        # Provenance fields (tuned vs observed vs default knob choices,
        # docs/AUTOTUNING.md): a silent flip of where a decision came
        # from is exactly what post-hoc debugging needs surfaced.
        return "exact"
    if leaf == "chunks":
        # top-level "chunks" is leg config (n / chunk_rows); the nested
        # streaming_report.chunks is the MEASURED count — an invariant
        return "exact" if "." in key else "config"
    if any(s in key for s in _SKIP_SUBSTRINGS) or leaf == "wall_s":
        return "skip"  # leg wall_s includes warmup/compile — not a measure
    if leaf in _CONFIG_KEYS:
        return "config"
    if leaf == "parity_rel_err":
        return "parity"
    if leaf.endswith(("_ms", "_s", "_seconds")):
        return "timing"
    return "info"


def compare_leg(
    base: Dict[str, Any],
    cur: Dict[str, Any],
    tolerance: float,
    min_seconds: float,
    timings_comparable: bool,
) -> Dict[str, Any]:
    """Compare one leg; returns ``{"status", "checks", ...}`` where
    status is ok | improved | regression | skipped | incomparable."""
    if "error" in base or "skipped" in base or "truncated" in base:
        return {"status": "skipped", "note": "baseline leg has no clean data"}
    if "error" in cur or "skipped" in cur or "truncated" in cur:
        # a leg that used to finish cleanly and now errors OR blows its
        # child deadline (truncated partial data) is exactly the case
        # this gate exists for
        reason = cur.get("error", cur.get("skipped", cur.get("truncated")))
        return {
            "status": "regression",
            "note": f"leg regressed to failure: {reason}"[:300],
        }
    fb, fc = _flatten(base), _flatten(cur)
    checks: List[Dict[str, Any]] = []
    regressions = improvements = 0
    for key in sorted(set(fb) & set(fc)):
        kind = _classify(key)
        b, c = fb[key], fc[key]
        if isinstance(b, bool) or isinstance(c, bool):
            # invariant flags (overlap_ok, extrapolated): true→false is a
            # regression regardless of what the key name classifies as
            if bool(b) and not bool(c):
                checks.append({"key": key, "kind": "bool", "base": b,
                               "current": c, "verdict": "regression"})
                regressions += 1
            continue
        if kind in ("skip", "info"):
            continue
        if kind == "config":
            if b != c:
                return {
                    "status": "incomparable",
                    "note": f"config mismatch at {key}: {b!r} vs {c!r}",
                }
            continue
        if not isinstance(b, (int, float)) or not isinstance(c, (int, float)):
            # An exact-gated invariant must not evaporate when the value
            # degrades to None/non-numeric — that happens precisely when
            # the measured path is broken (e.g. compiles_steady_state is
            # None because no worker stats flowed), the one run the gate
            # exists to catch.
            if kind == "exact" and b != c:
                checks.append({"key": key, "kind": "exact", "base": b,
                               "current": c, "verdict": "regression"})
                regressions += 1
            continue
        if kind == "exact":
            verdict = "ok" if b == c else "regression"
            checks.append({"key": key, "kind": "exact", "base": b,
                           "current": c, "verdict": verdict})
            regressions += verdict == "regression"
        elif kind == "parity":
            bad = c > max(10.0 * max(b, 0.0), 1e-3)
            checks.append({"key": key, "kind": "parity", "base": b,
                           "current": c,
                           "verdict": "regression" if bad else "ok"})
            regressions += bad
        elif kind == "timing":
            if not timings_comparable:
                continue
            floor = min_seconds * (1000.0 if key.endswith("_ms") else 1.0)
            if b <= 0 or (b < floor and c < floor):
                continue
            ratio = c / b
            if ratio > 1.0 + tolerance and (c - b) > floor:
                verdict = "regression"
                regressions += 1
            elif ratio < 1.0 - tolerance:
                verdict = "improved"
                improvements += 1
            else:
                verdict = "ok"
            checks.append({"key": key, "kind": "timing", "base": b,
                           "current": c, "ratio": round(ratio, 3),
                           "verdict": verdict})
    for key in sorted(set(fb) - set(fc)):
        # Same rule for an invariant that DISAPPEARED from the current
        # run: a renamed or no-longer-measured exact key — or a bool
        # invariant that held true in the baseline (overlap_ok) — fails
        # loudly instead of silently un-gating itself.
        b = fb[key]
        kind = _classify(key)
        if kind == "exact":
            checks.append({"key": key, "kind": "exact", "base": b,
                           "current": None, "verdict": "regression"})
            regressions += 1
        elif kind != "skip" and isinstance(b, bool) and b:
            checks.append({"key": key, "kind": "bool", "base": b,
                           "current": None, "verdict": "regression"})
            regressions += 1
    status = "ok"
    if regressions:
        status = "regression"
    elif improvements and not regressions:
        status = "improved"
    return {"status": status, "checks": checks}


def diff_reports(
    baseline: Dict[str, Any],
    current: Dict[str, Any],
    legs: Optional[List[str]] = None,
    tolerance: float = 0.5,
    min_seconds: float = 0.05,
) -> Dict[str, Any]:
    base_platform = baseline.get("platform")
    cur_platform = current.get("platform")
    # Timings compare only when BOTH artifacts declare the same platform.
    # Unknown counts as incomparable: a truncated driver wrapper loses the
    # outer "platform" key while its recovered legs may be TPU walls —
    # ratio-ing those against CPU CI walls would be noise presented as a
    # verdict. Counts stay exact either way.
    timings_comparable = (
        base_platform is not None
        and cur_platform is not None
        and base_platform == cur_platform
    )
    # Legs the caller named explicitly (CI's --legs fusion,streaming) are
    # REQUIRED: a typo'd name, a renamed bench leg, or a regenerated
    # baseline that lost a leg must fail the gate, not leave it green
    # forever while comparing nothing. Auto-discovered legs (the union
    # sweep) still skip one-sided entries — artifacts legitimately differ
    # in coverage.
    required = legs is not None
    selected = legs or sorted(set(report_legs(baseline)) | set(report_legs(current)))
    out_legs: Dict[str, Any] = {}
    regressions: List[str] = []
    for leg in selected:
        b, c = baseline.get(leg), current.get(leg)
        if not isinstance(c, dict) or not isinstance(b, dict):
            where = "current" if not isinstance(c, dict) else "baseline"
            if required:
                out_legs[leg] = {
                    "status": "regression",
                    "note": f"required leg missing in {where}",
                }
                regressions.append(leg)
            else:
                out_legs[leg] = {
                    "status": "skipped", "note": f"missing in {where}",
                }
            continue
        result = compare_leg(b, c, tolerance, min_seconds, timings_comparable)
        out_legs[leg] = result
        if result["status"] == "regression":
            regressions.append(leg)
    return {
        "ok": not regressions,
        "regressions": regressions,
        "timings_comparable": timings_comparable,
        "baseline_platform": base_platform,
        "current_platform": cur_platform,
        "tolerance": tolerance,
        "legs": out_legs,
    }


# ----------------------------------------------------------------------- CLI


def add_bench_diff_arguments(parser: argparse.ArgumentParser) -> None:
    """Flags for ``keystone-tpu bench-diff`` (plain argparse — the CLI's
    --help path must stay jax-free)."""
    parser.add_argument(
        "--baseline", required=True,
        help="previous BENCH_*.json artifact (driver wrapper or raw report)",
    )
    parser.add_argument(
        "--current", required=True,
        help="fresh BENCH json (raw report or BENCH_CHILD_JSON payload)",
    )
    parser.add_argument(
        "--legs", default=None,
        help="comma-separated legs to compare (default: every shared leg)",
    )
    parser.add_argument(
        "--tolerance", type=float, default=0.5,
        help="relative timing tolerance before a slowdown counts "
             "(default 0.5 = +50%%, wide enough for CI noise)",
    )
    parser.add_argument(
        "--min-seconds", type=float, default=0.05,
        help="absolute timing floor: deltas below this never regress "
             "(default 0.05 s)",
    )
    parser.add_argument(
        "--out", default=None, help="also write the verdict JSON here",
    )


def bench_diff_from_args(args: argparse.Namespace) -> int:
    baseline = load_bench_report(args.baseline)
    current = load_bench_report(args.current)
    legs = [l.strip() for l in args.legs.split(",") if l.strip()] if args.legs else None
    verdict = diff_reports(
        baseline, current, legs=legs,
        tolerance=args.tolerance, min_seconds=args.min_seconds,
    )
    for leg, result in sorted(verdict["legs"].items()):
        line = f"{leg:24s} {result['status']}"
        if result.get("note"):
            line += f" ({result['note']})"
        bad = [c for c in result.get("checks", ())
               if c["verdict"] == "regression"]
        for c in bad:
            line += f"\n{'':24s}   {c['key']}: {c['base']} -> {c['current']}"
        print(line)
    if not verdict["timings_comparable"]:
        print(
            f"note: timings not compared (baseline platform "
            f"{verdict['baseline_platform']!r} != current "
            f"{verdict['current_platform']!r}); counts still exact"
        )
    print("BENCH_DIFF_JSON:" + json.dumps(verdict))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(verdict, f, indent=1)
    if verdict["ok"]:
        print("bench-diff: OK")
        return 0
    print(f"bench-diff: PERF REGRESSION in {verdict['regressions']}")
    return 1


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="bench_diff",
        description="compare two BENCH json artifacts; exit 1 on regression",
    )
    add_bench_diff_arguments(parser)
    return bench_diff_from_args(parser.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
