"""Hierarchical spans: the trace substrate every layer reports into.

One :class:`TraceSession` collects the spans of one instrumented run
(a ``keystone-tpu profile`` invocation, a ``workflow.tracing.trace()``
block, a bench leg). Spans nest through a per-thread stack —
``span("fit")`` inside ``span("pipeline")`` parents automatically — and
cross *threads* through explicit context handoff: a serving request
captures :func:`current_context` at submit time and the worker thread
re-parents its batch/request spans under it via :func:`attach`, so a
request's trace id survives submit → batch assembly → apply.

Design constraints (the serving 5%-overhead budget):

- **Inactive is free.** With no session installed, ``span()`` yields a
  shared no-op without allocating a record, and ``add_span_event`` is a
  single global read. Instrumentation can therefore stay in hot paths
  permanently.
- **Stdlib-only at import.** Like ``reliability/``, this module must be
  importable before any jax backend initializes (bench and CLI import it
  pre-backend).

Spans use ``time.perf_counter`` timestamps relative to the session start;
the session records a wall-clock anchor so exporters can emit absolute
times.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

TraceContext = Tuple[str, str]  # (trace_id, span_id)

#: JSON field name the serving control pipe carries a wire context under
#: (supervisor → worker request lines; docs/OBSERVABILITY.md "Fleet
#: tracing").
WIRE_FIELD = "trace"


# Span-id generator: seeded from the system entropy pool once, then a
# single C-level getrandbits per id (~0.5µs). uuid4 here cost ~17µs per
# span (an os.urandom syscall each) — at serving dispatch rates that
# alone blew the 5% tracing-overhead budget.
_id_rng = random.Random()


def _new_id() -> str:
    return "%016x" % _id_rng.getrandbits(64)


def to_wire(context: Optional[TraceContext]) -> Optional[str]:
    """Compact wire form of a trace context — ``"<trace_id>:<span_id>"``
    — for JSON-lines control messages. None stays None (tracing off adds
    zero bytes to the pipe)."""
    if context is None:
        return None
    return f"{context[0]}:{context[1]}"


def from_wire(value: Any) -> Optional[TraceContext]:
    """Parse a wire context; tolerant of garbage (a malformed trace field
    must never fail a request — it just drops the trace link)."""
    if not isinstance(value, str) or ":" not in value:
        return None
    trace_id, _, span_id = value.partition(":")
    if not trace_id:
        return None
    return (trace_id, span_id)


@dataclass(slots=True)
class SpanEvent:
    name: str
    ts_s: float  # perf_counter timestamp
    attributes: Dict[str, Any] = field(default_factory=dict)


@dataclass(slots=True)
class Span:
    """One finished (or in-flight) timed operation."""

    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start_s: float
    end_s: Optional[float] = None
    attributes: Dict[str, Any] = field(default_factory=dict)
    events: List[SpanEvent] = field(default_factory=list)
    status: str = "ok"
    thread_id: int = 0
    thread_name: str = ""

    @property
    def duration_s(self) -> float:
        return (self.end_s if self.end_s is not None else self.start_s) - self.start_s

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, **attributes: Any) -> None:
        self.events.append(SpanEvent(name, time.perf_counter(), dict(attributes)))

    def context(self) -> TraceContext:
        return (self.trace_id, self.span_id)


class _NoopSpan:
    """Shared do-nothing span yielded when no session is active."""

    __slots__ = ()
    name = ""
    span_id = ""
    trace_id = ""

    def set_attribute(self, key: str, value: Any) -> None:
        pass

    def add_event(self, name: str, **attributes: Any) -> None:
        pass

    def context(self) -> None:
        return None


NOOP_SPAN = _NoopSpan()


class TraceSession:
    """Bounded collector of the spans of one instrumented run.

    ``sync_timings`` declares whether this session needs REAL per-node
    device timings: when True (default — profiling sessions), the
    executor's ``timed_execute`` blocks on device results per node so a
    node span's duration is the node's work; when False, spans record
    dispatch time only and async dispatch between nodes is preserved
    (the right trade for sessions that exist to collect counters and
    coarse phase spans, e.g. metrics-only serving runs).

    ``ring`` selects what the cap sacrifices: False (default — bounded
    profiling runs) drops NEW spans past ``max_spans`` (``dropped``
    counts them), so a runaway run can't evict the phases you captured;
    True (process-lifetime sessions: serving workers, fleet tracing)
    evicts the OLDEST (``evicted`` counts them), so the buffer always
    holds the most recent window — a flight-recorder dump hours into a
    worker's life captures the crash window, not startup, and heartbeat
    shipping never goes dark. ``added`` counts every accepted span, so
    ring consumers (``fleet.drain_fragments``) can cursor by absolute
    index across evictions.
    """

    def __init__(
        self,
        name: str = "trace",
        max_spans: int = 100_000,
        sync_timings: bool = True,
        ring: bool = False,
    ):
        self.name = name
        self.sync_timings = sync_timings
        self.trace_id = _new_id()
        self.started_unix = time.time()
        self.started_s = time.perf_counter()
        self.max_spans = max_spans
        self.ring = ring
        self.dropped = 0
        self.evicted = 0
        self.added = 0
        self._lock = threading.Lock()
        self._spans: "deque[Span]" = deque()

    def add(self, span: Span) -> None:
        with self._lock:
            if len(self._spans) >= self.max_spans:
                if not self.ring:
                    self.dropped += 1
                    return
                self._spans.popleft()
                self.evicted += 1
            self._spans.append(span)
            self.added += 1

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def tail(self) -> Tuple[List[Span], int]:
        """(current buffer, total spans ever accepted): the absolute
        index of ``buffer[0]`` is ``total - len(buffer)`` — the datum
        ring-aware cursors (fleet shipping) advance against."""
        with self._lock:
            return list(self._spans), self.added

    def find(self, name_prefix: str) -> List[Span]:
        return [s for s in self.spans() if s.name.startswith(name_prefix)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


# ------------------------------------------------------------ active state

_session: Optional[TraceSession] = None
_session_lock = threading.Lock()
_state = threading.local()  # .stack: List[Span], .attached: TraceContext


def active_session() -> Optional[TraceSession]:
    return _session


def _stack() -> List[Span]:
    stack = getattr(_state, "stack", None)
    if stack is None:
        stack = []
        _state.stack = stack
    return stack


@contextmanager
def tracing_session(
    name: str = "trace", max_spans: int = 100_000, sync_timings: bool = True
) -> Iterator[TraceSession]:
    """Install a process-wide :class:`TraceSession`. Nested calls reuse the
    outer session (the yielded object is the ACTIVE session, which is what
    exporters should read — including its ``sync_timings`` choice)."""
    global _session
    with _session_lock:
        if _session is not None:
            outer = _session
            nested = True
        else:
            outer = TraceSession(name, max_spans=max_spans, sync_timings=sync_timings)
            _session = outer
            nested = False
    try:
        yield outer
    finally:
        if not nested:
            with _session_lock:
                _session = None


def install_session(
    name: str = "trace",
    max_spans: int = 100_000,
    sync_timings: bool = True,
    ring: bool = True,
) -> TraceSession:
    """Install a process-LIFETIME session (no context manager — worker
    processes and long-lived daemons own the process scope; the fleet
    tracing layer uses this so recent worker spans are shippable on
    heartbeats). Ring semantics by default: a long-lived process must
    keep its most RECENT spans — drop-newest would go permanently dark
    once full, and a crash dump would capture startup instead of the
    crash window. Idempotent: an existing session is reused, exactly
    like a nested :func:`tracing_session`."""
    global _session
    with _session_lock:
        if _session is None:
            _session = TraceSession(
                name, max_spans=max_spans, sync_timings=sync_timings, ring=ring
            )
        return _session


class _NoopSpanContext:
    """Shared no-op ``with`` target when no session is active."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return NOOP_SPAN

    def __exit__(self, *exc) -> bool:
        return False


_NOOP_SPAN_CM = _NoopSpanContext()


class _SpanContext:
    """Slotted context manager for one open span. Hand-rolled instead of
    ``@contextmanager``: the generator protocol costs several µs per
    span, and span() sits on the serving dispatch hot path where the
    fleet-tracing budget is 5% of a ~300µs request."""

    __slots__ = ("_record", "_stack", "_session")

    def __init__(self, record: Span, stack: List[Span], session: TraceSession):
        self._record = record
        self._stack = stack
        self._session = session

    def __enter__(self) -> Span:
        # Side effects happen HERE, not at span() call time: a
        # constructed-but-never-entered context manager must not leave a
        # phantom record on the thread's stack (it would corrupt every
        # later span's parentage and unbalance __exit__'s pop).
        record = self._record
        self._stack.append(record)
        record.start_s = time.perf_counter()
        return record

    def __exit__(self, exc_type, exc, tb) -> bool:
        record = self._record
        if exc_type is not None:
            record.status = "error"
            record.add_event(
                "exception", type=exc_type.__name__, message=str(exc)[:200]
            )
        record.end_s = time.perf_counter()
        self._stack.pop()
        self._session.add(record)
        return False  # always re-raise


def _thread_info() -> Tuple[int, str]:
    """(ident, name) of the current thread, cached thread-locally —
    ``threading.current_thread()`` costs ~0.5µs per call on the dispatch
    hot path and a thread's identity never changes."""
    info = getattr(_state, "thread_info", None)
    if info is None:
        thread = threading.current_thread()
        info = (thread.ident or 0, thread.name)
        _state.thread_info = info
    return info


def span(name: str, parent: Optional[TraceContext] = None, **attributes: Any):
    """Open a child span of the current thread's active span (or of the
    attached remote context, or a session root). No-op without a session.

    ``parent`` hands a REMOTE context in directly — shorthand for
    ``with attach(ctx), span(name)`` on threads with no open span (the
    worker request path), skipping the attach scope. An open span on
    this thread still wins: nesting is local first, like attach."""
    session = _session
    if session is None:
        return _NOOP_SPAN_CM
    stack = _stack()
    if stack:
        top = stack[-1]
        trace_id, parent_id = top.trace_id, top.span_id
    else:
        attached: Optional[TraceContext] = (
            parent
            if parent is not None
            else getattr(_state, "attached", None)
        )
        if attached is not None:
            trace_id, parent_id = attached
        else:
            trace_id, parent_id = session.trace_id, None
    thread_id, thread_name = _thread_info()
    record = Span(
        name=name,
        trace_id=trace_id,
        span_id=_new_id(),
        parent_id=parent_id,
        start_s=0.0,  # stamped in __enter__, where the stack push lives
        attributes=attributes,
        thread_id=thread_id,
        thread_name=thread_name,
    )
    return _SpanContext(record, stack, session)


def record_span(
    name: str,
    start_s: float,
    end_s: float,
    parent: Optional[TraceContext] = None,
    **attributes: Any,
) -> Optional[Span]:
    """Synthesize an already-finished span from measured timestamps (the
    serving worker reconstructs request spans from queue/apply timings this
    way). ``parent`` re-parents it under a captured context."""
    session = _session
    if session is None:
        return None
    if parent is not None:
        trace_id, parent_id = parent
    else:
        trace_id, parent_id = session.trace_id, None
    thread_id, thread_name = _thread_info()
    record = Span(
        name=name,
        trace_id=trace_id,
        span_id=_new_id(),
        parent_id=parent_id,
        start_s=start_s,
        end_s=end_s,
        attributes=dict(attributes),
        thread_id=thread_id,
        thread_name=thread_name,
    )
    session.add(record)
    return record


def current_span():
    """The innermost active span on this thread (NOOP_SPAN when none)."""
    if _session is None:
        return NOOP_SPAN
    stack = getattr(_state, "stack", None)
    return stack[-1] if stack else NOOP_SPAN


def current_context() -> Optional[TraceContext]:
    """(trace_id, span_id) handoff token for cross-thread continuation, or
    None when not tracing. On a thread with no open span but an attached
    remote context (a worker pipe thread continuing a supervisor trace),
    the ATTACHED context is the answer — a second hop of handoff must
    keep the originating trace, not restart at the local session root."""
    if _session is None:
        return None
    stack = getattr(_state, "stack", None)
    if stack:
        return stack[-1].context()
    attached: Optional[TraceContext] = getattr(_state, "attached", None)
    if attached is not None:
        return attached
    return (_session.trace_id, "")


def add_span_event(name: str, **attributes: Any) -> None:
    """Attach an event to the current span; single global read when
    tracing is off, so callers (retry loops, ladders) never gate on it."""
    if _session is None:
        return
    stack = getattr(_state, "stack", None)
    if stack:
        stack[-1].add_event(name, **attributes)


class _AttachContext:
    """Slotted attach scope (see :class:`_SpanContext` for why this is
    not ``@contextmanager``). The attachment is installed at
    construction — ``with attach(ctx):`` evaluates it immediately — and
    restored on exit."""

    __slots__ = ("_prev",)

    def __init__(self, context: Optional[TraceContext]):
        self._prev = getattr(_state, "attached", None)
        _state.attached = context

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc) -> bool:
        _state.attached = self._prev
        return False


def attach(context: Optional[TraceContext]) -> "_AttachContext":
    """Continue a trace captured on another thread: spans opened inside
    parent under ``context`` instead of starting a new root."""
    return _AttachContext(context)
