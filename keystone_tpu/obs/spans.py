"""Hierarchical spans: the trace substrate every layer reports into.

One :class:`TraceSession` collects the spans of one instrumented run
(a ``keystone-tpu profile`` invocation, a ``workflow.tracing.trace()``
block, a bench leg). Spans nest through a per-thread stack —
``span("fit")`` inside ``span("pipeline")`` parents automatically — and
cross *threads* through explicit context handoff: a serving request
captures :func:`current_context` at submit time and the worker thread
re-parents its batch/request spans under it via :func:`attach`, so a
request's trace id survives submit → batch assembly → apply.

Design constraints (the serving 5%-overhead budget):

- **Inactive is free.** With no session installed, ``span()`` yields a
  shared no-op without allocating a record, and ``add_span_event`` is a
  single global read. Instrumentation can therefore stay in hot paths
  permanently.
- **Stdlib-only at import.** Like ``reliability/``, this module must be
  importable before any jax backend initializes (bench and CLI import it
  pre-backend).

Spans use ``time.perf_counter`` timestamps relative to the session start;
the session records a wall-clock anchor so exporters can emit absolute
times.
"""

from __future__ import annotations

import threading
import time
import uuid
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

TraceContext = Tuple[str, str]  # (trace_id, span_id)


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass
class SpanEvent:
    name: str
    ts_s: float  # perf_counter timestamp
    attributes: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Span:
    """One finished (or in-flight) timed operation."""

    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start_s: float
    end_s: Optional[float] = None
    attributes: Dict[str, Any] = field(default_factory=dict)
    events: List[SpanEvent] = field(default_factory=list)
    status: str = "ok"
    thread_id: int = 0
    thread_name: str = ""

    @property
    def duration_s(self) -> float:
        return (self.end_s if self.end_s is not None else self.start_s) - self.start_s

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, **attributes: Any) -> None:
        self.events.append(SpanEvent(name, time.perf_counter(), dict(attributes)))

    def context(self) -> TraceContext:
        return (self.trace_id, self.span_id)


class _NoopSpan:
    """Shared do-nothing span yielded when no session is active."""

    __slots__ = ()
    name = ""
    span_id = ""
    trace_id = ""

    def set_attribute(self, key: str, value: Any) -> None:
        pass

    def add_event(self, name: str, **attributes: Any) -> None:
        pass

    def context(self) -> None:
        return None


NOOP_SPAN = _NoopSpan()


class TraceSession:
    """Bounded collector of the spans of one instrumented run.

    ``sync_timings`` declares whether this session needs REAL per-node
    device timings: when True (default — profiling sessions), the
    executor's ``timed_execute`` blocks on device results per node so a
    node span's duration is the node's work; when False, spans record
    dispatch time only and async dispatch between nodes is preserved
    (the right trade for sessions that exist to collect counters and
    coarse phase spans, e.g. metrics-only serving runs).
    """

    def __init__(
        self,
        name: str = "trace",
        max_spans: int = 100_000,
        sync_timings: bool = True,
    ):
        self.name = name
        self.sync_timings = sync_timings
        self.trace_id = _new_id()
        self.started_unix = time.time()
        self.started_s = time.perf_counter()
        self.max_spans = max_spans
        self.dropped = 0
        self._lock = threading.Lock()
        self._spans: List[Span] = []

    def add(self, span: Span) -> None:
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self.dropped += 1
                return
            self._spans.append(span)

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def find(self, name_prefix: str) -> List[Span]:
        return [s for s in self.spans() if s.name.startswith(name_prefix)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


# ------------------------------------------------------------ active state

_session: Optional[TraceSession] = None
_session_lock = threading.Lock()
_state = threading.local()  # .stack: List[Span], .attached: TraceContext


def active_session() -> Optional[TraceSession]:
    return _session


def _stack() -> List[Span]:
    stack = getattr(_state, "stack", None)
    if stack is None:
        stack = []
        _state.stack = stack
    return stack


@contextmanager
def tracing_session(
    name: str = "trace", max_spans: int = 100_000, sync_timings: bool = True
) -> Iterator[TraceSession]:
    """Install a process-wide :class:`TraceSession`. Nested calls reuse the
    outer session (the yielded object is the ACTIVE session, which is what
    exporters should read — including its ``sync_timings`` choice)."""
    global _session
    with _session_lock:
        if _session is not None:
            outer = _session
            nested = True
        else:
            outer = TraceSession(name, max_spans=max_spans, sync_timings=sync_timings)
            _session = outer
            nested = False
    try:
        yield outer
    finally:
        if not nested:
            with _session_lock:
                _session = None


@contextmanager
def span(name: str, **attributes: Any):
    """Open a child span of the current thread's active span (or of the
    attached remote context, or a session root). No-op without a session."""
    session = _session
    if session is None:
        yield NOOP_SPAN
        return
    stack = _stack()
    if stack:
        trace_id, parent_id = stack[-1].trace_id, stack[-1].span_id
    else:
        attached: Optional[TraceContext] = getattr(_state, "attached", None)
        if attached is not None:
            trace_id, parent_id = attached
        else:
            trace_id, parent_id = session.trace_id, None
    thread = threading.current_thread()
    record = Span(
        name=name,
        trace_id=trace_id,
        span_id=_new_id(),
        parent_id=parent_id,
        start_s=time.perf_counter(),
        attributes=dict(attributes),
        thread_id=thread.ident or 0,
        thread_name=thread.name,
    )
    stack.append(record)
    try:
        yield record
    except BaseException as exc:
        record.status = "error"
        record.add_event(
            "exception", type=type(exc).__name__, message=str(exc)[:200]
        )
        raise
    finally:
        record.end_s = time.perf_counter()
        stack.pop()
        session.add(record)


def record_span(
    name: str,
    start_s: float,
    end_s: float,
    parent: Optional[TraceContext] = None,
    **attributes: Any,
) -> Optional[Span]:
    """Synthesize an already-finished span from measured timestamps (the
    serving worker reconstructs request spans from queue/apply timings this
    way). ``parent`` re-parents it under a captured context."""
    session = _session
    if session is None:
        return None
    if parent is not None:
        trace_id, parent_id = parent
    else:
        trace_id, parent_id = session.trace_id, None
    thread = threading.current_thread()
    record = Span(
        name=name,
        trace_id=trace_id,
        span_id=_new_id(),
        parent_id=parent_id,
        start_s=start_s,
        end_s=end_s,
        attributes=dict(attributes),
        thread_id=thread.ident or 0,
        thread_name=thread.name,
    )
    session.add(record)
    return record


def current_span():
    """The innermost active span on this thread (NOOP_SPAN when none)."""
    if _session is None:
        return NOOP_SPAN
    stack = getattr(_state, "stack", None)
    return stack[-1] if stack else NOOP_SPAN


def current_context() -> Optional[TraceContext]:
    """(trace_id, span_id) handoff token for cross-thread continuation, or
    None when not tracing."""
    if _session is None:
        return None
    stack = getattr(_state, "stack", None)
    if stack:
        return stack[-1].context()
    return (_session.trace_id, "")


def add_span_event(name: str, **attributes: Any) -> None:
    """Attach an event to the current span; single global read when
    tracing is off, so callers (retry loops, ladders) never gate on it."""
    if _session is None:
        return
    stack = getattr(_state, "stack", None)
    if stack:
        stack[-1].add_event(name, **attributes)


@contextmanager
def attach(context: Optional[TraceContext]) -> Iterator[None]:
    """Continue a trace captured on another thread: spans opened inside
    parent under ``context`` instead of starting a new root."""
    prev = getattr(_state, "attached", None)
    _state.attached = context
    try:
        yield
    finally:
        _state.attached = prev
