"""Persistent profile store: measurements that survive the process.

KeystoneML's signature capability is an optimizer driven by *measured*
profiles — but measuring and forgetting makes every fit pay the
measurement again. This module is the system's long-term memory: a
JSON-lines store of per-node and per-subsystem observations, persisted
next to the XLA compilation cache (the other thing that makes second
runs cheap), keyed so an observation is only ever reused where it is
valid:

    (key, shape_class, backend)

- ``key`` — what was measured: a structural digest for pipeline nodes
  (``reliability.checkpoint.prefix_digest`` of the node's operator
  ancestry — content-hashed, so different data or config is a different
  key), or a namespaced string for subsystem observations
  (``stream:<chain>:cr<rows>``, ``solver:block_ls:bs<b>:prec<mode>``,
  ``bench:<leg>``).
- ``shape_class`` — the input scale bucket (:func:`shape_class`): row
  count bucketed to the next power of two plus exact trailing dims and
  dtype, so a measurement taken at n=100k is not applied to n=10.
- ``backend`` — jax platform (cpu/tpu): device economics differ.

Every entry additionally carries an **environment fingerprint** (jax
version, backend, device kind). A fingerprint mismatch at lookup time
invalidates the entry — a store written under jax 0.4.37 on a v5e says
nothing about the next jax on a v6 — counted in
``keystone_profile_store_invalidations_total``.

Durability/concurrency contract (same spirit as ``CheckpointStore``):

- Appends are single JSON lines under an exclusive ``flock`` on a
  sidecar lock file, so two processes profiling the same digest
  interleave whole lines, never torn ones; readers additionally skip
  unparseable lines, so even a torn write (crash mid-append) degrades to
  a missed observation, not a corrupt store.
- **Merge-on-write compaction**: when the file outgrows its bound, the
  whole file is re-read under the lock (picking up other processes'
  appends), merged newest-wins per key, evicted LRU-by-write down to
  ``max_entries``, and atomically replaced (tmp + rename).

Consumers (the measurement→decision loop, docs/OBSERVABILITY.md):

1. ``AutoCacheRule`` warm-starts its cost model from stored node
   profiles and skips scaled-sample re-execution entirely when the
   store covers every node of the plan.
2. ``MeasuredKnobRule`` (workflow/knobs.py) overrides chunk-rows /
   solver-precision / block-size *defaults* per shape class from the
   best recorded observation.
3. ``keystone-tpu bench-diff`` compares BENCH artifacts run-over-run
   (obs/benchdiff.py) — the store also keeps per-leg bench history.

Env knobs:
  KEYSTONE_PROFILE_STORE        off|0|disabled → disabled entirely;
                                a path → store file location; unset →
                                <compilation-cache root>/profile-store.jsonl
  KEYSTONE_PROFILE_STORE_MAX    max entries kept at compaction (4096)

Stdlib-only at import; jax is only touched (lazily, fallible) for the
environment fingerprint.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ..envknobs import env_disabled, env_int, env_str
from . import names as _names

logger = logging.getLogger(__name__)

_DEFAULT_MAX_ENTRIES = 4096
# Compact (merge + evict + rewrite) once this many lines have been
# appended beyond the loaded snapshot — bounds file growth at roughly
# loaded + slack without paying a rewrite per observation.
_COMPACT_SLACK = 256

#: Provenance prefix the cost-observatory drift sentinel writes onto an
#: entry whose predictions stopped matching reality (obs/cost.py,
#: docs/OBSERVABILITY.md "Cost observatory"). A ``stale:`` entry is
#: skipped by ``lookup``/``entries`` (counted as a miss) so consumers —
#: AutoCacheRule's warm start, MeasuredKnobRule's winners — re-measure
#: instead of replaying it; the fresh measurement's ``record()``
#: overwrites the mark.
STALE_PREFIX = "stale:"


def is_stale(measurements: Dict[str, Any]) -> bool:
    return str(measurements.get("source", "")).startswith(STALE_PREFIX)


# ------------------------------------------------------------- shape classes


def shape_class(rows: int, dims: Tuple[int, ...] = (), dtype: Any = None) -> str:
    """Canonical shape-class string: row count bucketed to the next power
    of two (measurements transfer within a ~2× scale band), trailing dims
    exact, dtype name. ``shape_class(100_000, (768,), 'float32')`` →
    ``'n2^17|768|float32'``."""
    rows = max(1, int(rows))
    bucket = 1 << max(0, math.ceil(math.log2(rows)))
    parts = [f"n2^{bucket.bit_length() - 1}"]
    if dims:
        parts.append("x".join(str(int(d)) for d in dims))
    if dtype is not None:
        parts.append(str(getattr(dtype, "name", dtype)))
    return "|".join(parts)


def rows_bucket(shape: str) -> str:
    """The row-bucket component of a :func:`shape_class` string — the
    coarse match key when trailing dims are unknowable at plan time."""
    return shape.split("|", 1)[0]


def dataset_shape_class(dataset: Any) -> str:
    """Shape class of a Dataset's raw records: row count plus the first
    record's dims/dtype at TRANSFER width (what streaming uploads)."""
    import numpy as np

    try:
        rows = len(dataset)
    except Exception:
        return "n?"
    dims: Tuple[int, ...] = ()
    dtype = None
    try:
        from ..data.dataset import ArrayDataset, transfer_dtype

        if isinstance(dataset, ArrayDataset):
            leaf = np.asarray(dataset.data)
            dims, dtype = tuple(leaf.shape[1:]), transfer_dtype(leaf.dtype)
        else:
            first = np.asarray(dataset.take(1)[0])
            dims, dtype = tuple(first.shape), transfer_dtype(first.dtype)
    except Exception:
        pass
    return shape_class(rows, dims, dtype)


# -------------------------------------------------------------- fingerprint

_fp_cache: Optional[Dict[str, str]] = None
_fp_lock = threading.Lock()


def environment_fingerprint() -> Dict[str, str]:
    """What must match for a stored measurement to still be believable:
    jax version, backend platform, device kind. Cached after first
    computation (device enumeration is not free); degrades to
    ``unknown`` fields when no backend is importable/initializable so
    jax-free tools (bench-diff, tests) can still read the store."""
    global _fp_cache
    if _fp_cache is not None:
        return _fp_cache
    with _fp_lock:
        if _fp_cache is not None:
            return _fp_cache
        fp = {"jax": "unknown", "backend": "unknown", "device_kind": "unknown"}
        try:
            import jax

            fp["jax"] = str(jax.__version__)
            dev = jax.devices()[0]
            fp["backend"] = str(dev.platform)
            fp["device_kind"] = str(getattr(dev, "device_kind", "unknown"))
        except Exception:
            pass
        _fp_cache = fp
        return fp


def _reset_fingerprint_cache() -> None:  # testing hook
    global _fp_cache
    with _fp_lock:
        _fp_cache = None


# --------------------------------------------------------------------- store


def _counter(name: str):
    return _names.metric(name)


class ProfileStore:
    """One JSON-lines profile store file with merge-on-write semantics.

    In-memory state is a dict keyed ``(key, shape, backend)`` holding the
    newest observation per key; the file may transiently hold multiple
    lines per key between compactions (newest ``seq`` wins on load).
    """

    def __init__(
        self,
        path: str,
        max_entries: Optional[int] = None,
        fingerprint: Optional[Dict[str, str]] = None,
    ):
        self.path = path
        self.max_entries = max_entries or env_int(
            "KEYSTONE_PROFILE_STORE_MAX", _DEFAULT_MAX_ENTRIES
        )
        self._fingerprint = fingerprint
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
        self._seq = 0
        self._appended_since_load = 0
        self.hits = 0
        self.misses = 0
        self.writes = 0
        self.invalidations = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._load()

    # ------------------------------------------------------------- plumbing
    def fingerprint(self) -> Dict[str, str]:
        return self._fingerprint or environment_fingerprint()

    @property
    def _lock_path(self) -> str:
        return self.path + ".lock"

    def _flock(self):
        """Exclusive advisory lock context over the sidecar lock file —
        the cross-process serialization point for appends/compactions."""
        import contextlib

        @contextlib.contextmanager
        def locked():
            try:
                import fcntl

                fd = os.open(self._lock_path, os.O_CREAT | os.O_RDWR, 0o644)
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX)
                    yield
                finally:
                    fcntl.flock(fd, fcntl.LOCK_UN)
                    os.close(fd)
            except ImportError:  # non-POSIX: single-process best effort
                yield

        return locked()

    @staticmethod
    def _parse_line(line: str) -> Optional[Dict[str, Any]]:
        line = line.strip()
        if not line:
            return None
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            return None  # torn write: a missed observation, not an error
        if not isinstance(rec, dict) or "k" not in rec or "s" not in rec:
            return None
        return rec

    def _load(self) -> None:
        """(Re)build the in-memory map from the file, newest-seq wins."""
        entries: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
        max_seq = 0
        try:
            with open(self.path, "r") as f:
                for line in f:
                    rec = self._parse_line(line)
                    if rec is None:
                        continue
                    seq = int(rec.get("seq", 0))
                    max_seq = max(max_seq, seq)
                    ident = (rec["k"], rec["s"], str(rec.get("b", "")))
                    prev = entries.get(ident)
                    if prev is None or int(prev.get("seq", 0)) <= seq:
                        if prev is not None:
                            rec = dict(rec)
                            rec["obs"] = int(prev.get("obs", 1)) + 1
                        entries[ident] = rec
        except OSError:
            pass
        with self._lock:
            self._entries = entries
            self._seq = max_seq
            self._appended_since_load = 0
        _names.metric(_names.PROFILE_STORE_ENTRIES).set(len(entries))

    # --------------------------------------------------------------- writes
    def record(
        self,
        key: str,
        shape: str,
        backend: Optional[str] = None,
        **measurements: Any,
    ) -> None:
        """Append one observation (merge-on-write: the newest observation
        per (key, shape, backend) wins at read time; the per-key ``obs``
        count survives merges). Never raises — a broken store must not
        break a fit.

        Every entry carries a ``source`` provenance field in its
        measurements: ``"observed"`` (default — recorded passively by a
        fit that happened to run) vs ``"tune"`` (written by the offline
        autotuner's active search, workflow/tune.py). Replayed and
        searched decisions stay distinguishable post-hoc — surfaced by
        ``keystone-tpu check --store`` and the tune/bench json."""
        backend = backend or self.fingerprint()["backend"]
        try:
            fields = {k: v for k, v in measurements.items() if v is not None}
            fields.setdefault("source", "observed")
            with self._lock:
                self._seq += 1
                rec = {
                    "k": key,
                    "s": shape,
                    "b": backend,
                    "m": fields,
                    "fp": self.fingerprint(),
                    "seq": self._seq,
                    "obs": 1,
                }
                prev = self._entries.get((key, shape, backend))
                if prev is not None:
                    rec["obs"] = int(prev.get("obs", 1)) + 1
                self._entries[(key, shape, backend)] = rec
                line = json.dumps(rec, sort_keys=True)
                self._appended_since_load += 1
                need_compact = (
                    len(self._entries) > self.max_entries
                    or self._appended_since_load >= _COMPACT_SLACK
                )
            with self._flock():
                with open(self.path, "a") as f:
                    f.write(line + "\n")
            with self._lock:
                # Stat counters share the state lock: record()/lookup()
                # run from serving and streaming threads concurrently,
                # and an unlocked += drops counts (KV601 discipline).
                self.writes += 1
            _counter(_names.PROFILE_STORE_WRITES).inc()
            _names.metric(_names.PROFILE_STORE_ENTRIES).set(len(self._entries))
            if need_compact:
                self.compact()
        except Exception as e:
            logger.warning("profile store write failed (%s)", e)

    def compact(self) -> None:
        """Merge the on-disk file (including other processes' appends)
        with this process's view, evict LRU-by-write past ``max_entries``,
        and atomically rewrite. Safe to call anytime."""
        try:
            with self._flock():
                # Re-read under the lock so concurrent appenders' lines
                # are merged, not clobbered. The snapshot of our own view
                # takes the thread lock: record() mutates _entries under
                # it, and an unlocked dict() copy can die mid-iteration.
                # No deadlock risk — record() never holds _lock while
                # taking the file lock.
                with self._lock:
                    ours = dict(self._entries)
                self._load()
                with self._lock:
                    for ident, rec in ours.items():
                        cur = self._entries.get(ident)
                        if cur is None or int(cur.get("seq", 0)) < int(
                            rec.get("seq", 0)
                        ):
                            self._entries[ident] = rec
                    ranked = sorted(
                        self._entries.items(),
                        key=lambda kv: int(kv[1].get("seq", 0)),
                    )
                    evicted = len(ranked) - self.max_entries
                    if evicted > 0:
                        for ident, _ in ranked[:evicted]:
                            del self._entries[ident]
                        _counter(_names.PROFILE_STORE_EVICTIONS).inc(evicted)
                    snapshot = [
                        self._entries[ident]
                        for ident, _ in ranked[max(evicted, 0):]
                    ]
                    self._seq = max(
                        [int(r.get("seq", 0)) for r in snapshot], default=0
                    )
                    self._appended_since_load = 0
                tmp = self.path + ".tmp"
                with open(tmp, "w") as f:
                    for rec in snapshot:
                        f.write(json.dumps(rec, sort_keys=True) + "\n")
                os.replace(tmp, self.path)
            _names.metric(_names.PROFILE_STORE_ENTRIES).set(len(self._entries))
        except Exception as e:
            logger.warning("profile store compaction failed (%s)", e)

    # --------------------------------------------------------------- staleness
    def mark_stale(
        self,
        key: str,
        shape: str,
        backend: Optional[str] = None,
        reason: str = "cost_drift",
    ) -> bool:
        """Stamp ``stale:`` provenance onto an entry the drift sentinel
        caught mis-predicting: the measurements survive for post-hoc
        inspection (``check --store`` shows ``stale:<source>``), but
        ``lookup``/``entries`` stop serving them, so the consumer rules
        re-measure. Returns True when an entry was newly marked."""
        backend = backend or self.fingerprint()["backend"]
        with self._lock:
            rec = self._entries.get((key, shape, backend))
        if rec is None:
            return False
        m = dict(rec.get("m", {}))
        if is_stale(m):
            return False  # already marked; one drift = one mark
        m["source"] = STALE_PREFIX + str(m.get("source", "observed"))
        m["stale_reason"] = reason
        self.record(key, shape, backend, **m)
        return True

    # ---------------------------------------------------------------- reads
    def lookup(
        self,
        key: str,
        shape: str,
        backend: Optional[str] = None,
        include_stale: bool = False,
    ) -> Optional[Dict[str, Any]]:
        """The newest valid measurements dict for (key, shape, backend),
        or None. Entries whose environment fingerprint no longer matches
        are invalidated (counted), never returned; ``stale:``-marked
        entries read as misses (the drift sentinel's contract: consumers
        must re-measure, not replay) unless ``include_stale``."""
        backend = backend or self.fingerprint()["backend"]
        fingerprint = self.fingerprint()
        # One critical section covers the fetch AND its stat counter:
        # record()/lookup() run from serving and streaming threads
        # concurrently, and an unlocked += drops counts (KV601
        # discipline); splitting fetch from count would let a stats()
        # snapshot see them inconsistent.
        with self._lock:
            rec = self._entries.get((key, shape, backend))
            if rec is None:
                self.misses += 1
                outcome = "miss"
            elif rec.get("fp") != fingerprint:
                self.invalidations += 1
                self.misses += 1
                outcome = "invalidated"
            elif not include_stale and is_stale(rec.get("m", {})):
                self.misses += 1
                outcome = "miss"
            else:
                self.hits += 1
                outcome = "hit"
                measurements = dict(rec.get("m", {}))
        if outcome == "miss":
            _counter(_names.PROFILE_STORE_MISSES).inc()
            return None
        if outcome == "invalidated":
            _counter(_names.PROFILE_STORE_INVALIDATIONS).inc()
            _counter(_names.PROFILE_STORE_MISSES).inc()
            return None
        _counter(_names.PROFILE_STORE_HITS).inc()
        return measurements

    def entries(
        self,
        key_prefix: str = "",
        shape: Optional[str] = None,
        rows: Optional[str] = None,
        backend: Optional[str] = None,
        any_env: bool = False,
        include_stale: bool = False,
    ) -> Iterator[Tuple[str, str, Dict[str, Any]]]:
        """Iterate valid (key, shape, measurements) tuples filtered by key
        prefix, exact shape class, or coarse rows bucket — the knob rule's
        query surface. Fingerprint-stale entries are skipped silently
        (invalidation is counted at lookup, the authoritative read), and
        drift-marked ``stale:`` entries are skipped unless
        ``include_stale`` (provenance reporting wants them; replay never
        does). ``any_env=True`` skips the fingerprint/backend filter —
        for provenance REPORTING only (``check --store`` runs jax-free
        and must still see what a tuned process wrote), never for
        replay."""
        if not any_env:
            backend = backend or self.fingerprint()["backend"]
            fp = self.fingerprint()
        with self._lock:
            snapshot: List[Dict[str, Any]] = list(self._entries.values())
        for rec in snapshot:
            if not any_env and (
                str(rec.get("b", "")) != backend or rec.get("fp") != fp
            ):
                continue
            if not include_stale and is_stale(rec.get("m", {})):
                continue
            if key_prefix and not rec["k"].startswith(key_prefix):
                continue
            if shape is not None and rec["s"] != shape:
                continue
            if rows is not None and rows_bucket(rec["s"]) != rows:
                continue
            yield rec["k"], rec["s"], dict(rec.get("m", {}))

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def by_source(self) -> Dict[str, int]:
        """Live entry counts per provenance source (``observed`` vs
        ``tune``) — the check/tune CLI surface for "which decisions were
        searched vs merely replayed"."""
        counts: Dict[str, int] = {}
        with self._lock:
            for rec in self._entries.values():
                src = str(rec.get("m", {}).get("source", "observed"))
                counts[src] = counts.get(src, 0) + 1
        return counts

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "path": self.path,
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "writes": self.writes,
                "invalidations": self.invalidations,
            }


# ---------------------------------------------------------- process singleton

_store: Optional[ProfileStore] = None
_store_target: Optional[str] = None
_store_lock = threading.Lock()


def store_enabled() -> bool:
    return not env_disabled("KEYSTONE_PROFILE_STORE")


def default_store_path() -> str:
    """The store file location: ``KEYSTONE_PROFILE_STORE`` when it names
    a path, else ``profile-store.jsonl`` under the same root as the XLA
    compilation cache (the two persistence layers travel together)."""
    env = env_str("KEYSTONE_PROFILE_STORE")
    if env and env.lower() not in ("on", "1", "true"):
        return env
    cache = env_str("KEYSTONE_COMPILATION_CACHE")
    if cache and cache.lower() not in ("off", "0", "disabled"):
        root = os.path.dirname(cache.rstrip(os.sep)) or cache
    else:
        root = os.path.join(os.path.expanduser("~"), ".cache", "keystone_tpu")
    return os.path.join(root, "profile-store.jsonl")


def get_store() -> Optional[ProfileStore]:
    """The process-wide :class:`ProfileStore`, or None when disabled.
    Re-resolves when ``KEYSTONE_PROFILE_STORE`` changes (tests point it at
    per-test temp files)."""
    global _store, _store_target
    if not store_enabled():
        return None
    target = default_store_path()
    with _store_lock:
        if _store is None or _store_target != target:
            try:
                _store = ProfileStore(target)
                _store_target = target
            except Exception as e:
                logger.warning("profile store unavailable (%s)", e)
                return None
        return _store


def set_store(store: Optional[ProfileStore]) -> None:
    """Install a specific store instance (tests); None drops the
    singleton so the next :func:`get_store` re-resolves from env."""
    global _store, _store_target
    with _store_lock:
        _store = store
        _store_target = store.path if store is not None else None
