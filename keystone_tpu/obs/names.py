"""The stable metric-name registry.

Every metric the system publishes is declared here — name, kind, help,
label names — so dashboards and tests have one source of truth. Names
follow Prometheus conventions (``_total`` counters, ``_seconds`` /
``_bytes`` base units). docs/OBSERVABILITY.md documents every name in
this table and ``tests/obs/test_metrics.py`` enforces that the two stay
in sync: renaming a metric is an API change, not a refactor.

:func:`register_all` pre-registers the whole schema into a registry so a
Prometheus export is complete (zero-valued series are legitimate data:
"no retries happened" is an answer) — the profile CLI calls it before
running anything.
"""

from __future__ import annotations

from typing import Dict, Tuple

from .metrics import DEFAULT_BUCKETS, RATIO_BUCKETS, MetricsRegistry, get_registry

# ----------------------------------------------------------- executor/workflow
NODES_EXECUTED = "keystone_executor_nodes_executed_total"
MEMO_HITS = "keystone_executor_memo_hits_total"
NODE_SECONDS = "keystone_executor_node_seconds"
OPTIMIZE_SECONDS = "keystone_optimizer_seconds"
RULE_RUNS = "keystone_optimizer_rule_runs_total"
RULE_REWRITES = "keystone_optimizer_rule_rewrites_total"

# ---------------------------------------------------------------------- fusion
FUSION_CHAINS = "keystone_fusion_chains_total"
FUSION_FUSED_NODES = "keystone_fusion_fused_nodes_total"
FUSION_DISPATCHES_SAVED = "keystone_fusion_dispatches_saved_total"
FUSION_COMPILES = "keystone_fusion_compiles_total"
FUSION_BATCH_DISPATCHES = "keystone_fusion_batch_dispatches_total"

# ------------------------------------------------------------------- streaming
STREAM_PLANS = "keystone_stream_plans_total"
STREAM_CHUNKS = "keystone_stream_chunks_total"
STREAM_BYTES = "keystone_stream_bytes_transferred_total"
STREAM_STALL_SECONDS = "keystone_stream_stall_seconds_total"
STREAM_PREFETCH_DEPTH = "keystone_stream_prefetch_depth"
STREAM_HOST_BUFFER_PEAK = "keystone_stream_host_buffer_peak_bytes"

# ---------------------------------------------------------------- partitioning
PARTITION_DECISIONS = "keystone_partition_decisions_total"
PARTITION_SHARDS = "keystone_partition_shards"
PARTITION_FALLBACKS = "keystone_partition_fallbacks_total"
PARTITION_COLLECTIVE_BYTES = "keystone_partition_collective_bytes_total"
PARTITION_IMBALANCE = "keystone_partition_imbalance"

# ------------------------------------------------------------------- autocache
AUTOCACHE_CACHED_NODES = "keystone_autocache_cached_nodes_total"
AUTOCACHE_HITS = "keystone_autocache_hits_total"
AUTOCACHE_MISSES = "keystone_autocache_misses_total"
AUTOCACHE_PROFILE_SECONDS = "keystone_autocache_profile_seconds"

# --------------------------------------------------------------- profile store
PROFILE_STORE_HITS = "keystone_profile_store_hits_total"
PROFILE_STORE_MISSES = "keystone_profile_store_misses_total"
PROFILE_STORE_WRITES = "keystone_profile_store_writes_total"
PROFILE_STORE_EVICTIONS = "keystone_profile_store_evictions_total"
PROFILE_STORE_INVALIDATIONS = "keystone_profile_store_invalidations_total"
PROFILE_STORE_ENTRIES = "keystone_profile_store_entries"
PROFILE_STORE_KNOB_OVERRIDES = "keystone_profile_store_knob_overrides_total"

# ------------------------------------------------------------------ autotuner
TUNE_CANDIDATES = "keystone_tune_candidates_total"
TUNE_WINNERS = "keystone_tune_winners_total"
TUNE_SECONDS = "keystone_tune_seconds"
KNOB_REJECTED = "keystone_knob_rejected_total"

# ---------------------------------------------------------------- block-sparse
BLOCKSPARSE_FITS = "keystone_blocksparse_fits_total"
BLOCKSPARSE_BLOCKS_SKIPPED = "keystone_blocksparse_blocks_skipped_total"

# --------------------------------------------------------------------- solvers
SOLVER_FIT_SECONDS = "keystone_solver_fit_seconds"
SOLVER_RUNG_ATTEMPTS = "keystone_solver_rung_attempts_total"
SOLVER_ITERATIONS = "keystone_solver_iterations_total"

# ---------------------------------------------------------------- sketch tier
SKETCH_FITS = "keystone_sketch_fits_total"
SKETCH_SIZE = "keystone_sketch_size"
SKETCH_STATE_BYTES = "keystone_sketch_state_bytes"
SKETCH_FINISH_SECONDS = "keystone_sketch_finish_seconds"

# ---------------------------------------------------------------------- ingest
INGEST_IMAGES = "keystone_ingest_images_total"
INGEST_CORRUPT = "keystone_ingest_corrupt_total"
INGEST_BYTES = "keystone_ingest_bytes_total"
INGEST_DECODE_SECONDS = "keystone_ingest_decode_seconds_total"

# ----------------------------------------------------------------- reliability
RELIABILITY_EVENTS = "keystone_reliability_events_total"
CHECKPOINT_HITS = "keystone_checkpoint_hits_total"
CHECKPOINT_MISSES = "keystone_checkpoint_misses_total"
CHECKPOINT_WRITES = "keystone_checkpoint_writes_total"

# ---------------------------------------------------------------- durable fits
DURABLE_CHECKPOINTS = "keystone_durable_fit_checkpoints_total"
DURABLE_RESUMES = "keystone_durable_fit_resumes_total"
DURABLE_RESUME_REFUSED = "keystone_durable_fit_resume_refused_total"
DURABLE_REINGESTED_CHUNKS = "keystone_durable_fit_reingested_chunks_total"
DURABLE_SHARD_LOSSES = "keystone_durable_fit_shard_losses_total"

# ---------------------------------------------------------------- verification
VERIFY_RUNS = "keystone_verify_runs_total"
VERIFY_DIAGNOSTICS = "keystone_verify_diagnostics_total"
VERIFY_NODES = "keystone_verify_nodes_annotated_total"
VERIFY_SECONDS = "keystone_verify_seconds"
VERIFY_LINT_FINDINGS = "keystone_verify_lint_findings_total"

# ----------------------------------------------------------------- compilation
XLA_COMPILES = "keystone_xla_compiles_total"

# --------------------------------------------------------------------- serving
SERVING_REQUESTS = "keystone_serving_requests_total"
SERVING_BATCHES = "keystone_serving_batches_total"
SERVING_SHEDS = "keystone_serving_sheds_total"
SERVING_TIMEOUTS = "keystone_serving_timeouts_total"
SERVING_RETRIES = "keystone_serving_retries_total"
SERVING_FAILURES = "keystone_serving_failures_total"
SERVING_BUCKET_HITS = "keystone_serving_bucket_hits_total"
SERVING_BUCKET_COMPILES = "keystone_serving_bucket_compiles_total"
SERVING_LATENCY_SECONDS = "keystone_serving_latency_seconds"
SERVING_QUEUE_WAIT_SECONDS = "keystone_serving_queue_wait_seconds"
SERVING_BATCH_OCCUPANCY = "keystone_serving_batch_occupancy"

# ------------------------------------------------- multi-worker serving / SLO
SERVING_WORKER_RESTARTS = "keystone_serving_worker_restarts_total"
SERVING_WORKER_REQUEUED = "keystone_serving_requeued_requests_total"
SERVING_WORKERS_ALIVE = "keystone_serving_workers_alive"
SERVING_WORKER_HEARTBEATS = "keystone_serving_worker_heartbeats_total"
SERVING_SLO_P99_MS = "keystone_serving_slo_p99_ms"
SERVING_SLO_TARGET_MS = "keystone_serving_slo_target_ms"
SERVING_SLO_RUNG = "keystone_serving_slo_rung"
SERVING_SLO_TRANSITIONS = "keystone_serving_slo_transitions_total"

# --------------------------------------------------- elastic fleet / autoscale
SERVING_SCALE_EVENTS = "keystone_serving_scale_events_total"
SERVING_SCALE_TARGET_WORKERS = "keystone_serving_scale_target_workers"
SERVING_SCALE_WORKERS_DRAINING = "keystone_serving_scale_workers_draining"
SERVING_SCALE_DRAIN_SECONDS = "keystone_serving_scale_drain_seconds"

# ------------------------------------------------------------------ boot image
BOOTIMAGE_BUILDS = "keystone_bootimage_builds_total"
BOOTIMAGE_LOADS = "keystone_bootimage_loads_total"
BOOTIMAGE_BUILD_SECONDS = "keystone_bootimage_build_seconds"
BOOTIMAGE_LOAD_SECONDS = "keystone_bootimage_load_seconds"

# ------------------------------------------------------------ continuous refit
REFIT_ROUNDS = "keystone_refit_rounds_total"
REFIT_PUBLISHES = "keystone_refit_publishes_total"
REFIT_ROLLBACKS = "keystone_refit_rollbacks_total"
REFIT_TAP_ROWS = "keystone_refit_tap_rows_total"
REFIT_STATE_ROWS = "keystone_refit_state_rows"
REFIT_FOLD_SECONDS = "keystone_refit_fold_seconds"
REFIT_SCORE = "keystone_refit_score"

# ----------------------------------------------------------- mesh co-scheduler
SCHED_LEASES = "keystone_sched_leases_total"
SCHED_IDLE_HARVEST_SECONDS = "keystone_sched_idle_harvest_seconds_total"
SCHED_LEASE_WALL_RATIO = "keystone_sched_lease_wall_ratio"
SCHED_REFIT_INTERVAL_SECONDS = "keystone_sched_refit_interval_seconds"

# --------------------------------------------------------------- fleet tracing
FLEET_SPAN_FRAGMENTS = "keystone_fleet_span_fragments_total"
FLEET_TRACE_BYTES = "keystone_fleet_trace_bytes_total"
FLEET_CLOCK_SKEW = "keystone_fleet_clock_skew_seconds"
FLEET_REQUESTS = "keystone_fleet_requests_total"
FLEET_FAILURES = "keystone_fleet_failures_total"
FLEET_WORKER_SERIES = "keystone_fleet_worker_series"

# ------------------------------------------------------------- flight recorder
FLIGHT_RECORDS = "keystone_flight_records_total"
FLIGHT_DUMPS = "keystone_flight_dumps_total"
FLIGHT_DUMP_BYTES = "keystone_flight_dump_bytes"

# ------------------------------------------------------------ cost observatory
COST_LEDGER_ENTRIES = "keystone_cost_ledger_entries_total"
COST_DRIFT_EVENTS = "keystone_cost_drift_events_total"
COST_DRIFT_RATIO = "keystone_cost_drift_ratio"
COST_HARVEST_COMPILES = "keystone_cost_harvest_compiles_total"
COST_ROOFLINE_PEAK = "keystone_cost_roofline_peak"

# --------------------------------------------------------------- quality plane
QUALITY_SCORES = "keystone_quality_scores_total"
QUALITY_SCORE_MEAN = "keystone_quality_score_mean"
QUALITY_SCORE_QUANTILE = "keystone_quality_score_quantile"
QUALITY_LABEL_JOINS = "keystone_quality_label_joins_total"
QUALITY_JOIN_LAG_ROWS = "keystone_quality_join_lag_rows"
QUALITY_SKETCH_ROWS = "keystone_quality_sketch_rows"
QUALITY_SKETCH_BYTES = "keystone_quality_sketch_bytes"
QUALITY_SKETCH_MERGES = "keystone_quality_sketch_merges_total"
QUALITY_DRIFT_EVENTS = "keystone_quality_drift_events_total"
QUALITY_DRIFT_SCORE = "keystone_quality_drift_score"
QUALITY_STATE_DECAY = "keystone_quality_state_decay"
QUALITY_GATE_DECISIONS = "keystone_quality_gate_decisions_total"
QUALITY_GATE_OPEN = "keystone_quality_gate_open"
QUALITY_GATE_SAMPLES = "keystone_quality_gate_samples"

# ---------------------------------------------------------------------- memory
MEMORY_IN_USE_BYTES = "keystone_memory_in_use_bytes"
PEAK_MEMORY_BYTES = "keystone_peak_memory_bytes"


# name → (kind, help, label names). Histograms may carry a 4th element
# naming a bucket preset ("ratio" → RATIO_BUCKETS).
SCHEMA: Dict[str, Tuple] = {
    NODES_EXECUTED: ("counter", "Graph nodes executed (memo misses)", ()),
    MEMO_HITS: ("counter", "Graph-node memo table hits", ()),
    NODE_SECONDS: ("histogram", "Per-node forced execution wall time (traced runs)", ("op",)),
    OPTIMIZE_SECONDS: ("histogram", "Whole optimizer-stack runs", ()),
    RULE_RUNS: ("counter", "Optimizer rule applications", ("rule",)),
    RULE_REWRITES: ("counter", "Optimizer rule applications that changed the graph", ("rule",)),
    FUSION_CHAINS: ("counter", "Fused operator chains created by NodeFusionRule", ()),
    FUSION_FUSED_NODES: ("counter", "Member transformer nodes absorbed into fused operators", ()),
    FUSION_DISPATCHES_SAVED: ("counter", "Per-execution dispatches avoided by fusion (members-1 per chain)", ()),
    FUSION_COMPILES: ("counter", "Fused-chain executable traces (one per new shape/dtype)", ()),
    FUSION_BATCH_DISPATCHES: ("counter", "Transformer batch-apply dispatches, split fused vs unfused", ("fused",)),
    STREAM_PLANS: ("counter", "Estimator fits rewritten onto the streaming engine by StreamingPlanRule", ()),
    STREAM_CHUNKS: ("counter", "Chunks dispatched by the streaming execution engine", ()),
    STREAM_BYTES: ("counter", "Host-to-device bytes uploaded by the streaming engine (post narrow-dtype)", ()),
    STREAM_STALL_SECONDS: ("counter", "Seconds the streaming dispatch loop spent waiting on the host prefetch pipeline", ()),
    STREAM_PREFETCH_DEPTH: ("gauge", "Chunks currently buffered in the host prefetch queue", ()),
    STREAM_HOST_BUFFER_PEAK: ("gauge", "Peak bytes of host chunk buffers concurrently live in the last streaming fit", ()),
    PARTITION_DECISIONS: ("counter", "Partitioner decisions recorded into plans, split by kind and eligibility", ("kind", "eligible")),
    PARTITION_SHARDS: ("gauge", "Shards chosen by the last eligible partition decision, per kind and mesh axis (data = rows, model = feature blocks)", ("kind", "axis")),
    PARTITION_FALLBACKS: ("counter", "Partition decisions that fell back (whole decision or just the model axis), by reason key", ("reason",)),
    PARTITION_COLLECTIVE_BYTES: ("counter", "Payload bytes entering partitioner-managed cross-device reductions, per mesh axis (per-device payload × (axis shards−1))", ("axis",)),
    PARTITION_IMBALANCE: ("gauge", "Fraction of sharded rows that are padding in the last partitioned dispatch, per kind", ("kind",)),
    AUTOCACHE_CACHED_NODES: ("counter", "Cacher nodes inserted by the auto-cache planner", ()),
    AUTOCACHE_HITS: ("counter", "Re-reads of a cached (Cacher) node's memoized result", ()),
    AUTOCACHE_MISSES: ("counter", "First executions of a Cacher node", ()),
    AUTOCACHE_PROFILE_SECONDS: ("histogram", "Auto-cache sample-profiling passes", ()),
    PROFILE_STORE_HITS: ("counter", "Profile-store lookups served from a valid persisted entry", ()),
    PROFILE_STORE_MISSES: ("counter", "Profile-store lookups with no usable entry", ()),
    PROFILE_STORE_WRITES: ("counter", "Observations appended to the profile store", ()),
    PROFILE_STORE_EVICTIONS: ("counter", "Entries evicted (LRU-by-write) at profile-store compaction", ()),
    PROFILE_STORE_INVALIDATIONS: ("counter", "Entries rejected for a stale environment fingerprint", ()),
    PROFILE_STORE_ENTRIES: ("gauge", "Live entries in the profile store", ()),
    PROFILE_STORE_KNOB_OVERRIDES: ("counter", "Plan knobs overridden from measured observations by MeasuredKnobRule", ("knob",)),
    TUNE_CANDIDATES: ("counter", "Candidate configurations measured by the offline autotuner", ("task",)),
    TUNE_WINNERS: ("counter", "Winning configurations persisted to the profile store by the autotuner", ("task",)),
    TUNE_SECONDS: ("histogram", "Whole autotuner task runs (all budgeted measurements)", ("task",)),
    KNOB_REJECTED: ("counter", "Measured knob overrides rejected before applying, by knob and reason", ("knob", "reason")),
    BLOCKSPARSE_FITS: ("counter", "Estimator fits dispatched onto the block-sparse Gram path, by kernel impl", ("impl",)),
    BLOCKSPARSE_BLOCKS_SKIPPED: ("counter", "Zero feature tiles skipped by block-sparse kernels (MACs never dispatched)", ()),
    SOLVER_FIT_SECONDS: ("histogram", "Solver fit wall time", ("solver",)),
    SOLVER_RUNG_ATTEMPTS: ("counter", "Degradation-ladder rung attempts inside solvers", ("solver",)),
    SOLVER_ITERATIONS: ("counter", "Host-level solver iterations (e.g. L-BFGS steps)", ("solver",)),
    SKETCH_FITS: ("counter", "Sketched least-squares fits completed, by sketch variant (countsketch/srht)", ("variant",)),
    SKETCH_SIZE: ("gauge", "Sketch rows s chosen for the last sketched fit (knob/tuned/width default)", ()),
    SKETCH_STATE_BYTES: ("gauge", "Bytes of the last sketched fit's O(s·d) carry — the number KV308 compares to the device budget", ()),
    SKETCH_FINISH_SECONDS: ("histogram", "Sketch finish solves (s×s dual ridge or lstsq fallback)", ()),
    INGEST_IMAGES: ("counter", "Records successfully decoded by ingest", ()),
    INGEST_CORRUPT: ("counter", "Records quarantined by ingest", ()),
    INGEST_BYTES: ("counter", "Raw bytes read by ingest", ()),
    INGEST_DECODE_SECONDS: ("counter", "Cumulative decode wall time", ()),
    RELIABILITY_EVENTS: ("counter", "Recovery-ledger events", ("kind",)),
    CHECKPOINT_HITS: ("counter", "CheckpointStore lookups that restored a fit", ()),
    CHECKPOINT_MISSES: ("counter", "CheckpointStore lookups that missed", ()),
    CHECKPOINT_WRITES: ("counter", "CheckpointStore entries written", ()),
    DURABLE_CHECKPOINTS: ("counter", "Mid-stream fit checkpoints committed (StreamState + ingest cursor)", ()),
    DURABLE_RESUMES: ("counter", "Streamed fits resumed from a persisted cursor, by recovery kind (crash/shard/refit_journal)", ("kind",)),
    DURABLE_RESUME_REFUSED: ("counter", "Resume entries refused or discarded before seeding a fold, by reason (KV306 fingerprint mismatch / geometry drift)", ("reason",)),
    DURABLE_REINGESTED_CHUNKS: ("counter", "Chunks re-ingested by resumed or shard-loss-recovered folds", ()),
    DURABLE_SHARD_LOSSES: ("counter", "Simulated/observed device losses absorbed mid-stream by the elastic fold", ()),
    VERIFY_RUNS: ("counter", "Plan-time verification runs", ("context",)),
    VERIFY_DIAGNOSTICS: ("counter", "Plan-time verification diagnostics emitted", ("code", "severity")),
    VERIFY_NODES: ("counter", "Graph nodes annotated with propagated specs by the verifier", ()),
    VERIFY_SECONDS: ("histogram", "Whole-graph verification passes", ()),
    VERIFY_LINT_FINDINGS: ("counter", "keystone-lint findings", ("rule",)),
    XLA_COMPILES: ("counter", "Backend XLA compiles observed by jax.monitoring", ()),
    SERVING_REQUESTS: ("counter", "Requests served to completion", ("model",)),
    SERVING_BATCHES: ("counter", "Micro-batches dispatched", ("model",)),
    SERVING_SHEDS: ("counter", "Requests shed by admission control", ("model",)),
    SERVING_TIMEOUTS: ("counter", "Requests expired before batch assembly", ("model",)),
    SERVING_RETRIES: ("counter", "Apply-path retry attempts", ("model",)),
    SERVING_FAILURES: ("counter", "Requests failed by apply errors", ("model",)),
    SERVING_BUCKET_HITS: ("counter", "Batches padded onto an already-warm bucket", ("model",)),
    SERVING_BUCKET_COMPILES: ("counter", "First batches at a cold bucket", ("model",)),
    SERVING_LATENCY_SECONDS: ("histogram", "End-to-end request latency", ("model",)),
    SERVING_QUEUE_WAIT_SECONDS: ("histogram", "Submit-to-apply queue wait", ("model",)),
    SERVING_BATCH_OCCUPANCY: ("histogram", "Batch size / max_batch", ("model",), "ratio"),
    SERVING_WORKER_RESTARTS: ("counter", "Worker processes restarted by the supervisor", ("reason",)),
    SERVING_WORKER_REQUEUED: ("counter", "In-flight requests requeued off a dead worker", ()),
    SERVING_WORKERS_ALIVE: ("gauge", "Worker processes currently serving", ()),
    SERVING_WORKER_HEARTBEATS: ("counter", "Worker heartbeats received by the supervisor", ("status",)),
    SERVING_SLO_P99_MS: ("gauge", "Observed serving p99 latency, per worker and aggregate", ("worker",)),
    SERVING_SLO_TARGET_MS: ("gauge", "SLO controller p99 target", ()),
    SERVING_SLO_RUNG: ("gauge", "Admission ladder rung index pinned by the SLO controller", ()),
    SERVING_SLO_TRANSITIONS: ("counter", "SLO-driven admission ladder transitions", ("direction",)),
    SERVING_SCALE_EVENTS: ("counter", "Autoscaler fleet scale events, by direction (up/down)", ("direction",)),
    SERVING_SCALE_TARGET_WORKERS: ("gauge", "Worker count the autoscaler is currently steering toward", ()),
    SERVING_SCALE_WORKERS_DRAINING: ("gauge", "Workers currently draining ahead of scale-down removal", ()),
    SERVING_SCALE_DRAIN_SECONDS: ("histogram", "Drain duration from scale-down decision to worker retirement", ()),
    BOOTIMAGE_BUILDS: ("counter", "Boot images built (exported bucket executables + fitted weights)", ()),
    BOOTIMAGE_LOADS: ("counter", "Boot-image load attempts, by status (loaded/refused)", ("status",)),
    BOOTIMAGE_BUILD_SECONDS: ("histogram", "Whole boot-image builds (export + cache population + parity gate)", ()),
    BOOTIMAGE_LOAD_SECONDS: ("histogram", "Boot-image loads (verify + deserialize, before first request)", ()),
    REFIT_ROUNDS: ("counter", "Refit daemon rounds, by outcome (published/skipped_nodata/skipped_eval/rolled_back/error)", ("outcome",)),
    REFIT_PUBLISHES: ("counter", "Candidate models published by the refit controller", ()),
    REFIT_ROLLBACKS: ("counter", "Automatic rollbacks triggered by the post-publish watch window", ()),
    REFIT_TAP_ROWS: ("counter", "Traffic-tap rows, by status (labeled/mirrored/dropped)", ("status",)),
    REFIT_STATE_ROWS: ("gauge", "Examples absorbed into the persisted refit sufficient statistics", ()),
    REFIT_FOLD_SECONDS: ("histogram", "Incremental refit folds (drain + fold + finish wall time)", ()),
    REFIT_SCORE: ("gauge", "Latest shadow-evaluation score, per role (candidate/incumbent/live)", ("role",)),
    SCHED_LEASES: ("counter", "Mesh-scheduler leases, by work kind and outcome (admitted/deferred/preempted/resumed/completed)", ("kind", "outcome")),
    SCHED_IDLE_HARVEST_SECONDS: ("counter", "Serving idle-gap seconds harvested by admitted background leases", ()),
    SCHED_LEASE_WALL_RATIO: ("histogram", "Measured / predicted lease wall, by price provenance (tune/store/roofline/default); >1 = lease ran slower than priced", ("source",), "ratio"),
    SCHED_REFIT_INTERVAL_SECONDS: ("gauge", "Last pressure-aware refit cadence chosen by the scheduler-governed daemon loop", ()),
    FLEET_SPAN_FRAGMENTS: ("counter", "Span fragments folded into the fleet trace collector, per shipping process role", ("role",)),
    FLEET_TRACE_BYTES: ("counter", "Serialized span-fragment bytes shipped over the heartbeat channel", ()),
    FLEET_CLOCK_SKEW: ("gauge", "Estimated per-process wall-clock offset vs the collector at heartbeat receipt", ("role",)),
    FLEET_REQUESTS: ("counter", "Fleet-aggregated requests served per worker id, monotonic across worker incarnations", ("worker",)),
    FLEET_FAILURES: ("counter", "Fleet-aggregated failed requests per worker id, monotonic across worker incarnations", ("worker",)),
    FLEET_WORKER_SERIES: ("gauge", "Fleet-summed worker-process registry series (heartbeat metric deltas, folded across incarnations), keyed by flat series name", ("series",)),
    COST_LEDGER_ENTRIES: ("counter", "Perf-ledger entries recorded by the cost observatory, by roofline classification", ("roofline",)),
    COST_DRIFT_EVENTS: ("counter", "Sustained cost-model drift events fired by the drift sentinel, by model", ("model",)),
    COST_DRIFT_RATIO: ("gauge", "Latest measured-vs-predicted cost ratio observed per model (>1 = slower than predicted)", ("model",)),
    COST_HARVEST_COMPILES: ("counter", "Backend compiles triggered by cost harvesting — must stay 0 (harvest rides the jit trace cache)", ()),
    COST_ROOFLINE_PEAK: ("gauge", "Probe-calibrated roofline peaks for this process's backend, by resource (flops_per_s/bytes_per_s)", ("resource",)),
    FLIGHT_RECORDS: ("counter", "Entries appended to the flight-recorder ring buffers, by kind (ledger/metrics/mark/quality)", ("kind",)),
    FLIGHT_DUMPS: ("counter", "Flight-recorder dump artifacts written, by trigger", ("trigger",)),
    FLIGHT_DUMP_BYTES: ("gauge", "Size of the last flight-recorder dump artifact written by this process", ()),
    QUALITY_SCORES: ("counter", "Prediction scores observed by the quality plane, per model and stream role (live/labeled/candidate/incumbent)", ("model", "role")),
    QUALITY_SCORE_MEAN: ("gauge", "Running mean of a model's score stream, per role", ("model", "role")),
    QUALITY_SCORE_QUANTILE: ("gauge", "P² quantile markers of a model's score stream (p10/p50/p90), per role", ("model", "role", "q")),
    QUALITY_LABEL_JOINS: ("counter", "Delayed labels joined against served predictions into the labeled score stream (exactly-once via the refit journal)", ("model",)),
    QUALITY_JOIN_LAG_ROWS: ("gauge", "Labeled rows buffered in the tap awaiting the next refit round's label join", ("model",)),
    QUALITY_SKETCH_ROWS: ("gauge", "Payload rows folded into the fleet-merged input-distribution sketch", ("model",)),
    QUALITY_SKETCH_BYTES: ("gauge", "Serialized size of the fleet-merged quality sketch (the bounded-memory contract)", ("model",)),
    QUALITY_SKETCH_MERGES: ("counter", "Worker heartbeat sketch deltas merged fleet-wide, per shipping role", ("role",)),
    QUALITY_DRIFT_EVENTS: ("counter", "Drift events fired by the quality drift detector (edge-triggered threshold crossings)", ("model",)),
    QUALITY_DRIFT_SCORE: ("gauge", "Latest standardized score-shift vs the frozen baseline window, in baseline standard deviations", ("model",)),
    QUALITY_STATE_DECAY: ("gauge", "Effective refit state_decay chosen adaptively from the drift score", ("model",)),
    QUALITY_GATE_DECISIONS: ("counter", "Sequential-gate decisions emitted, by model and decision (promote/rollback)", ("model", "decision")),
    QUALITY_GATE_OPEN: ("gauge", "Sequential tests currently open (still sampling)", ()),
    QUALITY_GATE_SAMPLES: ("gauge", "Samples consumed so far by a model's open sequential gate", ("model",)),
    MEMORY_IN_USE_BYTES: ("gauge", "Current memory in use", ("source", "device")),
    PEAK_MEMORY_BYTES: ("gauge", "Peak memory observed, attributed per stage", ("stage", "device")),
}

ALL_METRIC_NAMES: Tuple[str, ...] = tuple(sorted(SCHEMA))


def metric(name: str, registry: MetricsRegistry = None):
    """Get-or-create a schema metric by name — kind, help text, label
    names, and bucket preset all come from :data:`SCHEMA`, so call sites
    can never drift from the documented registry."""
    registry = registry or get_registry()
    spec = SCHEMA[name]
    kind, help_text, labels = spec[0], spec[1], spec[2]
    if kind == "counter":
        return registry.counter(name, help_text, labels)
    if kind == "gauge":
        return registry.gauge(name, help_text, labels)
    buckets = RATIO_BUCKETS if len(spec) > 3 and spec[3] == "ratio" else DEFAULT_BUCKETS
    return registry.histogram(name, help_text, labels, buckets=buckets)


def register_all(registry: MetricsRegistry = None) -> MetricsRegistry:
    """Pre-register every schema metric (idempotent) so exports include
    zero-valued series. Returns the registry."""
    registry = registry or get_registry()
    for name in SCHEMA:
        metric(name, registry)
    return registry
