"""Exporters: Chrome trace-event JSON (Perfetto-loadable), Prometheus
text exposition, and a human-readable span report.

Chrome format: one complete event (``"ph": "X"``) per span with
microsecond ``ts``/``dur`` relative to the session start, one instant
event (``"ph": "i"``) per span event, plus thread-name metadata events so
Perfetto's track labels read "keystone-serving-worker" instead of a bare
tid. Span ids/parent ids ride in ``args`` — the visual nesting Perfetto
draws from ts/dur containment matches the parent chain because children
are opened and closed inside their parents by construction.

Prometheus format follows the text exposition rules: ``# HELP`` /
``# TYPE`` headers for every registered metric (including zero-series
ones — an exported schema with no samples is itself information),
histograms as cumulative ``_bucket{le=...}`` plus ``_sum``/``_count``.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from .metrics import Counter, Gauge, Histogram, MetricsRegistry, get_registry
from .spans import Span, TraceSession


# ------------------------------------------------------------- chrome trace


def _json_safe(value: Any) -> Any:
    if isinstance(value, (bool, int, float, str)) or value is None:
        return value
    return str(value)


# Synthetic track ids for the streaming engine's per-chunk slices: the
# upload and compute frontiers are pipeline stages, not threads, so they
# get their own named Perfetto tracks next to the real span threads.
_STREAM_UPLOAD_TID = 900001
_STREAM_COMPUTE_TID = 900002

# Synthetic track for the cost observatory's per-node counters.
_COST_LEDGER_TID = 900003

# Synthetic track for the quality plane's drift/gate event stream.
_QUALITY_TID = 900004


def quality_events(
    entries: Any, base_unix: float, pid: int
) -> List[Dict[str, Any]]:
    """Quality-plane ring entries (obs/flight.py ``quality`` ring) as a
    Chrome ``quality`` track: drift scores and gate likelihood ratios as
    ``ph:C`` counter samples, plus one instant event per drift firing /
    gate decision so the moment a model went bad is findable next to the
    serving spans. Ring entries carry ``unix`` stamps; ``base_unix`` is
    the session's wall-clock origin (``TraceSession.started_unix``)."""
    events: List[Dict[str, Any]] = []
    for entry in entries or []:
        ts = round((float(entry.get("unix", base_unix)) - base_unix) * 1e6, 3)
        kind = entry.get("kind")
        counters: Dict[str, Any] = {}
        if kind == "drift" and entry.get("score") is not None:
            counters["drift_score"] = round(float(entry["score"]), 4)
        elif kind == "gate_decision" and entry.get("lr") is not None:
            counters["gate_lr"] = round(float(entry["lr"]), 4)
        if counters:
            events.append(
                {
                    "name": "quality",
                    "cat": "quality",
                    "ph": "C",
                    "ts": ts,
                    "pid": pid,
                    "tid": _QUALITY_TID,
                    "args": counters,
                }
            )
        label = kind or "quality"
        if kind == "gate_decision":
            label = "gate:%s" % entry.get("decision", "?")
        elif kind == "drift":
            label = "drift:%s" % entry.get("model", "?")
        events.append(
            {
                "name": label,
                "cat": "quality",
                "ph": "i",
                "s": "t",
                "ts": ts,
                "pid": pid,
                "tid": _QUALITY_TID,
                "args": {k: _json_safe(v) for k, v in entry.items()},
            }
        )
    if events:
        events.append(
            {"name": "thread_name", "ph": "M", "pid": pid,
             "tid": _QUALITY_TID, "args": {"name": "quality"}}
        )
    return events


def cost_ledger_events(
    entries: Any, base_s: float, pid: int
) -> List[Dict[str, Any]]:
    """Perf-ledger entries (obs/cost.py) as Chrome ``ph:C`` counter
    events on a ``cost-ledger`` track: achieved GFLOP/s, GB/s, and
    measured-vs-predicted ratio sampled at each node's finalize time —
    roofline placement over the session timeline, next to the node spans
    that produced it. ``base_s`` is the session's perf_counter origin
    (entries carry their own ``t_s`` anchor)."""
    events: List[Dict[str, Any]] = []
    for entry in entries or []:
        ts = round((getattr(entry, "t_s", 0.0) - base_s) * 1e6, 3)
        args: Dict[str, Any] = {}
        if getattr(entry, "flops_per_s", None):
            args["gflops_per_s"] = round(entry.flops_per_s / 1e9, 4)
        if getattr(entry, "bytes_per_s", None):
            args["gbytes_per_s"] = round(entry.bytes_per_s / 1e9, 4)
        if getattr(entry, "ratio", None) is not None:
            args["measured_vs_predicted"] = round(entry.ratio, 4)
        if not args:
            continue
        events.append(
            {
                "name": "cost-ledger",
                "cat": "cost",
                "ph": "C",
                "ts": ts,
                "pid": pid,
                "tid": _COST_LEDGER_TID,
                "args": args,
            }
        )
    if events:
        events.append(
            {"name": "thread_name", "ph": "M", "pid": pid,
             "tid": _COST_LEDGER_TID, "args": {"name": "cost-ledger"}}
        )
    return events


def stream_report_events(
    report: Any, base_s: float, pid: int
) -> List[Dict[str, Any]]:
    """The last streaming fit's per-chunk event log as Chrome ``ph:X``
    slices: one ``chunk i upload`` slice (upload issued → dispatch) on a
    ``stream-upload`` track and one ``chunk i compute`` slice (dispatch →
    compute observed done) on ``stream-compute`` — so the double-buffer
    overlap (``StreamReport.overlap_ok``) is visually inspectable in
    Perfetto alongside node spans. ``base_s`` is the session's
    perf_counter origin; the report's timestamps are offsets from its own
    ``t0_s`` anchor."""
    events: List[Dict[str, Any]] = []
    if report is None or not getattr(report, "dispatch_t", None):
        return events
    origin = (getattr(report, "t0_s", 0.0) or 0.0) - base_s

    def slice_event(name: str, tid: int, start: float, end: float, **args):
        events.append(
            {
                "name": name,
                "cat": "stream",
                "ph": "X",
                "ts": round((origin + start) * 1e6, 3),
                "dur": round(max(end - start, 0.0) * 1e6, 3),
                "pid": pid,
                "tid": tid,
                "args": args,
            }
        )

    uploads = report.upload_issued_t
    dispatches = report.dispatch_t
    done = report.compute_done_t
    for i, t_disp in enumerate(dispatches):
        if i < len(uploads):
            slice_event(
                f"chunk {i} upload", _STREAM_UPLOAD_TID, uploads[i], t_disp,
                chunk=i, chunk_rows=report.chunk_rows,
            )
        if i < len(done):
            slice_event(
                f"chunk {i} compute", _STREAM_COMPUTE_TID, t_disp, done[i],
                chunk=i,
            )
    for tid, name in (
        (_STREAM_UPLOAD_TID, "stream-upload"),
        (_STREAM_COMPUTE_TID, "stream-compute"),
    ):
        events.append(
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": name}}
        )
    return events


def chrome_trace(
    session: TraceSession,
    stream_report: Any = None,
    cost_ledger: Any = None,
    quality_ring: Any = None,
) -> Dict[str, Any]:
    """The session's spans as a Chrome trace-event JSON object; pass the
    last :class:`~keystone_tpu.workflow.streaming.StreamReport` to also
    emit its per-chunk upload/compute slices (:func:`stream_report_events`),
    a list of perf-ledger entries (``obs.cost.get_ledger().tail(n)``)
    for the ``cost-ledger`` counter track (:func:`cost_ledger_events`),
    and the flight recorder's quality ring
    (``get_flight_recorder().quality_ring()``) for the ``quality``
    drift/gate track (:func:`quality_events`)."""
    import os

    pid = os.getpid()
    base = session.started_s
    events: List[Dict[str, Any]] = []
    seen_threads: Dict[int, str] = {}
    for span in session.spans():
        tid = span.thread_id or 0
        if tid not in seen_threads:
            seen_threads[tid] = span.thread_name
        end = span.end_s if span.end_s is not None else span.start_s
        args = {k: _json_safe(v) for k, v in span.attributes.items()}
        args["span_id"] = span.span_id
        if span.parent_id:
            args["parent_id"] = span.parent_id
        args["trace_id"] = span.trace_id
        if span.status != "ok":
            args["status"] = span.status
        events.append(
            {
                "name": span.name,
                "cat": span.name.split(":", 1)[0] or "span",
                "ph": "X",
                "ts": round((span.start_s - base) * 1e6, 3),
                "dur": round((end - span.start_s) * 1e6, 3),
                "pid": pid,
                "tid": tid,
                "args": args,
            }
        )
        for event in span.events:
            events.append(
                {
                    "name": event.name,
                    "cat": "event",
                    "ph": "i",
                    "s": "t",  # thread-scoped instant
                    "ts": round((event.ts_s - base) * 1e6, 3),
                    "pid": pid,
                    "tid": tid,
                    "args": {k: _json_safe(v) for k, v in event.attributes.items()},
                }
            )
    for tid, thread_name in seen_threads.items():
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": thread_name or f"thread-{tid}"},
            }
        )
    events.extend(stream_report_events(stream_report, session.started_s, pid))
    events.extend(cost_ledger_events(cost_ledger, session.started_s, pid))
    events.extend(quality_events(quality_ring, session.started_unix, pid))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_id": session.trace_id,
            "session": session.name,
            "started_unix": session.started_unix,
            "dropped_spans": session.dropped,
        },
    }


def write_chrome_trace(
    session: TraceSession,
    path: str,
    stream_report: Any = None,
    cost_ledger: Any = None,
    quality_ring: Any = None,
) -> str:
    with open(path, "w") as f:
        json.dump(
            chrome_trace(
                session, stream_report=stream_report, cost_ledger=cost_ledger,
                quality_ring=quality_ring,
            ),
            f,
        )
    return path


# -------------------------------------------------------------- prometheus


def _escape_label(value: str) -> str:
    return value.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _fmt_labels(key, extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(str(v))}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_value(value: float) -> str:
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def prometheus_text(registry: Optional[MetricsRegistry] = None) -> str:
    """Prometheus text exposition of every registered metric."""
    registry = registry or get_registry()
    lines: List[str] = []
    for metric in registry.collect():
        lines.append(f"# HELP {metric.name} {metric.help or metric.name}")
        lines.append(f"# TYPE {metric.name} {metric.kind}")
        series = metric.series()
        if isinstance(metric, Histogram):
            for key, s in sorted(series.items()):
                cumulative = 0
                for bound, count in zip(metric.buckets, s.bucket_counts):
                    cumulative += count
                    le = 'le="%r"' % (bound,)
                    lines.append(
                        f"{metric.name}_bucket{_fmt_labels(key, le)} {cumulative}"
                    )
                cumulative += s.bucket_counts[-1]
                inf = 'le="+Inf"'
                lines.append(
                    f"{metric.name}_bucket{_fmt_labels(key, inf)} {cumulative}"
                )
                lines.append(f"{metric.name}_sum{_fmt_labels(key)} {repr(float(s.sum))}")
                lines.append(f"{metric.name}_count{_fmt_labels(key)} {s.count}")
        elif isinstance(metric, (Counter, Gauge)):
            if not series and not metric.label_names:
                lines.append(f"{metric.name} 0")
            for key, value in sorted(series.items()):
                lines.append(f"{metric.name}{_fmt_labels(key)} {_fmt_value(value)}")
    return "\n".join(lines) + "\n"


def write_prometheus(path: str, registry: Optional[MetricsRegistry] = None) -> str:
    with open(path, "w") as f:
        f.write(prometheus_text(registry))
    return path


# ------------------------------------------------------------ human report


def report(session: TraceSession, max_depth: int = 6) -> str:
    """Indented span tree, children in start order, slowest roots first."""
    spans = session.spans()
    children: Dict[Optional[str], List[Span]] = {}
    by_id = {s.span_id: s for s in spans}
    for s in spans:
        parent = s.parent_id if s.parent_id in by_id else None
        children.setdefault(parent, []).append(s)
    for siblings in children.values():
        siblings.sort(key=lambda s: s.start_s)
    roots = sorted(children.get(None, []), key=lambda s: -s.duration_s)

    width = max(
        [len("span")] + [min(len(s.name), 48) + 2 * max_depth for s in spans]
    )
    lines = [f"{'span':<{width}}  {'ms':>10}  {'self ms':>10}"]

    def walk(span: Span, depth: int) -> None:
        kids = children.get(span.span_id, [])
        child_s = sum(k.duration_s for k in kids)
        label = ("  " * depth) + span.name[:48]
        flag = " !" if span.status != "ok" else ""
        lines.append(
            f"{label:<{width}}  {span.duration_s * 1e3:>10.3f}  "
            f"{max(span.duration_s - child_s, 0.0) * 1e3:>10.3f}{flag}"
        )
        if depth + 1 < max_depth:
            for kid in kids:
                walk(kid, depth + 1)

    for root in roots:
        walk(root, 0)
    if session.dropped:
        lines.append(f"... {session.dropped} spans dropped (session cap)")
    return "\n".join(lines)
