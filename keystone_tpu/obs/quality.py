"""The live model-quality plane (docs/OBSERVABILITY.md "Quality plane").

The rest of obs/ measures *performance* — spans, fleet traces, the cost
observatory. This module measures *model quality on live traffic* and
turns it into decisions with controlled error rates:

- :class:`ScoreStream` — bounded-memory online accumulators over
  prediction scores: count/mean/M2 (Welford) plus P² quantile markers.
  O(1) state per stream, serializable, so the daemon's label-joined
  accuracy stream rides the refit journal and survives restarts.
- :class:`ChannelSketch` / :class:`PayloadSketch` — MERGEABLE moment +
  quantile sketches over serving payload features and scores. Workers
  accumulate a delta sketch between heartbeats and ship it exactly like
  PR-13 metric fragments; the supervisor merges deltas fleet-wide.
  Moment merges are exact (Chan's parallel update); quantile merges are
  bounded-error (Ben-Haim/Tom-Tov streaming histogram).
- :class:`SequentialGate` — an anytime-valid sequential test comparing
  two score streams (candidate vs production, or current vs baseline
  window) built on empirical-Bernstein confidence sequences. It emits
  ``promote`` / ``rollback`` / ``continue`` with a configured
  false-positive bound ``alpha``: the radii hold simultaneously over all
  sample sizes (union bound over n), so peeking every sample is sound —
  this is the statistical gate the canary item needs, and it upgrades
  the refit daemon's fixed watch window.
- :class:`DriftDetector` — standardized-shift detector over the stream
  and sketch moments that drives ``refit.state_decay`` adaptively: a
  quiet tenant keeps full history, a drifting tenant forgets faster.
- :class:`QualityPlane` — the per-model registry tying it together,
  publishing the ``keystone_quality_*`` metric family and feeding the
  flight recorder's ``quality`` ring.

Everything here is stdlib-only and cheap on the request path: one
Welford update plus a handful of histogram inserts per sampled payload.
The serving-overhead budget (≤5%, asserted by scripts/quality_smoke.sh)
is the contract.

Environment knobs (read at call time via envknobs):

- ``KEYSTONE_QUALITY`` — tri-state; ``off``/``0``/``disabled``
  disables all observation (the overhead-budget A/B switch).
- ``KEYSTONE_QUALITY_ALPHA`` — sequential-gate false-positive bound
  (default 0.05).
- ``KEYSTONE_QUALITY_MIN_SAMPLES`` / ``KEYSTONE_QUALITY_MAX_SAMPLES``
  — gate decision window (defaults 24 / 512).
- ``KEYSTONE_QUALITY_MAX_FEATURES`` — payload coordinates sketched per
  model (default 8).
- ``KEYSTONE_QUALITY_SKETCH_BINS`` — histogram bins per channel
  (default 64).
- ``KEYSTONE_QUALITY_SAMPLE`` — 1-in-N payload sampling (default 1).
- ``KEYSTONE_QUALITY_DRIFT_THRESHOLD`` — standardized-shift threshold
  (default 0.5 baseline standard deviations).
- ``KEYSTONE_QUALITY_DRIFT_MIN_COUNT`` — samples before the detector
  may fire (default 64).
- ``KEYSTONE_QUALITY_DECAY_FLOOR`` — lowest adaptive ``state_decay``
  the detector will suggest (default 0.5).
"""

from __future__ import annotations

import bisect
import json
import math
import threading
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from ..envknobs import env_disabled, env_float, env_int

QUALITY_ENV = "KEYSTONE_QUALITY"


def quality_enabled() -> bool:
    """True unless ``KEYSTONE_QUALITY`` spells off (default-on plane)."""
    return not env_disabled(QUALITY_ENV)


def quality_alpha() -> float:
    return env_float("KEYSTONE_QUALITY_ALPHA", 0.05)


def quality_min_samples() -> int:
    return env_int("KEYSTONE_QUALITY_MIN_SAMPLES", 24)


def quality_max_samples() -> int:
    return env_int("KEYSTONE_QUALITY_MAX_SAMPLES", 512)


def quality_max_features() -> int:
    return env_int("KEYSTONE_QUALITY_MAX_FEATURES", 8)


def quality_sketch_bins() -> int:
    return env_int("KEYSTONE_QUALITY_SKETCH_BINS", 64)


def quality_sample_every() -> int:
    return max(env_int("KEYSTONE_QUALITY_SAMPLE", 1), 1)


def drift_threshold() -> float:
    return env_float("KEYSTONE_QUALITY_DRIFT_THRESHOLD", 0.5)


def drift_min_count() -> int:
    return env_int("KEYSTONE_QUALITY_DRIFT_MIN_COUNT", 64)


def decay_floor() -> float:
    return env_float("KEYSTONE_QUALITY_DECAY_FLOOR", 0.5)


# ------------------------------------------------------------------ moments


class Moments:
    """Welford count/mean/M2 plus min/max. ``merge`` is Chan's parallel
    update — EXACT (up to float rounding) for any split of the input, the
    property the sketch-mergeability test pins."""

    __slots__ = ("count", "mean", "m2", "min", "max")

    def __init__(self) -> None:
        self.count = 0
        self.mean = 0.0
        self.m2 = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, x: float) -> None:
        self.count += 1
        delta = x - self.mean
        self.mean += delta / self.count
        self.m2 += delta * (x - self.mean)
        if x < self.min:
            self.min = x
        if x > self.max:
            self.max = x

    def merge(self, other: "Moments") -> None:
        if other.count == 0:
            return
        if self.count == 0:
            self.count, self.mean, self.m2 = other.count, other.mean, other.m2
            self.min, self.max = other.min, other.max
            return
        n = self.count + other.count
        delta = other.mean - self.mean
        self.mean += delta * other.count / n
        self.m2 += other.m2 + delta * delta * self.count * other.count / n
        self.count = n
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)

    @property
    def variance(self) -> float:
        return self.m2 / (self.count - 1) if self.count > 1 else 0.0

    @property
    def std(self) -> float:
        return math.sqrt(max(self.variance, 0.0))

    def to_wire(self) -> list:
        return [
            self.count,
            self.mean,
            self.m2,
            self.min if self.count else None,
            self.max if self.count else None,
        ]

    @classmethod
    def from_wire(cls, wire: Sequence) -> "Moments":
        m = cls()
        m.count = int(wire[0])
        m.mean = float(wire[1])
        m.m2 = float(wire[2])
        m.min = float(wire[3]) if wire[3] is not None else math.inf
        m.max = float(wire[4]) if wire[4] is not None else -math.inf
        return m


# ------------------------------------------------------------- P² quantile


class P2Quantile:
    """The classic P² single-quantile estimator (Jain & Chlamtac): five
    markers, O(1) memory and update. Not mergeable — per-process score
    streams use it; the fleet view rides :class:`QuantileSketch`."""

    __slots__ = ("q", "_buf", "_h", "_pos", "_des", "_inc")

    def __init__(self, q: float) -> None:
        self.q = q
        self._buf: Optional[List[float]] = []
        self._h: List[float] = []
        self._pos = [1.0, 2.0, 3.0, 4.0, 5.0]
        self._des = [1.0, 1.0 + 2.0 * q, 1.0 + 4.0 * q, 3.0 + 2.0 * q, 5.0]
        self._inc = [0.0, q / 2.0, q, (1.0 + q) / 2.0, 1.0]

    def observe(self, x: float) -> None:
        if self._buf is not None:
            self._buf.append(x)
            if len(self._buf) == 5:
                self._h = sorted(self._buf)
                self._buf = None
            return
        h, pos = self._h, self._pos
        if x < h[0]:
            h[0] = x
            k = 0
        elif x >= h[4]:
            h[4] = x
            k = 3
        else:
            k = 0
            while k < 3 and not (h[k] <= x < h[k + 1]):
                k += 1
        for i in range(k + 1, 5):
            pos[i] += 1.0
        for i in range(5):
            self._des[i] += self._inc[i]
        for i in (1, 2, 3):
            d = self._des[i] - pos[i]
            if (d >= 1.0 and pos[i + 1] - pos[i] > 1.0) or (
                d <= -1.0 and pos[i - 1] - pos[i] < -1.0
            ):
                s = 1.0 if d > 0 else -1.0
                cand = h[i] + s / (pos[i + 1] - pos[i - 1]) * (
                    (pos[i] - pos[i - 1] + s)
                    * (h[i + 1] - h[i])
                    / (pos[i + 1] - pos[i])
                    + (pos[i + 1] - pos[i] - s)
                    * (h[i] - h[i - 1])
                    / (pos[i] - pos[i - 1])
                )
                if h[i - 1] < cand < h[i + 1]:
                    h[i] = cand
                else:  # parabolic left the bracket: fall back to linear
                    j = i + int(s)
                    h[i] += s * (h[j] - h[i]) / (pos[j] - pos[i])
                pos[i] += s

    def value(self) -> Optional[float]:
        if self._buf is not None:
            if not self._buf:
                return None
            srt = sorted(self._buf)
            idx = self.q * (len(srt) - 1)
            lo = int(math.floor(idx))
            hi = min(lo + 1, len(srt) - 1)
            return srt[lo] + (idx - lo) * (srt[hi] - srt[lo])
        return self._h[2]

    def to_wire(self) -> dict:
        if self._buf is not None:
            return {"q": self.q, "buf": list(self._buf)}
        return {"q": self.q, "h": list(self._h), "pos": list(self._pos),
                "des": list(self._des)}

    @classmethod
    def from_wire(cls, wire: dict) -> "P2Quantile":
        est = cls(float(wire["q"]))
        if "buf" in wire:
            est._buf = [float(v) for v in wire["buf"]]
        else:
            est._buf = None
            est._h = [float(v) for v in wire["h"]]
            est._pos = [float(v) for v in wire["pos"]]
            est._des = [float(v) for v in wire["des"]]
        return est


# -------------------------------------------------------- mergeable sketch


class QuantileSketch:
    """Bounded-error mergeable quantile sketch: a Ben-Haim/Tom-Tov
    streaming histogram of at most ``bins`` weighted centroids. Inserts
    are O(bins); merging concatenates centroid lists and re-compacts, so
    any heartbeat-sharded observation order converges to (nearly) the
    same histogram — the bounded half of the mergeability test."""

    __slots__ = ("bins", "_centroids")

    def __init__(self, bins: int = 64) -> None:
        self.bins = max(int(bins), 8)
        self._centroids: List[List[float]] = []  # sorted [value, weight]

    def add(self, x: float, weight: float = 1.0) -> None:
        c = self._centroids
        i = bisect.bisect_left(c, [x, -math.inf])
        if i < len(c) and c[i][0] == x:
            c[i][1] += weight
        else:
            c.insert(i, [x, weight])
            if len(c) > self.bins:
                self._compact()

    def _compact(self) -> None:
        c = self._centroids
        while len(c) > self.bins:
            gap_i = min(
                range(len(c) - 1), key=lambda i: (c[i + 1][0] - c[i][0], i)
            )
            v1, w1 = c[gap_i]
            v2, w2 = c[gap_i + 1]
            w = w1 + w2
            c[gap_i] = [(v1 * w1 + v2 * w2) / w, w]
            del c[gap_i + 1]

    def merge(self, other: "QuantileSketch") -> None:
        for value, weight in other._centroids:
            self.add(value, weight)

    def quantile(self, q: float) -> Optional[float]:
        c = self._centroids
        if not c:
            return None
        total = sum(w for _, w in c)
        if total <= 0:
            return None
        target = q * total
        cum = 0.0
        for i, (value, weight) in enumerate(c):
            if cum + weight / 2.0 >= target:
                if i == 0:
                    return value
                pv, pw = c[i - 1]
                prev_mid = cum - pw / 2.0
                mid = cum + weight / 2.0
                frac = (target - prev_mid) / max(mid - prev_mid, 1e-12)
                return pv + frac * (value - pv)
            cum += weight
        return c[-1][0]

    def to_wire(self) -> list:
        return [[round(v, 9), w] for v, w in self._centroids]

    @classmethod
    def from_wire(cls, wire: Sequence, bins: int = 64) -> "QuantileSketch":
        sk = cls(bins)
        sk._centroids = sorted([float(v), float(w)] for v, w in wire)
        sk._compact()
        return sk


class ChannelSketch:
    """One observed channel (a payload feature, or the score itself):
    exact-mergeable moments plus a bounded-error quantile histogram."""

    __slots__ = ("moments", "quantiles")

    def __init__(self, bins: int = 64) -> None:
        self.moments = Moments()
        self.quantiles = QuantileSketch(bins)

    def observe(self, x: float) -> None:
        self.moments.observe(x)
        self.quantiles.add(x)

    def merge(self, other: "ChannelSketch") -> None:
        self.moments.merge(other.moments)
        self.quantiles.merge(other.quantiles)

    def to_wire(self) -> dict:
        return {"m": self.moments.to_wire(), "q": self.quantiles.to_wire()}

    @classmethod
    def from_wire(cls, wire: dict, bins: int = 64) -> "ChannelSketch":
        sk = cls(bins)
        sk.moments = Moments.from_wire(wire["m"])
        sk.quantiles = QuantileSketch.from_wire(wire["q"], bins)
        return sk

    def summary(self) -> dict:
        m = self.moments
        return {
            "count": m.count,
            "mean": round(m.mean, 6) if m.count else None,
            "std": round(m.std, 6) if m.count else None,
            "min": m.min if m.count else None,
            "max": m.max if m.count else None,
            "p50": self.quantiles.quantile(0.5),
            "p90": self.quantiles.quantile(0.9),
        }


class PayloadSketch:
    """Per-model input-distribution sketch: one :class:`ChannelSketch`
    per tracked payload coordinate (``f0``..``f<max_features-1>``) plus
    the ``score`` channel. Workers accumulate one of these as a DELTA
    between heartbeats (drained and reset each beat); the supervisor
    merges deltas into its cumulative fleet sketch. Because deltas are
    increments — not level snapshots — worker restarts need no
    incarnation folding: a dead worker simply stops contributing."""

    SCORE = "score"

    def __init__(self, max_features: Optional[int] = None,
                 bins: Optional[int] = None) -> None:
        self.max_features = (
            quality_max_features() if max_features is None else max_features
        )
        self.bins = quality_sketch_bins() if bins is None else bins
        self.rows = 0
        self.channels: Dict[str, ChannelSketch] = {}

    def _channel(self, key: str) -> ChannelSketch:
        ch = self.channels.get(key)
        if ch is None:
            ch = self.channels[key] = ChannelSketch(self.bins)
        return ch

    def observe_row(self, row: Sequence[float]) -> None:
        self.rows += 1
        for i, value in enumerate(row):
            if i >= self.max_features:
                break
            try:
                self._channel("f%d" % i).observe(float(value))
            except (TypeError, ValueError):
                continue

    def observe_score(self, score: float) -> None:
        self._channel(self.SCORE).observe(float(score))

    def merge(self, other: "PayloadSketch") -> None:
        self.rows += other.rows
        for key, ch in other.channels.items():
            self._channel(key).merge(ch)

    def to_wire(self) -> dict:
        return {
            "rows": self.rows,
            "ch": {k: ch.to_wire() for k, ch in self.channels.items()},
        }

    @classmethod
    def from_wire(cls, wire: dict, max_features: Optional[int] = None,
                  bins: Optional[int] = None) -> "PayloadSketch":
        sk = cls(max_features, bins)
        sk.rows = int(wire.get("rows", 0))
        for key, ch_wire in wire.get("ch", {}).items():
            sk.channels[key] = ChannelSketch.from_wire(ch_wire, sk.bins)
        return sk

    def wire_bytes(self) -> int:
        return len(json.dumps(self.to_wire(), separators=(",", ":")))

    def summary(self) -> dict:
        return {
            "rows": self.rows,
            "bytes": self.wire_bytes(),
            "channels": {k: self.channels[k].summary()
                         for k in sorted(self.channels)},
        }


# ------------------------------------------------------------ score stream


class ScoreStream:
    """Bounded-memory accumulator over one score stream: Welford moments
    plus P² markers at p10/p50/p90. O(1) state, JSON-serializable — the
    label-joined stream persists its state through the refit store so a
    daemon restart resumes exactly where the journal says it left off."""

    QUANTILES = (0.1, 0.5, 0.9)

    def __init__(self) -> None:
        self.moments = Moments()
        self._p2 = {q: P2Quantile(q) for q in self.QUANTILES}

    def observe(self, score: float) -> None:
        score = float(score)
        self.moments.observe(score)
        for est in self._p2.values():
            est.observe(score)

    def observe_many(self, scores: Sequence[float]) -> None:
        for s in scores:
            self.observe(s)

    @property
    def count(self) -> int:
        return self.moments.count

    @property
    def mean(self) -> float:
        return self.moments.mean

    def quantile(self, q: float) -> Optional[float]:
        est = self._p2.get(q)
        return est.value() if est is not None else None

    def summary(self) -> dict:
        m = self.moments
        out = {
            "count": m.count,
            "mean": round(m.mean, 6) if m.count else None,
            "std": round(m.std, 6) if m.count else None,
            "min": m.min if m.count else None,
            "max": m.max if m.count else None,
        }
        for q in self.QUANTILES:
            v = self.quantile(q)
            out["p%d" % int(q * 100)] = round(v, 6) if v is not None else None
        return out

    def to_state(self) -> dict:
        return {
            "m": self.moments.to_wire(),
            "p2": {str(q): est.to_wire() for q, est in self._p2.items()},
        }

    @classmethod
    def from_state(cls, state: dict) -> "ScoreStream":
        stream = cls()
        stream.moments = Moments.from_wire(state["m"])
        for key, wire in state.get("p2", {}).items():
            stream._p2[float(key)] = P2Quantile.from_wire(wire)
        return stream


# --------------------------------------------------------- sequential gate


def _eb_radius(n: int, variance: float, value_range: float,
               alpha: float) -> float:
    """Anytime-valid empirical-Bernstein confidence radius for a sample
    mean after ``n`` observations bounded in a range of width
    ``value_range``. The ``log(3 n (n+1) / alpha)`` term is the union
    bound over all n simultaneously (time-uniform stitching), which is
    what makes peeking at every sample sound."""
    if n < 2:
        return math.inf
    t = math.log(3.0 * n * (n + 1) / alpha)
    return math.sqrt(2.0 * max(variance, 0.0) * t / n) + 3.0 * value_range * t / n


class SequentialGate:
    """Anytime-valid two-stream comparison: candidate vs baseline score
    streams, decided with empirical-Bernstein confidence sequences.

    ``observe()`` feeds one score into either side; ``evaluate()`` may
    be called after EVERY observation (that is the point) and returns:

    - ``"rollback"`` — the candidate mean is significantly below the
      baseline mean (confidence intervals separated), at family error
      ≤ ``alpha`` over the whole run;
    - ``"promote"`` — significantly above, same guarantee — or the
      sample budget is exhausted with no detected regression (no
      evidence of harm inside the configured window);
    - ``"continue"`` — keep sampling.

    A decision is sticky: once non-``continue``, the gate is closed.
    """

    def __init__(self, model: str, kind: str = "candidate_vs_incumbent",
                 alpha: Optional[float] = None,
                 min_samples: Optional[int] = None,
                 max_samples: Optional[int] = None,
                 method: str = "msprt") -> None:
        self.model = model
        self.kind = kind
        self.method = method
        self.alpha = quality_alpha() if alpha is None else alpha
        self.min_samples = (
            quality_min_samples() if min_samples is None else min_samples
        )
        self.max_samples = (
            quality_max_samples() if max_samples is None else max_samples
        )
        self.candidate = Moments()
        self.baseline = Moments()
        self.decision: Optional[str] = None
        self.budget_exhausted = False

    def observe(self, candidate: Optional[float] = None,
                baseline: Optional[float] = None) -> str:
        if candidate is not None:
            self.candidate.observe(float(candidate))
        if baseline is not None:
            self.baseline.observe(float(baseline))
        return self.evaluate()

    @property
    def samples(self) -> int:
        return self.candidate.count + self.baseline.count

    def _range(self) -> float:
        lo = min(self.candidate.min, self.baseline.min)
        hi = max(self.candidate.max, self.baseline.max)
        if not math.isfinite(lo) or not math.isfinite(hi):
            return 1.0
        return max(hi - lo, 1e-6)

    def _mixture_lr(self) -> float:
        """The mSPRT mixture likelihood ratio for the mean difference:
        H0 says the two streams share a mean; the alternative mixes a
        Gaussian prior of scale tau over the difference. With the Welch
        plug-in variance ``v_n`` of the difference estimator,

            LR_n = sqrt(v_n / (v_n + tau^2))
                   * exp(delta^2 * tau^2 / (2 v_n (v_n + tau^2)))

        is a (approximate, plug-in) nonnegative supermartingale under
        H0, so rejecting when LR_n >= 1/alpha is anytime-valid: the
        gate may be evaluated after every sample. tau^2 defaults to the
        pooled per-observation variance (effect sizes of about one
        observation sigma get the most mixture mass)."""
        v_n = (
            self.candidate.variance / self.candidate.count
            + self.baseline.variance / self.baseline.count
        )
        v_n = max(v_n, 1e-18)
        tau2 = max(
            (self.candidate.variance + self.baseline.variance) / 2.0, 1e-12
        )
        delta = self.candidate.mean - self.baseline.mean
        exponent = delta * delta * tau2 / (2.0 * v_n * (v_n + tau2))
        # Cap before exp() so an enormous separation cannot overflow.
        return math.sqrt(v_n / (v_n + tau2)) * math.exp(min(exponent, 700.0))

    def evaluate(self) -> str:
        if self.decision is not None:
            return self.decision
        nc, nb = self.candidate.count, self.baseline.count
        if min(nc, nb) < 2 or self.samples < self.min_samples:
            return "continue"
        separated = 0  # -1 candidate worse, +1 candidate better
        if self.method == "eb":
            rng = self._range()
            # alpha/2 per side so the pair of sequences holds jointly.
            rc = _eb_radius(nc, self.candidate.variance, rng, self.alpha / 2.0)
            rb = _eb_radius(nb, self.baseline.variance, rng, self.alpha / 2.0)
            if self.candidate.mean - rc > self.baseline.mean + rb:
                separated = 1
            elif self.candidate.mean + rc < self.baseline.mean - rb:
                separated = -1
        else:
            if self._mixture_lr() >= 1.0 / self.alpha:
                separated = (
                    1 if self.candidate.mean > self.baseline.mean else -1
                )
        if separated > 0:
            self.decision = "promote"
        elif separated < 0:
            self.decision = "rollback"
        elif self.samples >= self.max_samples:
            # Budget exhausted with no separation: no evidence of harm.
            self.decision = "promote"
            self.budget_exhausted = True
        else:
            return "continue"
        return self.decision

    def evidence(self) -> dict:
        rng = self._range()
        nc, nb = self.candidate.count, self.baseline.count
        return {
            "model": self.model,
            "kind": self.kind,
            "method": self.method,
            "alpha": self.alpha,
            "lr": (round(min(self._mixture_lr(), 1e12), 4)
                   if min(nc, nb) >= 2 else None),
            "decision": self.decision or "continue",
            "budget_exhausted": self.budget_exhausted,
            "samples": self.samples,
            "max_samples": self.max_samples,
            "candidate": {
                "n": nc,
                "mean": round(self.candidate.mean, 6) if nc else None,
                "radius": (
                    round(_eb_radius(nc, self.candidate.variance, rng,
                                     self.alpha / 2.0), 6)
                    if nc >= 2 else None
                ),
            },
            "baseline": {
                "n": nb,
                "mean": round(self.baseline.mean, 6) if nb else None,
                "radius": (
                    round(_eb_radius(nb, self.baseline.variance, rng,
                                     self.alpha / 2.0), 6)
                    if nb >= 2 else None
                ),
            },
        }


# ----------------------------------------------------------- drift detector


class DriftDetector:
    """Standardized-shift drift detector over a model's live score
    stream. ``freeze_baseline()`` pins the reference window; after that,
    ``drift_score`` is the current-window mean shift measured in
    baseline standard deviations (a population-shift scale, deliberately
    NOT a standard error — huge n must not turn noise into "drift").
    Crossing the threshold fires ONE drift event (edge-triggered; the
    detector re-arms only when the score falls back under threshold) and
    lowers the suggested ``state_decay`` toward the floor so the refit
    fold forgets stale history faster."""

    def __init__(self, threshold: Optional[float] = None,
                 min_count: Optional[int] = None,
                 floor: Optional[float] = None) -> None:
        self.threshold = drift_threshold() if threshold is None else threshold
        self.min_count = drift_min_count() if min_count is None else min_count
        self.floor = decay_floor() if floor is None else floor
        self.baseline: Optional[Moments] = None
        self.current = Moments()
        self.last_score = 0.0
        self.events = 0
        self._armed = True

    def observe(self, score: float) -> None:
        self.current.observe(float(score))

    def freeze_baseline(self) -> None:
        """Adopt the current window as the reference and start a fresh
        current window (e.g. at publish time, or on first quiet fill)."""
        if self.current.count:
            self.baseline = self.current
            self.current = Moments()

    def drift_score(self) -> float:
        if self.baseline is None or self.baseline.count < 2:
            return 0.0
        if self.current.count < self.min_count:
            return 0.0
        scale = max(self.baseline.std, 1e-9)
        return abs(self.current.mean - self.baseline.mean) / scale

    def check(self) -> Optional[dict]:
        """Recompute the drift score; return an event dict exactly once
        per threshold crossing, else None."""
        self.last_score = self.drift_score()
        if self.last_score > self.threshold:
            if self._armed:
                self._armed = False
                self.events += 1
                return {
                    "kind": "drift",
                    "score": round(self.last_score, 6),
                    "threshold": self.threshold,
                    "baseline_mean": round(self.baseline.mean, 6),
                    "current_mean": round(self.current.mean, 6),
                    "baseline_n": self.baseline.count,
                    "current_n": self.current.count,
                }
        else:
            self._armed = True
        return None

    def suggested_decay(self, base: float) -> float:
        """Map the drift score onto ``state_decay``: quiet → ``base``,
        at threshold → start shrinking, at 2× threshold → the floor."""
        score = self.last_score
        if score <= self.threshold:
            return base
        over = min((score - self.threshold) / max(self.threshold, 1e-9), 1.0)
        return max(self.floor, base - (base - self.floor) * over)


# ------------------------------------------------------------ the plane


class QualityPlane:
    """Per-model quality registry for one process.

    Workers observe scores/payloads into their process-local plane and
    drain heartbeat deltas; the supervisor merges those deltas into its
    own plane for the fleet view; the refit daemon joins delayed labels
    and runs gates/drift against its plane. All methods are no-ops when
    ``KEYSTONE_QUALITY`` spells off, so the request-path cost can be
    A/B-measured honestly.
    """

    MAX_DECISIONS = 64

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._streams: Dict[Tuple[str, str], ScoreStream] = {}
        self._sketches: Dict[str, PayloadSketch] = {}
        self._pending: Dict[str, PayloadSketch] = {}
        self._drift: Dict[str, DriftDetector] = {}
        self._gates: Dict[str, SequentialGate] = {}
        self._sample_counter = 0
        self._label_joins: Dict[str, int] = {}
        self._merges = 0
        self.decisions: deque = deque(maxlen=self.MAX_DECISIONS)

    # -- streams -----------------------------------------------------

    def stream(self, model: str, role: str = "live") -> ScoreStream:
        with self._lock:
            key = (model, role)
            stream = self._streams.get(key)
            if stream is None:
                stream = self._streams[key] = ScoreStream()
            return stream

    def observe_score(self, model: str, score: float,
                      role: str = "live") -> None:
        if not quality_enabled():
            return
        with self._lock:
            self.stream(model, role).observe(score)
            if role == "live":
                self.drift(model).observe(score)
        from . import names
        names.metric(names.QUALITY_SCORES).inc(model=model, role=role)

    def observe_served(self, model: str, row: Sequence[float],
                       score: Optional[float] = None) -> None:
        """One served request: sketch the payload (sampled) and feed the
        prediction score into the live stream + drift window. This is
        the request-path entry point — backends call exactly this."""
        if not quality_enabled():
            return
        self.observe_payload(model, row, score)
        if score is not None:
            self.observe_score(model, score, role="live")

    def join_labels(self, model: str, scores: Sequence[float]) -> int:
        """Fold a batch of label-joined per-row scores (accuracy/loss
        from delayed labels) into the ``labeled`` stream. Returns rows
        joined. The caller (the refit daemon) provides exactly-once
        semantics by persisting :meth:`state` with its journal."""
        if not quality_enabled() or not len(scores):
            return 0
        with self._lock:
            self.stream(model, "labeled").observe_many(scores)
            self._label_joins[model] = (
                self._label_joins.get(model, 0) + len(scores)
            )
        from . import names
        names.metric(names.QUALITY_LABEL_JOINS).inc(len(scores), model=model)
        return len(scores)

    # -- payload sketches --------------------------------------------

    def observe_payload(self, model: str, row: Sequence[float],
                        score: Optional[float] = None) -> None:
        """Worker-side: sketch one served payload row (1-in-N sampled)
        and its prediction score into the pending heartbeat delta."""
        if not quality_enabled():
            return
        with self._lock:
            self._sample_counter += 1
            if self._sample_counter % quality_sample_every():
                return
            pending = self._pending.get(model)
            if pending is None:
                pending = self._pending[model] = PayloadSketch()
            pending.observe_row(row)
            if score is not None:
                pending.observe_score(score)

    def drain_delta(self) -> Optional[dict]:
        """Ship-and-reset the pending sketches: the heartbeat payload.
        Returns ``{model: wire}`` or None when nothing was observed."""
        with self._lock:
            if not self._pending:
                return None
            wire = {m: sk.to_wire() for m, sk in self._pending.items()}
            self._pending.clear()
            return wire

    def merge_delta(self, wire: dict, role: str = "worker") -> None:
        """Supervisor-side: fold one worker heartbeat delta into the
        cumulative fleet sketches (and the live score streams/drift,
        via the delta's score-channel moments)."""
        if not wire:
            return
        with self._lock:
            for model, sk_wire in wire.items():
                sketch = self._sketches.get(model)
                if sketch is None:
                    sketch = self._sketches[model] = PayloadSketch()
                sketch.merge(PayloadSketch.from_wire(sk_wire))
            self._merges += 1
        from . import names
        names.metric(names.QUALITY_SKETCH_MERGES).inc(role=role)

    def sketch(self, model: str) -> Optional[PayloadSketch]:
        with self._lock:
            return self._sketches.get(model)

    # -- drift --------------------------------------------------------

    def drift(self, model: str) -> DriftDetector:
        with self._lock:
            det = self._drift.get(model)
            if det is None:
                det = self._drift[model] = DriftDetector()
            return det

    def check_drift(self, model: str) -> Optional[dict]:
        """Edge-triggered drift check; on a firing, bumps the metric and
        feeds the flight recorder's quality ring (which dumps)."""
        if not quality_enabled():
            return None
        event = self.drift(model).check()
        from . import names
        names.metric(names.QUALITY_DRIFT_SCORE).set(
            self.drift(model).last_score, model=model
        )
        if event is None:
            return None
        event["model"] = model
        names.metric(names.QUALITY_DRIFT_EVENTS).inc(model=model)
        from .flight import get_flight_recorder
        recorder = get_flight_recorder()
        if recorder is not None:
            recorder.observe_quality(dict(event))
        return event

    def suggested_decay(self, model: str, base: float) -> float:
        if not quality_enabled():
            return base
        decay = self.drift(model).suggested_decay(base)
        from . import names
        names.metric(names.QUALITY_STATE_DECAY).set(decay, model=model)
        return decay

    # -- gates --------------------------------------------------------

    def open_gate(self, model: str, kind: str = "candidate_vs_incumbent",
                  alpha: Optional[float] = None,
                  min_samples: Optional[int] = None,
                  max_samples: Optional[int] = None) -> SequentialGate:
        gate = SequentialGate(model, kind, alpha, min_samples, max_samples)
        with self._lock:
            self._gates["%s:%s" % (model, kind)] = gate
        return gate

    def record_decision(self, gate: SequentialGate) -> dict:
        """Close a gate: archive its evidence, bump the decision metric,
        feed the flight recorder's quality ring (a ``rollback`` dumps)."""
        evidence = gate.evidence()
        with self._lock:
            self.decisions.append(evidence)
            self._gates.pop("%s:%s" % (gate.model, gate.kind), None)
        from . import names
        names.metric(names.QUALITY_GATE_DECISIONS).inc(
            model=gate.model, decision=evidence["decision"]
        )
        from .flight import get_flight_recorder
        recorder = get_flight_recorder()
        if recorder is not None:
            # The gate's own "kind" (which streams it compared) must not
            # clobber the ring entry's event kind — the recorder dumps on
            # kind == "gate_decision" + decision == "rollback".
            event = dict(evidence)
            event["gate"] = event.pop("kind")
            event["kind"] = "gate_decision"
            recorder.observe_quality(event)
        return evidence

    def open_gates(self) -> List[dict]:
        with self._lock:
            return [g.evidence() for g in self._gates.values()]

    # -- surfacing ----------------------------------------------------

    def publish_metrics(self, registry=None) -> None:
        """Set the level-style ``keystone_quality_*`` gauges from current
        state (counters were bumped at event time)."""
        from . import names
        with self._lock:
            for (model, role), stream in self._streams.items():
                if not stream.count:
                    continue
                names.metric(names.QUALITY_SCORE_MEAN, registry).set(
                    stream.mean, model=model, role=role
                )
                for q in ScoreStream.QUANTILES:
                    v = stream.quantile(q)
                    if v is not None:
                        names.metric(names.QUALITY_SCORE_QUANTILE,
                                     registry).set(
                            v, model=model, role=role,
                            q="p%d" % int(q * 100)
                        )
            for model, sketch in self._sketches.items():
                names.metric(names.QUALITY_SKETCH_ROWS, registry).set(
                    sketch.rows, model=model
                )
                names.metric(names.QUALITY_SKETCH_BYTES, registry).set(
                    sketch.wire_bytes(), model=model
                )
            names.metric(names.QUALITY_GATE_OPEN, registry).set(
                len(self._gates)
            )
            for key, gate in self._gates.items():
                names.metric(names.QUALITY_GATE_SAMPLES, registry).set(
                    gate.samples, model=gate.model
                )

    def report(self) -> dict:
        """The CLI/bench-facing view: per-model score summaries, drift
        state, open gates, and archived decisions with evidence."""
        with self._lock:
            models = sorted(
                {m for m, _ in self._streams}
                | set(self._sketches)
                | set(self._drift)
            )
            out: dict = {"models": {}, "decisions": list(self.decisions),
                         "open_gates": [g.evidence()
                                        for g in self._gates.values()]}
            for model in models:
                streams = {
                    role: stream.summary()
                    for (m, role), stream in self._streams.items()
                    if m == model and stream.count
                }
                det = self._drift.get(model)
                sketch = self._sketches.get(model)
                out["models"][model] = {
                    "streams": streams,
                    "label_joins": self._label_joins.get(model, 0),
                    "drift": {
                        "score": round(det.last_score, 6) if det else 0.0,
                        "threshold": det.threshold if det else None,
                        "events": det.events if det else 0,
                        "drifting": bool(
                            det and det.last_score > det.threshold
                        ),
                    },
                    "sketch": sketch.summary() if sketch else None,
                }
            out["sketch_merges"] = self._merges
            return out

    # -- persistence (label-joined streams ride the refit journal) ----

    def state(self, model: str) -> dict:
        """Serializable restart-state for one model's label-joined
        plane: the labeled stream plus the drift windows. The refit
        daemon persists this next to its stream state so a crash between
        journal phases replays the join exactly once."""
        with self._lock:
            labeled = self._streams.get((model, "labeled"))
            det = self._drift.get(model)
            return {
                "labeled": labeled.to_state() if labeled else None,
                "joins": self._label_joins.get(model, 0),
                "drift": {
                    "baseline": (det.baseline.to_wire()
                                 if det and det.baseline else None),
                    "current": det.current.to_wire() if det else None,
                    "events": det.events if det else 0,
                    "armed": det._armed if det else True,
                } if det else None,
            }

    def restore(self, model: str, state: Optional[dict]) -> None:
        if not state:
            return
        with self._lock:
            if state.get("labeled"):
                self._streams[(model, "labeled")] = ScoreStream.from_state(
                    state["labeled"]
                )
            self._label_joins[model] = int(state.get("joins", 0))
            drift_state = state.get("drift")
            if drift_state:
                det = self.drift(model)
                if drift_state.get("baseline"):
                    det.baseline = Moments.from_wire(drift_state["baseline"])
                if drift_state.get("current"):
                    det.current = Moments.from_wire(drift_state["current"])
                det.events = int(drift_state.get("events", 0))
                det._armed = bool(drift_state.get("armed", True))


# ------------------------------------------------------- process singleton

_PLANE: Optional[QualityPlane] = None
_PLANE_LOCK = threading.Lock()


def get_quality_plane() -> QualityPlane:
    """The process-wide plane (workers and in-process serving observe
    here; the supervisor keeps its own instance for the fleet view)."""
    global _PLANE
    with _PLANE_LOCK:
        if _PLANE is None:
            _PLANE = QualityPlane()
        return _PLANE


def reset_quality_plane() -> None:
    global _PLANE
    with _PLANE_LOCK:
        _PLANE = None
