"""``keystone-tpu quality`` — the quality-plane report command.

Runs a deterministic seeded traffic scenario through a fresh
:class:`~keystone_tpu.obs.quality.QualityPlane` (stdlib-only — no jax,
no serving stack) and prints the operator-facing report: per-model score
summaries, drift state, open sequential tests, and archived decisions
with their evidence. The final ``QUALITY_STATS:{...}`` JSON line is the
machine contract ``scripts/quality_smoke.sh`` asserts on.

The scenario: a baseline window of Gaussian scores is observed and
frozen as the drift reference, then a current window — shifted by
``--shift`` baseline standard deviations — is served against it while a
candidate-vs-incumbent :class:`SequentialGate` compares the two streams
pairwise. With ``--shift 0`` (clean traffic) the gate must stay open and
the drift detector quiet: ZERO decisions, ZERO drift events, exit 0.
With a real shift the detector fires exactly one edge-triggered drift
event, the gate decides ``rollback``, and the process exits 2 — the
smoke's positive case.

Exit codes: 0 quiet, 2 drift detected or rollback decided.
"""

from __future__ import annotations

import json
import random
from typing import List

# score distribution for the synthetic streams: mean/std chosen so the
# default drift threshold (0.5 sigma) sits well clear of seeded noise.
_BASE_MEAN = 1.0
_BASE_STD = 0.1


def add_quality_arguments(parser) -> None:
    """Flags for ``keystone-tpu quality`` (plain argparse — the CLI's
    --help path must stay jax-free)."""
    parser.add_argument(
        "--rows", type=int, default=256,
        help="scores per window (baseline and current each see this many)",
    )
    parser.add_argument(
        "--shift", type=float, default=0.0,
        help="quality REGRESSION in the current window, in baseline "
        "standard deviations (scores drop by this many sigmas; 0 = clean "
        "traffic, the smoke's drift case uses ~3)",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--model", default="default")
    parser.add_argument(
        "--features", type=int, default=4,
        help="payload feature coordinates sketched per request",
    )
    parser.add_argument(
        "--alpha", type=float, default=None,
        help="sequential-gate false-positive bound "
        "(default KEYSTONE_QUALITY_ALPHA, 0.05)",
    )
    parser.add_argument(
        "--max-samples", type=int, default=None,
        help="gate sample budget (default: one more than the scenario "
        "feeds, so a clean run ends with the test still OPEN — no "
        "decision without evidence)",
    )
    parser.add_argument(
        "--labels", type=int, default=64,
        help="delayed labels joined into the labeled stream (shows the "
        "label-join path in the report)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="print only the QUALITY_STATS: line (skip the human report)",
    )


def _window(rng: random.Random, n: int, mean: float) -> List[float]:
    return [rng.gauss(mean, _BASE_STD) for _ in range(n)]


def _human_report(report: dict, decay: dict) -> List[str]:
    lines: List[str] = []
    for model, view in sorted(report["models"].items()):
        lines.append(f"model {model}")
        for role, summary in sorted(view["streams"].items()):
            lines.append(
                "  stream %-8s n=%-6d mean=%-10s p50=%s"
                % (role, summary["count"], summary["mean"], summary.get("p50"))
            )
        drift = view["drift"]
        lines.append(
            "  drift    score=%.4f threshold=%s events=%d %s"
            % (
                drift["score"], drift["threshold"], drift["events"],
                "DRIFTING" if drift["drifting"] else "quiet",
            )
        )
        lines.append(
            "  decay    suggested state_decay=%s (base 1.0)"
            % decay.get(model)
        )
        lines.append("  labels   joined=%d" % view["label_joins"])
        sketch = view.get("sketch")
        if sketch:
            lines.append(
                "  sketch   rows=%d channels=%d"
                % (sketch["rows"], len(sketch["channels"]))
            )
    for gate in report["open_gates"]:
        lines.append(
            "open gate %s:%s samples=%d/%d lr=%s"
            % (gate["model"], gate["kind"], gate["samples"],
               gate["max_samples"], gate["lr"])
        )
    for decision in report["decisions"]:
        lines.append(
            "decision %s %s after %d samples (lr=%s alpha=%s%s)"
            % (
                decision["model"], decision["decision"].upper(),
                decision["samples"], decision["lr"], decision["alpha"],
                ", budget exhausted" if decision["budget_exhausted"] else "",
            )
        )
    return lines


def quality_from_args(args) -> int:
    from .quality import QualityPlane

    rng = random.Random(args.seed)
    plane = QualityPlane()
    model = args.model

    # Baseline window: live traffic before the change under watch.
    baseline = _window(rng, args.rows, _BASE_MEAN)
    for score in baseline:
        row = [rng.gauss(0.0, 1.0) for _ in range(args.features)]
        plane.observe_served(model, row, score)
    plane.drift(model).freeze_baseline()

    # Delayed labels land for part of the baseline window.
    if args.labels > 0:
        plane.join_labels(model, baseline[: args.labels])

    # Current window, degraded by --shift baseline sigmas, gated pairwise
    # against a replay of the baseline scores. The default budget sits
    # just above the scenario's sample count: an anytime-valid test with
    # no evidence ends OPEN, it does not decide.
    max_samples = (
        args.max_samples if args.max_samples is not None else 2 * args.rows + 2
    )
    gate = plane.open_gate(model, alpha=args.alpha, max_samples=max_samples)
    current = _window(rng, args.rows, _BASE_MEAN - args.shift * _BASE_STD)
    drift_events = 0
    for cand, base in zip(current, baseline):
        row = [rng.gauss(0.0, 1.0) for _ in range(args.features)]
        plane.observe_served(model, row, cand)
        if plane.check_drift(model) is not None:
            drift_events += 1
        if gate.decision is None:
            if gate.observe(candidate=cand, baseline=base) != "continue":
                plane.record_decision(gate)

    # Fleet path: the pending worker delta merges like a heartbeat would.
    delta = plane.drain_delta()
    if delta is not None:
        plane.merge_delta(delta, role="worker")

    decay = {model: plane.suggested_decay(model, base=1.0)}
    report = plane.report()
    decisions = [d["decision"] for d in report["decisions"]]
    rollbacks = decisions.count("rollback")
    stats = {
        "model": model,
        "rows": args.rows,
        "shift": args.shift,
        "seed": args.seed,
        "drift_events": drift_events,
        "decisions": decisions,
        "rollbacks": rollbacks,
        "state_decay": decay,
        "report": report,
    }
    if not args.as_json:
        for line in _human_report(report, decay):
            print(line)
    print("QUALITY_STATS:" + json.dumps(stats), flush=True)
    return 2 if (drift_events or rollbacks) else 0
