"""Metrics registry: labeled counters, gauges, and histograms with a
Prometheus-compatible data model.

Absorbs the telemetry math that previously lived in three fragments —
``serving/telemetry.py`` percentiles, the ``utils/compilation_cache``
compile counter, and the reliability recovery-ledger tallies — behind one
process-wide registry (:func:`get_registry`) that ``obs.export`` renders
as Prometheus text and ``bench.py`` snapshots per leg.

Histograms keep BOTH cumulative buckets (for Prometheus ``_bucket``
export) and a bounded sample window, so :meth:`Histogram.percentile`
reproduces exactly the linear-interpolated percentiles
``ServingTelemetry`` has always reported (tested for parity in
``tests/obs/test_metrics.py``).

Stdlib-only at import time; thread-safe (one lock per metric — the
serving hot path increments a handful per request).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def percentile(samples: Sequence[float], q: float) -> float:
    """Linear-interpolated percentile (q in [0, 100]) of ``samples``.

    The canonical implementation — ``serving.telemetry`` re-exports it, so
    every percentile the system reports interpolates the same way.
    """
    if not samples:
        return 0.0
    data = sorted(samples)
    if len(data) == 1:
        return float(data[0])
    rank = (q / 100.0) * (len(data) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(data) - 1)
    frac = rank - lo
    return float(data[lo] * (1.0 - frac) + data[hi] * frac)


# Latency-oriented default buckets (seconds), sub-ms to minutes.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)
# Ratio-oriented buckets (occupancy, hit rates).
RATIO_BUCKETS: Tuple[float, ...] = (
    0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0,
)


def _label_key(label_names: Tuple[str, ...], labels: Dict[str, Any]) -> LabelKey:
    if tuple(sorted(labels)) != tuple(sorted(label_names)):
        raise ValueError(
            f"labels {sorted(labels)} do not match declared {sorted(label_names)}"
        )
    return tuple((k, str(labels[k])) for k in label_names)


class Metric:
    """Base: name, help text, declared label names, per-series storage."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", labels: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.label_names: Tuple[str, ...] = tuple(labels)
        self._lock = threading.Lock()
        self._series: Dict[LabelKey, Any] = {}

    def series(self) -> Dict[LabelKey, Any]:
        with self._lock:
            return dict(self._series)


class Counter(Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative increment {amount}")
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels: Any) -> float:
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._series.get(key, 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._series.values())


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, **labels: Any) -> None:
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def max(self, value: float, **labels: Any) -> None:
        """Keep the running maximum (peak-memory style gauges)."""
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = max(self._series.get(key, float("-inf")), float(value))

    def value(self, **labels: Any) -> float:
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._series.get(key, 0.0)


class _HistogramSeries:
    __slots__ = ("bucket_counts", "sum", "count", "window")

    def __init__(self, num_buckets: int, window: int):
        self.bucket_counts = [0] * (num_buckets + 1)  # +1 for +Inf
        self.sum = 0.0
        self.count = 0
        self.window: deque = deque(maxlen=window)


class Histogram(Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        labels: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        window: int = 2048,
    ):
        super().__init__(name, help, labels)
        self.buckets: Tuple[float, ...] = tuple(sorted(buckets))
        self.window = window

    def observe(self, value: float, **labels: Any) -> None:
        key = _label_key(self.label_names, labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = _HistogramSeries(
                    len(self.buckets), self.window
                )
            idx = len(self.buckets)
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    idx = i
                    break
            series.bucket_counts[idx] += 1
            series.sum += value
            series.count += 1
            series.window.append(value)

    def percentile(self, q: float, **labels: Any) -> float:
        """Linear-interpolated percentile over the bounded sample window —
        the exact math ``ServingTelemetry`` snapshots always used."""
        key = _label_key(self.label_names, labels)
        with self._lock:
            series = self._series.get(key)
            samples = list(series.window) if series is not None else []
        return percentile(samples, q)

    def count(self, **labels: Any) -> int:
        key = _label_key(self.label_names, labels)
        with self._lock:
            series = self._series.get(key)
            return series.count if series is not None else 0

    def sum(self, **labels: Any) -> float:
        key = _label_key(self.label_names, labels)
        with self._lock:
            series = self._series.get(key)
            return series.sum if series is not None else 0.0


class MetricsRegistry:
    """Name → metric table with idempotent get-or-create registration."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, labels: Sequence[str], **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or existing.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}{existing.label_names}, requested "
                        f"{cls.kind}{tuple(labels)}"
                    )
                return existing
            metric = cls(name, help=help, labels=labels, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "", labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(
        self,
        name: str,
        help: str = "",
        labels: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        window: int = 2048,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labels, buckets=buckets, window=window
        )

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def collect(self) -> List[Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> Dict[str, float]:
        """Flat ``name{k=v,...}`` → value view: counters/gauges directly,
        histograms as ``_count`` and ``_sum``. The bench embeds per-leg
        diffs of this (see :func:`delta`)."""
        out: Dict[str, float] = {}
        for metric in self.collect():
            for key, value in metric.series().items():
                labels = ",".join(f"{k}={v}" for k, v in key)
                suffix = "{" + labels + "}" if labels else ""
                if isinstance(metric, Histogram):
                    out[f"{metric.name}_count{suffix}"] = float(value.count)
                    out[f"{metric.name}_sum{suffix}"] = round(value.sum, 6)
                else:
                    out[f"{metric.name}{suffix}"] = round(float(value), 6)
        return out

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


def delta(
    after: Dict[str, float], before: Dict[str, float]
) -> Dict[str, float]:
    """Changed-series view between two :meth:`MetricsRegistry.snapshot`
    calls: every key whose value moved, as ``after − before`` (new keys
    count from 0)."""
    out: Dict[str, float] = {}
    for key, value in after.items():
        prev = before.get(key, 0.0)
        if value != prev:
            out[key] = round(value - prev, 6)
    return out


_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _registry


def reset_registry() -> None:
    """Testing hook: drop every registered metric. Cached metric handles
    held by long-lived objects keep working but detach from the registry —
    modules that cache handles must re-resolve via their accessor."""
    _registry.reset()
