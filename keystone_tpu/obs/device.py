"""Device/profiling hooks: memory sampling, peak-memory attribution, and
optional XLA trace annotations.

Memory sampling prefers the accelerator's own accounting
(``Device.memory_stats()`` — bytes_in_use / peak_bytes_in_use on TPU) and
falls back to host RSS (``/proc/self/statm``, then ``resource``) on CPU
test meshes, where XLA allocates out of the process heap anyway. Either
way the snapshot says which source it used, so a reader never mistakes
RSS for HBM.

``device_annotation`` wraps a code region in
``jax.profiler.TraceAnnotation`` so per-node executor work shows up
inside ``jax.profiler.trace`` captures (TensorBoard/XProf). It is gated —
default off — because annotations are only useful under an active XLA
profiler session and cost a host call each.

Imports jax lazily; importable before any backend initializes.
"""

from __future__ import annotations

import os
from contextlib import contextmanager, nullcontext
from typing import Any, Dict, Iterator, Optional

from ..envknobs import env_flag
from . import names, spans

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096

# Tri-state like fusion/streaming enablement: None → read the env at CALL
# time. (This used to be a module-level env read, so flipping
# KEYSTONE_DEVICE_ANNOTATIONS after import — or monkeypatching it in a
# test — was silently ignored; keystone-lint KV501 now forbids
# import-time environment reads, pinned by tests/lint/test_lint_rules.py.)
_annotations_enabled: "bool | None" = None


def set_device_annotations(enabled: "bool | None") -> None:
    """Force annotations on/off process-wide; ``None`` restores the env
    default."""
    global _annotations_enabled
    _annotations_enabled = enabled


def annotations_enabled() -> bool:
    if _annotations_enabled is not None:
        return _annotations_enabled
    return env_flag("KEYSTONE_DEVICE_ANNOTATIONS")


def device_annotation(name: str):
    """Context manager: ``jax.profiler.TraceAnnotation(name)`` when
    enabled and jax is importable, else a no-op."""
    if not annotations_enabled():
        return nullcontext()
    try:
        import jax.profiler

        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return nullcontext()


def rss_bytes() -> int:
    """Resident set size of this process (0 if unavailable)."""
    try:
        with open("/proc/self/statm", "rb") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except Exception:
        pass
    try:
        import resource

        # ru_maxrss is the PEAK, in KiB on Linux — last resort only.
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return 0


def peak_rss_bytes() -> int:
    """Process-lifetime peak RSS (0 if unavailable)."""
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return 0


def memory_snapshot() -> Dict[str, Any]:
    """Best-available memory numbers right now.

    Returns ``{"source": "device"|"rss", "bytes_in_use": int,
    "peak_bytes_in_use": int}``; device stats only when the backend
    exposes them (TPU/GPU — CPU meshes report RSS)."""
    try:
        import jax

        dev = jax.devices()[0]
        stats = dev.memory_stats()
        if stats and "bytes_in_use" in stats:
            return {
                "source": "device",
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(
                    stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))
                ),
            }
    except Exception:
        pass
    return {
        "source": "rss",
        "bytes_in_use": rss_bytes(),
        "peak_bytes_in_use": peak_rss_bytes(),
    }


def per_device_snapshots() -> list:
    """One memory snapshot per local accelerator device, labeled with the
    device's stable id (``tpu:0`` …). Devices that expose no
    ``memory_stats`` (CPU meshes) collapse to a single host-RSS entry
    labeled ``host`` — per-virtual-device RSS attribution would be
    fiction. Empty list when jax is unavailable."""
    out = []
    try:
        import jax

        for dev in jax.local_devices():
            try:
                stats = dev.memory_stats()
            except AttributeError:
                stats = None  # backend has no memory_stats: not an error
            except Exception as e:
                # The chip most likely to be OOMing/wedged is exactly the
                # one whose stats call fails — surface it as an error
                # entry instead of silently shrinking the device list.
                out.append(
                    {
                        "device": f"{dev.platform}:{dev.id}",
                        "source": "error",
                        "error": f"{type(e).__name__}: {e}",
                    }
                )
                continue
            if stats and "bytes_in_use" in stats:
                out.append(
                    {
                        "device": f"{dev.platform}:{dev.id}",
                        "source": "device",
                        "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                        "peak_bytes_in_use": int(
                            stats.get(
                                "peak_bytes_in_use",
                                stats.get("bytes_in_use", 0),
                            )
                        ),
                    }
                )
    except Exception:
        return out
    if not out:
        host = memory_snapshot()
        host["device"] = "host"
        out.append(host)
    return out


def publish_memory(stage: Optional[str] = None) -> Dict[str, Any]:
    """Sample memory and publish it to the registry: the aggregate in-use
    gauge always (``device="all"``), plus per-stage peak attribution when
    ``stage`` is given. :func:`publish_per_device_memory` adds the
    per-device series."""
    snap = memory_snapshot()
    names.metric(names.MEMORY_IN_USE_BYTES).set(
        snap["bytes_in_use"], source=snap["source"], device="all"
    )
    if stage is not None:
        names.metric(names.PEAK_MEMORY_BYTES).max(
            snap["peak_bytes_in_use"], stage=stage, device="all"
        )
    return snap


def publish_per_device_memory(stage: Optional[str] = None) -> list:
    """Publish one gauge series per local device (multichip runs — one
    chip OOMing while seven idle is invisible in the aggregate) and
    return the snapshots."""
    snaps = per_device_snapshots()
    in_use = names.metric(names.MEMORY_IN_USE_BYTES)
    peak = names.metric(names.PEAK_MEMORY_BYTES)
    for snap in snaps:
        if "error" in snap:
            continue  # error entries carry no bytes to publish
        in_use.set(
            snap["bytes_in_use"], source=snap["source"], device=snap["device"]
        )
        if stage is not None:
            peak.max(
                snap["peak_bytes_in_use"], stage=stage, device=snap["device"]
            )
    return snaps


def device_obs_payload(snapshots: Optional[list] = None) -> Dict[str, Any]:
    """The per-device observability payload multichip dryruns embed in
    their artifact (MULTICHIP_r0*.json recorded parity but no telemetry):
    per-device memory plus the process compile count. Pass ``snapshots``
    (e.g. :func:`publish_per_device_memory`'s return) to reuse an
    already-taken sample — the published gauges and the embedded payload
    then agree instead of re-walking the devices twice."""
    from ..utils.compilation_cache import compile_count

    return {
        "devices": per_device_snapshots() if snapshots is None else snapshots,
        "xla_compiles": compile_count(),
    }


@contextmanager
def stage_memory(stage: str) -> Iterator[None]:
    """Attribute peak memory to a pipeline stage: snapshot before/after,
    stamp the delta and peak onto the current span, and keep the per-stage
    peak gauge. Cheap enough for per-node use only under tracing — callers
    gate on an active span session."""
    before = publish_memory(stage=stage)
    try:
        yield
    finally:
        after = publish_memory(stage=stage)
        sp = spans.current_span()
        sp.set_attribute("mem_bytes_before", before["bytes_in_use"])
        sp.set_attribute("mem_bytes_after", after["bytes_in_use"])
        sp.set_attribute("mem_peak_bytes", after["peak_bytes_in_use"])
        sp.set_attribute("mem_source", after["source"])
