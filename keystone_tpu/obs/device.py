"""Device/profiling hooks: memory sampling, peak-memory attribution, and
optional XLA trace annotations.

Memory sampling prefers the accelerator's own accounting
(``Device.memory_stats()`` — bytes_in_use / peak_bytes_in_use on TPU) and
falls back to host RSS (``/proc/self/statm``, then ``resource``) on CPU
test meshes, where XLA allocates out of the process heap anyway. Either
way the snapshot says which source it used, so a reader never mistakes
RSS for HBM.

``device_annotation`` wraps a code region in
``jax.profiler.TraceAnnotation`` so per-node executor work shows up
inside ``jax.profiler.trace`` captures (TensorBoard/XProf). It is gated —
default off — because annotations are only useful under an active XLA
profiler session and cost a host call each.

Imports jax lazily; importable before any backend initializes.
"""

from __future__ import annotations

import os
from contextlib import contextmanager, nullcontext
from typing import Any, Dict, Iterator, Optional

from . import names, spans

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096

_annotations_enabled = os.environ.get(
    "KEYSTONE_DEVICE_ANNOTATIONS", ""
).lower() in ("1", "true", "on")


def set_device_annotations(enabled: bool) -> None:
    global _annotations_enabled
    _annotations_enabled = bool(enabled)


def annotations_enabled() -> bool:
    return _annotations_enabled


def device_annotation(name: str):
    """Context manager: ``jax.profiler.TraceAnnotation(name)`` when
    enabled and jax is importable, else a no-op."""
    if not _annotations_enabled:
        return nullcontext()
    try:
        import jax.profiler

        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return nullcontext()


def rss_bytes() -> int:
    """Resident set size of this process (0 if unavailable)."""
    try:
        with open("/proc/self/statm", "rb") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except Exception:
        pass
    try:
        import resource

        # ru_maxrss is the PEAK, in KiB on Linux — last resort only.
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return 0


def peak_rss_bytes() -> int:
    """Process-lifetime peak RSS (0 if unavailable)."""
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return 0


def memory_snapshot() -> Dict[str, Any]:
    """Best-available memory numbers right now.

    Returns ``{"source": "device"|"rss", "bytes_in_use": int,
    "peak_bytes_in_use": int}``; device stats only when the backend
    exposes them (TPU/GPU — CPU meshes report RSS)."""
    try:
        import jax

        dev = jax.devices()[0]
        stats = dev.memory_stats()
        if stats and "bytes_in_use" in stats:
            return {
                "source": "device",
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(
                    stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))
                ),
            }
    except Exception:
        pass
    return {
        "source": "rss",
        "bytes_in_use": rss_bytes(),
        "peak_bytes_in_use": peak_rss_bytes(),
    }


def publish_memory(stage: Optional[str] = None) -> Dict[str, Any]:
    """Sample memory and publish it to the registry: the in-use gauge
    always, plus per-stage peak attribution when ``stage`` is given."""
    snap = memory_snapshot()
    names.metric(names.MEMORY_IN_USE_BYTES).set(
        snap["bytes_in_use"], source=snap["source"]
    )
    if stage is not None:
        names.metric(names.PEAK_MEMORY_BYTES).max(
            snap["peak_bytes_in_use"], stage=stage
        )
    return snap


@contextmanager
def stage_memory(stage: str) -> Iterator[None]:
    """Attribute peak memory to a pipeline stage: snapshot before/after,
    stamp the delta and peak onto the current span, and keep the per-stage
    peak gauge. Cheap enough for per-node use only under tracing — callers
    gate on an active span session."""
    before = publish_memory(stage=stage)
    try:
        yield
    finally:
        after = publish_memory(stage=stage)
        sp = spans.current_span()
        sp.set_attribute("mem_bytes_before", before["bytes_in_use"])
        sp.set_attribute("mem_bytes_after", after["bytes_in_use"])
        sp.set_attribute("mem_peak_bytes", after["peak_bytes_in_use"])
        sp.set_attribute("mem_source", after["source"])
