"""Co-scheduled serving + refit on one mesh: the demo CI drives.

One `PipelineServer` serves a paced, seeded request trace while a
`RefitDaemon` folds labeled traffic under `MeshScheduler` leases. The
demo measures the question the scheduler exists to answer — *is
co-locating background folds inside serving idle gaps cheaper than
serializing them?* — and stages one deterministic preemption to prove
the contract:

- **serial phase**: each round serves its trace to completion, THEN
  runs a full refit round over its rows on an *unscheduled* daemon (the
  legacy deployment: the mesh context-switches; nothing overlaps). Its
  final state doubles as the *parity reference*.
- **co-scheduled phase**: the same traces and the same rows, but the
  refit round runs as an admitted lease *while* the trace is in flight
  on a scheduler-governed daemon.
- **seeded preemption**: in ``pressure_round`` the scheduler's
  deterministic door (:meth:`MeshScheduler.seed_pressure_after`) turns
  pressure on after admission — the fold yields at a chunk boundary
  with its durable cursor committed, the round defers, and the very
  next round resumes from the cursor and publishes. Zero requests drop
  throughout, and the final co-scheduled state must match the serial
  reference to ≤1e-6 (resume ≡ uninterrupted fold).

Everything deterministic in ``seed``; the evidence dict is what
``scripts/sched_smoke.sh`` and the ``cosched`` bench leg gate on
(docs/SCHEDULING.md "The demo").
"""

from __future__ import annotations

import tempfile
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from ..reliability.recovery import get_recovery_log
from .scheduler import MeshScheduler


@dataclass
class CoschedDemoConfig:
    d: int = 32
    classes: int = 4
    rounds: int = 4
    rows_per_round: int = 8192
    chunk_rows: int = 1024
    serve_requests: int = 96        # per round, per phase
    serve_rps: float = 320.0        # paced — the idle gaps ARE the point
    pressure_round: int = 2         # seeded mid-fold preemption here
    settle_round: int = 1           # steady-compile assertions start after
    slo_target_ms: float = 500.0
    seed: int = 0
    reg: float = 1e-2
    store_dir: Optional[str] = None


def run_cosched_demo(config: CoschedDemoConfig) -> Dict[str, Any]:
    from ..data.dataset import ArrayDataset
    from ..obs.quality import reset_quality_plane
    from ..ops.learning.linear import LinearMapEstimator
    from ..refit.daemon import RefitConfig, RefitDaemon
    from ..refit.publish import InProcessPublisher
    from ..refit.shadow import ShadowEvaluator
    from ..refit.tap import TrafficTap
    from ..reliability.checkpoint import CheckpointStore
    from ..serving.config import ServingConfig
    from ..serving.loadgen import run_load
    from ..serving.server import PipelineServer
    from ..workflow.streaming import ChunkStream

    cfg = config
    reset_quality_plane()
    rng = np.random.default_rng(cfg.seed)
    w_true = rng.standard_normal((cfg.d, cfg.classes)).astype(np.float32)

    def make_rows(n: int):
        x = rng.standard_normal((n, cfg.d)).astype(np.float32)
        labels = np.argmax(x @ w_true, axis=1)
        y = np.eye(cfg.classes, dtype=np.float32)[labels]
        return x, y

    def stream_over(x, y):
        return ChunkStream(
            ArrayDataset(x), ArrayDataset(y), (),
            chunk_rows=min(cfg.chunk_rows, len(x)),
        )

    # All round data up front: both phases serve and fold the SAME rows.
    x0, y0 = make_rows(cfg.rows_per_round)
    rounds_data = [make_rows(cfg.rows_per_round) for _ in range(cfg.rounds)]
    offsets = [i / cfg.serve_rps for i in range(cfg.serve_requests)]

    store_root = cfg.store_dir or tempfile.mkdtemp(prefix="keystone-cosched-")

    estimator = LinearMapEstimator(reg=cfg.reg)
    v1_model = estimator.fit_stream(stream_over(x0, y0))
    v1_state = estimator.export_stream_state()

    tap = TrafficTap(capacity_rows=cfg.rows_per_round * 4, mirror_rows=512)
    server = PipelineServer(
        config=ServingConfig(
            max_batch=8, queue_depth=cfg.serve_requests + 64
        ),
        name="cosched",
        tap=tap,
    )
    server.registry.publish("cosched", v1_model, source="fit")
    # The serial baseline daemon publishes under its own name; serving
    # stays pinned to the default "cosched" model either way.
    server.registry.publish("cosched-serial", v1_model, source="fit")
    server.start()
    example = np.zeros((cfg.d,), np.float32)
    server.warmup(example)

    # sustain_checks pinned (not env-read): the seeded preemption lands
    # at a deterministic chunk boundary on every machine.
    scheduler = MeshScheduler(store=None, name="cosched", sustain_checks=2)

    def make_daemon(name: str, est, daemon_tap, sched):
        return RefitDaemon(
            est,
            daemon_tap,
            InProcessPublisher(server, name=name, example=example),
            store=CheckpointStore(f"{store_root}/{name}"),
            scheduler=sched,
            # Wide-open gates: this demo pins scheduling and parity, not
            # candidate quality (the refit demo owns the gate behaviors).
            shadow=ShadowEvaluator(margin=0.5),
            config=RefitConfig(
                name=name,
                min_rows=cfg.rows_per_round // 2,
                chunk_rows=cfg.chunk_rows,
                watch_margin=0.5,
                state_decay=1.0,  # pure accumulation → exact parity
            ),
            state=v1_state,
        )

    daemon = make_daemon("cosched", estimator, tap, scheduler)
    # The serial baseline: identical rounds on the LEGACY, unscheduled
    # path (scheduler=None — byte-for-byte the pre-scheduler daemon). It
    # publishes under its own model name, so serving (pinned to the
    # default "cosched" model) never sees it.
    serial_est = LinearMapEstimator(reg=cfg.reg)
    serial_tap = TrafficTap(
        capacity_rows=cfg.rows_per_round * 4, mirror_rows=512
    )
    serial_daemon = make_daemon(
        "cosched-serial", serial_est, serial_tap, None
    )

    def serve_round(r: int) -> Dict[str, Any]:
        x, _y = rounds_data[r - 1]
        payloads = [row for row in x[: cfg.serve_requests]]
        report = run_load(
            server.submit,
            offsets,
            payload=lambda i: payloads[i % len(payloads)],
            deadline_s=60.0,
            settle_timeout_s=120.0,
        )
        return report.summary()

    # ------------------------------------------------------- serial phase
    # Serve to completion, THEN run the refit round — the mesh
    # context-switches, nothing overlaps. Identical rows, identical
    # chunk grid, identical round machinery to the co-scheduled phase.
    serial_wall = 0.0
    dropped = 0
    for r in range(1, cfg.rounds + 1):
        x, y = rounds_data[r - 1]
        serial_tap.feed(x, y)
        t0 = time.perf_counter()
        load = serve_round(r)
        serial_daemon.run_once()
        serial_wall += time.perf_counter() - t0
        dropped += int(load["dropped"])
        server.restamp_compile_baseline()

    # ------------------------------------------------- co-scheduled phase
    cosched_wall = 0.0
    steady_compiles = 0
    round_records: List[Dict[str, Any]] = []
    preempted_at_chunk = None
    for r in range(1, cfg.rounds + 1):
        x, y = rounds_data[r - 1]
        tap.feed(x, y)
        if r == cfg.pressure_round:
            # One idle consultation (admission), then pressure: the fold
            # preempts at the first sustained chunk boundary.
            scheduler.seed_pressure_after(1)
        box: Dict[str, Any] = {}

        def load_body() -> None:
            box["load"] = serve_round(r)

        t0 = time.perf_counter()
        load_thread = threading.Thread(target=load_body, name="cosched-load")
        load_thread.start()
        outcomes = [daemon.run_once()]
        if r == cfg.pressure_round:
            preempted_at_chunk = daemon.outcomes[-1].get("preempted_at_chunk")
            scheduler.seed_pressure_after(None)
            # Resume INSIDE the same serving window: the deferred fold
            # picks up from its durable cursor, not from row zero.
            outcomes.append(daemon.run_once())
        load_thread.join()
        cosched_wall += time.perf_counter() - t0
        load = box["load"]
        dropped += int(load["dropped"])
        stats = server.stats()
        if r > cfg.settle_round:
            steady_compiles = max(
                steady_compiles,
                int(stats.get("xla_compiles_since_warmup") or 0),
            )
        server.restamp_compile_baseline()
        round_records.append(
            {
                "round": r,
                "outcomes": outcomes,
                "p99_ms": load["p99_ms"],
                "completed": load["completed"],
                "dropped": load["dropped"],
            }
        )
    server.stop(drain=True)

    # ------------------------------------------------------------ evidence
    # Parity: the scheduled chain (including the preempt→resume round)
    # against the unscheduled serial chain — resume ≡ uninterrupted fold,
    # and the scheduled path ≡ the legacy path on the same rows.
    live_model = daemon.estimator.finish_from_state(daemon._state)
    serial_model = serial_daemon.estimator.finish_from_state(
        serial_daemon._state
    )
    parity = float(
        np.max(
            np.abs(
                np.asarray(live_model.weights, dtype=np.float64)
                - np.asarray(serial_model.weights, dtype=np.float64)
            )
        )
    )

    sched_stats = scheduler.stats()
    outcomes_flat = [o for rec in round_records for o in rec["outcomes"]]
    p99_worst = max(rec["p99_ms"] for rec in round_records)
    ledger_kinds = sorted(
        {
            e.kind
            for e in get_recovery_log().events()
            if e.kind.startswith("sched_")
        }
    )
    ratio = cosched_wall / serial_wall if serial_wall else None
    return {
        "d": cfg.d,
        "classes": cfg.classes,
        "rounds": round_records,
        "publishes": outcomes_flat.count("published"),
        "deferred_rounds": outcomes_flat.count("deferred"),
        "dropped": int(dropped),
        "compiles_steady_state_post_settle": int(steady_compiles),
        "serial_wall_s": round(serial_wall, 4),
        "cosched_wall_s": round(cosched_wall, 4),
        "cosched_vs_serial_ratio": round(ratio, 4) if ratio else None,
        "cosched_faster": bool(ratio is not None and ratio < 1.0),
        "p99_ms_worst": p99_worst,
        "slo_target_ms": cfg.slo_target_ms,
        "p99_within_slo": bool(p99_worst < cfg.slo_target_ms),
        "leases": int(sched_stats["leases"]),
        "leases_completed": int(sched_stats["outcomes"].get("completed", 0)),
        "preemptions": int(sched_stats["outcomes"].get("preempted", 0)),
        "preempted_at_chunk": preempted_at_chunk,
        "parity_max_abs_diff": parity,
        "parity_ok": bool(parity <= 1e-6),
        "idle_harvest_s": sched_stats["idle_harvest_s"],
        "ledger_kinds": ledger_kinds,
        "obs": {"schedule": scheduler.schedule()},
    }
