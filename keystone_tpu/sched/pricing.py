"""Lease pricing and chunk policy: the cost observatory made executable.

The scheduler never admits unpriced work. Every lease request carries its
fold geometry (rows x width x classes) and is priced *before* admission
down a provenance ladder:

1. **store/tune** — a valid (non-stale) ProfileStore ``stream:<chain>:``
   entry measured on this backend: predicted wall = rows / measured
   rows_per_s. ``source`` records whether the entry was searched by
   ``keystone-tpu tune`` (``tune``) or merely observed (``store``).
2. **roofline** — no measurement: first-principles floor from the
   probe-calibrated :class:`~keystone_tpu.obs.cost.Roofline` over the
   Gram fold's flop/byte facts (``source="roofline"``).
3. **default** — no roofline either (cost observatory off): a flat
   rows/s guess (``KEYSTONE_SCHED_DEFAULT_ROWS_PER_S``).

The same ladder chooses chunk geometry for *scheduled* folds
(:func:`choose_chunk_rows`): a tuned/measured entry wins outright;
otherwise the roofline placement decides — memory-bound folds take
larger chunks (amortize the host->device transfer) up to the KV304-style
per-device residency budget, replacing the static 4096 default on the
scheduled path (docs/SCHEDULING.md "Pricing").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from ..envknobs import env_float, env_int


def gram_stream_facts(
    rows: int, width: int, classes: int
) -> Tuple[float, float]:
    """(flops, bytes) for a Gram-statistics fold over ``rows`` examples:
    X'X (2*w*w per row) + X'Y (2*w*k per row) flops; bytes = the
    streamed operands (x and y rows at f32) plus one carry round-trip.
    Deliberately first-order — the roofline only needs the right decade.
    """
    w, k = max(int(width), 1), max(int(classes), 1)
    n = max(int(rows), 0)
    flops = float(n) * (2.0 * w * w + 2.0 * w * k)
    bytes_accessed = 4.0 * n * (w + k) + 8.0 * (w * w + w * k)
    return flops, bytes_accessed


@dataclass(frozen=True)
class LeasePrice:
    """A lease's predicted cost with its provenance — what admission
    compares against the idle-gap budget and what the ledger joins the
    measured wall to."""

    seconds: Optional[float]
    source: str  # tune | store | roofline | default
    rows_per_s: Optional[float] = None
    roofline: Optional[str] = None  # compute-bound | memory-bound | None
    intensity: Optional[float] = None

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"source": self.source}
        for field in ("seconds", "rows_per_s", "roofline", "intensity"):
            v = getattr(self, field)
            if v is not None:
                out[field] = round(v, 6) if isinstance(v, float) else v
        return out


def _store_rate(
    store: Any, chain: str
) -> Optional[Tuple[float, str, Optional[int], Optional[int]]]:
    """Best measured rows/s under ``stream:<chain>:`` among valid
    entries: (rows_per_s, source, chunk_rows, prefetch_depth). Stale
    (drift-marked) and fingerprint-invalid entries never price a lease —
    the drift sentinel's whole point."""
    if store is None:
        return None
    best = None
    try:
        rows_iter = sorted(store.entries(key_prefix=f"stream:{chain}:"))
    except Exception:
        return None
    for key, _shape, m in rows_iter:
        rate = m.get("rows_per_s")
        if not rate:
            continue
        rate = float(rate)
        if best is None or rate > best[0]:
            source = "tune" if m.get("source") == "tune" else "store"
            chunk = m.get("chunk_rows")
            best = (
                rate,
                source,
                int(chunk) if chunk else None,
                int(m["prefetch_depth"]) if m.get("prefetch_depth") else None,
            )
    return best


def price_stream_fold(
    rows: int,
    width: int,
    classes: int,
    chain: str = "()",
    store: Any = None,
) -> LeasePrice:
    """Price one streamed Gram fold down the provenance ladder."""
    flops, bytes_accessed = gram_stream_facts(rows, width, classes)
    intensity = flops / bytes_accessed if bytes_accessed else None

    roof = None
    placement = None
    try:
        from ..obs import cost as _cost

        roof = _cost.get_roofline()
    except Exception:
        roof = None
    if roof is not None:
        placement = roof.classify(intensity)

    measured = _store_rate(store, chain)
    if measured is not None:
        rate, source, _chunk, _prefetch = measured
        return LeasePrice(
            seconds=rows / rate if rate > 0 else None,
            source=source,
            rows_per_s=rate,
            roofline=placement,
            intensity=intensity,
        )
    if roof is not None:
        seconds = roof.predicted_seconds(flops, bytes_accessed)
        if seconds is not None:
            return LeasePrice(
                seconds=seconds,
                source="roofline",
                rows_per_s=rows / seconds if seconds > 0 else None,
                roofline=placement,
                intensity=intensity,
            )
    rate = env_float("KEYSTONE_SCHED_DEFAULT_ROWS_PER_S", 200_000.0)
    return LeasePrice(
        seconds=rows / rate if rate > 0 else None,
        source="default",
        rows_per_s=rate,
        roofline=placement,
        intensity=intensity,
    )


# ------------------------------------------------------------ chunk policy


def _residency_budget_bytes() -> int:
    """Per-device bytes a scheduled fold may hold resident for staged
    chunks — the KV304 discipline applied prospectively. Real
    accelerators report ``bytes_limit``; CPU meshes don't, so the env
    knob's default (256 MiB) stands in."""
    explicit = env_int("KEYSTONE_SCHED_RESIDENCY_BYTES", 0)
    if explicit > 0:
        return explicit
    try:
        import jax

        stats = jax.devices()[0].memory_stats()  # keystone: allow-sync
        limit = int((stats or {}).get("bytes_limit", 0))
        if limit > 0:
            # Same fraction KV304 allows a fit's working set.
            return limit // 4
    except Exception:
        pass
    return 256 * 1024 * 1024


def choose_chunk_rows(
    rows: int,
    width: int,
    classes: int,
    chain: str = "()",
    store: Any = None,
    default: Optional[int] = None,
) -> Tuple[int, int, str]:
    """(chunk_rows, prefetch_depth, source) for a *scheduled* fold.

    A tuned/measured ProfileStore entry wins outright (``source`` =
    ``tune``/``store``); with no measurement the roofline placement
    decides: memory-bound folds are transfer-starved, so take larger
    chunks (deeper amortization) up to the residency budget across the
    prefetch pipeline; compute-bound folds keep the moderate default —
    chunk size barely moves their wall, and smaller chunks preempt
    sooner. Always bounded by the dataset and a power-of-two grid (one
    compiled shape family)."""
    measured = _store_rate(store, chain)
    if measured is not None and measured[2]:
        _rate, source, chunk, prefetch = measured
        return int(chunk), int(prefetch or 2), source

    price = price_stream_fold(rows, width, classes, chain=chain, store=None)
    base = int(default or 4096)
    prefetch = 2
    if price.roofline == "memory-bound":
        # Budget covers prefetch+in-flight staged chunks, double-buffered.
        per_row = 4.0 * (max(width, 1) + max(classes, 1))
        prefetch = 4
        cap = int(_residency_budget_bytes() / (per_row * (prefetch + 1)))
        chunk = base
        while chunk * 2 <= min(cap, 65536):
            chunk *= 2
    else:
        chunk = base
    chunk = max(min(chunk, max(int(rows), 1)), 1)
    return chunk, prefetch, "roofline"
