"""Cost-governed admission onto the serving mesh: train where you serve.

Background work — refit daemon fold rounds, ``keystone-tpu tune``
probes, sketched/Gram finish reductions — historically ran in separate
processes while serving devices idled between batches. The
:class:`MeshScheduler` co-locates them on one mesh under one cost model:

- every unit of background work arrives as a :class:`LeaseRequest` and
  is **priced before admission** (sched/pricing.py: tuned/measured
  ProfileStore rate, else the calibrated roofline, else a flat default);
- admission happens only into **predicted serving idle gaps**: the SLO
  controller's p99 headroom plus the supervisor's pending/backlog signal
  must both read idle, otherwise the lease is *deferred* (the rows stay
  in the tap; nothing is lost);
- an admitted fold carries its :class:`Lease` into the streaming engine,
  which consults :meth:`Lease.should_yield` at every chunk boundary —
  **sustained** SLO pressure (``sustain_checks`` consecutive pressured
  boundaries, so one slow batch never kills a fold) preempts the fold
  *at the boundary*: the durable cursor commits and the fold returns
  partial; the next admission resumes from the cursor, not from scratch
  (PR 15's durable-fold substrate is the preemption mechanism);
- every lease lands in the schedule log — predicted vs measured wall,
  price provenance, who displaced it — which ``keystone-tpu explain
  --schedule`` prints and the ``keystone_sched_*`` metric family
  aggregates (docs/SCHEDULING.md).

Stdlib-only at import time (the serving-package discipline): pricing
imports jax lazily and only when the cost observatory is reachable.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..envknobs import env_disabled, env_float, env_int
from ..obs import names as _names
from ..obs import spans as _spans
from ..reliability.recovery import get_recovery_log
from .pricing import LeasePrice, choose_chunk_rows, price_stream_fold


@dataclass
class LeaseRequest:
    """One unit of background work asking for mesh time. ``rows`` x
    ``width`` x ``classes`` is the fold geometry pricing consumes;
    ``chain`` is the featurization chain class keying the ProfileStore."""

    name: str
    kind: str = "refit_fold"  # refit_fold | tune_probe | finish
    rows: int = 0
    width: int = 0
    classes: int = 0
    chain: str = "()"
    #: lease id this request resumes (a previously preempted fold).
    resume_of: Optional[str] = None


class Lease:
    """A priced admission onto the mesh. Handed to the streaming engine
    (``ChunkStream.lease``), which calls :meth:`should_yield` at chunk
    boundaries; everything else is scheduler-internal bookkeeping."""

    def __init__(
        self, scheduler: "MeshScheduler", request: LeaseRequest,
        price: LeasePrice, lease_id: str,
    ):
        self.scheduler = scheduler
        self.request = request
        self.price = price
        self.id = lease_id
        self.admitted = False
        self.state = "pending"  # pending|deferred|running|preempted|completed
        self.deferrals = 0
        self.displaced_by: Optional[str] = None
        self.preempted_at_chunk: Optional[int] = None
        self.admitted_t: Optional[float] = None
        self.measured_s: Optional[float] = None
        self.boundary_checks = 0
        self._pressure_streak = 0
        self._span_stack: Optional[contextlib.ExitStack] = None

    # ------------------------------------------------------- fold-side API
    def should_yield(self) -> bool:
        """Chunk-boundary check: yield only under *sustained* pressure —
        ``sustain_checks`` consecutive pressured boundaries."""
        self.boundary_checks += 1
        reason = self.scheduler.pressure_reason()
        if reason is None:
            self._pressure_streak = 0
            return False
        self._pressure_streak += 1
        if self._pressure_streak >= self.scheduler.sustain_checks:
            self.displaced_by = reason
            return True
        return False

    def mark_preempted(self, chunk_index: int) -> None:
        """The fold yielded at ``chunk_index`` (cursor committed by the
        stream before this call)."""
        self.preempted_at_chunk = int(chunk_index)
        self.state = "preempted"

    def predicted_vs_measured_ratio(self) -> Optional[float]:
        if self.measured_s is None or not self.price.seconds:
            return None
        return self.measured_s / self.price.seconds


class MeshScheduler:
    """Admission + preemption authority for one mesh's background work.

    ``slo`` is an :class:`~keystone_tpu.serving.slo.SLOController` (or
    anything with ``headroom()``/``stats()``); ``backlog_fn`` returns the
    serving backlog (supervisor ``backlog()`` or a server queue depth).
    Either may be None — an absent signal reads as idle, so the
    scheduler degrades to "always admit" instead of wedging work.
    """

    def __init__(
        self,
        slo: Any = None,
        backlog_fn: Optional[Callable[[], int]] = None,
        store: Any = None,
        clock: Callable[[], float] = time.monotonic,
        name: str = "mesh",
        sustain_checks: Optional[int] = None,
        headroom_floor: Optional[float] = None,
        backlog_limit: Optional[int] = None,
    ):
        self.slo = slo
        self.backlog_fn = backlog_fn
        self.store = store
        self.clock = clock
        self.name = name
        self.sustain_checks = (
            sustain_checks
            if sustain_checks is not None
            else env_int("KEYSTONE_SCHED_SUSTAIN_CHECKS", 2)
        )
        self.headroom_floor = (
            headroom_floor
            if headroom_floor is not None
            else env_float("KEYSTONE_SCHED_HEADROOM_FLOOR", 0.25)
        )
        self.backlog_limit = (
            backlog_limit
            if backlog_limit is not None
            else env_int("KEYSTONE_SCHED_BACKLOG_LIMIT", 8)
        )
        self._forced_pressure: Optional[bool] = None
        self._seed_countdown: Optional[int] = None
        self._lock = threading.Lock()
        self._seq = 0
        self._log: List[Dict[str, Any]] = []
        self._idle_harvest_s = 0.0
        self._m_leases = _names.metric(_names.SCHED_LEASES)
        self._m_harvest = _names.metric(_names.SCHED_IDLE_HARVEST_SECONDS)
        self._m_ratio = _names.metric(_names.SCHED_LEASE_WALL_RATIO)

    # ----------------------------------------------------------- pressure
    def force_pressure(self, value: Optional[bool]) -> None:
        """Deterministic override for tests/demos (None restores the
        live signals) — the faultinject-style seeding door the smoke
        script drives a preemption through."""
        self._forced_pressure = value

    def seed_pressure_after(self, checks: Optional[int]) -> None:
        """Deterministic mid-fold preemption door (demos/tests): the
        next ``checks`` pressure consultations read idle — enough for
        admission and the first chunk boundaries — then every later one
        reads pressured, until cleared with None. Makes "SLO pressure
        arrives while the fold is running" reproducible without racing
        real traffic against chunk timing."""
        self._seed_countdown = checks

    def pressure_reason(self) -> Optional[str]:
        """None when the mesh reads idle, else a human string naming the
        displacer — recorded on deferred/preempted leases so the
        schedule answers "what displaced this?"."""
        if self._seed_countdown is not None:
            self._seed_countdown -= 1
            if self._seed_countdown < 0:
                return "seeded pressure (mid-fold)"
            return None
        if self._forced_pressure is not None:
            return "forced pressure (seeded)" if self._forced_pressure else None
        if self.slo is not None:
            try:
                rung = int(getattr(self.slo.admission, "rung_index", 0))
            except Exception:
                rung = 0
            if rung > 0:
                return f"serving-slo rung_index={rung}"
            headroom = getattr(self.slo, "headroom", None)
            if callable(headroom):
                h = headroom()
                if h is not None and h < self.headroom_floor:
                    return (
                        f"serving-slo headroom {h:.2f} < "
                        f"{self.headroom_floor:.2f}"
                    )
        if self.backlog_fn is not None:
            try:
                backlog = int(self.backlog_fn())
            except Exception:
                backlog = 0
            if backlog > self.backlog_limit:
                return f"serving backlog {backlog} > {self.backlog_limit}"
        return None

    def pressure(self) -> bool:
        return self.pressure_reason() is not None

    # ---------------------------------------------------------- admission
    def submit(
        self,
        request: LeaseRequest,
        wait_s: float = 0.0,
        poll_s: Optional[float] = None,
    ) -> Lease:
        """Price ``request`` and admit it into the current idle gap.
        Under pressure the lease is *deferred*: with ``wait_s`` budget it
        polls for a gap, otherwise it returns un-admitted (caller keeps
        its rows and retries on its own cadence)."""
        price = price_stream_fold(
            request.rows, request.width, request.classes,
            chain=request.chain, store=self.store,
        )
        with self._lock:
            self._seq += 1
            lease = Lease(self, request, price, f"{self.name}-{self._seq}")
        poll = (
            poll_s if poll_s is not None
            else env_float("KEYSTONE_SCHED_DEFER_POLL_S", 0.05)
        )
        deadline = self.clock() + max(wait_s, 0.0)
        while True:
            reason = self.pressure_reason()
            if reason is None:
                return self._admit(lease)
            if lease.deferrals == 0:
                # Count the deferral once per submit, not per poll.
                lease.state = "deferred"
                lease.displaced_by = reason
                self._m_leases.inc(kind=request.kind, outcome="deferred")
                get_recovery_log().record(
                    "sched_defer", request.name,
                    lease=lease.id, work=request.kind,
                    displaced_by=reason,
                    predicted_s=price.seconds, price_source=price.source,
                )
            lease.deferrals += 1
            if self.clock() >= deadline:
                self._append_log(lease)
                return lease
            time.sleep(poll)  # lock-free admission backoff

    def _admit(self, lease: Lease) -> Lease:
        request, price = lease.request, lease.price
        lease.admitted = True
        lease.state = "running"
        lease.admitted_t = self.clock()
        self._m_leases.inc(kind=request.kind, outcome="admitted")
        event = "sched_resume" if request.resume_of else "sched_admit"
        get_recovery_log().record(
            event, request.name,
            lease=lease.id, work=request.kind,
            predicted_s=price.seconds, price_source=price.source,
            roofline=price.roofline, rows=request.rows,
            deferrals=lease.deferrals,
            **(
                {"resume_of": request.resume_of}
                if request.resume_of else {}
            ),
        )
        if request.resume_of:
            self._m_leases.inc(kind=request.kind, outcome="resumed")
        # The lease span carries the cost provenance: the trace shows
        # WHY this work was allowed to run where it ran.
        lease._span_stack = contextlib.ExitStack()
        lease._span_stack.enter_context(
            _spans.span(
                "sched:lease",
                lease=lease.id, work=request.name, kind=request.kind,
                predicted_s=price.seconds or 0.0,
                price_source=price.source,
                roofline=price.roofline or "unknown",
                rows=request.rows, deferrals=lease.deferrals,
            )
        )
        return lease

    def release(self, lease: Lease) -> None:
        """The leased work returned (complete or preempted): join the
        measured wall to the prediction and retire the lease."""
        if lease.admitted and lease.admitted_t is not None:
            lease.measured_s = self.clock() - lease.admitted_t
        if lease._span_stack is not None:
            lease._span_stack.close()
            lease._span_stack = None
        kind = lease.request.kind
        if lease.preempted_at_chunk is not None:
            self._m_leases.inc(kind=kind, outcome="preempted")
            get_recovery_log().record(
                "sched_preempt", lease.request.name,
                lease=lease.id, work=kind,
                chunk_index=lease.preempted_at_chunk,
                displaced_by=lease.displaced_by,
                measured_s=lease.measured_s,
            )
        elif lease.admitted:
            lease.state = "completed"
            self._m_leases.inc(kind=kind, outcome="completed")
        if lease.admitted and lease.measured_s is not None:
            with self._lock:
                self._idle_harvest_s += lease.measured_s
            self._m_harvest.inc(lease.measured_s)
            if lease.price.seconds:
                self._m_ratio.observe(
                    lease.measured_s / lease.price.seconds,
                    source=lease.price.source,
                )
            try:
                from ..obs.cost import note_lease_result

                note_lease_result(
                    lease.request.name, kind, lease.price.seconds,
                    lease.measured_s, lease.price.source,
                )
            except Exception:
                pass  # the observatory is evidence, never a failure path
        self._append_log(lease)

    @contextlib.contextmanager
    def lease(self, request: LeaseRequest, wait_s: float = 0.0):
        """``with scheduler.lease(req) as lease:`` — admit (or defer),
        run, release. Yields None when the lease stayed deferred."""
        handle = self.submit(request, wait_s=wait_s)
        if not handle.admitted:
            yield None
            return
        try:
            yield handle
        finally:
            self.release(handle)

    # ------------------------------------------------------- chunk policy
    def chunk_rows_for(
        self, rows: int, width: int, classes: int,
        chain: str = "()", default: Optional[int] = None,
    ) -> Tuple[int, int, str]:
        """Chunk geometry for a scheduled fold (pricing ladder: tuned
        entry wins, else roofline placement; docs/SCHEDULING.md)."""
        return choose_chunk_rows(
            rows, width, classes, chain=chain, store=self.store,
            default=default,
        )

    # ------------------------------------------------------------- report
    def _append_log(self, lease: Lease) -> None:
        entry = {
            "lease": lease.id,
            "name": lease.request.name,
            "kind": lease.request.kind,
            "rows": lease.request.rows,
            "outcome": lease.state,
            "deferrals": lease.deferrals,
            "price": lease.price.to_json(),
            "predicted_s": lease.price.seconds,
            "measured_s": lease.measured_s,
        }
        if lease.displaced_by:
            entry["displaced_by"] = lease.displaced_by
        if lease.preempted_at_chunk is not None:
            entry["preempted_at_chunk"] = lease.preempted_at_chunk
        if lease.request.resume_of:
            entry["resume_of"] = lease.request.resume_of
        if lease.predicted_vs_measured_ratio() is not None:
            entry["ratio"] = round(lease.predicted_vs_measured_ratio(), 4)
        with self._lock:
            self._log.append(entry)

    def schedule(self) -> List[Dict[str, Any]]:
        """The lease log, oldest first — what ``explain --schedule``
        renders: who ran on the mesh, what was displaced or deferred,
        predicted vs measured wall per lease."""
        with self._lock:
            return [dict(e) for e in self._log]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            log = list(self._log)
            harvest = self._idle_harvest_s
        outcomes: Dict[str, int] = {}
        for e in log:
            outcomes[e["outcome"]] = outcomes.get(e["outcome"], 0) + 1
        return {
            "name": self.name,
            "leases": len(log),
            "outcomes": outcomes,
            "idle_harvest_s": round(harvest, 6),
            "pressure": self.pressure(),
        }


# ------------------------------------------------------- pressure cadence


def pressure_aware_interval(
    base_s: float,
    tap_fill_frac: float,
    pressure: bool,
    min_s: Optional[float] = None,
    max_s: Optional[float] = None,
) -> float:
    """The refit daemon's sleep, driven by the two live signals instead
    of a fixed knob: a tap filling toward its drop-oldest bound shrinks
    the interval (drain sooner — dropped rows are unrecoverable), SLO
    pressure doubles it (serving owns the mesh right now). Pure in its
    inputs — the deterministic-clock unit test pins the shape."""
    lo = min_s if min_s is not None else base_s / 8.0
    hi = max_s if max_s is not None else base_s * 4.0
    frac = min(max(float(tap_fill_frac), 0.0), 1.0)
    interval = base_s * (1.0 - frac)
    if pressure:
        interval = max(interval, base_s) * 2.0
    return min(max(interval, lo), hi)


# ------------------------------------------------------------ module global

_scheduler: Optional[MeshScheduler] = None
_scheduler_lock = threading.Lock()


def set_scheduler(scheduler: Optional[MeshScheduler]) -> None:
    global _scheduler
    with _scheduler_lock:
        _scheduler = scheduler


def get_scheduler() -> Optional[MeshScheduler]:
    """The process's mesh scheduler, or None (unscheduled paths are
    byte-for-byte the old behavior). ``KEYSTONE_SCHED=off`` disables
    even an installed scheduler."""
    if env_disabled("KEYSTONE_SCHED"):
        return None
    with _scheduler_lock:
        return _scheduler


@contextlib.contextmanager
def maybe_lease(
    name: str, kind: str, rows: int = 0, width: int = 0, classes: int = 0,
    chain: str = "()", wait_s: float = 0.0,
):
    """Lease mesh time when a scheduler is installed; a no-op (yields
    None) otherwise — how tune probes and finish reductions opt in
    without taking a hard dependency on the scheduler."""
    scheduler = get_scheduler()
    if scheduler is None:
        yield None
        return
    with scheduler.lease(
        LeaseRequest(
            name=name, kind=kind, rows=rows, width=width,
            classes=classes, chain=chain,
        ),
        wait_s=wait_s,
    ) as lease:
        yield lease
