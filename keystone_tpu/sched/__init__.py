"""Cost-governed co-scheduling: background work admitted into serving
idle gaps on one mesh, priced from the PR-14 cost observatory and
preempted at chunk boundaries through the PR-15 durable-fold substrate
(docs/SCHEDULING.md)."""

from .pricing import (  # noqa: F401
    LeasePrice,
    choose_chunk_rows,
    gram_stream_facts,
    price_stream_fold,
)
from .scheduler import (  # noqa: F401
    Lease,
    LeaseRequest,
    MeshScheduler,
    get_scheduler,
    maybe_lease,
    pressure_aware_interval,
    set_scheduler,
)
