"""The solver-agnostic stream-state contract.

``fit_stream`` estimators accumulate *mergeable* state: for the Gram
family that is the ``(AᵀA, AᵀY, Σx, Σy)`` carry ``parallel/linalg.py``
threads through the chunk plan — O(d²), additive over row chunks, and
sufficient to finish a fit with zero data passes. This module freezes
that property into a portable envelope so the statistics captured at fit
time can be persisted, shipped, merged with later traffic, and finished
into a NEW fitted transformer without ever refitting from scratch — the
heart of the continuous-refit loop (docs/REFIT.md).

The contract is deliberately NOT Gram-specific: an envelope names its
accumulation ``kind`` and carries an opaque host-numpy carry pytree plus
the example count. ``merge_stream_states`` applies the kind's merge rule
(``additive`` today; a future sketch tier registers its own), so the
Panther-style sketched solvers (PAPERS.md) ride the same loop by
exporting a different kind with O(s·d) carries.

Estimator surface (the three ``supports_fit_stream`` estimators —
``LinearMapEstimator``, ``BlockLeastSquaresEstimator``, and the
``LeastSquaresEstimator`` meta-solver — all implement it):

- ``fit_stream(stream, state=None)`` — ``state`` seeds the fold carry
  with previously captured statistics, so new chunks EXTEND the old fit.
- ``export_stream_state()`` — the envelope captured by this instance's
  most recent ``fit_stream`` (host numpy; safe to pickle), or ``None``.
- ``merge_stream_state(a, b)`` — combine two envelopes (disjoint data).
- ``finish_from_state(state)`` — a fitted transformer from statistics
  alone: no stream, no data, one device round for the solve.

Persistence rides the reliability checkpoint store
(:class:`~keystone_tpu.reliability.checkpoint.CheckpointStore`): the
same atomic-write ``<digest>.pkl`` directory training checkpoints and
serving artifacts already share, keyed by :func:`stream_state_key`.

Import discipline: stdlib + numpy only at module scope (jax loads
lazily inside the few device touch points), so the serving/refit control
plane can import this without paying a backend import.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

#: Envelope format — bump when the layout changes; loads refuse unknown
#: versions loudly rather than mis-merging silently.
FORMAT_VERSION = 1

#: kind → merge rule. "additive" is the Gram family's algebra (leafwise
#: sum of carries, sum of example counts). The sketch tier's carry
#: (keystone_tpu/sketch) is additive by construction — every row's
#: contribution is a deterministic function of its absolute index — so
#: it registers the SAME rule and inherits merge/scaled()/resume whole.
MERGE_RULES: Dict[str, str] = {"gram": "additive", "sketch": "additive"}

#: Per-kind meta keys that must AGREE for two envelopes to combine
#: (lenient when either side never recorded them — old envelopes).
#: Sketch carries are sums of hash-seeded row contributions: adding
#: sketches drawn from different (variant, seed) maps is algebra on
#: unrelated projections and must fail loudly.
MERGE_META_KEYS: Dict[str, Tuple[str, ...]] = {
    "sketch": ("sketch_variant", "sketch_seed"),
}


class StateMismatch(ValueError):
    """Two envelopes (or an envelope and a stream) that can never be
    combined: different kinds, shapes, or format versions. Raised BEFORE
    any accumulation happens — a mismatched merge must fail loudly, not
    produce statistics that solve to garbage."""


@dataclass
class StreamState:
    """One estimator's exported sufficient statistics.

    ``carry`` is a tuple of host numpy arrays (the estimator's fold
    carry, device-fetched), ``num_examples`` the rows it has absorbed,
    ``meta`` whatever the estimator needs to finish (d, k, reg...).
    """

    kind: str
    estimator: str
    num_examples: int
    carry: Tuple[np.ndarray, ...]
    meta: Dict[str, Any] = field(default_factory=dict)
    format_version: int = FORMAT_VERSION

    def nbytes(self) -> int:
        return int(sum(a.nbytes for a in self.carry))

    def scaled(self, decay: float) -> "StreamState":
        """Exponential forgetting for additive kinds: every statistic
        (and the effective example count) scaled by ``decay`` ∈ (0, 1].
        Folding new rows on a decayed state is a recency-weighted fit —
        the knob that lets a drifting workload's OLD distribution stop
        dominating the Gram (docs/REFIT.md). ``decay=1`` is a no-op;
        the algebra stays exact because the centering identity uses the
        same effective count the sums were scaled by."""
        if not 0.0 < decay <= 1.0:
            raise StateMismatch(f"decay must be in (0, 1], got {decay}")
        if decay == 1.0:
            return self
        return StreamState(
            kind=self.kind,
            estimator=self.estimator,
            num_examples=max(int(round(self.num_examples * decay)), 1),
            carry=tuple(np.asarray(a) * decay for a in self.carry),
            meta=dict(self.meta),
            format_version=self.format_version,
        )

    def describe(self) -> Dict[str, Any]:
        """Telemetry/ledger view — shapes and counts, never payloads."""
        return {
            "kind": self.kind,
            "estimator": self.estimator,
            "num_examples": int(self.num_examples),
            "carry_shapes": [tuple(a.shape) for a in self.carry],
            "nbytes": self.nbytes(),
            "format_version": self.format_version,
        }


def _check_compatible(a: StreamState, b: StreamState) -> None:
    if a.format_version != b.format_version:
        raise StateMismatch(
            f"format versions differ: {a.format_version} vs {b.format_version}"
        )
    if a.kind != b.kind:
        raise StateMismatch(f"state kinds differ: {a.kind!r} vs {b.kind!r}")
    shapes_a = [tuple(x.shape) for x in a.carry]
    shapes_b = [tuple(x.shape) for x in b.carry]
    if shapes_a != shapes_b:
        raise StateMismatch(
            f"carry shapes differ: {shapes_a} vs {shapes_b} — these "
            "statistics were captured over different feature spaces"
        )
    for key in MERGE_META_KEYS.get(a.kind, ()):
        va, vb = a.meta.get(key), b.meta.get(key)
        if va is not None and vb is not None and va != vb:
            raise StateMismatch(
                f"{a.kind!r} states disagree on {key}: {va!r} vs {vb!r} — "
                "carries under different sketch maps cannot be summed"
            )


def merge_stream_states(a: StreamState, b: StreamState) -> StreamState:
    """Combine two envelopes captured over DISJOINT data. For additive
    kinds the merged statistics are exactly what one pass over the union
    would have produced — the property the round-trip tests pin."""
    _check_compatible(a, b)
    rule = MERGE_RULES.get(a.kind)
    if rule != "additive":
        raise StateMismatch(
            f"no merge rule for state kind {a.kind!r} "
            f"(known: {sorted(MERGE_RULES)})"
        )
    return StreamState(
        kind=a.kind,
        estimator=a.estimator,
        num_examples=int(a.num_examples) + int(b.num_examples),
        carry=tuple(
            np.asarray(x) + np.asarray(y) for x, y in zip(a.carry, b.carry)
        ),
        meta=dict(a.meta),
        format_version=a.format_version,
    )


# --------------------------------------------------------------- persistence


def stream_state_key(name: str) -> str:
    """Stable checkpoint-store digest for a named refit state. Namespaced
    so refit states can never collide with prefix-digest fit entries in
    a shared store directory."""
    return hashlib.sha1(f"keystone-refit-state:{name}".encode()).hexdigest()


def save_stream_state(store: Any, name: str, state: StreamState) -> bool:
    """Persist ``state`` under ``name`` in a reliability
    :class:`CheckpointStore` (atomic tmp+rename write). Returns False
    when the store refused (unpicklable — should never happen for numpy
    carries)."""
    return store.save(None, state, digest=stream_state_key(name))


def load_stream_state(store: Any, name: str) -> Optional[StreamState]:
    """The persisted state for ``name``, or None (missing/torn entries
    are misses, the checkpoint-store contract)."""
    from ..reliability.checkpoint import _MISS

    value = store.lookup(None, digest=stream_state_key(name))
    if value is _MISS or not isinstance(value, StreamState):
        return None
    if value.format_version != FORMAT_VERSION:
        return None  # refuse to extend a layout this build doesn't speak
    return value


# ------------------------------------------------------------ the Gram mixin


class GramStreamStateMixin:
    """State-contract plumbing shared by the Gram-family estimators.

    Concrete estimators implement ``_finish_from_stats(carry, n)`` —
    fitted transformer from the (device) carry and total row count — and
    get ``export_stream_state`` / ``merge_stream_state`` /
    ``finish_from_state`` plus the fold-side helpers for free. The
    captured envelope lands on ``self._stream_state`` (underscored on
    purpose: excluded from checkpoint digests, so capturing state never
    changes an estimator's structural identity).
    """

    stream_state_kind = "gram"

    def export_stream_state(self) -> Optional[StreamState]:
        return getattr(self, "_stream_state", None)

    def merge_stream_state(self, a: StreamState, b: StreamState) -> StreamState:
        return merge_stream_states(a, b)

    def finish_from_state(self, state: StreamState):
        """A fitted transformer from statistics alone (no data pass).

        The finish is a standalone mesh reduction (the Gram/sketch
        solve), so it opts into the co-scheduler when one is installed
        (docs/SCHEDULING.md): admitted into an idle gap it is priced,
        spanned, and harvested; under pressure the deferral is ledgered
        but the solve still runs — callers (publish, rollback, boot)
        need the model synchronously."""
        import jax.numpy as jnp

        from ..sched.scheduler import maybe_lease

        self._check_state_kind(state)
        carry = tuple(jnp.asarray(a) for a in state.carry)
        width, classes = (
            (int(carry[1].shape[0]), int(carry[1].shape[-1]))
            if len(carry) > 1 and getattr(carry[1], "ndim", 0) >= 1
            else (0, 0)
        )
        with maybe_lease(
            f"{type(self).__name__}:finish", "finish",
            rows=int(state.num_examples), width=width, classes=classes,
        ):
            return self._finish_from_stats(carry, int(state.num_examples))

    # ------------------------------------------------------- fold-side hooks
    def _check_state_kind(self, state: StreamState) -> None:
        if state.format_version != FORMAT_VERSION:
            raise StateMismatch(
                f"state format v{state.format_version} != v{FORMAT_VERSION}"
            )
        if state.kind != self.stream_state_kind:
            raise StateMismatch(
                f"{type(self).__name__} accumulates {self.stream_state_kind!r} "
                f"state, got {state.kind!r}"
            )

    def _seed_carry(self, state: Optional[StreamState], d: int, k: int):
        """The fold's initial carry: fresh zeros, or ``state``'s
        statistics (shape-checked against the stream's featurized
        width) so new chunks extend the old fit."""
        from ..parallel import linalg

        if state is None:
            return linalg.gram_stream_init(d, k)
        self._check_state_kind(state)
        want = [(d, d), (d, k), (d,), (k,)]
        got = [tuple(a.shape) for a in state.carry]
        if got != want:
            raise StateMismatch(
                f"resume state shaped {got} cannot seed a (d={d}, k={k}) "
                f"stream (want {want})"
            )
        import jax
        import jax.numpy as jnp

        carry = tuple(jnp.asarray(a, jnp.float32) for a in state.carry)
        # One-time fold setup, and load-bearing: the fold's step jit
        # DONATES the carry, and with a warm compilation cache the first
        # chunk dispatches immediately — donating a buffer whose async
        # host→device transfer has not committed corrupts the seed
        # (observed as nondeterministic garbage fits). Commit the O(d²)
        # transfer before the donating dispatch can race it.
        # keystone: allow-sync
        return jax.block_until_ready(carry)

    def _capture_state(self, carry, n_total: int, **meta: Any) -> StreamState:
        """Device-fetch the post-fold carry into a portable envelope and
        remember it on the instance for ``export_stream_state``."""
        import jax

        # Export crosses to host by definition: the envelope must pickle
        # into the checkpoint store.  # keystone: allow-sync
        host = tuple(np.asarray(jax.device_get(a)) for a in carry)
        state = StreamState(
            kind=self.stream_state_kind,
            estimator=f"{type(self).__module__}.{type(self).__qualname__}",
            num_examples=int(n_total),
            carry=host,
            meta=dict(meta),
        )
        self._stream_state = state
        return state


# ---------------------------------------------------------- the sketch mixin


class SketchStreamStateMixin(GramStreamStateMixin):
    """State-contract plumbing for the sketch tier (keystone_tpu/sketch).

    Identical protocol to the Gram mixin — the carry is additive, so
    export/merge/``scaled()``/resume are inherited verbatim — with a
    different kind tag, a 5-leaf ``(SA, SY, s1, Σx, Σy)`` carry whose
    leading dimension is the sketch size s (not d), and a meta
    compatibility check: a resumed fold must keep accumulating under the
    SAME (variant, seed) sketch map or the sum is meaningless.
    """

    stream_state_kind = "sketch"

    def _check_state_kind(self, state: StreamState) -> None:
        super()._check_state_kind(state)
        mine = getattr(self, "stream_state_meta", {}) or {}
        for key in MERGE_META_KEYS["sketch"]:
            va, vb = state.meta.get(key), mine.get(key)
            if va is not None and vb is not None and va != vb:
                raise StateMismatch(
                    f"resume state's {key}={va!r} != estimator's {vb!r} — "
                    "a fold cannot extend a sketch drawn from a different map"
                )

    def _seed_carry(self, state: Optional[StreamState], s: int, d: int, k: int):
        """Fresh zeros, or ``state``'s sketch seeded onto device —
        shape-checked so a fold never extends statistics captured over a
        different (s, d, k) geometry."""
        if state is None:
            from ..sketch.core import sketch_stream_init

            return sketch_stream_init(s, d, k)
        self._check_state_kind(state)
        want = [(s, d), (s, k), (s,), (d,), (k,)]
        got = [tuple(a.shape) for a in state.carry]
        if got != want:
            raise StateMismatch(
                f"resume state shaped {got} cannot seed a (s={s}, d={d}, "
                f"k={k}) sketch stream (want {want})"
            )
        import jax
        import jax.numpy as jnp

        carry = tuple(jnp.asarray(a, jnp.float32) for a in state.carry)
        # Same commit-before-donate discipline as the Gram seed: the fold
        # step donates this buffer on the first dispatch.
        # keystone: allow-sync
        return jax.block_until_ready(carry)
