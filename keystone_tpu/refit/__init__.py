"""Continuous refit: a live-traffic incremental retraining loop.

KeystoneML's batch model ends at ``fit``; this package composes the
repo's existing investments into a living system (docs/REFIT.md):

- :mod:`state`   — the solver-agnostic stream-state contract: estimators
                   with ``supports_fit_stream`` export their mergeable
                   O(d²) sufficient statistics (``export_stream_state``),
                   extend them later (``fit_stream(..., state=)``), merge
                   partials (``merge_stream_states``), and finish a
                   fitted transformer from statistics alone
                   (``finish_from_state``) — no refit-from-scratch.
                   Persisted through the reliability checkpoint store.
- :mod:`tap`     — the traffic tap: a bounded spill buffer fed by served
                   requests (sampled) and/or a labeled side-channel,
                   with drop-counting backpressure that never blocks the
                   serve path.
- :mod:`shadow`  — shadow evaluation: score a candidate against the
                   incumbent with the ``evaluation/`` suite (and
                   mirrored live traffic) before anything publishes.
- :mod:`publish` — the publish/rollback controller: passing candidates
                   publish via ``ModelRegistry`` hot-swap (in-process)
                   or ``WorkerSupervisor.swap`` (per-worker re-warm
                   acks); a post-publish watch window on serving metrics
                   and live score triggers automatic rollback to the
                   retained previous version. Every publish/skip/
                   rollback lands in the recovery ledger and the
                   ``keystone_refit_*`` metrics.
- :mod:`daemon`  — the supervised refit loop driving tap → fold →
                   shadow-eval → publish/watch, plus the synthetic
                   drifting-workload demo behind ``keystone-tpu refit``.

Exports resolve lazily (PEP 562, like the package root): the Gram
estimators import :mod:`state` at module scope, and pulling the whole
control plane in from there would both slow that import and risk cycles.
"""

from __future__ import annotations

_LAZY = {
    "GramStreamStateMixin": "keystone_tpu.refit.state",
    "StateMismatch": "keystone_tpu.refit.state",
    "StreamState": "keystone_tpu.refit.state",
    "load_stream_state": "keystone_tpu.refit.state",
    "merge_stream_states": "keystone_tpu.refit.state",
    "save_stream_state": "keystone_tpu.refit.state",
    "stream_state_key": "keystone_tpu.refit.state",
    "TrafficTap": "keystone_tpu.refit.tap",
    "ShadowEvaluator": "keystone_tpu.refit.shadow",
    "ShadowReport": "keystone_tpu.refit.shadow",
    "InProcessPublisher": "keystone_tpu.refit.publish",
    "PublishTicket": "keystone_tpu.refit.publish",
    "SupervisorPublisher": "keystone_tpu.refit.publish",
    "RefitConfig": "keystone_tpu.refit.daemon",
    "RefitDaemon": "keystone_tpu.refit.daemon",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        module = importlib.import_module(_LAZY[name])
        value = getattr(module, name)
        globals()[name] = value
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
