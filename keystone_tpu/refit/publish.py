"""The publish/rollback controller: how a passing candidate reaches
traffic, and how a regressing one leaves it.

Two publishers, one contract:

- :class:`InProcessPublisher` — the single-process
  :class:`~keystone_tpu.serving.server.PipelineServer`: publish is a
  registry hot-swap (in-flight batches finish on the entry they
  resolved) followed by an AOT re-warm of every bucket, which restamps
  the compile baseline — steady state after a settled publish does zero
  XLA compiles, the same contract the worker swap path keeps.
- :class:`SupervisorPublisher` — the multi-worker fleet: the candidate
  is persisted to the reliability checkpoint store (atomic write, the
  shared training/serving artifact format) and broadcast via
  ``WorkerSupervisor.swap`` with the checkpoint digest; every ready
  worker re-warms and acks WITH the version it warmed. The supervisor's
  restart spec is repointed at the published digest, so a worker that
  crashes later comes back up on the version the fleet is serving, not
  the boot-time one.

Before any swap, the candidate passes the KV305 publish verifier
(:func:`~keystone_tpu.workflow.verify.verify_refit_publish`): a
candidate whose apply spec or bucket set disagrees with the incumbent's
warmed buckets would recompile on live traffic after the ack said
"warm" — warn-by-default, ``KEYSTONE_VERIFY=strict`` refuses the
publish (the standard verifier enforcement contract).

Rollback is an O(1) pointer swap to the registry's retained previous
version (bounded history, serving/registry.py) — no artifact re-load.
Every publish and rollback lands in the recovery ledger
(``refit_publish`` / ``refit_rollback``) and the ``keystone_refit_*``
counters; the daemon's post-publish watch window decides WHEN to roll
back (refit/daemon.py).
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import numpy as np

from ..obs import names as _names
from ..reliability.faultinject import probe
from ..reliability.recovery import get_recovery_log


@dataclass
class PublishTicket:
    """One publish, with everything rollback needs held in hand."""

    name: str
    version: Any
    prev_version: Any
    source: str
    acks: Dict[str, Any] = field(default_factory=dict)
    digest: Optional[str] = None
    prev_digest: Optional[str] = None
    published_at: float = field(default_factory=time.time)

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "version": self.version,
            "prev_version": self.prev_version,
            "source": self.source,
            "acks": {k: dict(v) for k, v in self.acks.items()},
            "digest": self.digest,
        }


def _verify_publish(candidate, incumbent, example, buckets, warmed) -> None:
    """KV305 gate under the standard KEYSTONE_VERIFY enforcement: warn
    logs, strict raises VerificationError, off skips. An internal
    verifier crash never blocks a publish (only verified findings do)."""
    from ..workflow.verify import (
        VerificationError,
        verification_mode,
        verify_refit_publish,
    )

    mode = verification_mode()
    if mode == "off":
        return
    try:
        report = verify_refit_publish(
            candidate,
            incumbent,
            example=example,
            buckets=buckets,
            warmed_buckets=warmed,
        )
    except Exception:
        import logging

        logging.getLogger(__name__).warning(
            "refit publish verification failed internally (ignored)",
            exc_info=True,
        )
        return
    if not report.ok:
        import logging

        for d in report.errors():
            logging.getLogger(__name__).warning(
                "refit publish verify: %s", d.render()
            )
        if mode == "strict":
            raise VerificationError(report)


class InProcessPublisher:
    """Publish/rollback against a live :class:`PipelineServer`."""

    def __init__(
        self,
        server: Any,
        name: Optional[str] = None,
        example: Any = None,
        warm: bool = True,
    ):
        self.server = server
        self.name = name or server.default_model
        #: one request payload — drives the per-bucket re-warm after
        #: every publish/rollback (no example = no re-warm, the caller
        #: owns warming).
        self.example = example
        self.warm = warm
        self._m_publishes = _names.metric(_names.REFIT_PUBLISHES)
        self._m_rollbacks = _names.metric(_names.REFIT_ROLLBACKS)

    # ------------------------------------------------------------------ state
    def current_entry(self):
        return self.server.registry.resolve(self.name)

    def current_model(self):
        return self.current_entry().model

    def apply_live(self, x: np.ndarray) -> np.ndarray:
        """Predictions of the LIVE (currently published) version — the
        watch window scores exactly what traffic is being served by."""
        from ..data.dataset import ArrayDataset

        out = self.current_entry().batch_apply(
            ArrayDataset(np.asarray(x, np.float32))
        )
        data = getattr(out, "data", out)
        # Watch-window scoring is host-side numpy.  # keystone: allow-sync
        return np.asarray(data)[: np.asarray(x).shape[0]]

    def serving_stats(self) -> Dict[str, Any]:
        return self.server.stats()

    def settle(self) -> None:
        """End-of-round baseline restamp: every refit-side compile (fold
        step, shadow/watch scoring of fresh model objects) lands before
        this, so serving-only traffic between rounds reads
        ``xla_compiles_since_warmup == 0`` — the invariant the chaos
        smoke asserts."""
        restamp = getattr(self.server, "restamp_compile_baseline", None)
        if restamp is not None:
            restamp()

    # ---------------------------------------------------------------- publish
    def publish(self, candidate: Any, round_index: int = 0) -> PublishTicket:
        probe("refit.publish")
        incumbent = self.current_entry()
        _verify_publish(
            candidate,
            incumbent.model,
            self.example,
            self.server.config.buckets(),
            self.server.telemetry.warmed_buckets(),
        )
        entry = self.server.registry.publish(
            self.name, candidate, source=f"refit:round{round_index}"
        )
        t0 = time.monotonic()
        if self.warm and self.example is not None:
            # The in-process re-warm "ack": every bucket AOT-driven
            # through the new version, compile baseline restamped —
            # steady state after this does zero compiles.
            self.server.warmup(self.example, models=[self.name])
        ticket = PublishTicket(
            name=self.name,
            version=entry.version,
            prev_version=incumbent.version,
            source=entry.source,
            acks={
                "in-process": {
                    "kind": "swapped",
                    "version": entry.version,
                    "warmup_s": round(time.monotonic() - t0, 3),
                }
            },
        )
        self._m_publishes.inc()
        get_recovery_log().record(
            "refit_publish",
            self.name,
            version=entry.version,
            prev_version=incumbent.version,
            round=round_index,
        )
        return ticket

    def rollback(self, ticket: PublishTicket, reason: str = "") -> Any:
        """O(1) pointer swap back to the retained previous version, then
        re-warm so rolled-back steady state is compile-free too."""
        entry = self.server.registry.rollback(self.name, ticket.prev_version)
        if self.warm and self.example is not None:
            self.server.warmup(self.example, models=[self.name])
        self._m_rollbacks.inc()
        get_recovery_log().record(
            "refit_rollback",
            self.name,
            from_version=ticket.version,
            to_version=entry.version,
            reason=reason,
        )
        return entry


class SupervisorPublisher:
    """Publish/rollback across a :class:`WorkerSupervisor` fleet via the
    checkpoint store + swap broadcast (per-worker re-warm acks)."""

    def __init__(
        self,
        supervisor: Any,
        store_path: str,
        name: Optional[str] = None,
        incumbent: Any = None,
        incumbent_digest: Optional[str] = None,
    ):
        from ..reliability.checkpoint import CheckpointStore

        self.supervisor = supervisor
        self.store = CheckpointStore(store_path)
        self.name = name or supervisor.config.model_name
        #: the daemon fits candidates in THIS process; the incumbent
        #: model object is tracked here for shadow eval (workers hold
        #: their own copies loaded from the store).
        self._current = incumbent
        self._current_digest = incumbent_digest
        self._version = 0
        self._m_publishes = _names.metric(_names.REFIT_PUBLISHES)
        self._m_rollbacks = _names.metric(_names.REFIT_ROLLBACKS)

    def current_model(self):
        return self._current

    def serving_stats(self) -> Dict[str, Any]:
        return self.supervisor.stats()

    def apply_live(self, x: np.ndarray) -> np.ndarray:
        """Live predictions through the FLEET (real served traffic)."""
        futures = self.supervisor.submit_many(
            [row.tolist() for row in np.asarray(x, np.float32)],
            deadline_s=30.0,
        )
        return np.asarray([f.result(timeout=60.0) for f in futures])

    def _persist(self, candidate: Any, tag: str) -> str:
        import pickle

        # Content-addressed like every other store entry: a digest built
        # from (name, round) alone would collide across daemon restarts —
        # a new run's round-1 candidate would OVERWRITE the entry the
        # previous ticket's rollback points at, silently re-installing
        # the regressing model.
        try:
            content = hashlib.sha1(pickle.dumps(candidate)).hexdigest()
        except Exception as exc:
            raise RuntimeError(
                f"checkpoint store refused refit candidate {tag!r} "
                f"(unpicklable model of type {type(candidate).__name__})"
            ) from exc
        digest = hashlib.sha1(
            f"refit-candidate:{self.name}:{tag}:{content}".encode()
        ).hexdigest()
        if not self.store.save(None, candidate, digest=digest):
            raise RuntimeError(
                f"checkpoint store refused refit candidate {tag!r} "
                f"(unpicklable model of type {type(candidate).__name__})"
            )
        return digest

    def _swap_to(self, digest: str) -> Dict[str, Dict[str, Any]]:
        spec = {"checkpoint_dir": self.store.path, "digest": digest}
        acks = self.supervisor.swap(spec, name=self.name)
        swapped = [a for a in acks.values() if a.get("kind") == "swapped"]
        if acks and not swapped:
            raise RuntimeError(f"no worker acked the swap: {acks}")
        # Restarts must come up on what the fleet is serving NOW.
        self.supervisor.spec = spec
        return acks

    def publish(self, candidate: Any, round_index: int = 0) -> PublishTicket:
        probe("refit.publish")
        _verify_publish(candidate, self._current, None, None, None)
        digest = self._persist(candidate, f"round{round_index}")
        acks = self._swap_to(digest)
        self._version += 1
        ticket = PublishTicket(
            name=self.name,
            version=self._version,
            prev_version=self._version - 1,
            source=f"refit:round{round_index}",
            acks=acks,
            digest=digest,
            prev_digest=self._current_digest,
        )
        self._prev = self._current
        self._current = candidate
        self._current_digest = digest
        self._m_publishes.inc()
        get_recovery_log().record(
            "refit_publish",
            self.name,
            digest=digest[:12],
            round=round_index,
            acked=len([a for a in acks.values() if a.get("kind") == "swapped"]),
        )
        return ticket

    def rollback(self, ticket: PublishTicket, reason: str = "") -> Any:
        if ticket.prev_digest is None:
            raise RuntimeError(
                "no previous digest retained — cannot roll the fleet back"
            )
        acks = self._swap_to(ticket.prev_digest)
        self._current = getattr(self, "_prev", self._current)
        self._current_digest = ticket.prev_digest
        self._m_rollbacks.inc()
        get_recovery_log().record(
            "refit_rollback",
            self.name,
            to_digest=ticket.prev_digest[:12],
            reason=reason,
            acked=len([a for a in acks.values() if a.get("kind") == "swapped"]),
        )
        return self._current
