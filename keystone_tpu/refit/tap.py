"""The traffic tap: a bounded spill buffer between serving and refit.

The serve path is sacred — nothing the refit loop does may add latency
to a request. So the tap is a pair of bounded host-numpy ring buffers
behind one lock, with O(1) non-blocking ``offer`` semantics:

- ``feed(x, y)``   — the LABELED side-channel (delayed labels, human
                     review, a downstream join): the rows the refit
                     daemon actually trains on.
- ``observe(x)``   — sampled served payloads (no labels): the mirror
                     set the shadow evaluator uses to compare candidate
                     vs incumbent predictions on real live traffic.

Backpressure is drop-oldest with loud accounting, never blocking: a
slow (or dead) refit daemon means the buffer wraps and the
``keystone_refit_tap_rows_total{status="dropped"}`` counter climbs —
and serving latency does not move (pinned by
tests/refit/test_tap.py::test_slow_daemon_never_stalls_serving).
Drop-OLDEST is deliberate: under drift the freshest rows are the ones
worth keeping.

Hook points (both opt-in, both default-off):

- ``PipelineServer(..., tap=...)`` samples settled request payloads into
  ``observe`` after each batch (off the submit hot path — the batch
  worker thread pays one lock + memcpy per sampled row).
- ``WorkerSupervisor(..., tap=...)`` samples accepted payloads at
  ``submit`` (the parent process is the only place that sees every
  request in the multi-worker runtime).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..obs import names as _names


class TrafficTap:
    """Bounded labeled + mirror buffers with drop-counting backpressure."""

    def __init__(
        self,
        capacity_rows: int = 65536,
        mirror_rows: int = 1024,
        sample_every: int = 1,
    ):
        self.capacity_rows = max(1, int(capacity_rows))
        self.mirror_capacity = max(1, int(mirror_rows))
        #: keep 1-in-N served payloads in the mirror set (labeled feeds
        #: are never sampled — labels are too expensive to discard at
        #: the door; the bound handles overload).
        self.sample_every = max(1, int(sample_every))
        self._lock = threading.Lock()
        self._labeled: List[Tuple[np.ndarray, np.ndarray]] = []
        self._mirror: List[np.ndarray] = []
        self._seen = 0
        self.fed = 0
        self.mirrored = 0
        self.dropped = 0
        self._m_rows = _names.metric(_names.REFIT_TAP_ROWS)

    # ------------------------------------------------------------------ doors
    def feed(self, x: Any, y: Any) -> int:
        """Offer labeled rows (one row, or a stacked batch). Returns how
        many rows were RETAINED after the bound dropped the oldest.
        Never blocks; never raises on full."""
        xs = np.atleast_2d(np.asarray(x))
        ys = np.asarray(y)
        if ys.ndim == 0:
            ys = ys.reshape(1)
        if ys.ndim == 1:
            # 1-D labels are one scalar label PER ROW (the class-label
            # form shadow eval supports) — except the single-row case,
            # where a length-k vector is that row's label vector.
            if ys.shape[0] == xs.shape[0] and xs.shape[0] != 1:
                ys = ys[:, None]
            else:
                ys = ys.reshape(1, -1)
        if ys.shape[0] != xs.shape[0]:
            # Misaligned batches are a caller bug worth refusing quietly
            # here (the serve path must never crash on a tap error).
            return 0
        rows = list(zip(xs, ys))
        with self._lock:
            self._labeled.extend(rows)
            overflow = len(self._labeled) - self.capacity_rows
            if overflow > 0:
                del self._labeled[:overflow]  # drop-OLDEST: keep fresh
            self.fed += len(rows)
            retained = len(rows) - max(overflow, 0)
            if overflow > 0:
                self.dropped += overflow
        self._m_rows.inc(len(rows), status="labeled")
        if overflow > 0:
            self._m_rows.inc(overflow, status="dropped")
        return max(retained, 0)

    def observe(self, x: Any) -> bool:
        """Sample one served payload into the mirror set (1-in-N).
        Returns True when the row was kept. O(1), non-blocking."""
        with self._lock:
            self._seen += 1
            if self._seen % self.sample_every:
                return False
            try:
                row = np.asarray(x)
            except Exception:
                return False  # unstackable payloads just aren't mirrored
            self._mirror.append(row)
            if len(self._mirror) > self.mirror_capacity:
                del self._mirror[: len(self._mirror) - self.mirror_capacity]
            self.mirrored += 1
        self._m_rows.inc(status="mirrored")
        return True

    def observe_batch(self, payloads: Any) -> None:
        for p in payloads:
            self.observe(p)

    # ------------------------------------------------------------------ drain
    def drain(self, max_rows: Optional[int] = None) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Take up to ``max_rows`` labeled rows (oldest first) out of the
        buffer as stacked ``(x, y)`` float32 matrices; None when empty.
        Rows whose shapes disagree with the MAJORITY shape of the drain
        are dropped (and counted) rather than poisoning the stack — or
        worse, being requeued to become the next drain's reference shape
        and starve it down to the anomalous minority."""
        with self._lock:
            if not self._labeled:
                return None
            take = len(self._labeled) if max_rows is None else min(
                max_rows, len(self._labeled)
            )
            rows = self._labeled[:take]
            self._labeled = self._labeled[take:]
            shapes: Dict[Any, int] = {}
            for r in rows:
                key = (r[0].shape, r[1].shape)
                shapes[key] = shapes.get(key, 0) + 1
            majority = max(shapes, key=shapes.get)
            keep = [r for r in rows if (r[0].shape, r[1].shape) == majority]
            misfits = len(rows) - len(keep)
            self.dropped += misfits
        if misfits:
            self._m_rows.inc(misfits, status="dropped")
        x = np.stack([r[0] for r in keep]).astype(np.float32)
        y = np.stack([r[1] for r in keep]).astype(np.float32)
        return x, y

    def mirror(self, max_rows: Optional[int] = None) -> Optional[np.ndarray]:
        """A COPY of the freshest mirrored payloads (they stay buffered —
        shadow evaluation reads them, it doesn't consume them)."""
        with self._lock:
            if not self._mirror:
                return None
            rows = self._mirror[-(max_rows or len(self._mirror)):]
            shape = rows[-1].shape
            rows = [r for r in rows if r.shape == shape]
        return np.stack(rows).astype(np.float32)

    # ------------------------------------------------------------------ stats
    def depth(self) -> int:
        with self._lock:
            return len(self._labeled)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "labeled_depth": len(self._labeled),
                "mirror_depth": len(self._mirror),
                "fed": self.fed,
                "mirrored": self.mirrored,
                "dropped": self.dropped,
                "capacity_rows": self.capacity_rows,
            }
